"""Runtime lock sanitizer: online order checking + a hold watchdog.

The static layer (``analysis/lockorder.py``) proves what the AST can
resolve; dynamic dispatch, module-attribute objects and data-dependent
paths are invisible to it. This module covers the remainder at TEST
time: under ``GOL_LOCKSAN=1`` the instrumented classes' locks
(``locksan.lock("Class._name")`` sites across engine/, rpc/, obs/) are
instrumented wrappers that maintain

* a per-thread HELD STACK (label, acquire time, acquiring stack), and
* a global online order graph: the first observed A-held-acquiring-B
  records the A→B edge with its stack; a later acquisition that closes
  a path back (B..→A observed while holding A and taking B reversed)
  is a :class:`LockOrderViolation` raised IN the acquiring thread —
  both stacks in the message, ``gol_locksan_violations_total{kind=
  "order"}`` metered, and the evidence written to
  ``out/locksan_<ts>.txt`` so a violation swallowed by a broad handler
  still fails ``scripts/check --locksan`` (which globs for artifacts).

A watchdog thread (daemon, started with the first instrumented lock)
fires when a lock has been held past ``GOL_LOCKSAN_DEADLINE`` seconds
(default 30) WITH waiters queued — the wedged-broker shape — dumping
all-thread tracebacks to the same artifact path and metering
``gol_locksan_violations_total{kind="watchdog"}``.

With ``GOL_LOCKSAN`` unset the factories return PLAIN ``threading``
objects — no wrapper type, no per-acquire bookkeeping, zero hot-path
overhead; the one ``if`` runs at construction time only. Identity is
the LABEL, not the instance: two SessionTables nesting each other's
``_lock`` is an unordered-instances hazard the label graph flags, and
cross-run order knowledge accumulates per lock ROLE, which is what the
static checker reasons about too.

Tests drive the sanitizer in-process via :func:`install` /
:func:`uninstall` / :func:`reset` (env is read once at import, so a
monkeypatched environ alone would not re-arm it).
"""

from __future__ import annotations

import os
import pathlib
import sys
import threading
import time
import traceback
import weakref
from typing import Dict, List, Optional, Tuple

_ENV = "GOL_LOCKSAN"
_DEADLINE_ENV = "GOL_LOCKSAN_DEADLINE"

_active = os.environ.get(_ENV, "") not in ("", "0")
_deadline = float(os.environ.get(_DEADLINE_ENV, "") or 30.0)
_out_dir = "out"


class LockOrderViolation(RuntimeError):
    """An observed acquisition inverted the recorded lock order. Raised
    in the acquiring thread BEFORE it blocks — the deadlock is reported
    as a test failure instead of a hang."""


class _Edges:
    """The global order graph + the live-lock registry, guarded by one
    internal lock that is NEVER held while blocking on a user lock."""

    def __init__(self):
        self.meta = threading.Lock()
        # (src label, dst label) -> (stack summary, thread name)
        self.edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.locks: List = []  # every live instrumented lock
        self.violations: List[str] = []
        self.watchdog_fires = 0
        self.watchdog_thread: Optional[threading.Thread] = None

    def reachable(self, src: str, dst: str) -> Optional[List[str]]:
        """A recorded path src -> .. -> dst, or None. Caller holds meta."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        stack = [(src, [src])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in adj.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None


_STATE = _Edges()
_TLS = threading.local()


def _held_stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _meter(kind: str) -> None:
    # lazy import: this module must stay importable before obs/ (and the
    # disabled path must not pay the import at all)
    try:
        from ..obs import instruments as _ins

        _ins.LOCKSAN_VIOLATIONS_TOTAL.labels(kind).inc()
    # gol: allow(hygiene): the violation report/abort that FOLLOWS this
    # meter is the evidence; a broken obs import must not mask it, and
    # logging from inside the sanitizer would recurse into the very
    # locks under test
    except Exception:  # pragma: no cover - metrics must never mask the abort
        pass


def _artifact_path() -> pathlib.Path:
    ts = time.strftime("%Y%m%d_%H%M%S")
    out = pathlib.Path(_out_dir)
    path = out / f"locksan_{ts}.txt"
    n = 1
    while path.exists():
        path = out / f"locksan_{ts}_{n}.txt"
        n += 1
    return path


def _all_thread_tracebacks() -> str:
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sys._current_frames().items():
        parts.append(
            f"--- thread {names.get(ident, '?')} (ident {ident}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    return "\n".join(parts)


def _write_artifact(header: str, body: str) -> Optional[pathlib.Path]:
    """Best-effort evidence file (temp-name + rename, the repo's
    artifact posture); a broken disk must not mask the violation."""
    try:
        path = _artifact_path()
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(header + "\n\n" + body + "\n")
        tmp.replace(path)
        return path
    except OSError:
        return None


def _site() -> str:
    """The acquiring call site (file:line in func), skipping locksan's
    own frames — cheap enough to stamp EVERY acquisition (full stacks
    are formatted only on first-edge recording and on violations)."""
    f = sys._getframe(1)
    while f is not None and f.f_globals.get("__name__") == __name__:
        f = f.f_back
    if f is None:  # pragma: no cover - only if called from module top
        return "<unknown>"
    co = f.f_code
    return f"{co.co_filename}:{f.f_lineno} in {co.co_name}"


class _Held:
    __slots__ = ("lock", "count", "t0", "site")

    def __init__(self, lock, site):
        self.lock = lock
        self.count = 1
        self.t0 = time.monotonic()
        self.site = site


class _SanLock:
    """Instrumented ``threading.Lock`` (``reentrant=True``: RLock).
    Implements the full Condition delegate protocol (``_is_owned`` /
    ``_release_save`` / ``_acquire_restore``) so ``threading.Condition``
    over an instrumented lock keeps exact wait/notify semantics —
    including multi-level RLock recursion across a ``wait()``."""

    _reentrant = False

    def __init__(self, label: str):
        self.label = label
        self._inner = (
            threading.RLock() if self._reentrant else threading.Lock()
        )
        # watchdog surface, read without meta (monotonic flags/counters;
        # an occasional torn read costs one watchdog period, never
        # correctness)
        self.holder: Optional[int] = None
        self.held_since = 0.0
        self.waiters = 0
        self.reported = False
        # weakref: per-connection locks (RpcServer.write_lock) must not
        # accumulate in the registry for the process lifetime — the
        # watchdog prunes dead refs as it scans
        with _STATE.meta:
            _STATE.locks.append(weakref.ref(self))
        _ensure_watchdog()

    # -- the lock protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        stack = _held_stack()
        mine = next((h for h in stack if h.lock is self), None)
        if mine is not None:
            if not self._reentrant:
                if not blocking:
                    return False  # a try-acquire probe, not a deadlock
                self._violation(
                    f"non-reentrant lock '{self.label}' re-acquired by "
                    f"the thread already holding it (self-deadlock)",
                    mine.site,
                )
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                mine.count += 1
            return ok
        if blocking and stack:
            self._check_order(stack)
        self.waiters += 1
        try:
            ok = self._inner.acquire(blocking, timeout)
        finally:
            self.waiters -= 1
        if ok:
            # a successful TRY-acquire is a real hold (stack push) but
            # not an ordering commitment: it cannot block, so the
            # hold-A/try-B backoff pattern must not poison the graph
            # with an A->B edge that a blocking B->A path then trips
            self._note_acquired(stack, record_edges=blocking)
        return ok

    def release(self):
        stack = _held_stack()
        mine = next(
            (h for h in reversed(stack) if h.lock is self), None
        )
        if mine is not None:
            mine.count -= 1
            if mine.count <= 0:
                stack.remove(mine)
                self.holder = None
                self.reported = False
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # -- the Condition delegate protocol -------------------------------------

    def _is_owned(self):
        return any(h.lock is self for h in _held_stack())

    def _release_save(self):
        """Fully release for a Condition.wait, whatever the recursion
        depth, returning what _acquire_restore needs to rebuild it."""
        stack = _held_stack()
        mine = next(
            (h for h in reversed(stack) if h.lock is self), None
        )
        count = mine.count if mine is not None else 1
        if mine is not None:
            stack.remove(mine)
            self.holder = None
            self.reported = False
        if self._reentrant:
            return (count, self._inner._release_save())
        self._inner.release()
        return (count, None)

    def _acquire_restore(self, saved):
        count, inner_state = saved
        stack = _held_stack()
        if stack:
            self._check_order(stack)
        self.waiters += 1
        try:
            if inner_state is not None:
                self._inner._acquire_restore(inner_state)
            else:
                self._inner.acquire()
        finally:
            self.waiters -= 1
        self._note_acquired(stack, count=count)

    # -- bookkeeping ---------------------------------------------------------

    def _note_acquired(self, stack, count: int = 1,
                       record_edges: bool = True):
        site = _site()
        if stack and record_edges:
            # record first-observed edges with a full stack: the price
            # is paid once per NEW edge, not per acquisition
            with _STATE.meta:
                for held in stack:
                    key = (held.lock.label, self.label)
                    if key not in _STATE.edges:
                        _STATE.edges[key] = (
                            "".join(traceback.format_stack(limit=16)[:-2]),
                            threading.current_thread().name,
                        )
        self.holder = threading.get_ident()
        self.held_since = time.monotonic()
        held = _Held(self, site)
        held.count = count
        stack.append(held)

    def _check_order(self, stack):
        """Abort BEFORE blocking when taking this lock closes a cycle
        against the recorded order: for any held H, a recorded path
        self -> .. -> H means some thread takes them the other way."""
        for held in stack:
            if held.lock.label == self.label:
                self._violation(
                    f"a second '{self.label}' instance acquired while "
                    f"one is already held — same lock ROLE nested with "
                    f"no defined instance order (ABBA across instances)",
                    held.site,
                )
        with _STATE.meta:
            for held in stack:
                path = _STATE.reachable(self.label, held.lock.label)
                if path is None:
                    continue
                first = _STATE.edges.get((path[0], path[1]))
                self._violation(
                    f"acquiring '{self.label}' while holding "
                    f"'{held.lock.label}' inverts the recorded order "
                    f"{' -> '.join(path + [self.label])}",
                    held.site,
                    recorded=first,
                    locked=True,
                )

    def _violation(self, summary, holder_site, recorded=None,
                   locked=False):
        current = "".join(traceback.format_stack(limit=16)[:-2])
        report = [
            f"LOCK ORDER VIOLATION: {summary}",
            f"thread: {threading.current_thread().name}",
            "",
            "--- acquiring thread, at the violating acquisition ---",
            current,
            f"--- same thread acquired the held lock at ---",
            f"  {holder_site}",
        ]
        if recorded is not None:
            report += [
                f"--- first-recorded conflicting edge (thread "
                f"{recorded[1]}) ---",
                recorded[0],
            ]
        text = "\n".join(report)
        if locked:
            _STATE.violations.append(text)
        else:
            with _STATE.meta:
                _STATE.violations.append(text)
        _meter("order")
        path = _write_artifact("gol_locksan order violation", text)
        raise LockOrderViolation(
            text + (f"\n(evidence: {path})" if path else "")
        )


class _SanRLock(_SanLock):
    _reentrant = True


def _ensure_watchdog() -> None:
    with _STATE.meta:
        if _STATE.watchdog_thread is not None:
            return
        t = threading.Thread(
            target=_watch_loop, name="gol-locksan-watchdog", daemon=True
        )
        _STATE.watchdog_thread = t
    t.start()


def _watch_loop() -> None:
    while True:
        time.sleep(max(0.02, min(_deadline / 4.0, 0.5)))
        now = time.monotonic()
        with _STATE.meta:
            live = [(ref, ref()) for ref in _STATE.locks]
            dead = [ref for ref, lk in live if lk is None]
            if dead:
                _STATE.locks[:] = [ref for ref, lk in live if lk is not None]
        locks = [lk for _ref, lk in live if lk is not None]
        for lk in locks:
            if (
                lk.holder is not None
                and lk.waiters > 0
                and not lk.reported
                and now - lk.held_since > _deadline
            ):
                lk.reported = True
                with _STATE.meta:
                    _STATE.watchdog_fires += 1
                _meter("watchdog")
                _write_artifact(
                    f"gol_locksan watchdog: '{lk.label}' held "
                    f"{now - lk.held_since:.1f}s (deadline {_deadline}s) "
                    f"with {lk.waiters} waiter(s) queued — all-thread "
                    f"tracebacks follow",
                    _all_thread_tracebacks(),
                )


# -- the factories (the ONLY public wiring surface) ---------------------------


def enabled() -> bool:
    return _active


def lock(label: str):
    """A ``threading.Lock`` — instrumented iff the sanitizer is active.
    ``label`` is the lock's ROLE (``Class._attr``), the identity the
    order graph reasons about."""
    return _SanLock(label) if _active else threading.Lock()


def rlock(label: str):
    return _SanRLock(label) if _active else threading.RLock()


def condition(label: str, lock=None):
    """A ``threading.Condition``. Over an instrumented lock the wait /
    notify bookkeeping comes free — Condition delegates acquire/release
    to the lock object, and ``wait()`` releasing the lock pops the held
    stack exactly like a ``with`` exit. With no lock given the implicit
    lock is an instrumented RLock (matching threading's default)."""
    if not _active:
        return threading.Condition(lock)
    if lock is None:
        lock = _SanRLock(label)
    return threading.Condition(lock)


# -- test / tooling surface ---------------------------------------------------


def install(deadline: Optional[float] = None, out_dir=None) -> None:
    """Arm the sanitizer in-process (tests; entry points use the env).
    Affects locks created AFTER the call — existing plain locks stay
    plain, which is fine for tests that construct their subjects after
    installing."""
    global _active, _deadline, _out_dir
    _active = True
    if deadline is not None:
        _deadline = float(deadline)
    if out_dir is not None:
        _out_dir = str(out_dir)
    reset()


def uninstall() -> None:
    """Revert :func:`install`: back to what the ENVIRONMENT says (so a
    test teardown under an env-armed ``--locksan`` run does not disarm
    the sanitizer for the rest of the process)."""
    global _active, _deadline, _out_dir
    _active = os.environ.get(_ENV, "") not in ("", "0")
    _deadline = float(os.environ.get(_DEADLINE_ENV, "") or 30.0)
    _out_dir = "out"
    reset()


def reset() -> None:
    """Forget recorded edges, violations, and registered locks (the
    watchdog thread, once started, idles over an empty registry)."""
    with _STATE.meta:
        _STATE.edges.clear()
        _STATE.locks.clear()
        _STATE.violations.clear()
        _STATE.watchdog_fires = 0


def violations() -> List[str]:
    with _STATE.meta:
        return list(_STATE.violations)


def watchdog_fires() -> int:
    with _STATE.meta:
        return _STATE.watchdog_fires


def set_out_dir(path) -> None:
    """Artifact directory override (entry points with an ``-out`` notion
    and tests; default ``out/``)."""
    global _out_dir
    _out_dir = str(path)
