"""Name lints: the code's registries and the README's tables must agree.

Two operator-facing name contracts live in this package: metric names
(``obs/instruments.py`` — RunReports, Status payloads, Prometheus scrapes)
and span names (``obs/tracing.py`` — Chrome trace exports, flight-recorder
events). The README "Observability" and "Tracing" sections are their
documentation of record; the device-telemetry families (obs/device.py)
additionally must sit in the dedicated "Device telemetry" table, and the
operator-facing sections themselves ("Device telemetry", "Perf regression
gate", ...) must exist. These lints fail when a name registered in code
is missing from the README — so adding an instrument or a span site
without documenting it breaks the build (``tests/test_obs.py`` and
``tests/test_tracing.py`` run them;
``python -m gol_distributed_final_tpu.obs.lint`` and the ``scripts/check``
wrapper run them standalone, outside pytest).
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent


def undocumented_metrics(readme_path=None, histograms_only: bool = False) -> List[str]:
    """Names registered in code but absent from the README text."""
    from . import instruments  # noqa: F401 - registers every family
    from .metrics import registry

    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    missing = []
    for fam in registry().families():
        if histograms_only and fam.kind != "histogram":
            continue
        if fam.name not in text:
            missing.append(fam.name)
    return sorted(missing)


def undocumented_spans(readme_path=None) -> List[str]:
    """Span names declared in obs/tracing.py but absent from the README."""
    from .tracing import registered_span_names

    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    return sorted(n for n in registered_span_names() if n not in text)


# prefixes of the device-telemetry metric families (obs/device.py): these
# must be documented in the README's dedicated "Device telemetry" table,
# not just anywhere in the file (gol_compile_cache_* predates obs/device
# and lives in the main Observability table)
_DEVICE_METRIC_PREFIXES = (
    "gol_compile_seconds", "gol_kernel_", "gol_device_hbm_",
)

# operator-facing sections the README must keep: the doc anchors the name
# lints point at, and the regression-gate/watch docs this package's CLIs
# reference in their own help text
_REQUIRED_SECTIONS = (
    "## Observability",
    "## Tracing",
    "Device telemetry",
    "Perf regression gate",
    "Fault tolerance",
    "Wire modes",
)

# the wire data-plane metric families (rpc/protocol.py frames + the
# workers-backend wire modes): these must be documented in the README's
# "Wire modes" section specifically — they are the contract the wire-mode
# bench cases embed and scripts/bench_diff gates
_WIRE_METRIC_NAMES = (
    "gol_wire_bytes_total", "gol_turn_batch_size", "gol_strip_resync_total",
)


def undocumented_device_metrics(readme_path=None) -> List[str]:
    """Device-telemetry metric names (obs/device.py's families) missing
    from the README's "Device telemetry" section specifically — a name
    mentioned elsewhere in the file does not count as documented here."""
    from . import instruments  # noqa: F401 - registers every family
    from .metrics import registry

    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    anchor = text.find("Device telemetry")
    if anchor >= 0:
        # bound the section at the next top-level heading: a name that
        # only appears in a LATER section must still be flagged
        end = text.find("\n## ", anchor)
        section = text[anchor:] if end < 0 else text[anchor:end]
    else:
        section = ""
    return sorted(
        fam.name
        for fam in registry().families()
        if fam.name.startswith(_DEVICE_METRIC_PREFIXES)
        and fam.name not in section
    )


def undocumented_wire_metrics(readme_path=None) -> List[str]:
    """Wire data-plane metric names missing from the README's
    "Wire modes" section specifically (the device-table posture: a name
    mentioned elsewhere in the file does not count as documented here)."""
    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    # anchor on the HEADING: cross-references ("see **Wire modes**")
    # elsewhere in the file must not shadow the real section
    anchor = text.find("## Wire modes")
    if anchor >= 0:
        end = text.find("\n## ", anchor)
        section = text[anchor:] if end < 0 else text[anchor:end]
    else:
        section = ""
    return sorted(n for n in _WIRE_METRIC_NAMES if n not in section)


def missing_readme_sections(readme_path=None) -> List[str]:
    """Required operator-facing README sections that are absent."""
    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    return [s for s in _REQUIRED_SECTIONS if s not in text]


def main(argv=None) -> int:
    rc = 0
    missing = undocumented_metrics()
    if missing:
        print(
            "metrics registered in obs/instruments.py but missing from "
            "README.md's Observability table:",
            file=sys.stderr,
        )
        for name in missing:
            print(f"  {name}", file=sys.stderr)
        rc = 1
    else:
        print("metric-name lint ok: every registered metric is documented")
    missing_spans = undocumented_spans()
    if missing_spans:
        print(
            "span names declared in obs/tracing.py but missing from "
            "README.md's Tracing table:",
            file=sys.stderr,
        )
        for name in missing_spans:
            print(f"  {name}", file=sys.stderr)
        rc = 1
    else:
        print("span-name lint ok: every declared span name is documented")
    missing_dev = undocumented_device_metrics()
    if missing_dev:
        print(
            "device metrics registered in obs/instruments.py but missing "
            "from README.md's Device telemetry table:",
            file=sys.stderr,
        )
        for name in missing_dev:
            print(f"  {name}", file=sys.stderr)
        rc = 1
    else:
        print(
            "device-metric lint ok: every device metric is in the Device "
            "telemetry table"
        )
    missing_wire = undocumented_wire_metrics()
    if missing_wire:
        print(
            "wire data-plane metrics missing from README.md's Wire modes "
            "section:",
            file=sys.stderr,
        )
        for name in missing_wire:
            print(f"  {name}", file=sys.stderr)
        rc = 1
    else:
        print(
            "wire-metric lint ok: every wire metric is in the Wire modes "
            "section"
        )
    missing_sections = missing_readme_sections()
    if missing_sections:
        print(
            "required README sections missing:", file=sys.stderr,
        )
        for section in missing_sections:
            print(f"  {section}", file=sys.stderr)
        rc = 1
    else:
        print("section lint ok: every required README section present")
    return rc


if __name__ == "__main__":
    sys.exit(main())
