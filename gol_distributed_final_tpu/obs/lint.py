"""Name lints: the code's registries and the README's tables must agree.

Two operator-facing name contracts live in this package: metric names
(``obs/instruments.py`` — RunReports, Status payloads, Prometheus scrapes)
and span names (``obs/tracing.py`` — Chrome trace exports, flight-recorder
events). The README "Observability" and "Tracing" sections are their
documentation of record; the device-telemetry families (obs/device.py)
additionally must sit in the dedicated "Device telemetry" table, and the
operator-facing sections themselves ("Device telemetry", "Perf regression
gate", ...) must exist. These lints fail when a name registered in code
is missing from the README — so adding an instrument or a span site
without documenting it breaks the build (``tests/test_obs.py`` and
``tests/test_tracing.py`` run them;
``python -m gol_distributed_final_tpu.obs.lint`` and the ``scripts/check``
wrapper run them standalone, outside pytest).
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent


def undocumented_metrics(readme_path=None, histograms_only: bool = False) -> List[str]:
    """Names registered in code but absent from the README text."""
    from . import instruments  # noqa: F401 - registers every family
    from .metrics import registry

    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    missing = []
    for fam in registry().families():
        if histograms_only and fam.kind != "histogram":
            continue
        if fam.name not in text:
            missing.append(fam.name)
    return sorted(missing)


def undocumented_spans(readme_path=None) -> List[str]:
    """Span names declared in obs/tracing.py but absent from the README."""
    from .tracing import registered_span_names

    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    return sorted(n for n in registered_span_names() if n not in text)


# prefixes of the device-telemetry metric families (obs/device.py): these
# must be documented in the README's dedicated "Device telemetry" table,
# not just anywhere in the file (gol_compile_cache_* predates obs/device
# and lives in the main Observability table)
_DEVICE_METRIC_PREFIXES = (
    "gol_compile_seconds", "gol_kernel_", "gol_device_hbm_",
)

# operator-facing sections the README must keep: the doc anchors the name
# lints point at, and the regression-gate/watch docs this package's CLIs
# reference in their own help text
_REQUIRED_SECTIONS = (
    "## Observability",
    "## Tracing",
    "Device telemetry",
    "Perf regression gate",
    "Fault tolerance",
    "Wire modes",
    # the 2-D checkerboard tile plane (-grid, rpc/broker._tile_turn_loop):
    # grid knobs, the corner-halo cost table, the fault/attestation
    # contract, and the halo-depth/sync-interval/sparse-sync interactions
    "## 2-D tiles",
    "Integrity",
    "Sessions",
    "SLOs & alerting",
    "## Doctor",
    # the analysis/ checker suite's operator contract: checker table,
    # suppression syntax, how to add a checker (lint-enforced like the
    # metric tables — analysis/lints.py checks the checker ids are IN it)
    "## Static analysis",
    # the tenant-attribution contract (obs/accounting.py): the session-tag
    # packing convention, the top-K cardinality bound, the Status payload
    # size budget, and the reconciliation guarantees
    "## Accounting & capacity",
    # the blackbox measurement surface (obs/canary.py + obs/loadgen.py):
    # probe verbs, metric tables, loadgen CLI examples
    "## Canary & load harness",
    # the roofline/straggler attribution contract (obs/perf.py +
    # obs/critical.py): metric table, bound-class semantics, CLI
    # examples, and the honest calibration caveats
    "## Performance attribution",
    # the activity-sparse stepping contract (ops/sparse.py + the
    # dirty-tile wire/checkpoint deltas): the activity invariant, the
    # density crossover, the delta-frame format, the early-exit
    # contract, and the knobs
    "## Sparse stepping",
    # the fused K-turns-per-launch contract (ops/fused.py + the engine
    # chunk driver + the worker strip paths): the K/VMEM trade-off
    # table, the routing knobs, and the launch-amortisation metric pair
    "## Fused stepping",
    # the durable lifecycle journal contract (obs/journal.py +
    # obs/history.py): the event-kind table, the HLC semantics, the
    # retention knobs, and the history CLI examples
    "## Journal & history",
    # the continuous-profiler contract (obs/profiler.py + obs/flame.py):
    # the cadence/backoff knobs, overhead budget, artifact formats
    # (collapsed + speedscope), flame diff semantics, and the GC pause
    # meter feeding the gc-pause SLO rule
    "## Profiling",
    # the fleet-collector contract (obs/fleet.py): the collector CLI,
    # scrape/staleness semantics, the fleet rule table, and the
    # gol_fleet_* metric table
    "## Fleet",
)

# the wire data-plane metric families (rpc/protocol.py frames + the
# workers-backend wire modes): these must be documented in the README's
# "Wire modes" section specifically — they are the contract the wire-mode
# bench cases embed and scripts/bench_diff gates
_WIRE_METRIC_NAMES = (
    "gol_wire_bytes_total", "gol_turn_batch_size", "gol_strip_resync_total",
)


def _readme_section(readme_path, anchor: str) -> str:
    """The README text from ``anchor`` to the next top-level heading —
    the section-scoped lint surface: a name that only appears in a LATER
    section must still be flagged. Anchor on the heading itself (e.g.
    ``"## Wire modes"``) when cross-references elsewhere in the file
    could shadow the real section. Missing anchor -> empty section, so
    every required name is reported rather than silently passed."""
    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    start = text.find(anchor)
    if start < 0:
        return ""
    end = text.find("\n## ", start)
    return text[start:] if end < 0 else text[start:end]


def undocumented_device_metrics(readme_path=None) -> List[str]:
    """Device-telemetry metric names (obs/device.py's families) missing
    from the README's "Device telemetry" section specifically — a name
    mentioned elsewhere in the file does not count as documented here."""
    from . import instruments  # noqa: F401 - registers every family
    from .metrics import registry

    section = _readme_section(readme_path, "Device telemetry")
    return sorted(
        fam.name
        for fam in registry().families()
        if fam.name.startswith(_DEVICE_METRIC_PREFIXES)
        and fam.name not in section
    )


# the integrity metric families (rpc/integrity.py: checked frames,
# resident-strip attestation, verified checkpoints): these must be
# documented in the README's "Integrity" section specifically — the
# operator contract for the silent-corruption detection surface
_INTEGRITY_METRIC_NAMES = (
    "gol_integrity_checks_total",
    "gol_integrity_failures_total",
    "gol_ckpt_verify_total",
)


def undocumented_integrity_metrics(readme_path=None) -> List[str]:
    """Integrity metric names missing from the README's "Integrity"
    section specifically (the wire/device-table posture: a name mentioned
    elsewhere in the file does not count as documented here)."""
    section = _readme_section(readme_path, "## Integrity")
    return sorted(n for n in _INTEGRITY_METRIC_NAMES if n not in section)


# the multi-universe serving metric families (engine/sessions.py +
# rpc/broker.SessionScheduler): these must be documented in the README's
# "Sessions" section specifically — the operator contract for the
# batched serving surface (admission control, capacity refusals)
_SESSION_METRIC_NAMES = (
    "gol_sessions_active",
    "gol_sessions_admitted_total",
    "gol_sessions_rejected_total",
    "gol_session_turns_total",
)


def undocumented_session_metrics(readme_path=None) -> List[str]:
    """Session metric names missing from the README's "Sessions" section
    specifically (the wire/device-table posture: a name mentioned
    elsewhere in the file does not count as documented here)."""
    section = _readme_section(readme_path, "## Sessions")
    return sorted(n for n in _SESSION_METRIC_NAMES if n not in section)


def undocumented_wire_metrics(readme_path=None) -> List[str]:
    """Wire data-plane metric names missing from the README's
    "Wire modes" section specifically (the device-table posture: a name
    mentioned elsewhere in the file does not count as documented here)."""
    section = _readme_section(readme_path, "## Wire modes")
    return sorted(n for n in _WIRE_METRIC_NAMES if n not in section)


# the 2-D tile data plane's operator names (rpc/broker.py -grid + the
# tile-resident wire): the per-axis halo counter, the layout gauges, and
# the -grid knob itself must be documented in the README's "2-D tiles"
# section specifically — the contract the tile bench pair embeds and the
# regress halo-byte gate enforces
_TILE_DOC_NAMES = (
    "gol_halo_bytes_total",
    "gol_tile_edge_cells",
    "gol_tile_grid_rows",
    "gol_tile_grid_cols",
    "-grid",
)


def undocumented_tile_names(readme_path=None) -> List[str]:
    """Tile data-plane names (metrics + the -grid knob) missing from the
    README's "2-D tiles" section specifically (the wire/device-table
    posture: a name mentioned elsewhere in the file does not count as
    documented here)."""
    section = _readme_section(readme_path, "## 2-D tiles")
    return sorted(n for n in _TILE_DOC_NAMES if n not in section)


# the serving-SLO metric families (obs/timeline.py sampler + obs/slo.py
# rules + their instrument feeds): these must be documented in the
# README's "SLOs & alerting" section specifically — the operator
# contract for the alerting surface
_SLO_METRIC_NAMES = (
    "gol_session_turn_seconds",
    "gol_session_admit_wait_seconds",
    "gol_rpc_dispatch_seconds",
    "gol_scatter_deadline_seconds",
    "gol_slo_alerts_total",
)


def undocumented_slo_metrics(readme_path=None) -> List[str]:
    """SLO metric names missing from the README's "SLOs & alerting"
    section specifically (the wire/device-table posture: a name
    mentioned elsewhere in the file does not count as documented
    here)."""
    section = _readme_section(readme_path, "## SLOs & alerting")
    return sorted(n for n in _SLO_METRIC_NAMES if n not in section)


def undocumented_slo_rules(readme_path=None) -> List[str]:
    """Default SLO rule names (obs/slo.DEFAULT_RULE_NAMES — the stable
    alert-identity contract, the ``gol_slo_alerts_total{rule}`` label
    set) missing from the README's "SLOs & alerting" section."""
    from .slo import DEFAULT_RULE_NAMES

    section = _readme_section(readme_path, "## SLOs & alerting")
    return sorted(n for n in DEFAULT_RULE_NAMES if n not in section)


# the blackbox measurement metric families (obs/canary.py prober +
# obs/loadgen.py generator): these must be documented in the README's
# "Canary & load harness" section specifically — the operator contract
# for the end-to-end correctness probe and the arrival-process harness
_CANARY_METRIC_NAMES = (
    "gol_canary_probes_total",
    "gol_canary_latency_seconds",
    "gol_loadgen_admit_to_first_turn_seconds",
    "gol_loadgen_session_seconds",
    "gol_loadgen_sessions_total",
)


def undocumented_canary_metrics(readme_path=None) -> List[str]:
    """Canary/loadgen metric names missing from the README's "Canary &
    load harness" section specifically (the wire/device-table posture:
    a name mentioned elsewhere does not count as documented here)."""
    section = _readme_section(readme_path, "## Canary & load harness")
    return sorted(n for n in _CANARY_METRIC_NAMES if n not in section)


# the accounting section's contract names: the ledger attributes the
# session meters per tenant, so its section of record must name the
# meters it reconciles against (and the wire field polls echo)
_ACCOUNTING_DOC_NAMES = (
    "gol_sessions_rejected_total",
    "gol_session_turns_total",
    "gol_session_turn_seconds",
    "accounting_since",
)


def undocumented_accounting_names(readme_path=None) -> List[str]:
    """Reconciliation-contract names missing from the README's
    "Accounting & capacity" section specifically."""
    section = _readme_section(readme_path, "## Accounting & capacity")
    return sorted(n for n in _ACCOUNTING_DOC_NAMES if n not in section)


# the performance-attribution metric families (obs/perf.py roofline,
# obs/critical.py straggler, the dispatch-wall decomposition) plus the
# classifier's stable class vocabulary: these must be documented in the
# README's "Performance attribution" section specifically — the contract
# the next perf PR's admission gate reads
_PERF_METRIC_NAMES = (
    "gol_kernel_dispatch_seconds",
    "gol_kernel_achieved_flops",
    "gol_kernel_achieved_bytes_per_s",
    "gol_kernel_bound",
    "gol_turn_segment_seconds",
    "gol_strip_step_seconds",
    "gol_worker_skew_ratio",
    "compute-bound",
    "memory-bound",
    "launch-bound",
)


def undocumented_perf_names(readme_path=None) -> List[str]:
    """Performance-attribution metric/class names missing from the
    README's "Performance attribution" section specifically (the
    wire/device-table posture: a name mentioned elsewhere in the file
    does not count as documented here)."""
    section = _readme_section(readme_path, "## Performance attribution")
    return sorted(n for n in _PERF_METRIC_NAMES if n not in section)


# the activity-sparse metric families (ops/sparse.py, the rpc/ dirty-tile
# deltas, the engine/sessions early exits) plus the contract vocabulary:
# these must be documented in the README's "Sparse stepping" section
# specifically — the operator contract for the frontier/skip/delta/exit
# surface the SPARSITY watch panel renders and bench_diff gates
_SPARSE_DOC_NAMES = (
    "gol_active_tiles",
    "gol_tile_skips_total",
    "gol_sparse_frame_bytes_total",
    "gol_early_exit_total",
    "GOL_SPARSE",
    "-sparse-sync",
)


def undocumented_sparse_names(readme_path=None) -> List[str]:
    """Sparse-stepping metric/knob names missing from the README's
    "Sparse stepping" section specifically (the wire/device-table
    posture: a name mentioned elsewhere in the file does not count as
    documented here)."""
    section = _readme_section(readme_path, "## Sparse stepping")
    return sorted(n for n in _SPARSE_DOC_NAMES if n not in section)


# the fused-stepping contract names (ops/fused.py, the engine's counted
# chunk driver, the worker's skip/fused strip paths): the launch-
# amortisation metric pair, the row-skip meter, and the routing knobs —
# these must be documented in the README's "Fused stepping" section
# specifically, the operator contract bench's fused-vs-serial pair and
# the roofline's fused sites are read against
_FUSED_DOC_NAMES = (
    "gol_fused_launches_total",
    "gol_fused_turns_per_launch",
    "gol_strip_rows_skipped_total",
    "GOL_FUSED",
    "GOL_WORKER_FUSED",
    "-halo-depth",
)


def undocumented_fused_names(readme_path=None) -> List[str]:
    """Fused-stepping metric/knob names missing from the README's
    "Fused stepping" section specifically (the wire/device-table
    posture: a name mentioned elsewhere in the file does not count as
    documented here)."""
    section = _readme_section(readme_path, "## Fused stepping")
    return sorted(n for n in _FUSED_DOC_NAMES if n not in section)


# the durable-journal contract names (obs/journal.py writer +
# obs/history.py merge CLI): the journal meters, the enablement/retention
# knobs, and the incremental Status window field — these must be
# documented in the README's "Journal & history" section specifically,
# the operator contract postmortem reconstruction is read against
_JOURNAL_DOC_NAMES = (
    "gol_journal_events_total",
    "gol_journal_bytes_total",
    "gol_journal_rotations_total",
    "gol_journal_drops_total",
    "-journal",
    "journal_since",
)


def undocumented_journal_names(readme_path=None) -> List[str]:
    """Journal metric/knob names missing from the README's "Journal &
    history" section specifically (the wire/device-table posture: a name
    mentioned elsewhere in the file does not count as documented
    here)."""
    section = _readme_section(readme_path, "## Journal & history")
    return sorted(n for n in _JOURNAL_DOC_NAMES if n not in section)


# the continuous-profiler contract names (obs/profiler.py sampler +
# obs/flame.py render/diff CLI): the sampler meters, the GC pause
# surface, the enablement knob, and the incremental Status window field
# — these must be documented in the README's "Profiling" section
# specifically, the operator contract flame graphs and the doctor's
# hotspot finding are read against
_PROFILER_DOC_NAMES = (
    "gol_profile_samples_total",
    "gol_profile_backoffs_total",
    "gol_gc_pause_seconds",
    "gol_gc_collections_total",
    "-profile",
    "profile_since",
)


def undocumented_profiler_names(readme_path=None) -> List[str]:
    """Profiler metric/knob names missing from the README's "Profiling"
    section specifically (the wire/device-table posture: a name
    mentioned elsewhere in the file does not count as documented
    here)."""
    section = _readme_section(readme_path, "## Profiling")
    return sorted(n for n in _PROFILER_DOC_NAMES if n not in section)


# the fleet-collector contract names (obs/fleet.py): the gol_fleet_*
# metric families, the fleet SLO rule identities (obs/slo.py
# FLEET_RULE_NAMES), and the collector's CLI/staleness knobs — these
# must be documented in the README's "Fleet" section specifically, the
# operator contract the collector's scrape/merge semantics are read
# against
_FLEET_DOC_NAMES = (
    "gol_fleet_scrapes_total",
    "gol_fleet_targets_total",
    "gol_fleet_targets_down",
    "gol_fleet_scrape_seconds",
    "gol_fleet_merge_failures_total",
    "gol_fleet_sessions_active",
    "gol_fleet_capacity_total",
    "gol_fleet_tenant_skew",
    "target-down",
    "fleet-capacity-headroom",
    "fleet-tenant-skew",
    "-interval",
    "-port",
)


def undocumented_fleet_names(readme_path=None) -> List[str]:
    """Fleet metric/rule/knob names missing from the README's "Fleet"
    section specifically (the wire/device-table posture: a name
    mentioned elsewhere in the file does not count as documented
    here)."""
    section = _readme_section(readme_path, "## Fleet")
    return sorted(n for n in _FLEET_DOC_NAMES if n not in section)


def undeclared_journal_kinds(readme_path=None, package_root=None) -> List[str]:
    """Registry drift between the journal's event-kind table and its
    emit sites: every literal kind passed to ``journal.record(...)``
    anywhere in the package must exist in ``obs/journal.EVENT_KINDS``
    (and every event kind the README table documents comes FROM that
    dict, so an undeclared emit is also an undocumented one). Scans
    source text, not imports — an emit site behind an optional dep
    still counts. ``readme_path`` is accepted (and ignored) so the
    analysis wrapper can call every CHECKS entry uniformly."""
    import re

    from .journal import EVENT_KINDS

    if package_root is None:
        package_root = pathlib.Path(__file__).resolve().parent.parent
    pat = re.compile(r"""\b_?journal\.record\(\s*["']([a-z._]+)["']""")
    missing = set()
    for path in sorted(pathlib.Path(package_root).rglob("*.py")):
        try:
            text = path.read_text()
        except OSError:
            continue
        for kind in pat.findall(text):
            if kind not in EVENT_KINDS:
                missing.add(f"{kind} (emitted in {path.name})")
    return sorted(missing)


def missing_readme_sections(readme_path=None) -> List[str]:
    """Required operator-facing README sections that are absent."""
    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    return [s for s in _REQUIRED_SECTIONS if s not in text]


# the lint suite, named: ``(check id, function, fail message, ok message)``.
# The ids are the analysis-framework handles — gol_distributed_final_tpu/
# analysis/lints.py re-seats every entry as a repo-level checker under the
# same runner/finding/suppression contract as the AST checkers, so this
# table is the single registry both surfaces share (``scripts/check``
# default + ``--lint`` alias, one behavior).
CHECKS = (
    (
        "lint-metrics",
        undocumented_metrics,
        "metrics registered in obs/instruments.py but missing from "
        "README.md's Observability table:",
        "metric-name lint ok: every registered metric is documented",
    ),
    (
        "lint-spans",
        undocumented_spans,
        "span names declared in obs/tracing.py but missing from "
        "README.md's Tracing table:",
        "span-name lint ok: every declared span name is documented",
    ),
    (
        "lint-device-metrics",
        undocumented_device_metrics,
        "device metrics registered in obs/instruments.py but missing "
        "from README.md's Device telemetry table:",
        "device-metric lint ok: every device metric is in the Device "
        "telemetry table",
    ),
    (
        "lint-wire-metrics",
        undocumented_wire_metrics,
        "wire data-plane metrics missing from README.md's Wire modes "
        "section:",
        "wire-metric lint ok: every wire metric is in the Wire modes "
        "section",
    ),
    (
        "lint-tile-names",
        undocumented_tile_names,
        "tile data-plane names (metrics / the -grid knob) missing from "
        "README.md's 2-D tiles section:",
        "tile-name lint ok: every tile data-plane name is in the "
        "2-D tiles section",
    ),
    (
        "lint-integrity-metrics",
        undocumented_integrity_metrics,
        "integrity metrics missing from README.md's Integrity "
        "section:",
        "integrity-metric lint ok: every integrity metric is in the "
        "Integrity section",
    ),
    (
        "lint-session-metrics",
        undocumented_session_metrics,
        "session metrics missing from README.md's Sessions section:",
        "session-metric lint ok: every session metric is in the "
        "Sessions section",
    ),
    (
        "lint-slo-metrics",
        undocumented_slo_metrics,
        "SLO metrics missing from README.md's SLOs & alerting "
        "section:",
        "slo-metric lint ok: every SLO metric is in the SLOs & "
        "alerting section",
    ),
    (
        "lint-slo-rules",
        undocumented_slo_rules,
        "default SLO rule names missing from README.md's SLOs & "
        "alerting section:",
        "slo-rule lint ok: every default rule name is in the SLOs & "
        "alerting section",
    ),
    (
        "lint-canary-metrics",
        undocumented_canary_metrics,
        "canary/loadgen metrics missing from README.md's Canary & load "
        "harness section:",
        "canary-metric lint ok: every canary/loadgen metric is in the "
        "Canary & load harness section",
    ),
    (
        "lint-accounting-docs",
        undocumented_accounting_names,
        "accounting-contract names missing from README.md's Accounting "
        "& capacity section:",
        "accounting lint ok: the reconciliation contract is documented "
        "in the Accounting & capacity section",
    ),
    (
        "lint-perf-metrics",
        undocumented_perf_names,
        "performance-attribution metric/class names missing from "
        "README.md's Performance attribution section:",
        "perf lint ok: every attribution metric and bound class is in "
        "the Performance attribution section",
    ),
    (
        "lint-sparse-metrics",
        undocumented_sparse_names,
        "sparse-stepping metric/knob names missing from README.md's "
        "Sparse stepping section:",
        "sparse lint ok: every sparse metric and knob is in the Sparse "
        "stepping section",
    ),
    (
        "lint-fused-metrics",
        undocumented_fused_names,
        "fused-stepping metric/knob names missing from README.md's "
        "Fused stepping section:",
        "fused lint ok: every fused metric and knob is in the Fused "
        "stepping section",
    ),
    (
        "lint-journal-metrics",
        undocumented_journal_names,
        "journal metric/knob names missing from README.md's Journal & "
        "history section:",
        "journal lint ok: every journal metric and knob is in the "
        "Journal & history section",
    ),
    (
        "lint-profiler-metrics",
        undocumented_profiler_names,
        "profiler metric/knob names missing from README.md's Profiling "
        "section:",
        "profiler lint ok: every profiler metric and knob is in the "
        "Profiling section",
    ),
    (
        "lint-fleet-metrics",
        undocumented_fleet_names,
        "fleet metric/rule/knob names missing from README.md's Fleet "
        "section:",
        "fleet lint ok: every fleet metric, rule, and knob is in the "
        "Fleet section",
    ),
    (
        "lint-journal-kinds",
        undeclared_journal_kinds,
        "event kinds emitted via journal.record() but missing from "
        "obs/journal.EVENT_KINDS (declare them there AND in the README "
        "table):",
        "journal-kind lint ok: every emitted event kind is declared in "
        "EVENT_KINDS",
    ),
    (
        "lint-sections",
        missing_readme_sections,
        "required README sections missing:",
        "section lint ok: every required README section present",
    ),
)


def main(argv=None) -> int:
    rc = 0
    for _check_id, check, fail_msg, ok_msg in CHECKS:
        missing = check()
        if missing:
            print(fail_msg, file=sys.stderr)
            for name in missing:
                print(f"  {name}", file=sys.stderr)
            rc = 1
        else:
            print(ok_msg)
    return rc


if __name__ == "__main__":
    sys.exit(main())
