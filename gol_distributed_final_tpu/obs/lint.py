"""Name lints: the code's registries and the README's tables must agree.

Two operator-facing name contracts live in this package: metric names
(``obs/instruments.py`` — RunReports, Status payloads, Prometheus scrapes)
and span names (``obs/tracing.py`` — Chrome trace exports, flight-recorder
events). The README "Observability" and "Tracing" sections are their
documentation of record. These lints fail when a name registered in code
is missing from the README — so adding an instrument or a span site
without documenting it breaks the build (``tests/test_obs.py`` and
``tests/test_tracing.py`` run them;
``python -m gol_distributed_final_tpu.obs.lint`` and the ``scripts/check``
wrapper run them standalone, outside pytest).
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent


def undocumented_metrics(readme_path=None, histograms_only: bool = False) -> List[str]:
    """Names registered in code but absent from the README text."""
    from . import instruments  # noqa: F401 - registers every family
    from .metrics import registry

    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    missing = []
    for fam in registry().families():
        if histograms_only and fam.kind != "histogram":
            continue
        if fam.name not in text:
            missing.append(fam.name)
    return sorted(missing)


def undocumented_spans(readme_path=None) -> List[str]:
    """Span names declared in obs/tracing.py but absent from the README."""
    from .tracing import registered_span_names

    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    return sorted(n for n in registered_span_names() if n not in text)


def main(argv=None) -> int:
    rc = 0
    missing = undocumented_metrics()
    if missing:
        print(
            "metrics registered in obs/instruments.py but missing from "
            "README.md's Observability table:",
            file=sys.stderr,
        )
        for name in missing:
            print(f"  {name}", file=sys.stderr)
        rc = 1
    else:
        print("metric-name lint ok: every registered metric is documented")
    missing_spans = undocumented_spans()
    if missing_spans:
        print(
            "span names declared in obs/tracing.py but missing from "
            "README.md's Tracing table:",
            file=sys.stderr,
        )
        for name in missing_spans:
            print(f"  {name}", file=sys.stderr)
        rc = 1
    else:
        print("span-name lint ok: every declared span name is documented")
    return rc


if __name__ == "__main__":
    sys.exit(main())
