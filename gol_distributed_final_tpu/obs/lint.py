"""Metric-name lint: the code's registry and the README's table must agree.

The metric names in ``obs/instruments.py`` are a stable operator contract
(they appear in RunReports, Status payloads, and Prometheus scrapes), and
the README "Observability" section is their documentation of record. This
lint fails when a name registered in code is missing from the README — so
adding an instrument without documenting it breaks the build
(``tests/test_obs.py`` runs it; ``python -m gol_distributed_final_tpu.obs.lint``
runs it standalone).
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent


def undocumented_metrics(readme_path=None, histograms_only: bool = False) -> List[str]:
    """Names registered in code but absent from the README text."""
    from . import instruments  # noqa: F401 - registers every family
    from .metrics import registry

    if readme_path is None:
        readme_path = REPO_ROOT / "README.md"
    text = pathlib.Path(readme_path).read_text()
    missing = []
    for fam in registry().families():
        if histograms_only and fam.kind != "histogram":
            continue
        if fam.name not in text:
            missing.append(fam.name)
    return sorted(missing)


def main(argv=None) -> int:
    missing = undocumented_metrics()
    if missing:
        print(
            "metrics registered in obs/instruments.py but missing from "
            "README.md's Observability table:",
            file=sys.stderr,
        )
        for name in missing:
            print(f"  {name}", file=sys.stderr)
        return 1
    print("metric-name lint ok: every registered metric is documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
