"""Performance attribution: the roofline classifier + dispatch-wall
decomposition summary.

    python -m gol_distributed_final_tpu.obs.perf :8040          # live poll
    python -m gol_distributed_final_tpu.obs.perf BENCH_r04.json # bench round
    python -m gol_distributed_final_tpu.obs.perf --selfcheck    # CI smoke

Every unclaimed ROADMAP compute lever (fused-K kernel, 2-D sharding,
sparsity) is justified by a claim like "the 128² case is latency-bound"
that until now lived only as a prose note beside BENCH_r04. This module
turns that claim into a measurement: it joins the per-site XLA cost
analysis PR 3 already captures (``gol_kernel_flops{site}`` /
``gol_kernel_bytes_accessed{site}``) with the measured dispatch wall
(``gol_kernel_dispatch_seconds{site}``, accumulated exactly per executed
call in obs/device.py) to compute achieved FLOP/s and bytes/s per kernel
site, and classifies each site against calibrated device ceilings:

* ``compute-bound``   — FLOP utilization dominates and is substantial;
* ``memory-bound``    — bytes/s utilization dominates and is substantial;
* ``launch-bound``    — the site achieves a small fraction of BOTH
  ceilings: neither the ALUs nor the memory system is the limit, so the
  wall is launch/issue latency — the class the fused-K kernel exists to
  kill, and the class admission for that PR is gated on.

Ceilings are calibrated ONCE per device kind and cached: TPU kinds map
to a table of known (approximate, vector-unit) peaks; anything else gets
a fitted CPU ceiling from a one-shot numpy microbench (GEMM for FLOP/s,
a large copy for bytes/s). The calibration caveats are documented in the
README "Performance attribution" section — the classes are coarse by
design (an order-of-magnitude utilization call), not a profiler.

``decomposition_summary`` renders the dispatch-wall decomposition
(``gol_turn_segment_seconds{component,segment}`` — engine/sessions/
broker walls split into host_prep / device_compute / wire / demux) from
any registry snapshot: the RunReport embeds it and the watch dashboard's
WHERE-TIME-GOES panel renders it.

``set_attribution(False)`` disables the whole hot-loop attribution layer
(segment observes, per-worker call walls, the critical-path tracker) —
the A/B lever the bench's ≤2 % decomposition-overhead gate prices.
"""

from __future__ import annotations

import argparse
import json
import logging
import re
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from . import instruments as _ins
from . import metrics as _metrics

logger = logging.getLogger(__name__)

#: the stable class vocabulary (the ``gol_kernel_bound{class}`` label set)
BOUND_CLASSES = ("compute-bound", "memory-bound", "launch-bound")

#: a site achieving less than this fraction of BOTH ceilings is limited by
#: neither compute nor memory — launch/issue latency is the residual
LAUNCH_UTILIZATION = 0.10

#: analytic cost model for bench cases (per cell per turn) — used when a
#: BENCH round carries only the per-turn fit (salvaged tails have no
#: stage_timings). The packed bitboard kernel does ~44 word ops per 32
#: cells per turn (~1.4 ops/cell) and touches each packed word twice
#: (read + write: 2/8 byte per cell). Documented caveats in the README.
MODEL_FLOPS_PER_CELL = 1.4
MODEL_BYTES_PER_CELL = 0.25

#: approximate VECTOR-unit peaks per TPU device kind: (flop/s, bytes/s).
#: These are deliberately coarse published-order-of-magnitude numbers for
#: the non-MXU ops a bitboard stencil issues — good enough to separate
#: "saturating a ceiling" from "two orders below every ceiling", which is
#: all the classifier claims. Matched by substring on device_kind.
KNOWN_TPU_PEAKS = (
    ("v6e", 4.0e13, 1.6e12),
    ("trillium", 4.0e13, 1.6e12),
    ("v5p", 2.3e13, 2.7e12),
    ("v5e", 2.0e13, 8.1e11),
    ("v5lite", 2.0e13, 8.1e11),
    ("v4", 1.5e13, 1.2e12),
    ("v3", 1.0e13, 9.0e11),
    ("v2", 6.0e12, 7.0e11),
    ("tpu", 1.5e13, 8.0e11),  # unrecognised TPU kind: a conservative floor
)


@dataclass
class Ceilings:
    """One device kind's calibrated roofline ceilings."""

    device_kind: str
    flops_per_s: float
    bytes_per_s: float
    launch_seconds: float  # per-dispatch floor (reported, not classifying)
    source: str  # "known" (TPU table) | "fitted" (numpy microbench)


# one-time-per-device-kind calibration cache (the ISSUE's contract: the
# microbench runs on first use per kind, never per classification)
_CEILINGS_CACHE: Dict[str, Ceilings] = {}
_CEILINGS_LOCK = threading.Lock()
# microbench invocation count — the test hook pinning the cache contract
_FIT_RUNS = 0

# hot-loop attribution switch (segments + per-call walls + the critical-
# path tracker): the bench's decomposition-overhead gate A/Bs it
_ATTRIBUTION = True

# refresh-failure tally: paces the warning log so a per-poll bug does not
# flood stderr while still leaving UNCONDITIONAL evidence (the PR 9
# rulebook-evaluation posture — a silently dead roofline layer is the
# failure mode this exists to prevent)
_REFRESH_ERRORS = 0


def set_attribution(on: bool) -> None:
    global _ATTRIBUTION
    _ATTRIBUTION = bool(on)


def attribution_enabled() -> bool:
    """One module-global read — the hot-loop guard every decomposition
    site checks alongside ``metrics.enabled()``."""
    return _ATTRIBUTION


def _fit_cpu_ceilings() -> tuple:
    """One-shot numpy microbench: attainable FLOP/s from a small GEMM
    (the classic peak proxy) and bytes/s from a large array copy. Both
    min-over-reps so a scheduler hiccup inflates nothing."""
    global _FIT_RUNS
    _FIT_RUNS += 1
    import numpy as np

    n = 384
    a = np.random.default_rng(0).random((n, n), dtype=np.float32)
    b = np.random.default_rng(1).random((n, n), dtype=np.float32)
    a @ b  # warm
    t_gemm = None
    for _ in range(3):
        t0 = time.perf_counter()
        a @ b
        dt = time.perf_counter() - t0
        t_gemm = dt if t_gemm is None else min(t_gemm, dt)
    flops = 2.0 * n * n * n / max(t_gemm, 1e-9)

    src = np.zeros(32 << 20, dtype=np.uint8)  # 32 MiB
    np.copy(src)  # warm
    t_copy = None
    for _ in range(3):
        t0 = time.perf_counter()
        np.copy(src)
        dt = time.perf_counter() - t0
        t_copy = dt if t_copy is None else min(t_copy, dt)
    bytes_per_s = 2.0 * src.nbytes / max(t_copy, 1e-9)
    return flops, bytes_per_s


def _measure_launch_floor() -> float:
    """Median wall of a tiny synchronous jitted dispatch — the per-launch
    floor the launch-bound class names. 0.0 when jax is unavailable or
    was never imported (a jax-free process has no launches to floor)."""
    if "jax" not in sys.modules:
        return 0.0
    try:
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x + 1)
        x = jnp.zeros((8, 8), jnp.int32)
        f(x).block_until_ready()  # compile outside the timing
        walls = []
        for _ in range(7):
            t0 = time.perf_counter()
            f(x).block_until_ready()
            walls.append(time.perf_counter() - t0)
        walls.sort()
        return walls[len(walls) // 2]
    # the launch floor is reported decoration, not a classifying input —
    # a backend that cannot measure it returns the 0 sentinel (a handler
    # that returns is already hygiene-clean; no allow needed)
    except Exception:
        return 0.0


def _local_device_kind() -> str:
    """The local accelerator's kind string, without forcing a jax import
    (a jax-free process classifies nothing locally anyway)."""
    if "jax" not in sys.modules:
        return "cpu"
    try:
        import jax

        dev = jax.devices()[0]
        return str(getattr(dev, "device_kind", "") or dev.platform).lower()
    # an unqueryable backend degrades to the fitted CPU ceilings —
    # calibration must never raise out of a Status poll (the return is
    # the handling; hygiene accepts it without an allow)
    except Exception:
        return "cpu"


def calibrate(device_kind: Optional[str] = None) -> Ceilings:
    """The ceilings for one device kind, calibrated once and cached.

    TPU kinds resolve from ``KNOWN_TPU_PEAKS`` (source="known"); anything
    else pays the one-shot numpy microbench (source="fitted"). The cache
    key is the NORMALISED kind string, so every later call — Status
    polls, bench cases, CLI renders — is a dict hit."""
    kind = (device_kind or _local_device_kind()).lower()
    with _CEILINGS_LOCK:
        hit = _CEILINGS_CACHE.get(kind)
    if hit is not None:
        return hit
    peaks = None
    for needle, fl, by in KNOWN_TPU_PEAKS:
        if needle in kind:
            peaks = (fl, by, "known")
            break
    if peaks is None:
        fl, by = _fit_cpu_ceilings()
        peaks = (fl, by, "fitted")
    ceil = Ceilings(
        device_kind=kind,
        flops_per_s=peaks[0],
        bytes_per_s=peaks[1],
        launch_seconds=_measure_launch_floor(),
        source=peaks[2],
    )
    with _CEILINGS_LOCK:
        # first writer wins: a racing second calibration of the same kind
        # must not replace the object callers already hold
        return _CEILINGS_CACHE.setdefault(kind, ceil)


def _ceilings_if_ready() -> Optional[Ceilings]:
    """The local device's ceilings WITHOUT paying calibration inline:
    a cache hit returns immediately; a miss kicks ONE background daemon
    calibration and returns None. This is the Status-poll path — the
    poll that exists to debug a busy broker must not block on a GEMM
    microbench or queue launch-floor dispatches behind the workload
    (classes appear from the next poll on, typically <1 s later)."""
    kind = _local_device_kind()
    with _CEILINGS_LOCK:
        hit = _CEILINGS_CACHE.get(kind)
        if hit is not None:
            return hit
        if _CALIBRATING[0]:
            return None
        _CALIBRATING[0] = True

    def _bg():
        try:
            calibrate(kind)
        except Exception as exc:
            logger.warning("background ceiling calibration failed: %s", exc)
        finally:
            _CALIBRATING[0] = False

    threading.Thread(target=_bg, name="gol-perf-calibrate", daemon=True).start()
    return None


# one in-flight background calibration at a time (list: mutated from the
# worker thread without rebinding a module global under the lock)
_CALIBRATING = [False]


def reset_ceilings() -> None:
    """Forget the calibration cache and fit counter (tests)."""
    global _FIT_RUNS
    with _CEILINGS_LOCK:
        _CEILINGS_CACHE.clear()
    _FIT_RUNS = 0


# -- the classifier core ------------------------------------------------------


def classify(
    achieved_flops: float, achieved_bytes_per_s: float, ceilings: Ceilings
) -> dict:
    """One site/case's roofline verdict from its achieved throughputs.

    A site far below BOTH ceilings (< ``LAUNCH_UTILIZATION`` of each) is
    ``launch-bound`` — neither the ALUs nor the memory system explains
    its wall, so launch/issue latency does. Otherwise the larger
    utilization names the binding ceiling. A zero-flops degenerate site
    (cost analysis reported nothing) can still be memory-bound via its
    bytes; all-zero sites are launch-bound by definition."""
    u_c = achieved_flops / ceilings.flops_per_s if ceilings.flops_per_s else 0.0
    u_m = (
        achieved_bytes_per_s / ceilings.bytes_per_s
        if ceilings.bytes_per_s
        else 0.0
    )
    if max(u_c, u_m) < LAUNCH_UTILIZATION:
        bound = "launch-bound"
    elif u_c >= u_m:
        bound = "compute-bound"
    else:
        bound = "memory-bound"
    return {
        "achieved_flops": achieved_flops,
        "achieved_bytes_per_s": achieved_bytes_per_s,
        "flops_utilization": u_c,
        "memory_utilization": u_m,
        "bound_class": bound,
    }


def classify_case(
    height: int, width: int, per_turn_s: float, ceilings: Ceilings
) -> dict:
    """A bench kernel case's roofline fields from its geometry and
    per-turn fit, via the analytic stencil cost model (the path salvaged
    BENCH rounds — no stage_timings — still support). Returns the fields
    bench.py embeds per case: achieved_flops / achieved_bytes_per_s /
    bound_class (+ utilizations)."""
    cells = float(height) * float(width)
    if per_turn_s <= 0:
        return classify(0.0, 0.0, ceilings)
    out = classify(
        cells * MODEL_FLOPS_PER_CELL / per_turn_s,
        cells * MODEL_BYTES_PER_CELL / per_turn_s,
        ceilings,
    )
    out["cost_model"] = (
        f"{MODEL_FLOPS_PER_CELL} flops + {MODEL_BYTES_PER_CELL} B "
        "per cell-turn (packed bitboard model)"
    )
    return out


# -- live-site classification (the obs/device.py accumulators) ---------------


def refresh_metrics(ceilings: Optional[Ceilings] = None) -> List[dict]:
    """Classify every instrumented kernel site from the exact dispatch
    accumulators (obs/device.dispatch_stats) and publish the results on
    the ``gol_kernel_achieved_flops`` / ``_achieved_bytes_per_s`` /
    ``gol_kernel_bound`` gauges. Called from Status polls and report
    writes; a process with no dispatch stats (a jax-free worker) returns
    immediately. Never raises — attribution must only observe."""
    from . import device as _device

    try:
        stats = _device.dispatch_stats()
        if not stats or not _metrics.enabled():
            return []
        if ceilings is None:
            # never calibrate INLINE on this path (Status polls ride it):
            # a miss kicks a background calibration and this poll
            # publishes achieved gauges only — classes follow next poll
            ceilings = _ceilings_if_ready()
        rows = []
        for site, s in sorted(stats.items()):
            wall = s["wall_s"]
            if wall <= 0 or not s["calls"]:
                continue
            af = s["flops"] / wall
            ab = s["bytes_accessed"] / wall
            _ins.KERNEL_ACHIEVED_FLOPS.labels(site).set(af)
            _ins.KERNEL_ACHIEVED_BYTES.labels(site).set(ab)
            if ceilings is None:
                continue
            row = classify(af, ab, ceilings)
            row.update(
                site=site,
                calls=s["calls"],
                wall_s=wall,
                mean_dispatch_s=wall / s["calls"],
            )
            for cls in BOUND_CLASSES:
                _ins.KERNEL_BOUND.labels(site, cls).set(
                    1.0 if cls == row["bound_class"] else 0.0
                )
            rows.append(row)
        return rows
    except Exception as exc:
        # refresh rides Status polls and report writes — a calibration/
        # attribution bug must degrade to "no roofline rows", never break
        # the poll that exists to debug it. But it must leave evidence:
        # paced (first + every 60th) so a broken roofline layer is
        # visible instead of silently never classifying again.
        global _REFRESH_ERRORS
        _REFRESH_ERRORS += 1
        if _REFRESH_ERRORS == 1 or _REFRESH_ERRORS % 60 == 0:
            logger.warning(
                "roofline refresh failed (%d time(s)): %s",
                _REFRESH_ERRORS, exc,
            )
        return []


# -- dispatch-wall decomposition summary --------------------------------------

SEGMENTS = ("host_prep", "device_compute", "wire", "demux")


def decomposition_summary(snap: Optional[dict] = None) -> dict:
    """WHERE-TIME-GOES from a registry snapshot: per component, each
    segment's total wall, observation count, and share of the
    component's decomposed wall — the RunReport's embedded breakdown and
    the watch panel's feed. Empty dict when nothing was decomposed."""
    if snap is None:
        snap = _metrics.registry().snapshot()
    per: Dict[str, Dict[str, dict]] = {}
    for fam in snap.get("families", []):
        if fam.get("name") != "gol_turn_segment_seconds":
            continue
        for s in fam.get("series", []):
            labels = s.get("labels") or []
            if len(labels) != 2 or not s.get("count"):
                continue
            component, segment = labels
            per.setdefault(component, {})[segment] = {
                "sum_s": round(s.get("sum", 0.0), 6),
                "count": s.get("count", 0),
            }
    for component, segs in per.items():
        total = sum(e["sum_s"] for e in segs.values())
        for e in segs.values():
            e["share"] = round(e["sum_s"] / total, 4) if total > 0 else 0.0
        segs["_total_s"] = round(total, 6)
    return per


# -- rendering ----------------------------------------------------------------


def _fmt_rate(v: float) -> str:
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if v >= scale:
            return f"{v / scale:.2f}{suffix}"
    return f"{v:.1f}"


def render_roofline(rows: List[dict], ceilings: Ceilings) -> str:
    """The roofline table — pure function of classified rows (the
    obs/watch.py renderer posture, unit-testable without a device)."""
    head = (
        f"roofline vs {ceilings.device_kind} ceilings "
        f"({_fmt_rate(ceilings.flops_per_s)}FLOP/s, "
        f"{_fmt_rate(ceilings.bytes_per_s)}B/s, {ceilings.source}"
        + (
            f", launch floor {ceilings.launch_seconds * 1e6:.1f}us"
            if ceilings.launch_seconds
            else ""
        )
        + ")"
    )
    cols = (
        f"{'site/case':<30} {'flop/s':>9} {'bytes/s':>9} "
        f"{'%flop':>6} {'%mem':>6}  class"
    )
    lines = [head, cols, "-" * len(cols)]
    for row in rows:
        lines.append(
            f"{row.get('site') or row.get('case', '?'):<30} "
            f"{_fmt_rate(row['achieved_flops']):>9} "
            f"{_fmt_rate(row['achieved_bytes_per_s']):>9} "
            f"{100 * row['flops_utilization']:>5.1f}% "
            f"{100 * row['memory_utilization']:>5.1f}%  "
            f"{row['bound_class']}"
        )
    return "\n".join(lines)


# -- BENCH round rendering ----------------------------------------------------

# kernel-case geometry parses from the stable case-name convention
# (c2_128_..., c5_65536_...); non-kernel cases (wire, loadgen) have no
# board size in their name and are skipped by the model path
_CASE_SIZE_RE = re.compile(r"^c\d+_(\d+)_")


def rows_from_bench(path, ceilings: Ceilings, bench: Optional[dict] = None) -> List[dict]:
    """Roofline rows for one BENCH round: per kernel case, the embedded
    roofline fields when the round carries them (bench.py embeds them
    from this PR on), else the analytic model from the case-name
    geometry + per-turn fit (the only path a salvaged tail supports).
    ``bench`` skips the load when the caller already holds the loaded
    round (the CLI loads once for provenance and reuses it here)."""
    from .regress import load_bench

    if bench is None:
        bench = load_bench(path)
    rows = []
    for name, case in sorted(bench["cases"].items()):
        per_turn_us = case.get("per_turn_us")
        if not per_turn_us or per_turn_us <= 0:
            # a non-positive fit is a broken measurement (the round-2 c5
            # negative-throughput class): excluded, never classified
            continue
        if case.get("bound_class") and case.get("achieved_flops") is not None:
            row = {
                "achieved_flops": case["achieved_flops"],
                "achieved_bytes_per_s": case.get("achieved_bytes_per_s", 0.0),
                "flops_utilization": case.get("flops_utilization", 0.0),
                "memory_utilization": case.get("memory_utilization", 0.0),
                "bound_class": case["bound_class"],
            }
        else:
            m = _CASE_SIZE_RE.match(name)
            if not m:
                continue
            size = int(m.group(1))
            row = classify_case(size, size, per_turn_us * 1e-6, ceilings)
        row["case"] = name
        row["per_turn_us"] = per_turn_us
        rows.append(row)
    return rows


def server_bound_classes(snap: dict) -> Dict[str, str]:
    """``{site: class}`` from a snapshot's ``gol_kernel_bound`` gauges —
    the one extraction of the server-published classification, shared by
    ``rows_from_status`` and the watch ROOFLINE panel so the gauge's
    label shape cannot silently diverge between the two readers."""
    from .status import series_map

    return {
        labels[0]: labels[1]
        for labels, s in series_map(snap, "gol_kernel_bound").items()
        if len(labels) == 2 and s.get("value")
    }


def rows_from_status(payload: dict, ceilings: Ceilings) -> List[dict]:
    """Roofline rows from a live Status payload. The SERVER's published
    bound class (``gol_kernel_bound`` — classified against the ceilings
    of the device that actually ran the kernels) is authoritative and
    kept when present (``class_source: "server"``); the caller-side
    ``ceilings`` only fill in the utilization columns and the class for
    version-skewed servers that never published one — the only case
    where a local reclassification is honest."""
    from .status import series_map

    snap = payload.get("metrics") or {}
    achieved_f = series_map(snap, "gol_kernel_achieved_flops")
    achieved_b = series_map(snap, "gol_kernel_achieved_bytes_per_s")
    dispatch = series_map(snap, "gol_kernel_dispatch_seconds")
    server_cls = server_bound_classes(snap)
    rows = []
    for labels in sorted(achieved_f):
        site = labels[0] if labels else "?"
        af = (achieved_f.get(labels) or {}).get("value") or 0.0
        ab = (achieved_b.get(labels) or {}).get("value") or 0.0
        row = classify(af, ab, ceilings)
        if site in server_cls:
            row["bound_class"] = server_cls[site]
            row["class_source"] = "server"
        else:
            row["class_source"] = "local-ceilings"
        d = dispatch.get(labels)
        if d and d.get("count"):
            row["calls"] = d["count"]
            row["mean_dispatch_s"] = d.get("sum", 0.0) / d["count"]
        row["site"] = site
        rows.append(row)
    return rows


# -- CLI ----------------------------------------------------------------------


def _selfcheck() -> int:
    """The ``scripts/check --perf`` smoke: enable metrics, push a real
    (CPU) kernel through the instrumented dispatch path, calibrate the
    fitted ceilings, classify, and render — failing on an empty table,
    an unknown class, or a calibration cache miss on the second hit."""
    import numpy as np

    from ..models import CONWAY
    from ..ops.auto import auto_plane
    from . import device as _device

    _metrics.enable()
    plane = auto_plane(CONWAY, (128, 128))
    if plane is None:
        from ..ops.plane import BytePlane

        plane = BytePlane(CONWAY)
    rng = np.random.default_rng(3)
    board = np.where(rng.random((128, 128)) < 0.3, 255, 0).astype(np.uint8)
    state = plane.encode(board)
    for _ in range(3):
        state = plane.step_n(state, 8)
        plane.alive_count(state)  # force the dispatch to completion
    stats = _device.dispatch_stats()
    if not stats:
        print("perf selfcheck FAILED: no instrumented dispatches recorded",
              file=sys.stderr)
        return 1
    ceilings = calibrate()
    fits_before = _FIT_RUNS
    again = calibrate()
    if again is not ceilings or _FIT_RUNS != fits_before:
        print("perf selfcheck FAILED: ceiling calibration was not cached",
              file=sys.stderr)
        return 1
    rows = refresh_metrics(ceilings)
    if not rows or any(r["bound_class"] not in BOUND_CLASSES for r in rows):
        print("perf selfcheck FAILED: no classified roofline rows",
              file=sys.stderr)
        return 1
    print(render_roofline(rows, ceilings))
    decomp = decomposition_summary()
    print(f"perf selfcheck ok: {len(rows)} site(s) classified, "
          f"{len(decomp)} decomposed component(s)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="roofline attribution: classify kernel sites/cases "
        "as compute-/memory-/launch-bound against calibrated device "
        "ceilings"
    )
    parser.add_argument(
        "target", nargs="?", default=None,
        help="a broker host:port (live Status poll) or a BENCH_r*.json "
             "round",
    )
    parser.add_argument(
        "--device-kind", dest="device_kind", default=None,
        help="classify against this device kind's ceilings instead of "
             "the local device's (required for honest classes when a "
             "BENCH round's provenance was truncated away)",
    )
    parser.add_argument(
        "-timeout", type=float, default=5.0, metavar="SECONDS",
        help="live-poll reply bound (default 5)",
    )
    parser.add_argument(
        "-json", action="store_true",
        help="print the classified rows as JSON instead of the table",
    )
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="loopback smoke: instrumented CPU dispatches -> calibrate "
             "-> classify -> render (the scripts/check --perf gate)",
    )
    args = parser.parse_args(argv)
    if args.selfcheck:
        return _selfcheck()
    if not args.target:
        parser.error("a target is required (or --selfcheck)")

    import pathlib

    is_file = args.target.endswith(".json") or pathlib.Path(args.target).is_file()
    if is_file:
        from .regress import BenchLoadError, load_bench

        try:
            bench = load_bench(args.target)
        except (OSError, BenchLoadError) as exc:
            print(f"perf: {exc}", file=sys.stderr)
            return 2
        prov = bench.get("provenance") or {}
        kind = args.device_kind or prov.get("device_kind") or prov.get("platform")
        if kind is None:
            print(
                "warning: round carries no provenance (truncated tail?) "
                "and no --device-kind was given — classifying against "
                "the LOCAL device's ceilings, which is only honest if "
                "this round was measured here", file=sys.stderr,
            )
        ceilings = calibrate(kind)
        rows = rows_from_bench(args.target, ceilings, bench=bench)
    else:
        from .status import StatusUnavailable, fetch_status

        try:
            payload = fetch_status(args.target, timeout=args.timeout)
        except StatusUnavailable as exc:
            print(f"perf: no status — {exc}", file=sys.stderr)
            return 1
        except Exception as exc:
            print(f"perf: poll failed — {exc}", file=sys.stderr)
            return 1
        ceilings = calibrate(args.device_kind)
        rows = rows_from_status(payload, ceilings)
        if not args.device_kind and any(
            r.get("class_source") == "local-ceilings" for r in rows
        ):
            print(
                "warning: the server published no bound class for some "
                "sites (version skew) — those classes are computed "
                "against the LOCAL device's ceilings, which is only "
                "honest if the server runs the same device kind (pass "
                "--device-kind otherwise)", file=sys.stderr,
            )
        decomp = decomposition_summary(payload.get("metrics") or {})
        if decomp and not args.json:
            print("WHERE TIME GOES (per component):")
            for component, segs in sorted(decomp.items()):
                parts = [
                    f"{seg} {e['sum_s']:.3f}s ({100 * e['share']:.0f}%)"
                    for seg, e in sorted(segs.items())
                    if isinstance(e, dict)
                ]
                print(f"  {component:<10} " + "  ".join(parts))
            print()
    if not rows:
        print("perf: nothing to classify (no kernel sites/cases with "
              "dispatch data)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(
            {"ceilings": ceilings.__dict__, "rows": rows}, indent=1,
            default=str,
        ))
    else:
        print(render_roofline(rows, ceilings))
    return 0


if __name__ == "__main__":
    sys.exit(main())
