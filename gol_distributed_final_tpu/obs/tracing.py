"""Cross-process span tracer + Chrome trace-event export.

The metrics registry (obs/metrics.py) answers "how much / how fast" in
aggregate; this module answers the *causal* questions aggregates cannot —
which turn, on which host, inside which RPC, was in flight when a run
wedged. Podracer-style TPU stacks (arXiv:2104.06272) debug exactly this
class of stall from per-actor timelines; here the timeline is a set of
SPANS with explicit start/end:

* **Cheap when off.** Like the registry, the process-global tracer starts
  disabled; every instrumented site is one attribute load and a branch —
  no clock reads, no id generation, no allocation — until an entry point
  opts in (the ``-trace`` CLI flags).
* **Cross-process.** Spans carry ``trace_id``/``span_id``/``parent_id``.
  The RPC client stamps its current context into ``Request.trace_ctx``
  and the server parents its dispatch span on it (both sides read the
  field via ``getattr``, so version skew degrades to "no trace", exactly
  like the other extension fields). One session's controller ticker,
  broker verbs, worker Update strips, and engine chunk dispatches all
  share one ``trace_id``.
* **Bounded.** Finished spans land in a ring (``deque(maxlen=...)``), so
  a million-turn run keeps the most recent window instead of growing
  without bound — the same posture as the flight recorder (obs/flight.py).
* **Perfetto-loadable.** ``write_chrome_trace`` renders any collection of
  span records (from any number of processes — the Status verb ships them
  across) as Chrome trace-event JSON: ``ph: "X"`` complete events with
  ``process_name`` metadata per process, one named track each.

Span *names* are a stable operator contract like metric names: declared
once here (``span_name(...)``), documented in the README "Tracing" table,
and linted by ``obs/lint.py``.

Device-side timelines: ``device_trace`` routes a ``jax.profiler`` trace
(utils/trace.py) into the same out dir and flips a flag that makes
``annotate(name)`` return a real ``jax.profiler.TraceAnnotation`` — so the
host spans and the profiler's device tracks line up by name.
"""

from __future__ import annotations

import contextlib
import json
import os
import pathlib
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

from . import flight as _flight

# -- span-name registry (the lint contract, like obs/instruments.py) ---------

_SPAN_NAMES: set = set()


def span_name(name: str) -> str:
    """Declare a span name. All names flow into the README lint
    (obs/lint.py): adding a site without documenting it fails the build."""
    _SPAN_NAMES.add(name)
    return name


def registered_span_names() -> List[str]:
    return sorted(_SPAN_NAMES)


SPAN_CONTROLLER_SESSION = span_name("controller.session")
SPAN_CONTROLLER_TICK = span_name("controller.tick")
SPAN_CONTROLLER_KEY = span_name("controller.key")
SPAN_RPC_CLIENT = span_name("rpc.client.call")
SPAN_RPC_SERVER = span_name("rpc.server.dispatch")
SPAN_ENGINE_CHUNK = span_name("engine.chunk")
SPAN_ENGINE_PARK = span_name("engine.park")
SPAN_ENGINE_CHECKPOINT = span_name("engine.checkpoint")
SPAN_BROKER_TURN = span_name("broker.turn")
SPAN_HALO_DISPATCH = span_name("halo.dispatch")
SPAN_BENCH_STAGE = span_name("bench.stage")


def _new_id() -> str:
    """A 64-bit random id as 16 hex chars (os.urandom: no seeding, safe
    across fork, unique enough for per-run traces)."""
    return os.urandom(8).hex()


class Span:
    """One in-flight span. Created only when the tracer records (enabled
    and sampled) — the disabled path returns None before any allocation."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "sampled",
        "t0_wall", "t0_mono", "tid", "args",
    )

    def __init__(self, name, trace_id, span_id, parent_id, sampled, args):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.t0_wall = time.time()
        self.t0_mono = time.monotonic()  # durations come from monotonic
        self.tid = threading.get_ident()
        self.args = args

    def ctx(self) -> dict:
        """The wire form carried in Request/Response.trace_ctx: plain dict
        of strings/bool, so it crosses the restricted unpickler."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "sampled": self.sampled,
        }


class Tracer:
    """Explicit start/end span tracer with a per-thread context stack.

    ``start_span`` parents on (in order) an explicit ``parent_ctx`` (an
    RPC peer's wire context, or a captured local one for work handed to a
    pool thread), else the calling thread's innermost open span, else
    starts a new trace (root) — applying ``sample_rate`` once per trace,
    at the root; the decision propagates in the context.
    """

    def __init__(self, enabled: bool = False, capacity: int = 4096):
        self.enabled = enabled
        self.sample_rate = 1.0
        self.process_name = ""  # role label for the Chrome process track
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._tls = threading.local()

    # -- recording --------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def start_span(
        self, name: str, parent_ctx: Optional[dict] = None, **args
    ) -> Optional[Span]:
        """Open a span; returns None (one flag check, nothing else) when
        the tracer is off. The span is pushed as the thread's current
        context until ``end_span``."""
        if not self.enabled:
            return None
        stack = self._stack()
        if parent_ctx is None and stack:
            parent = stack[-1]
            trace_id, parent_id, sampled = (
                parent.trace_id, parent.span_id, parent.sampled,
            )
        elif parent_ctx:
            trace_id = str(parent_ctx.get("trace_id") or _new_id())
            parent_id = str(parent_ctx.get("span_id") or "")
            sampled = bool(parent_ctx.get("sampled", True))
        else:  # a new trace root: the one place sampling is decided
            trace_id, parent_id = _new_id(), ""
            sampled = (
                self.sample_rate >= 1.0
                or int.from_bytes(os.urandom(2), "big") / 65536.0
                < self.sample_rate
            )
        span = Span(name, trace_id, _new_id(), parent_id, sampled, args)
        stack.append(span)
        if sampled:
            _flight.record("span.open", name, trace_id=trace_id,
                           span_id=span.span_id)
        return span

    def end_span(self, span: Optional[Span], **more_args) -> None:
        """Close ``span`` (None-safe: the disabled path's start returned
        None) and commit it to the ring if its trace is sampled."""
        if span is None:
            return
        stack = getattr(self._tls, "stack", None)
        if stack and span in stack:
            # remove through the top so a missed inner end can't leave the
            # stack permanently wedged on this thread
            while stack and stack.pop() is not span:
                pass
        if not span.sampled:
            return
        dur_us = int((time.monotonic() - span.t0_mono) * 1e6)
        if more_args:
            span.args.update(more_args)
        record = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "pid": os.getpid(),
            "tid": span.tid,
            "role": self.process_name,
            "ts_us": int(span.t0_wall * 1e6),
            "dur_us": dur_us,
            "args": span.args,
        }
        with self._lock:
            self._spans.append(record)
        _flight.record("span.close", span.name, trace_id=span.trace_id,
                       span_id=span.span_id, dur_us=dur_us)

    @contextlib.contextmanager
    def span(self, name: str, parent_ctx: Optional[dict] = None, **args):
        s = self.start_span(name, parent_ctx=parent_ctx, **args)
        try:
            yield s
        finally:
            self.end_span(s)

    # -- context ----------------------------------------------------------

    def current_ctx(self) -> Optional[dict]:
        """The calling thread's innermost open span as a wire context
        (what the RPC client stamps into Request.trace_ctx); None when no
        span is open or the tracer is off."""
        if not self.enabled:
            return None
        stack = getattr(self._tls, "stack", None)
        return stack[-1].ctx() if stack else None

    # -- inspection -------------------------------------------------------

    def snapshot(self, clear: bool = False) -> List[dict]:
        """Finished span records, oldest first (the Status payload form)."""
        with self._lock:
            out = list(self._spans)
            if clear:
                self._spans.clear()
        return out

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


# -- the process-global default tracer ---------------------------------------

_DEFAULT = Tracer(enabled=False)


def tracer() -> Tracer:
    return _DEFAULT


def enable(on: bool = True, sample_rate: float = 1.0) -> None:
    _DEFAULT.sample_rate = sample_rate
    _DEFAULT.enabled = on


def enabled() -> bool:
    return _DEFAULT.enabled


def set_process_name(role: str) -> None:
    """Label this process's Chrome track (controller / broker / worker)."""
    _DEFAULT.process_name = role


def start_span(name: str, parent_ctx: Optional[dict] = None, **args):
    return _DEFAULT.start_span(name, parent_ctx=parent_ctx, **args)


def end_span(span, **more_args) -> None:
    _DEFAULT.end_span(span, **more_args)


def span(name: str, parent_ctx: Optional[dict] = None, **args):
    return _DEFAULT.span(name, parent_ctx=parent_ctx, **args)


def current_ctx() -> Optional[dict]:
    return _DEFAULT.current_ctx()


# -- Chrome trace-event export -----------------------------------------------


def to_chrome_trace(spans: Iterable[dict], counters: Iterable[dict] = ()) -> dict:
    """Render span records (from any number of processes) as a Chrome
    trace-event JSON object Perfetto accepts: one ``ph: "X"`` complete
    event per span (``ts``/``dur`` in microseconds — ``ts`` is wall-clock
    so processes align; ``dur`` came from each process's monotonic clock),
    plus ``process_name`` metadata so every process is a named track.

    Tracks are keyed by (role, pid), not pid alone: two processes on
    DIFFERENT hosts can share an os.getpid(), and a cross-host span set
    (collect_remote_spans) must not interleave them on one track. Each
    distinct process gets a synthetic track id; the real pid rides in the
    span args.

    ``counters`` are metric-timeline samples (obs/timeline.py
    ``chrome_counter_samples``: ``{"name", "ts_us", "value"}`` dicts),
    rendered as ``ph: "C"`` counter events on one dedicated "metrics
    timeline" track — so Perfetto shows throughput/HBM/queue depth on
    the SAME timeline as the spans."""
    spans = list(spans)
    track_ids: Dict[tuple, int] = {}
    roles: Dict[tuple, str] = {}
    for s in spans:
        pid = int(s["pid"])
        role = s.get("role") or ""
        key = (role, pid)
        if key not in track_ids:
            track_ids[key] = len(track_ids) + 1
        # first writer wins; a later span with a proper role upgrades a
        # fallback label (a process that set its name after early spans)
        if roles.get(key, "") == "":
            roles[key] = role or f"pid {pid}"
    events: List[dict] = []
    for s in spans:
        pid = int(s["pid"])
        args = dict(s.get("args") or {})
        method = args.get("method")
        args.update(
            trace_id=s["trace_id"], span_id=s["span_id"],
            parent_id=s.get("parent_id", ""), os_pid=pid,
        )
        events.append({
            "name": f"{s['name']} {method}" if method else s["name"],
            "cat": s["name"],
            "ph": "X",
            "ts": int(s["ts_us"]),
            "dur": max(1, int(s["dur_us"])),
            "pid": track_ids[(s.get("role") or "", pid)],
            "tid": int(s["tid"]),
            "args": args,
        })
    for key, track in sorted(track_ids.items(), key=lambda kv: kv[1]):
        events.append({
            "name": "process_name", "ph": "M", "ts": 0, "pid": track,
            "tid": 0, "args": {"name": roles[key]},
        })
    counters = list(counters or ())
    if counters:
        counter_track = len(track_ids) + 1
        for c in counters:
            events.append({
                "name": str(c["name"]), "ph": "C",
                "ts": int(c["ts_us"]), "pid": counter_track, "tid": 0,
                "args": {"value": float(c["value"])},
            })
        events.append({
            "name": "process_name", "ph": "M", "ts": 0,
            "pid": counter_track, "tid": 0,
            "args": {"name": "metrics timeline"},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_path(params, out_dir="out") -> pathlib.Path:
    # rides the <W>x<H>x<Turns> naming convention like report_path
    return pathlib.Path(out_dir) / f"trace_{params.output_filename}.json"


def write_chrome_trace(
    path, spans: Iterable[dict], counters: Iterable[dict] = ()
) -> pathlib.Path:
    """Dump spans (plus optional timeline counter samples — see
    ``to_chrome_trace``) as Chrome trace JSON, via temp-name + atomic
    rename like the checkpoint and report writers."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(to_chrome_trace(spans, counters)))
    tmp.replace(path)
    return path


# -- device-trace fold-in (utils/trace.py's jax.profiler surface) ------------

_DEVICE_TRACE_ACTIVE = False


def device_trace_active() -> bool:
    return _DEVICE_TRACE_ACTIVE


@contextlib.contextmanager
def device_trace(log_dir):
    """A ``jax.profiler`` trace (utils/trace.trace) routed into ``log_dir``
    with host-span alignment: while active, ``annotate(name)`` pushes real
    ``TraceAnnotation``s so the profiler's device timeline carries the same
    names as the host spans (the ``-trace-device`` flag)."""
    global _DEVICE_TRACE_ACTIVE
    from ..utils.trace import trace as _profiler_trace

    with _profiler_trace(str(log_dir)) as p:
        _DEVICE_TRACE_ACTIVE = True
        try:
            yield p
        finally:
            _DEVICE_TRACE_ACTIVE = False


# genuinely SHARED (nullcontext is stateless and reentrant): the inactive
# path of annotate() must not allocate per call — it sits inside per-chunk
# (and, under emit_flips, per-turn) dispatch loops
_NULL_CTX = contextlib.nullcontext()


def annotate(name: str):
    """A ``jax.profiler.TraceAnnotation(name)`` while a device trace is
    active, else a shared no-op context — one flag check, no allocation,
    on the hot path."""
    if not _DEVICE_TRACE_ACTIVE:
        return _NULL_CTX
    import jax

    return jax.profiler.TraceAnnotation(name)
