"""Live cluster dashboard — a ``top`` for a running GoL deployment.

    python -m gol_distributed_final_tpu.obs.watch :8040
    python -m gol_distributed_final_tpu.obs.watch 10.0.0.2:8040 \\
        -worker 10.0.0.3:8030 -worker 10.0.0.4:8030 -interval 2

Polls the broker's read-only ``Status`` verb — workers are discovered
from its ``worker_health`` roster automatically; ``-worker`` adds
extras — and renders a refreshing terminal panel: turn throughput, per-verb
RPC latency, compile-cache hit rate + kernel cost analysis, per-device
HBM occupancy, and the flight-recorder tail. Built ENTIRELY on the Status
surface — the dashboard can be attached to and detached from a live run
at will, costs the server one registry snapshot per poll, and never
touches the engine or the board (unlike ``RetrieveCurrentData``).

Rates (turns/s, calls/s) are derived client-side from successive counter
snapshots, so the servers stay stateless about their observers.

Every payload read goes through ``dict.get``: a server that predates a
field renders a gap, not a crash — the skew contract of the whole obs
surface. Pure stdlib, no jax import (pollable from any machine).

``-once`` renders a single frame and exits (scripting / test hook);
the default loop clears the screen between frames until Ctrl-C.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict, List, Optional, Tuple

from .status import fetch_many, norm_address
from .status import scalar_value as _scalar
from .status import series_map as _series_map
from .timeline import counter_delta

_CLEAR = "\x1b[2J\x1b[H"


def _hist_stats(series: dict) -> Tuple[int, float]:
    """(count, mean seconds) of one histogram series."""
    count = series.get("count") or 0
    return count, (series.get("sum", 0.0) / count if count else 0.0)


def _human_bytes(n: Optional[float]) -> str:
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return "?"


def _human_seconds(s: float) -> str:
    if s < 1e-3:
        return f"{s * 1e6:.1f}us"
    if s < 1.0:
        return f"{s * 1e3:.1f}ms"
    return f"{s:.2f}s"


# -- panel renderers ---------------------------------------------------------


def _alert_lines(payload: dict) -> List[str]:
    """The SLO panel (obs/slo.py states shipped in the Status payload):
    firing alerts are the headline — rule, severity, age, and the
    server-side evaluation detail. All-ok rulebooks render one quiet
    summary line; servers without ``-timeline`` render nothing."""
    alerts = payload.get("alerts")
    if not alerts:
        return []
    firing = [a for a in alerts if a.get("state") == "firing"]
    if not firing:
        fired = sum(int(a.get("fired_total") or 0) for a in alerts)
        line = f"  {len(alerts)} rules evaluated, none firing"
        if fired:
            line += f"   ({fired} past firing(s) — see flight ring)"
        return ["ALERTS (slo rulebook ok)", line]
    now = time.time()
    out = [f"ALERTS — {len(firing)} FIRING"]
    for a in firing:
        since = a.get("since_unix")
        age = (
            f"{now - since:6.0f}s"
            if isinstance(since, (int, float)) and since
            else "     ?"
        )
        out.append(
            f"  ** {str(a.get('severity', '?')).upper():<4} "
            f"{a.get('rule', '?'):<24} for {age}   "
            f"{a.get('detail', '')}"
        )
    return out


def _fleet_lines(payload: dict) -> List[str]:
    """The cluster panel (obs/fleet.py collector payloads): per-target
    scrape health — a STALE target is the headline — merge exclusions
    (version skew, named and counted, never averaged in), per-broker
    sessions + server-side turn rates from each broker's own timeline
    summary, and the cross-broker tenant-skew verdict. Non-fleet
    payloads render nothing."""
    fl = payload.get("fleet")
    if not isinstance(fl, dict):
        return []
    targets = fl.get("targets") or []
    stale = sum(1 for t in targets if t.get("state") == "stale")
    head = (
        f"FLEET ({len(targets)} target(s) @ {fl.get('interval_s', '?')}s "
        f"sweeps, {fl.get('sweeps', 0)} sweep(s) done)"
    )
    if stale:
        head += f"   ** {stale} STALE **"
    out = [head]
    for t in targets:
        state = str(t.get("state", "?"))
        mark = "**" if state == "stale" else "  "
        kind = "worker" if t.get("worker") else "broker"
        age = t.get("last_success_age_s")
        age_s = f"{age:.1f}s ago" if isinstance(age, (int, float)) else "never"
        line = (
            f"  {mark}{t.get('address', '?'):<22} {kind:<6} {state:<8}"
            f" ok {int(t.get('ok_total') or 0):>4}"
            f"  err {int(t.get('err_total') or 0):>4}  last ok {age_s}"
        )
        fails = t.get("consecutive_failures") or 0
        if fails:
            line += f"  ({fails} consecutive: {t.get('error')})"
        out.append(line)
    for addr, why in sorted((fl.get("merge_excluded") or {}).items()):
        out.append(f"  !! {addr} EXCLUDED from merge: {why}")
    brokers = fl.get("broker_status") or {}
    if brokers:
        out.append(
            "  per-broker                sessions  turns/s  universe-turns/s"
        )
        for addr in sorted(brokers):
            p = brokers[addr]
            summary = (p.get("timeline") or {}).get("summary") or {}
            tr = (summary.get("gol_engine_turns_total") or {}).get("rate_per_s")
            sr = (summary.get("gol_session_turns_total") or {}).get(
                "rate_per_s")
            active = _scalar(p.get("metrics") or {}, "gol_sessions_active")
            out.append(
                f"  {addr:<26} {int(active or 0):>8}  "
                f"{(f'{tr:,.1f}' if tr is not None else '-'):>7}  "
                f"{(f'{sr:,.1f}' if sr is not None else '-'):>7}"
            )
    skew = fl.get("tenant_skew") or {}
    if skew.get("tenant") is not None:
        out.append(
            f"  tenant skew {skew.get('value', 0):.2f}x fair share: "
            f"'{skew['tenant']}' hottest on {skew.get('address')}"
        )
    return out


# summary entries worth a dashboard line, in render order (the rest stay
# pollable via obs/status -format json)
_TIMELINE_KEYS = (
    "gol_engine_turns_total",
    "gol_session_turns_total",
    "gol_session_turn_seconds",
    "gol_session_admit_wait_seconds",
    "gol_rpc_dispatch_seconds{method=Operations.SessionRun}",
    "gol_rpc_server_errors_total",
    "gol_scatter_deadline_seconds",
    # GC observability (obs/profiler.py's gc.callbacks hook): pause
    # quantiles + per-generation collection rates on the dashboard —
    # a stop-the-world pause is wall no segment decomposition names
    "gol_gc_pause_seconds",
    "gol_gc_collections_total{gen=0}",
    "gol_gc_collections_total{gen=1}",
    "gol_gc_collections_total{gen=2}",
)


def _timeline_lines(payload: dict) -> List[str]:
    """Server-computed rates/quantiles (obs/timeline.py summary): unlike
    the client-side counter-delta rates elsewhere on this dashboard,
    these survive dashboard restarts and are exactly what the SLO rules
    evaluated."""
    tl = payload.get("timeline") or {}
    summary = tl.get("summary") or {}
    if not summary:
        return []
    out = [
        f"TIMELINE (server-side, last {int(tl.get('summary_window_s') or 0)}s"
        f" @ {tl.get('period_s', '?')}s cadence)"
    ]
    shown = 0
    for key in _TIMELINE_KEYS:
        entry = summary.get(key)
        if not isinstance(entry, dict):
            continue
        parts = [f"  {key:<44}"]
        rate = entry.get("rate_per_s")
        if rate is not None:
            parts.append(f"{rate:,.1f}/s")
        if entry.get("p99_s") is not None:
            parts.append(
                f"p50 {_human_seconds(entry.get('p50_s') or 0)}"
                f"  p99 {_human_seconds(entry['p99_s'])}"
            )
        elif "value" in entry:
            parts.append(f"now {entry['value']:,.3g}")
        out.append("  ".join(parts))
        shown += 1
    if shown == 0:
        out.append(f"  {len(summary)} active series (none on the dashboard shortlist)")
    return out


def _throughput_lines(snap: dict, rate: Optional[float]) -> List[str]:
    turns = _scalar(snap, "gol_engine_turns_total")
    chunks = _scalar(snap, "gol_engine_chunks_total")
    chunk_size = _scalar(snap, "gol_engine_chunk_size")
    step = _series_map(snap, "gol_engine_step_seconds").get(())
    if turns is None and step is None:
        return []
    rate_s = f"{rate:,.0f} turns/s" if rate is not None else "rate: first poll"
    line = (
        f"  turns {int(turns or 0):,}   {rate_s}   "
        f"chunks {int(chunks or 0):,}   chunk size {int(chunk_size or 0):,}"
    )
    out = ["THROUGHPUT", line]
    if step:
        count, mean = _hist_stats(step)
        if count:
            out.append(f"  step mean {_human_seconds(mean)}/turn over {count:,} turns")
    return out


def _rpc_lines(snap: dict) -> List[str]:
    calls = _series_map(snap, "gol_rpc_server_requests_total")
    errors = _series_map(snap, "gol_rpc_server_errors_total")
    latency = _series_map(snap, "gol_rpc_server_request_seconds")
    verbs = sorted(set(calls) | set(latency))
    if not verbs:
        return []
    out = ["RPC (server side)          calls    errs   mean"]
    for verb in verbs:
        n = int((calls.get(verb) or {}).get("value") or 0)
        e = int((errors.get(verb) or {}).get("value") or 0)
        lat = latency.get(verb)
        count, mean = _hist_stats(lat) if lat else (0, 0.0)
        mean_s = _human_seconds(mean) if count else "-"
        out.append(f"  {(verb[0] if verb else '?'):<24} {n:>6}  {e:>6}   {mean_s}")
    return out


def _wire_lines(snap: dict) -> List[str]:
    """The data-plane comms column: frame bytes this process's RPC
    clients moved per verb and direction (``gol_wire_bytes_total`` — the
    broker's scatter/StripStep traffic when polling a broker), the
    resident halo traffic split by axis (``gol_halo_bytes_total``:
    row/col/corner — the -grid tile plane's O(K·edge) claim, measured),
    the turns-per-batch histogram (``gol_turn_batch_size``: K in resident
    wire mode, 1 in full/haloed), and the resident full-resync count."""
    by_verb: Dict[str, Dict[str, float]] = {}
    for labels, series in _series_map(snap, "gol_wire_bytes_total").items():
        if len(labels) != 2:
            continue
        verb, direction = labels
        by_verb.setdefault(verb, {})[direction] = series.get("value") or 0.0
    batch = _series_map(snap, "gol_turn_batch_size").get(())
    resyncs = _scalar(snap, "gol_strip_resync_total")
    if not by_verb and not batch and not resyncs:
        return []
    out = ["WIRE (data plane)          sent        received"]
    for verb in sorted(by_verb):
        d = by_verb[verb]
        out.append(
            f"  {verb:<24} {_human_bytes(d.get('sent')):>9}  "
            f"{_human_bytes(d.get('received')):>9}"
        )
    halo = _series_map(snap, "gol_halo_bytes_total")
    if halo:
        # resident halo traffic split by axis: on a -grid tile run the
        # row/col/corner shares show the O(K*edge) scaling directly; the
        # strip plane is all row-axis
        parts = " ".join(
            f"{(labels[0] if labels else '?')} "
            f"{_human_bytes(series.get('value'))}"
            for labels, series in sorted(halo.items())
            if series.get("value")
        )
        if parts:
            out.append(f"  halo bytes by axis: {parts}")
    tail = []
    if batch:
        count, mean = _hist_stats(batch)
        if count:
            tail.append(f"batches {count:,} (mean {mean:.1f} turns/rpc)")
    if resyncs:
        tail.append(f"strip resyncs {int(resyncs)}")
    if tail:
        out.append("  " + "   ".join(tail))
    return out


def _session_lines(snap: dict) -> List[str]:
    """The multi-universe serving column (engine/sessions.py +
    rpc/broker.SessionScheduler): universes currently batched, admissions
    and refusals (by reason — a nonzero 'capacity' stream means traffic is
    hitting the -session-capacity bound), and universe-turns served. A
    broker that never serves sessions renders nothing."""
    active = _scalar(snap, "gol_sessions_active")
    admitted = _scalar(snap, "gol_sessions_admitted_total")
    rejected = _series_map(snap, "gol_sessions_rejected_total")
    turns = _scalar(snap, "gol_session_turns_total")
    total_rejected = sum(s.get("value") or 0 for s in rejected.values())
    if not active and not admitted and not total_rejected and not turns:
        return []
    out = ["SESSIONS (multi-universe)"]
    line = (
        f"  active {int(active or 0):,}   admitted {int(admitted or 0):,}"
        f"   rejected {int(total_rejected)}"
    )
    if total_rejected:
        reasons = ", ".join(
            f"{(labels[0] if labels else '?')} {int(s.get('value') or 0)}"
            for labels, s in sorted(rejected.items())
            if s.get("value")
        )
        line += f"  ({reasons})"
    out.append(line)
    if turns:
        out.append(f"  universe-turns served {int(turns):,}")
    return out


def _sparsity_lines(snap: dict) -> List[str]:
    """The activity-sparse column (ops/sparse.py + the dirty-tile wire
    deltas): current frontier size (``gol_active_tiles`` — the sparse
    stepper's bitmap, or a resident broker's latest batch dirty total),
    the tiles the activity bitmap saved, delta-frame bytes shipped
    instead of full gathers, and the runs short-circuited arithmetically
    by kind. A fully dense deployment renders nothing."""
    active = _scalar(snap, "gol_active_tiles")
    skips = _scalar(snap, "gol_tile_skips_total")
    delta_bytes = _scalar(snap, "gol_sparse_frame_bytes_total")
    exits = _series_map(snap, "gol_early_exit_total")
    total_exits = sum(s.get("value") or 0 for s in exits.values())
    if not active and not skips and not delta_bytes and not total_exits:
        return []
    out = ["SPARSITY (activity-sparse)"]
    out.append(
        f"  active tiles {int(active or 0):,}   tile skips "
        f"{int(skips or 0):,}   delta frames "
        f"{_human_bytes(delta_bytes or 0)}"
    )
    if total_exits:
        kinds = ", ".join(
            f"{(labels[0] if labels else '?')} {int(s.get('value') or 0)}"
            for labels, s in sorted(exits.items())
            if s.get("value")
        )
        out.append(f"  early exits {int(total_exits)}  ({kinds})")
    return out


def _tenant_lines(payload: dict, top: int = 8) -> List[str]:
    """The usage-accounting column (obs/accounting.py TenantLedger,
    shipped as the Status ``accounting`` payload): who is spending this
    broker's capacity — device-seconds, universe-turns, board bytes,
    rejects, and errors per tenant (top-K + the ``other`` overflow
    bucket), with the aggregate row last. Brokers that never served a
    session render nothing."""
    acct = payload.get("accounting") or {}
    tenants = acct.get("tenants") or []
    other = acct.get("other")
    totals = acct.get("totals") or {}
    if not tenants and not other:
        return []
    out = [
        f"TENANTS (usage, top-{acct.get('top_k', '?')})"
        f"{'':<10} dev-s      turns      bytes  rej  err"
    ]

    def row(e: dict, name: str) -> str:
        return (
            f"  {name:<22} {e.get('device_seconds') or 0.0:>9.3f} "
            f"{int(e.get('turns') or 0):>10,} "
            f"{_human_bytes(e.get('wire_bytes')):>10} "
            f"{int(e.get('rejects_total') or 0):>4} "
            f"{int(e.get('errors') or 0):>4}"
        )

    for e in tenants[:top]:
        out.append(row(e, str(e.get("tenant", "?"))))
    if len(tenants) > top:
        out.append(f"  ... {len(tenants) - top} more tracked tenant(s)")
    if other:
        out.append(row(
            other,
            f"other({other.get('distinct_tenants', '?')} tenants)",
        ))
    if totals:
        out.append(row(dict(totals, rejects_total=totals.get("rejects")),
                       "TOTAL"))
    return out


def _worker_lines(payload: dict) -> List[str]:
    """The broker's roster health column (WorkersBackend.worker_health)
    plus the fault-tolerance counters: who is connected, who is lost and
    when it will next be probed, and how much recovery has happened."""
    roster = payload.get("workers") or []
    snap = payload.get("metrics") or {}
    totals = [
        (label, _scalar(snap, name))
        for label, name in (
            ("lost", "gol_worker_lost_total"),
            ("readmitted", "gol_worker_readmitted_total"),
            ("turn retries", "gol_turn_retry_total"),
            ("auto ckpts", "gol_auto_checkpoint_total"),
        )
    ]
    if not roster and not any(v for _, v in totals):
        return []
    out = ["WORKERS (roster health)"]
    for w in roster:
        state = w.get("state", "?")
        line = f"  {w.get('address', '?'):<22} {state}"
        retry = w.get("retry_in_s")
        if state != "connected" and retry is not None:
            line += f"   next probe in {retry}s"
        out.append(line)
    counted = "   ".join(
        f"{label} {int(v)}" for label, v in totals if v
    )
    if counted:
        out.append(f"  {counted}")
    return out


def _integrity_lines(snap: dict) -> List[str]:
    """The silent-corruption column (rpc/integrity.py): verifications
    performed, failures broken out by kind (frame / strip / edges /
    attest / fetch — each one is a corruption that was CAUGHT), and
    checkpoint digest verifications by result. All-zero registries render
    nothing; a nonzero failure line is the headline an operator attaches
    this dashboard for."""
    checks = _scalar(snap, "gol_integrity_checks_total")
    fails = _series_map(snap, "gol_integrity_failures_total")
    ckpt = _series_map(snap, "gol_ckpt_verify_total")
    total_fail = sum(s.get("value") or 0 for s in fails.values())
    total_ckpt = sum(s.get("value") or 0 for s in ckpt.values())
    # value-based, not series-presence-based: a reset registry keeps its
    # label series at 0.0, and an all-zero panel is noise
    if not checks and not total_fail and not total_ckpt:
        return []
    out = ["INTEGRITY"]
    line = f"  checks {int(checks or 0):,}   failures {int(total_fail)}"
    if total_fail:
        kinds = ", ".join(
            f"{(labels[0] if labels else '?')} {int(s.get('value') or 0)}"
            for labels, s in sorted(fails.items())
            if s.get("value")
        )
        line += f"  ({kinds})  ** CORRUPTION CAUGHT **"
    out.append(line)
    if ckpt:
        ok = (ckpt.get(("ok",)) or {}).get("value") or 0
        bad = (ckpt.get(("fail",)) or {}).get("value") or 0
        out.append(f"  ckpt verify ok {int(ok)}   fail {int(bad)}")
    return out


def _where_time_lines(snap: dict) -> List[str]:
    """The dispatch-wall decomposition panel (obs/perf.py): per component
    (engine / sessions / broker), where each turn-chunk's wall went —
    host_prep / device_compute / wire / demux totals and shares. Servers
    that never decomposed a chunk render nothing."""
    from .perf import decomposition_summary

    decomp = decomposition_summary(snap)
    if not decomp:
        return []
    out = ["WHERE TIME GOES (dispatch-wall decomposition)"]
    for component, segs in sorted(decomp.items()):
        parts = [
            f"{seg} {_human_seconds(e['sum_s'])} ({100 * e['share']:.0f}%)"
            for seg, e in sorted(segs.items())
            if isinstance(e, dict)
        ]
        total = segs.get("_total_s") or 0.0
        out.append(
            f"  {component:<9} {_human_seconds(total):>9}   " + "  ".join(parts)
        )
    return out


def _critical_lines(payload: dict) -> List[str]:
    """The straggler/critical-path panel (obs/critical.py snapshot in the
    Status payload): per-worker service-time EWMAs, who gated how many
    K-batches, and the straggler headline when one worker persistently
    gates the gather."""
    cp = payload.get("critical_path") or {}
    workers = cp.get("workers") or []
    if not cp.get("batches") or not workers:
        return []
    out = [
        f"CRITICAL PATH ({cp.get('batches')} batch(es), skew "
        f"{cp.get('skew_ratio', 1.0):.2f}x)          ewma    gated  share"
    ]
    for w in workers:
        ewma = w.get("ewma_s")
        out.append(
            f"  {w.get('addr', '?'):<24} "
            f"{(_human_seconds(ewma) if ewma is not None else '-'):>10} "
            f"{w.get('gated', 0):>6} "
            f"{100 * (w.get('gated_share') or 0.0):>5.0f}%"
        )
    s = cp.get("straggler")
    if s:
        out.append(
            f"  ** STRAGGLER {s.get('addr', '?')}: gates "
            f"{100 * (s.get('gated_share') or 0):.0f}% of batches at "
            f"{s.get('skew', 0):.1f}x the roster median **"
        )
    return out


def _roofline_lines(snap: dict) -> List[str]:
    """The roofline classification panel (obs/perf.py): achieved FLOP/s
    and bytes/s per instrumented kernel site plus the bound class the
    server classified it as (the gol_kernel_bound gauge). Servers
    without instrumented dispatches render nothing."""
    from .perf import server_bound_classes

    achieved_f = _series_map(snap, "gol_kernel_achieved_flops")
    achieved_b = _series_map(snap, "gol_kernel_achieved_bytes_per_s")
    if not achieved_f:
        return []
    classes = server_bound_classes(snap)
    out = ["ROOFLINE (achieved per site)"]
    for labels in sorted(achieved_f):
        site = labels[0] if labels else "?"
        af = (achieved_f.get(labels) or {}).get("value") or 0.0
        ab = (achieved_b.get(labels) or {}).get("value") or 0.0
        out.append(
            f"  {site:<18} {af:.3g} flop/s   {_human_bytes(ab)}/s   "
            f"{classes.get(site, '?')}"
        )
    return out


def _compile_lines(snap: dict) -> List[str]:
    requests = _series_map(snap, "gol_compile_cache_requests_total")
    misses = _series_map(snap, "gol_compile_cache_misses_total")
    compile_s = _series_map(snap, "gol_compile_seconds")
    flops = _series_map(snap, "gol_kernel_flops")
    accessed = _series_map(snap, "gol_kernel_bytes_accessed")
    sites = sorted(set(requests) | set(compile_s) | set(flops))
    if not sites:
        return []
    out = ["COMPILE + KERNELS"]
    for site in sites:
        label = site[0] if site else "?"
        parts = [f"  {label:<18}"]
        req = (requests.get(site) or {}).get("value")
        if req:
            miss = (misses.get(site) or {}).get("value") or 0
            parts.append(
                f"cache {int(req - miss)}/{int(req)} hit "
                f"({100.0 * (req - miss) / req:.0f}%)"
            )
        comp = compile_s.get(site)
        if comp:
            count, mean = _hist_stats(comp)
            if count:
                parts.append(f"compiles {count} (mean {_human_seconds(mean)})")
        fl = (flops.get(site) or {}).get("value")
        if fl:
            parts.append(f"{fl:.3g} flops")
        by = (accessed.get(site) or {}).get("value")
        if by:
            parts.append(f"{_human_bytes(by)} accessed")
        if len(parts) > 1:
            out.append("  ".join(parts))
    return out if len(out) > 1 else []


def _hbm_lines(snap: dict) -> List[str]:
    in_use = _series_map(snap, "gol_device_hbm_bytes_in_use")
    peak = _series_map(snap, "gol_device_hbm_peak_bytes")
    limit = _series_map(snap, "gol_device_hbm_bytes_limit")
    devices = sorted(set(in_use) | set(peak))
    out = ["HBM (per device)"]
    if not devices:
        out.append("  no samples (CPU backend, or engine not running here)")
        return out
    for dev in devices:
        used = (in_use.get(dev) or {}).get("value")
        cap = (limit.get(dev) or {}).get("value")
        pk = (peak.get(dev) or {}).get("value")
        pct = f" ({100.0 * used / cap:.0f}%)" if used and cap else ""
        out.append(
            f"  device {dev[0] if dev else '?'}: "
            f"{_human_bytes(used)} / {_human_bytes(cap)}{pct}   "
            f"peak {_human_bytes(pk)}"
        )
    return out


def _flight_lines(payload: dict, tail: int = 6) -> List[str]:
    events = payload.get("flight") or []
    if not events:
        return []
    now = time.time()
    out = [f"FLIGHT (last {min(tail, len(events))} of {len(events)} events)"]
    for ev in events[-tail:]:
        age = now - (ev.get("t_unix") or now)
        out.append(
            f"  -{age:6.1f}s  {ev.get('kind', '?'):<12} {ev.get('name', '?')}"
        )
    return out


def _journal_lines(payload: dict, tail: int = 8) -> List[str]:
    """The lifecycle-journal tail (obs/journal.py window): the last few
    HLC-stamped events this process persisted — admissions, losses,
    recoveries, checkpoints — plus the drop counter, which must be loud
    on a dashboard (a dropping journal is an incomplete postmortem)."""
    jw = payload.get("journal")
    if not isinstance(jw, dict):
        return []
    events = jw.get("events") or []
    dropped = jw.get("dropped", 0)
    head = f"JOURNAL (seq {jw.get('seq', '?')}"
    if dropped:
        head += f", {dropped} DROPPED"
    head += ")"
    out = [head]
    if not events:
        out.append("  no new events this window")
        return out
    now = time.time()
    for ev in events[-tail:]:
        age = now - (ev.get("t_unix") or now)
        args = ev.get("args") or {}
        detail = " ".join(f"{k}={v}" for k, v in list(args.items())[:4])
        out.append(
            f"  -{age:6.1f}s  {ev.get('kind', '?'):<16} "
            f"{ev.get('name', '?')} {detail}".rstrip()
        )
    return out


def _profile_lines(payload: dict, top: int = 6) -> List[str]:
    """The continuous profiler's hot-frame shortlist (obs/profiler.py
    window): self/cum shares of the hottest frames, the adaptive
    cadence, and the gc-pause tally. Parked frames (accept/select/wait
    leaves) are skipped — the busy view; the full table stays pollable
    via obs/flame.py."""
    from .profiler import is_idle_frame

    pw = payload.get("profile")
    if not isinstance(pw, dict):
        return []
    stacks = pw.get("stacks") or 0
    head = (
        f"PROFILE (seq {pw.get('seq', '?')}, {stacks:,} stacks @ "
        f"{pw.get('period_ms', '?')}ms)"
    )
    backoffs = pw.get("backoffs") or 0
    if backoffs:
        head = head[:-1] + f", {backoffs} backoff(s))"
    out = [head]
    gc_sect = pw.get("gc") or {}
    if gc_sect.get("pauses"):
        out.append(
            f"  gc: {gc_sect['pauses']} pause(s), "
            f"max {_human_seconds(gc_sect.get('max_pause_s') or 0)}, "
            f"total {_human_seconds(gc_sect.get('pause_s') or 0)}"
        )
    shown = 0
    for row in pw.get("frames") or []:
        if shown >= top:
            break
        if is_idle_frame(str(row.get("func", "")), str(row.get("file", ""))):
            continue
        s = row.get("self") or 0
        c = row.get("cum") or 0
        denom = max(stacks, 1)
        out.append(
            f"  {100.0 * s / denom:>5.1f}% self {100.0 * c / denom:>5.1f}% "
            f"cum  {row.get('func', '?')} "
            f"({row.get('file', '?')}:{row.get('line', '?')})"
        )
        shown += 1
    if shown == 0:
        out.append("  no busy frames sampled yet")
    return out


def render_status(
    label: str,
    payload: dict,
    turns_rate: Optional[float] = None,
) -> str:
    """One target's full panel from its Status payload — pure function of
    the payload (plus the client-side rate), so it is unit-testable
    without a server."""
    role = payload.get("role", "?")
    pid = payload.get("pid", "?")
    enabled = payload.get("metrics_enabled")
    head = f"== {label}  ({role}, pid {pid})"
    if not enabled:
        head += "   [metrics DISABLED — start the server with -metrics]"
    snap = payload.get("metrics") or {}
    sections = [
        _alert_lines(payload),
        _fleet_lines(payload),
        _throughput_lines(snap, turns_rate),
        _timeline_lines(payload),
        _rpc_lines(snap),
        _wire_lines(snap),
        _session_lines(snap),
        _sparsity_lines(snap),
        _tenant_lines(payload),
        _integrity_lines(snap),
        _worker_lines(payload),
        _where_time_lines(snap),
        _critical_lines(payload),
        _roofline_lines(snap),
        _compile_lines(snap),
        _hbm_lines(snap),
        _flight_lines(payload),
        _journal_lines(payload),
        _profile_lines(payload),
    ]
    lines = [head]
    for sec in sections:
        if sec:
            lines.append("")
            lines.extend(sec)
    return "\n".join(lines)


class Watcher:
    """Polls one broker + N workers, remembering the previous poll per
    target so counter deltas become rates.

    Workers are AUTO-DISCOVERED from the broker's ``worker_health``
    roster each frame (manual ``-worker`` flags are additive extras,
    not a requirement), and all targets are polled in parallel
    (``status.fetch_many``) so one wedged target costs one timeout.
    Pointed at a fleet collector (obs/fleet.py, ``role="fleet"``), the
    frame renders the FLEET panel plus one sub-panel per broker from
    the collector's ``broker_status`` — one address, whole cluster."""

    def __init__(self, broker: str, workers: List[str], timeout: float):
        self.targets = [(norm_address(broker), False)] + [
            (norm_address(w), True) for w in workers
        ]
        self.timeout = timeout
        self._prev: Dict[str, Tuple[float, float]] = {}  # addr -> (t, turns)
        # addr -> last timeline seq received: echoed back so a -timeline
        # server ships incremental windows instead of the whole ring
        self._tl_seq: Dict[str, int] = {}
        # addr -> last journal seq received (the journal twin)
        self._jr_seq: Dict[str, int] = {}
        # addr -> last profile seq received + the frame cache the
        # incremental windows overlay (a -profile server ships only
        # frames whose hits MOVED past the echoed seq; the dashboard
        # merges them over what it already holds)
        self._pr_seq: Dict[str, int] = {}
        self._pr_frames: Dict[str, Dict[tuple, dict]] = {}

    def _turns_rate(self, addr: str, payload: dict) -> Optional[float]:
        now = time.monotonic()
        turns = _scalar(payload.get("metrics") or {}, "gol_engine_turns_total")
        prev = self._prev.get(addr)
        if turns is not None:
            self._prev[addr] = (now, turns)
        if prev is None or turns is None:
            return None
        t0, turns0 = prev
        dt = now - t0
        # counter_delta (obs/timeline.py — the server rings' reset logic,
        # shared): a broker or worker restarted between polls reports a
        # SMALLER total, and the raw subtraction used to render that as a
        # negative/garbage rate; reset-aware, the new total IS the delta
        return counter_delta(turns0, turns) / dt if dt > 0 else None

    def _merge_profile(self, addr: str, payload: dict) -> None:
        """Overlay an incremental profile window onto the cached frame
        table: a frame absent from this window simply hasn't MOVED since
        the echoed seq — its last-known counts still render."""
        pw = payload.get("profile")
        if not isinstance(pw, dict):
            return
        seq = pw.get("seq")
        if isinstance(seq, int):
            self._pr_seq[addr] = seq
        cache = self._pr_frames.setdefault(addr, {})
        for row in pw.get("frames") or []:
            if isinstance(row, dict):
                cache[(row.get("func"), row.get("file"),
                       row.get("line"))] = row
        pw["frames"] = sorted(
            cache.values(), key=lambda r: -(r.get("self") or 0)
        )[:40]

    def _spec(self, addr: str, is_worker: bool) -> dict:
        return {
            "address": addr, "worker": is_worker,
            "timeline_since": self._tl_seq.get(addr, 0),
            "journal_since": self._jr_seq.get(addr, 0),
            "profile_since": self._pr_seq.get(addr, 0),
        }

    def frame(self) -> Tuple[str, bool]:
        """(rendered frame, primary target ok)."""
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        blocks = [f"gol watch — {stamp}   (read-only Status polls)"]
        primary_ok = False
        ordered = list(self.targets)
        results = fetch_many(
            [self._spec(a, w) for a, w in ordered], timeout=self.timeout
        )
        # roster auto-discovery: workers each broker payload names get a
        # second (also parallel) round — no -worker flags required
        seen = {a for a, _ in ordered}
        discovered: List[Tuple[str, bool]] = []
        for addr, is_worker in list(ordered):
            payload = (results.get(addr) or (None,))[0]
            if payload is None or is_worker:
                continue
            for entry in payload.get("workers") or []:
                if not isinstance(entry, dict):
                    continue
                waddr = entry.get("address")
                if not isinstance(waddr, str) or ":" not in waddr:
                    continue
                waddr = norm_address(waddr)
                if waddr not in seen:
                    seen.add(waddr)
                    discovered.append((waddr, True))
        if discovered:
            results.update(fetch_many(
                [self._spec(a, w) for a, w in discovered],
                timeout=self.timeout,
            ))
            ordered.extend(discovered)
        for i, (addr, is_worker) in enumerate(ordered):
            kind = "worker" if is_worker else "broker"
            payload, _fetched_at, error = results.get(addr) or (
                None, 0.0, "no result")
            if error is not None:
                blocks.append(f"== {kind} {addr}: poll failed — {error}")
                continue
            seq = (payload.get("timeline") or {}).get("seq")
            if isinstance(seq, int):
                self._tl_seq[addr] = seq
            jseq = (payload.get("journal") or {}).get("seq")
            if isinstance(jseq, int):
                self._jr_seq[addr] = jseq
            self._merge_profile(addr, payload)
            if i == 0:
                primary_ok = True
            is_fleet = payload.get("role") == "fleet"
            blocks.append(
                render_status(
                    f"{'fleet' if is_fleet else kind} {addr}", payload,
                    self._turns_rate(addr, payload),
                )
            )
            if is_fleet:
                # one sub-panel per broker the collector scraped this
                # sweep — the whole cluster behind ONE address
                brokers = (payload.get("fleet") or {}).get(
                    "broker_status") or {}
                for baddr in sorted(brokers):
                    bp = brokers[baddr]
                    blocks.append(render_status(
                        f"broker {baddr} (via fleet)", bp,
                        self._turns_rate(baddr, bp),
                    ))
        return "\n\n".join(blocks), primary_ok


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="live terminal dashboard over the read-only Status verb"
    )
    parser.add_argument(
        "address",
        help="broker host:port (or :port) — or a fleet collector "
             "(obs/fleet.py) address, which renders the whole cluster",
    )
    parser.add_argument(
        "-worker", action="append", default=[], metavar="HOST:PORT",
        help="extra worker to poll beyond the broker's worker_health "
             "roster, which is auto-discovered every frame (repeatable)",
    )
    parser.add_argument(
        "-interval", type=float, default=2.0, metavar="SECONDS",
        help="seconds between polls (default 2)",
    )
    parser.add_argument(
        "-timeout", type=float, default=5.0, metavar="SECONDS",
        help="per-target poll timeout (default 5); a wedged server costs "
             "one interval, never hangs the dashboard",
    )
    parser.add_argument(
        "-once", action="store_true",
        help="render a single frame and exit (scripting hook)",
    )
    parser.add_argument(
        "-no-clear", dest="no_clear", action="store_true",
        help="append frames instead of clearing the screen (logs/pipes)",
    )
    args = parser.parse_args(argv)
    watcher = Watcher(args.address, args.worker, args.timeout)
    try:
        while True:
            frame, ok = watcher.frame()
            if not (args.once or args.no_clear):
                sys.stdout.write(_CLEAR)
            print(frame, flush=True)
            if args.once:
                return 0 if ok else 1
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
