"""Per-tenant usage accounting — who is spending this cluster's capacity.

The serving stack meters the CLUSTER (PR 7 session counters, PR 8 SLO
timelines) but attributes nothing to a TENANT: a noisy client's capacity
rejects, device-seconds, and error-budget burn are indistinguishable from
everyone else's. The multi-tenant front door on the ROADMAP is gated on
exactly that attribution — per-tenant SLO-driven admission needs a ledger
to admit against before it can be built.

**Tenant identity** rides the existing client-chosen ``Request.session_id``
tag (no new wire field): the tag's HIGH 32 bits are the tenant id, the low
32 bits the per-session nonce (``tenant_of``). A plain small tag (high
bits zero — every pre-convention client) is its own tenant, so old
clients attribute per-tag instead of failing. ``0`` / untagged sessions
land on the ``"-"`` tenant.

**Bounded cardinality** is the contract that makes the ledger safe against
a hostile tag flood: at most ``top_k`` tenants are tracked individually;
every tenant past that folds into ONE ``other`` bucket — memory is
O(top_k) regardless of how many distinct tags arrive. (First-K keyed,
not a true heavy-hitter sketch: the tenants that matter arrive early in
practice, and ``other``'s aggregate keeps the totals exact either way.)

**What is attributed, and where:**

* device-seconds + universe-turns — at ``SessionTable.advance`` chunk
  boundaries (engine/sessions.py): each chunk's dispatch wall splits
  evenly over the universes it advanced, so the per-tenant device-second
  sum reconciles exactly with ``gol_session_turn_seconds``'s sum and the
  per-tenant turn sum with ``gol_session_turns_total``.
* admission waits + board bytes in — at ``SessionScheduler.submit``.
* rejects by reason + session errors — the tenant's **SLO-burn
  contribution**: every reject and failed session is an error reply
  against the ``rpc-error-ratio`` budget, so the ledger names who is
  burning it.
* board bytes out — at session completion.

Shipped **incrementally** in ``Status`` like the PR 8 timeline: entries
carry the ledger ``seq`` of their last mutation, and a poller that echoes
``Request.accounting_since`` receives only tenants that changed since
(totals always ride along). Rendered as the watch ``TENANTS`` panel,
folded into RunReport, and fed to ``obs/doctor.py``'s tenant-skew
heuristic.

Like every obs surface: pure stdlib, and **free when metrics are off** —
every record method is one enabled-check and a branch until an entry
point opts in (``-metrics`` / ``-timeline``).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from . import metrics as _metrics
from ..utils import locksan as _locksan

SCHEMA = "gol-accounting/1"

#: the session-tag split: high bits = tenant id, low bits = session nonce
TENANT_SHIFT = 32
#: tenants tracked individually before folding into ``other``
DEFAULT_TOP_K = 16


def tenant_of(tag) -> str:
    """The ledger key for one ``Request.session_id`` tag: the tag's high
    32 bits when set (the packing convention loadgen/canary use), else
    the tag itself (a pre-convention small tag is its own tenant);
    ``"-"`` for untagged/invalid — attribution degrades, never raises."""
    if not isinstance(tag, int) or tag <= 0:
        return "-"
    hi = tag >> TENANT_SHIFT
    return str(hi) if hi else str(tag)


def make_tag(tenant: int, nonce: int) -> int:
    """The inverse convention: pack a tenant id and a per-session nonce
    into one ``session_id`` (nonce forced nonzero so the tag never
    collapses to the untagged 0)."""
    return (int(tenant) << TENANT_SHIFT) | ((int(nonce) & 0xFFFFFFFF) or 1)


class _Usage:
    """One tenant's (or the ``other`` bucket's) running totals."""

    __slots__ = (
        "device_seconds", "turns", "wire_bytes", "sessions",
        "admit_waits", "admit_wait_s", "rejects", "errors", "seq",
    )

    def __init__(self):
        self.device_seconds = 0.0
        self.turns = 0
        self.wire_bytes = 0
        self.sessions = 0
        self.admit_waits = 0
        self.admit_wait_s = 0.0
        self.rejects: Dict[str, int] = {}
        self.errors = 0
        self.seq = 0

    def as_dict(self, tenant: str) -> dict:
        rejects = dict(self.rejects)
        return {
            "tenant": tenant,
            "device_seconds": round(self.device_seconds, 6),
            "turns": self.turns,
            "wire_bytes": self.wire_bytes,
            "sessions": self.sessions,
            "admit_waits": self.admit_waits,
            "admit_wait_s_sum": round(self.admit_wait_s, 6),
            "rejects": rejects,
            "rejects_total": sum(rejects.values()),
            "errors": self.errors,
            "seq": self.seq,
        }


class TenantLedger:
    """Bounded per-tenant usage totals (module docstring). All mutators
    are no-ops while the metrics registry is disabled — the ledger's
    on/off switch is the same ``-metrics`` opt-in as every instrument."""

    # every entry and the seq move together under one lock: a Status
    # window must never pair a bumped seq with a half-applied chunk
    # (machine-enforced: analysis/locks.py)
    _GUARDED_BY = {
        "_tenants": "_lock",
        "_other": "_lock",
        "_seq": "_lock",
        "_overflow_seen": "_lock",
    }

    def __init__(self, top_k: int = DEFAULT_TOP_K):
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        self.top_k = top_k
        self._lock = _locksan.lock("TenantLedger._lock")
        self._tenants: Dict[str, _Usage] = {}
        self._other = _Usage()
        # DISTINCT tenants folded into other — itself bounded (8 x top_k
        # keys, a few KB) so a tag flood can't grow it either: the
        # reported distinct_tenants is exact below the cap and SATURATES
        # at it (a saturated reading IS the flood diagnosis)
        self._overflow_cap = 8 * top_k
        self._overflow_seen: set = set()
        self._seq = 0

    # -- the write surface (each: one enabled-check when metrics are off) --

    def _entry(self, tenant: str) -> _Usage:  # gol: holds(_lock)
        """The tenant's entry, or the ``other`` bucket once ``top_k``
        distinct tenants are tracked (the cardinality bound). Caller
        must hold ``self._lock``."""
        entry = self._tenants.get(tenant)
        if entry is None:
            if len(self._tenants) < self.top_k:
                entry = self._tenants[tenant] = _Usage()
            else:
                if len(self._overflow_seen) < self._overflow_cap:
                    self._overflow_seen.add(tenant)
                entry = self._other
        return entry

    def record_admit(self, tenant: str, wait_s: float, wire_bytes: int) -> None:
        """One admitted session: its admission wait and board bytes in."""
        if not _metrics.enabled():
            return
        with self._lock:
            self._seq += 1
            e = self._entry(tenant)
            e.sessions += 1
            e.admit_waits += 1
            e.admit_wait_s += wait_s
            e.wire_bytes += int(wire_bytes)
            e.seq = self._seq

    def record_chunk(self, tenants, turns: int, wall_s: float) -> None:
        """One batched dispatch: ``turns`` universe-turns for EACH listed
        tenant session, the chunk wall split evenly across them — so the
        ledger's device-second total reconciles with the chunk wall the
        ``gol_session_turn_seconds`` histogram records."""
        if not _metrics.enabled() or not tenants:
            return
        share = wall_s / len(tenants)
        with self._lock:
            self._seq += 1
            for tenant in tenants:
                e = self._entry(tenant)
                e.device_seconds += share
                e.turns += turns
                e.seq = self._seq

    def record_reject(self, tenant: str, reason: str) -> None:
        """One admission refusal — the per-tenant attribution behind the
        anonymous ``gol_sessions_rejected_total{reason}`` pool."""
        if not _metrics.enabled():
            return
        with self._lock:
            self._seq += 1
            e = self._entry(tenant)
            e.rejects[reason] = e.rejects.get(reason, 0) + 1
            e.seq = self._seq

    def record_error(self, tenant: str) -> None:
        """One failed session (error reply to the client) — SLO-burn."""
        if not _metrics.enabled():
            return
        with self._lock:
            self._seq += 1
            e = self._entry(tenant)
            e.errors += 1
            e.seq = self._seq

    def record_reply_bytes(self, tenant: str, nbytes: int) -> None:
        """Board bytes out at session completion."""
        if not _metrics.enabled():
            return
        with self._lock:
            self._seq += 1
            e = self._entry(tenant)
            e.wire_bytes += int(nbytes)
            e.seq = self._seq

    # -- the read surface --------------------------------------------------

    @property
    def has_data(self) -> bool:
        with self._lock:
            return bool(self._tenants) or self._other.seq > 0

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def window(self, since: int = 0) -> dict:
        """The Status payload form: tenants whose last mutation is newer
        than ``since`` (the poller echoes ``Request.accounting_since``,
        exactly like the timeline's ``timeline_since``), sorted by
        device-seconds descending, plus the ``other`` bucket and totals
        (always shipped — they are O(1)). Plain JSON-able: the payload
        crosses the restricted unpickler."""
        with self._lock:
            tenants = [
                e.as_dict(t)
                for t, e in self._tenants.items()
                if e.seq > since
            ]
            other = (
                self._other.as_dict("other")
                if self._other.seq > since else None
            )
            if other is not None:
                other["distinct_tenants"] = len(self._overflow_seen)
            entries = list(self._tenants.values()) + [self._other]
            totals = {
                "device_seconds": round(
                    sum(e.device_seconds for e in entries), 6
                ),
                "turns": sum(e.turns for e in entries),
                "wire_bytes": sum(e.wire_bytes for e in entries),
                "sessions": sum(e.sessions for e in entries),
                "rejects": sum(
                    sum(e.rejects.values()) for e in entries
                ),
                "errors": sum(e.errors for e in entries),
            }
            seq = self._seq
            tracked = len(self._tenants)
        tenants.sort(key=lambda e: -e["device_seconds"])
        return {
            "schema": SCHEMA,
            "seq": seq,
            "top_k": self.top_k,
            "tracked": tracked,
            "tenants": tenants,
            "other": other,
            "totals": totals,
        }

    def totals(self) -> dict:
        """The aggregate row alone (tests, reconciliation checks)."""
        return self.window().get("totals") or {}

    def reset(self) -> None:
        """Zero everything (test/bench isolation, like Registry.reset)."""
        with self._lock:
            self._tenants.clear()
            self._other = _Usage()
            self._overflow_seen.clear()
            self._seq = 0


# -- the process-global default ledger ---------------------------------------

_LEDGER = TenantLedger()


def ledger() -> TenantLedger:
    return _LEDGER
