"""Hot-frame tables, cross-process merges, and profile DIFFS.

    python -m gol_distributed_final_tpu.obs.flame out/profile_run.collapsed
    python -m gol_distributed_final_tpu.obs.flame broker:127.0.0.1:8040 \
        worker:127.0.0.1:8030 worker:127.0.0.1:8031
    python -m gol_distributed_final_tpu.obs.flame -diff \
        out/profile_clean.collapsed out/profile_slow.collapsed
    python -m gol_distributed_final_tpu.obs.flame -diff \
        BENCH_r04.json BENCH_r05.json
    python -m gol_distributed_final_tpu.obs.flame --selfcheck

The render side of obs/profiler.py: every lane the profiler ships
(live Status windows via ``profile_since``, collapsed-stack and
speedscope artifacts, the bench rounds' embedded ``profile_hot``) loads
into one flat shape — frame -> (self hits, cum hits) plus a total — so
tables, merges, and diffs compose across lanes. The diff is the key
tool: frames whose SELF-SHARE of the profile moved more than a noise
threshold between two profiles, regressions first — "what started
eating the wall between these two runs", answered by name.

``--selfcheck`` is the loopback proof the default ``scripts/check``
path runs: spawn a busy-loop subprocess under the profiler, load its
artifact, assert the hot function is named. If the sampler, the trie,
the artifact writer, or this parser breaks, the check names it.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

from .profiler import frame_name, is_idle_frame

#: diff noise floor: self-share moves below this many percentage points
#: are sampling jitter, not findings
DEFAULT_NOISE_PP = 0.5
DEFAULT_TOP = 20


def _empty(source: str) -> dict:
    return {"source": source, "total": 0, "frames": {}}


def parse_frame(name: str):
    """Invert profiler.frame_name: ``func (file:line)`` -> parts.
    Unparseable names come back as (name, "", 0) — foreign collapsed
    files still render and diff, they just can't be idle-filtered."""
    if name.endswith(")") and " (" in name:
        func, _, loc = name[:-1].rpartition(" (")
        file, _, line = loc.rpartition(":")
        if line.isdigit():
            return func, file, int(line)
    return name, "", 0


def _frame_idle(name: str) -> bool:
    func, file, _line = parse_frame(name)
    return is_idle_frame(func, file)


def load_collapsed(path, source: Optional[str] = None) -> dict:
    """A collapsed-stack artifact -> the flat shape. The first path
    token is the thread name (profiler.collapsed_lines writes it) and
    is dropped; self lands on the leaf, cum on every unique frame."""
    prof = _empty(source or str(path))
    for line in pathlib.Path(path).read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count_s = line.rpartition(" ")
        try:
            count = int(count_s)
        except ValueError:
            continue
        frames = stack.split(";")[1:]  # [0] is the thread name
        if not frames:
            continue
        prof["total"] += count
        table = prof["frames"]
        for f in dict.fromkeys(frames):
            table.setdefault(f, [0, 0])[1] += count
        table.setdefault(frames[-1], [0, 0])[0] += count
    return prof


def load_speedscope(path, source: Optional[str] = None) -> dict:
    """A speedscope-JSON artifact -> the flat shape (all profiles of
    the file merged — they are this process's threads)."""
    doc = json.loads(pathlib.Path(path).read_text())
    names = [
        frame_name(f.get("name", "?"), f.get("file", ""), f.get("line", 0))
        for f in (doc.get("shared") or {}).get("frames", [])
    ]
    prof = _empty(source or str(path))
    table = prof["frames"]
    for p in doc.get("profiles", []):
        for sample, weight in zip(p.get("samples", []),
                                  p.get("weights", [])):
            if not sample:
                continue
            prof["total"] += weight
            stack = [names[i] for i in sample if 0 <= i < len(names)]
            for f in dict.fromkeys(stack):
                table.setdefault(f, [0, 0])[1] += weight
            if stack:
                table.setdefault(stack[-1], [0, 0])[0] += weight
    return prof


def load_bench_round(path, source: Optional[str] = None) -> dict:
    """A BENCH_r*.json round -> the flat shape, from the profiler
    case's embedded ``profile_hot`` table (``{"frame", "self_share"}``
    rows — bench.py embeds them on the profiler-on wire case).
    Self-shares scale to a synthetic total of 10000 so round-vs-round
    diffs use the same share math as artifact diffs. Reuses the regress
    loader, so driver-wrapped and tail-salvaged rounds load here too."""
    from .regress import load_bench

    prof = _empty(source or str(path))
    for case in load_bench(path)["cases"].values():
        hot = case.get("profile_hot")
        if not isinstance(hot, list) or not hot:
            continue
        prof["total"] = 10000
        for row in hot:
            if not isinstance(row, dict) or "frame" not in row:
                continue
            share = float(row.get("self_share") or 0.0)
            prof["frames"][str(row["frame"])] = [
                int(round(share * 10000)), 0
            ]
        break
    return prof


def load_live(address: str, worker: bool = False,
              timeout: float = 5.0) -> dict:
    """A live process's profile via Status (full window: since=0)."""
    from .status import fetch_status

    payload = fetch_status(
        address, worker=worker, timeout=timeout, profile_since=0
    )
    window = payload.get("profile")
    if not isinstance(window, dict):
        raise RuntimeError(
            f"{address} answered Status but ships no profile window "
            "(started without -profile, or version skew)"
        )
    return from_window(window, source=f"live {address}")


def from_window(window: dict, source: str = "live") -> dict:
    """A Status profile window -> the flat shape."""
    prof = _empty(source)
    prof["total"] = int(window.get("stacks") or 0)
    for row in window.get("frames") or []:
        name = frame_name(
            row.get("func", "?"), row.get("file", ""), row.get("line", 0)
        )
        prof["frames"][name] = [
            int(row.get("self") or 0), int(row.get("cum") or 0)
        ]
    return prof


def load_source(source: str, timeout: float = 5.0) -> dict:
    """One CLI source string -> the flat shape. ``broker:ADDR`` /
    ``worker:ADDR`` poll live; anything else is an artifact path
    (collapsed, speedscope JSON, or a BENCH round)."""
    if source.startswith("broker:"):
        return load_live(source[7:], worker=False, timeout=timeout)
    if source.startswith("worker:"):
        return load_live(source[7:], worker=True, timeout=timeout)
    path = pathlib.Path(source)
    name = path.name
    if name.endswith(".collapsed"):
        return load_collapsed(path)
    if name.startswith("BENCH") and name.endswith(".json"):
        return load_bench_round(path)
    if name.endswith(".json"):
        return load_speedscope(path)
    return load_collapsed(path)


def merge_profiles(profiles: List[dict], source: str = "merged") -> dict:
    """Sum flat profiles — the cross-process view of a cluster run."""
    out = _empty(source)
    out["source"] = ", ".join(p["source"] for p in profiles) or source
    for p in profiles:
        out["total"] += p["total"]
        for name, (s, c) in p["frames"].items():
            row = out["frames"].setdefault(name, [0, 0])
            row[0] += s
            row[1] += c
    return out


def hot_rows(profile: dict, top: int = DEFAULT_TOP,
             active_only: bool = False) -> List[dict]:
    """The table form: hottest self first, shares over the total."""
    total = max(profile["total"], 1)
    rows = [
        {
            "frame": name,
            "self": s,
            "cum": c,
            "self_share": s / total,
            "cum_share": c / total,
            "idle": _frame_idle(name),
        }
        for name, (s, c) in profile["frames"].items()
        if s or c
    ]
    if active_only:
        rows = [r for r in rows if not r["idle"]]
    rows.sort(key=lambda r: (-r["self"], -r["cum"], r["frame"]))
    return rows[:top]


def diff_profiles(old: dict, new: dict,
                  noise_pp: float = DEFAULT_NOISE_PP,
                  active_only: bool = False) -> List[dict]:
    """Frames whose SELF-SHARE moved more than ``noise_pp`` percentage
    points between two profiles, biggest regression first. Shares (not
    raw hits) so profiles of different lengths diff honestly; a frame
    absent from one side diffs against share 0."""
    old_total = max(old["total"], 1)
    new_total = max(new["total"], 1)
    names = set(old["frames"]) | set(new["frames"])
    out = []
    for name in names:
        if active_only and _frame_idle(name):
            continue
        a = old["frames"].get(name, (0, 0))[0] / old_total
        b = new["frames"].get(name, (0, 0))[0] / new_total
        delta_pp = (b - a) * 100.0
        if abs(delta_pp) <= noise_pp:
            continue
        out.append({
            "frame": name,
            "old_share": round(a, 4),
            "new_share": round(b, 4),
            "delta_pp": round(delta_pp, 2),
        })
    out.sort(key=lambda r: (-r["delta_pp"], r["frame"]))
    return out


# -- rendering ----------------------------------------------------------------


def render_table(profile: dict, top: int = DEFAULT_TOP,
                 active_only: bool = False) -> str:
    rows = hot_rows(profile, top=top, active_only=active_only)
    lines = [
        f"profile {profile['source']}: {profile['total']} stack sample(s), "
        f"{len(profile['frames'])} frame(s)"
        + (" [active only]" if active_only else ""),
        f"  {'self%':>6} {'cum%':>6} {'hits':>8}  frame",
    ]
    for r in rows:
        mark = " ~" if r["idle"] else ""
        lines.append(
            f"  {100 * r['self_share']:>5.1f}% {100 * r['cum_share']:>5.1f}% "
            f"{r['self']:>8}  {r['frame']}{mark}"
        )
    if not rows:
        lines.append("  (no samples)")
    return "\n".join(lines)


def render_diff(movers: List[dict], old: dict, new: dict,
                top: int = DEFAULT_TOP, noise_pp: float = DEFAULT_NOISE_PP
                ) -> str:
    lines = [
        f"diff {old['source']} -> {new['source']} "
        f"({old['total']} -> {new['total']} samples, "
        f"noise floor {noise_pp:.2f}pp):",
    ]
    if not movers:
        lines.append(
            "  no frame's self-share moved past the noise floor"
        )
        return "\n".join(lines)
    lines.append(f"  {'old%':>6} {'new%':>6} {'delta':>8}  frame")
    for r in movers[:top]:
        lines.append(
            f"  {100 * r['old_share']:>5.1f}% {100 * r['new_share']:>5.1f}% "
            f"{r['delta_pp']:>+7.2f}pp  {r['frame']}"
        )
    if len(movers) > top:
        lines.append(f"  ... {len(movers) - top} more mover(s)")
    return "\n".join(lines)


# -- selfcheck ----------------------------------------------------------------

#: the child's workload: a named busy loop the parent must find by name
_SELFCHECK_CODE = """
import sys, time
from gol_distributed_final_tpu.obs import profiler

def selfcheck_spin(deadline):
    x = 0
    while time.perf_counter() < deadline:
        x = (x * 1103515245 + 12345) % (2 ** 31)
    return x

p = profiler.enable(period_ms=2.0, out_dir=sys.argv[1], tag="selfcheck")
selfcheck_spin(time.perf_counter() + float(sys.argv[2]))
p.stop()
paths = p.write_artifacts(sys.argv[1], "selfcheck")
profiler.disable()
print(paths[0])
"""


def selfcheck(spin_s: float = 0.8, verbose: bool = True) -> int:
    """Sample a busy-loop subprocess end to end; assert the hot
    function is named in its artifact. Returns 0 on success."""
    with tempfile.TemporaryDirectory(prefix="gol-flame-") as td:
        proc = subprocess.run(
            [sys.executable, "-c", _SELFCHECK_CODE, td, str(spin_s)],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=str(pathlib.Path(__file__).resolve().parent.parent.parent),
        )
        if proc.returncode != 0:
            print(
                f"flame selfcheck FAIL: child exited {proc.returncode}\n"
                f"{proc.stderr}", file=sys.stderr,
            )
            return 1
        artifact = proc.stdout.strip().splitlines()[-1]
        prof = load_collapsed(artifact)
        rows = hot_rows(prof, top=3, active_only=True)
        hot = rows[0]["frame"] if rows else "<none>"
        if "selfcheck_spin" not in hot:
            print(
                f"flame selfcheck FAIL: expected selfcheck_spin as the "
                f"hot frame, got {hot!r} "
                f"({prof['total']} samples)", file=sys.stderr,
            )
            return 1
        if verbose:
            print(
                f"flame selfcheck ok: {hot} holds "
                f"{100 * rows[0]['self_share']:.0f}% of "
                f"{prof['total']} samples"
            )
        return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="render/merge/diff continuous profiles "
                    "(obs/profiler.py artifacts, live -profile "
                    "endpoints, BENCH rounds)"
    )
    parser.add_argument(
        "sources", nargs="*", metavar="SOURCE",
        help="profile sources, merged: an artifact path (.collapsed / "
             ".speedscope.json / BENCH_r*.json) or a live endpoint "
             "(broker:HOST:PORT, worker:HOST:PORT)",
    )
    parser.add_argument(
        "-diff", nargs=2, metavar=("OLD", "NEW"), default=None,
        help="diff two sources instead: frames whose self-share moved "
             "past the noise floor, regressions first",
    )
    parser.add_argument(
        "-top", type=int, default=DEFAULT_TOP, metavar="N",
        help=f"rows rendered (default {DEFAULT_TOP})",
    )
    parser.add_argument(
        "-active", action="store_true",
        help="exclude parked frames (accept/select/wait leaves) — the "
             "busy view",
    )
    parser.add_argument(
        "-noise", type=float, default=DEFAULT_NOISE_PP, metavar="PP",
        help="diff noise floor in percentage points of self-share "
             f"(default {DEFAULT_NOISE_PP})",
    )
    parser.add_argument(
        "-out", default=None, metavar="PATH",
        help="also write the merged profile as a collapsed artifact",
    )
    parser.add_argument(
        "-timeout", type=float, default=5.0, metavar="SECS",
        help="bound per live Status fetch (default 5)",
    )
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="loopback check: profile a busy-loop subprocess, assert "
             "the hot function is named",
    )
    args = parser.parse_args(argv)
    if args.selfcheck:
        return selfcheck()
    if args.diff:
        try:
            old = load_source(args.diff[0], timeout=args.timeout)
            new = load_source(args.diff[1], timeout=args.timeout)
        except Exception as exc:
            print(f"flame: cannot load profile: {exc}", file=sys.stderr)
            return 1
        movers = diff_profiles(
            old, new, noise_pp=args.noise, active_only=args.active
        )
        print(render_diff(movers, old, new, top=args.top,
                          noise_pp=args.noise))
        return 0
    if not args.sources:
        parser.error("need at least one SOURCE (or -diff / --selfcheck)")
    profiles = []
    for s in args.sources:
        try:
            profiles.append(load_source(s, timeout=args.timeout))
        except Exception as exc:
            print(f"flame: cannot load {s}: {exc}", file=sys.stderr)
            return 1
    prof = profiles[0] if len(profiles) == 1 else merge_profiles(profiles)
    print(render_table(prof, top=args.top, active_only=args.active))
    if args.out:
        out = pathlib.Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        lines = [
            f"merged;{name} {s}"
            for name, (s, _c) in sorted(prof["frames"].items()) if s
        ]
        tmp = out.with_name(out.name + ".tmp")
        tmp.write_text("\n".join(lines) + "\n")
        tmp.replace(out)
        print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
