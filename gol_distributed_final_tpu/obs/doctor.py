"""Cluster triage doctor — one shot from symptoms to a ranked diagnosis.

    python -m gol_distributed_final_tpu.obs.doctor tcp://127.0.0.1:8040
    python -m gol_distributed_final_tpu.obs.doctor :8040 \\
        -worker :8030 -worker :8031 -out out

PR 4/6 made failures *detectable* (worker_health, quarantine backoff,
integrity counters, flight events); this CLI makes them *explained*: it
pulls ``Status`` from the broker and its workers (auto-discovered from
the ``worker_health`` roster; ``-worker`` adds extras) — or from a
fleet collector (obs/fleet.py), whose per-broker payloads are expanded
and whose scrape health becomes findings — correlates timelines,
flight rings, span statistics, worker health, and active SLO alerts into
a ranked finding list ("worker :8041 quarantined 3x, resync counter
climbing, wire bytes/turn 12x baseline -> suspect flapping transport"),
prints a terminal report, and writes ``out/doctor_<ts>.json`` so the
diagnosis is an artifact, not scrollback.

Built ENTIRELY on the read-only Status surface (the obs/watch.py
posture): attachable to a live, degraded, or wedged cluster; every
payload read goes through ``dict.get`` so version skew renders a gap,
never a crash. The correlation core (``diagnose``) is a pure function of
the fetched payloads — unit-testable on canned multi-process fixtures.

``--selfcheck`` spins a loopback broker in-process, runs a tiny job,
polls and diagnoses it, and fails on an empty or unrenderable diagnosis
— the ``scripts/check --doctor`` smoke gate.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time
from typing import Dict, List, Optional, Tuple

from .status import fetch_many
from .status import norm_address as _norm_addr
from .status import scalar_value as _scalar
from .status import series_map as _series_map

SCHEMA = "gol-doctor/1"

_SEVERITY_ORDER = {"page": 0, "warn": 1, "info": 2}


def collect(
    broker: str, workers: List[str], timeout: float = 5.0
) -> Dict[str, dict]:
    """One PARALLEL Status poll per target (``status.fetch_many`` — a
    wedged target costs one timeout, not the whole round). Failed polls
    become ``{"error": ...}`` entries — a dead worker is EVIDENCE, not a
    fetch failure.

    Workers are auto-discovered from each broker payload's
    ``worker_health`` roster (manual ``-worker`` flags stay additive
    extras), and a fleet collector payload (obs/fleet.py,
    ``role="fleet"``) is EXPANDED: every broker Status it scraped this
    sweep is diagnosed as if polled directly, so one address triages the
    whole cluster."""
    specs: List[dict] = []
    seen = set()
    for addr, is_worker in [(broker, False)] + [(w, True) for w in workers]:
        addr = _norm_addr(addr)
        if addr not in seen:
            seen.add(addr)
            specs.append({"address": addr, "worker": is_worker})
    results = fetch_many(specs, timeout=timeout)
    discovered: List[dict] = []
    for spec in specs:
        payload = (results.get(spec["address"]) or (None,))[0]
        if payload is None or spec["worker"]:
            continue
        for entry in payload.get("workers") or []:
            if not isinstance(entry, dict):
                continue
            waddr = entry.get("address")
            if not isinstance(waddr, str) or ":" not in waddr:
                continue
            waddr = _norm_addr(waddr)
            if waddr not in seen:
                seen.add(waddr)
                discovered.append({"address": waddr, "worker": True})
    if discovered:
        results.update(fetch_many(discovered, timeout=timeout))
        specs.extend(discovered)
    statuses: Dict[str, dict] = {}
    for spec in specs:
        addr = spec["address"]
        kind = "worker" if spec["worker"] else "broker"
        payload, _fetched_at, error = results.get(addr) or (
            None, 0.0, "no result")
        if error is not None:
            statuses[f"{kind} {addr}"] = {"error": f"poll failed: {error}"}
            continue
        if payload.get("role") == "fleet":
            statuses[f"fleet {addr}"] = payload
            brokers = (payload.get("fleet") or {}).get("broker_status") or {}
            for baddr in sorted(brokers):
                statuses.setdefault(f"broker {baddr}", brokers[baddr])
        else:
            statuses[f"{kind} {addr}"] = payload
    return statuses


def _label_total(snap: dict, name: str) -> Tuple[float, Dict[str, float]]:
    """(sum across label children, {label0: value}) for one counter."""
    by = {}
    for labels, s in _series_map(snap, name).items():
        v = s.get("value") or 0.0
        if v:
            by[labels[0] if labels else "?"] = v
    return sum(by.values()), by


def _finding(severity: str, score: float, title: str, detail: str,
             evidence: List[str], suspects: List[str],
             target: str) -> dict:
    return {
        "severity": severity,
        "score": round(score, 2),
        "title": title,
        "detail": detail,
        "evidence": evidence,
        "suspects": sorted(set(suspects)),
        "target": target,
    }


# -- correlation heuristics (each: payloads -> findings) ---------------------


def _flight_counts(payload: dict, kind: str) -> Dict[str, int]:
    """Occurrences of one flight-event kind by name (e.g. how many times
    each worker address appears in ``worker.lost`` events)."""
    out: Dict[str, int] = {}
    for ev in payload.get("flight") or []:
        if ev.get("kind") == kind:
            name = str(ev.get("name", "?"))
            out[name] = out.get(name, 0) + 1
    return out


def _find_unreachable(statuses) -> List[dict]:
    out = []
    for label, payload in statuses.items():
        if "error" in payload:
            sev = "page" if label.startswith("broker") else "warn"
            out.append(_finding(
                sev, 100.0 if sev == "page" else 60.0,
                f"{label} unreachable",
                str(payload["error"]),
                [f"Status poll failed: {payload['error']}"],
                [label.split(" ", 1)[-1]],
                label,
            ))
    return out


def _find_lost_workers(statuses) -> List[dict]:
    """The flapping-transport correlation: roster health + per-address
    loss/quarantine history + resync + wire-byte amplification."""
    out = []
    for label, payload in statuses.items():
        roster = payload.get("workers") or []
        lost = [w for w in roster if w.get("state") == "lost"]
        if not lost:
            continue
        snap = payload.get("metrics") or {}
        loss_events = _flight_counts(payload, "worker.lost")
        readmits = _scalar(snap, "gol_worker_readmitted_total") or 0
        resyncs = _scalar(snap, "gol_strip_resync_total") or 0
        retries = _scalar(snap, "gol_turn_retry_total") or 0
        turns = _scalar(snap, "gol_engine_turns_total")
        wire_total, _ = _label_total(snap, "gol_wire_bytes_total")
        for w in lost:
            addr = w.get("address", "?")
            losses = loss_events.get(addr, 0)
            evidence = [f"roster marks {addr} lost"]
            retry = w.get("retry_in_s")
            if retry is not None:
                evidence.append(f"next readmission probe in {retry}s")
            if losses:
                evidence.append(
                    f"flight ring shows {losses} loss event(s) for {addr}"
                )
            if readmits:
                evidence.append(f"{int(readmits)} readmission(s) so far")
            if resyncs:
                evidence.append(
                    f"strip resync counter at {int(resyncs)} and climbing "
                    "with each loss"
                )
            if retries:
                evidence.append(f"{int(retries)} turn retr(ies) paid")
            flapping = losses >= 2 or (losses >= 1 and readmits >= 1)
            if flapping:
                title = (
                    f"worker {addr} quarantined {losses}x — suspect "
                    "flapping transport"
                )
                detail = (
                    "repeat loss/readmit cycles: each readmission taxes "
                    "the next turn a scatter deadline; the probe backoff "
                    "is escalating. Check the network path or restart "
                    "the worker."
                )
            else:
                title = f"worker {addr} lost from the scatter set"
                detail = (
                    "the broker re-split its rows over the survivors; "
                    "the readmission probe is dialling it."
                )
            if wire_total and turns:
                evidence.append(
                    f"wire bytes/turn currently "
                    f"{wire_total / max(turns, 1):,.0f}"
                )
            out.append(_finding(
                "page", 90.0 + 5.0 * losses, title, detail,
                evidence, [addr], label,
            ))
    return out


def _find_alerts(statuses) -> List[dict]:
    out = []
    for label, payload in statuses.items():
        for alert in payload.get("alerts") or []:
            if alert.get("state") != "firing":
                continue
            sev = alert.get("severity", "warn")
            if sev not in _SEVERITY_ORDER:
                sev = "warn"
            since = alert.get("since_unix")
            age = (
                f"for {time.time() - since:.0f}s"
                if isinstance(since, (int, float)) and since else "now"
            )
            out.append(_finding(
                sev, 80.0 if sev == "page" else 50.0,
                f"SLO rule '{alert.get('rule', '?')}' firing {age}",
                str(alert.get("detail", "")),
                [f"server-side evaluation: {alert.get('detail', '')}"],
                [], label,
            ))
    return out


def _find_integrity(statuses) -> List[dict]:
    out = []
    for label, payload in statuses.items():
        snap = payload.get("metrics") or {}
        total, by_kind = _label_total(snap, "gol_integrity_failures_total")
        if not total:
            continue
        kinds = ", ".join(f"{k} {int(v)}" for k, v in sorted(by_kind.items()))
        suspects = sorted(_flight_counts(payload, "integrity.fail"))
        out.append(_finding(
            "page", 95.0,
            f"{int(total)} integrity failure(s) caught ({kinds})",
            "corrupted data was DETECTED and quarantined, never served; "
            "the suspect worker(s) were routed through loss recovery.",
            [f"gol_integrity_failures_total{{{kinds}}}"]
            + [f"flight names suspect {s}" for s in suspects],
            suspects, label,
        ))
    return out


def _find_error_ratio(statuses) -> List[dict]:
    out = []
    for label, payload in statuses.items():
        snap = payload.get("metrics") or {}
        errs, by_verb = _label_total(snap, "gol_rpc_server_errors_total")
        reqs, _ = _label_total(snap, "gol_rpc_server_requests_total")
        if not reqs or not errs:
            continue
        ratio = errs / reqs
        if ratio <= 0.01:
            continue
        verbs = ", ".join(
            f"{k.rsplit('.', 1)[-1]} {int(v)}"
            for k, v in sorted(by_verb.items())
        )
        out.append(_finding(
            "warn", 55.0 + min(30.0, 100.0 * ratio),
            f"RPC error ratio {100 * ratio:.1f}% ({int(errs)}/{int(reqs)})",
            f"error replies by verb: {verbs}",
            [f"gol_rpc_server_errors_total / _requests_total = {ratio:.4f}"],
            [], label,
        ))
    return out


def _rate_from_timeline(payload: dict, metric: str) -> Optional[float]:
    """The server-computed rate for one summary entry. The summary DROPS
    zero-increase counters (obs/timeline.py keeps it small), so when the
    timeline payload exists but the entry is absent, the truthful answer
    is 0.0 — exactly the stalled case; None only when the server ships
    no timeline at all (can't judge)."""
    tl = payload.get("timeline")
    if not isinstance(tl, dict):
        return None
    entry = (tl.get("summary") or {}).get(metric)
    if isinstance(entry, dict):
        return entry.get("rate_per_s")
    return 0.0


def _find_stall(statuses) -> List[dict]:
    """A process whose turn counters have history but a ~zero recent
    rate: wedged or starved, the flight tail names its last act."""
    out = []
    for label, payload in statuses.items():
        snap = payload.get("metrics") or {}
        turns = _scalar(snap, "gol_engine_turns_total")
        if not turns:
            continue
        rate = _rate_from_timeline(payload, "gol_engine_turns_total")
        if rate is None or rate > 0.01:
            continue
        tail = [
            f"last act: {ev.get('kind', '?')} {ev.get('name', '?')}"
            for ev in (payload.get("flight") or [])[-3:]
        ]
        out.append(_finding(
            "warn", 65.0,
            f"turn counter stalled at {int(turns)}",
            "the engine evolved turns earlier but the server-side "
            "timeline shows a ~zero recent rate — wedged, paused, or "
            "the run ended.",
            [f"timeline rate {rate:.4f} turns/s over the summary window"]
            + tail,
            [], label,
        ))
    return out


def _find_tenant_skew(statuses) -> List[dict]:
    """The hot-tenant correlation (the 'names the flapping worker'
    pattern, applied to the accounting ledger): one tenant holding the
    majority of device-seconds, or driving the dominant reject / burn
    share, is named with its ledger evidence rows — the operator's
    first question when the error budget burns is WHO."""
    out = []
    for label, payload in statuses.items():
        acct = payload.get("accounting") or {}
        tenants = acct.get("tenants") or []
        other = acct.get("other")
        totals = acct.get("totals") or {}
        entries = tenants + ([other] if other else [])
        if len(entries) < 2:
            continue  # one tenant IS 100% of everything — not skew

        def ev(e: dict) -> str:
            return (
                f"tenant {e.get('tenant', '?')}: "
                f"{e.get('device_seconds') or 0.0:.3f} dev-s, "
                f"{int(e.get('turns') or 0)} turns, "
                f"{int(e.get('rejects_total') or 0)} reject(s), "
                f"{int(e.get('errors') or 0)} error(s)"
            )

        total_dev = totals.get("device_seconds") or 0.0
        top = max(entries, key=lambda e: e.get("device_seconds") or 0.0)
        if total_dev > 0:
            share = (top.get("device_seconds") or 0.0) / total_dev
            if share > 0.5 and top is not other:
                out.append(_finding(
                    "warn", 64.0 + 20.0 * share,
                    f"tenant {top.get('tenant', '?')} holds "
                    f"{100 * share:.0f}% of device-seconds",
                    "one tenant dominates the batch's capacity: every "
                    "other tenant's admission waits and turn latency "
                    "ride behind it. Per-tenant admission quotas are "
                    "the fix the ROADMAP front door plans.",
                    [ev(e) for e in entries[:3]]
                    + [f"ledger totals: {total_dev:.3f} dev-s, "
                       f"{int(totals.get('turns') or 0)} turns"],
                    [f"tenant {top.get('tenant', '?')}"], label,
                ))
        total_rej = totals.get("rejects") or 0
        total_err = totals.get("errors") or 0
        burn_total = total_rej + total_err
        if burn_total >= 5:
            hot = max(
                entries,
                key=lambda e: (e.get("rejects_total") or 0)
                + (e.get("errors") or 0),
            )
            hot_burn = (hot.get("rejects_total") or 0) + (hot.get("errors") or 0)
            if hot_burn / burn_total > 0.5 and hot is not other:
                reasons = ", ".join(
                    f"{k} {v}"
                    for k, v in sorted((hot.get("rejects") or {}).items())
                ) or "errors only"
                out.append(_finding(
                    "warn", 60.0,
                    f"tenant {hot.get('tenant', '?')} drives "
                    f"{100 * hot_burn / burn_total:.0f}% of the "
                    "reject/error burn",
                    "the error-budget burn is one tenant's traffic "
                    f"({reasons}), not global overload: shed or quota "
                    "that tenant before raising -session-capacity.",
                    [ev(hot)]
                    + [f"cluster burn: {total_rej} reject(s) + "
                       f"{total_err} error(s)"],
                    [f"tenant {hot.get('tenant', '?')}"], label,
                ))
    return out


def _find_straggler(statuses) -> List[dict]:
    """The critical-path correlation (obs/critical.py snapshot riding the
    broker's Status): a worker that persistently GATES the K-batch
    gather — slow, not failed, so nothing else pages — is named with
    per-address service-time evidence rows. This is the finding that
    explains 'the cluster is healthy but turns are slow'."""
    out = []
    for label, payload in statuses.items():
        cp = payload.get("critical_path") or {}
        s = cp.get("straggler")
        if not s:
            continue
        rows = [
            f"{w.get('addr', '?')}: service ewma "
            f"{(w.get('ewma_s') or 0.0) * 1e3:.1f} ms, gated "
            f"{w.get('gated', 0)}/{cp.get('batches', 0)} batch(es) "
            f"({100 * (w.get('gated_share') or 0.0):.0f}%)"
            for w in cp.get("workers") or []
        ]
        out.append(_finding(
            "warn",
            85.0 + min(10.0, 2.0 * (s.get("skew") or 0.0)),
            f"worker {s.get('addr', '?')} is the persistent straggler — "
            f"gated {100 * (s.get('gated_share') or 0.0):.0f}% of "
            f"{cp.get('batches', 0)} K-batch gather(s)",
            "every fan-out turn completes at the slowest worker: this "
            f"one runs at {s.get('skew', 0.0):.1f}x the roster's median "
            "service time, so it sets the whole cluster's turn rate. "
            "Nothing has failed, so only this attribution sees it. "
            "Rebalance its strip share, or drain and replace the host.",
            rows,
            [s.get("addr", "?")],
            label,
        ))
    return out


def _find_hbm(statuses) -> List[dict]:
    out = []
    for label, payload in statuses.items():
        snap = payload.get("metrics") or {}
        in_use = _series_map(snap, "gol_device_hbm_bytes_in_use")
        limits = _series_map(snap, "gol_device_hbm_bytes_limit")
        for labels, s in in_use.items():
            used = s.get("value") or 0
            cap = (limits.get(labels) or {}).get("value") or 0
            if cap and used / cap > 0.9:
                dev = labels[0] if labels else "?"
                out.append(_finding(
                    "warn", 70.0,
                    f"device {dev} HBM at {100 * used / cap:.0f}%",
                    "the next admission or chunk growth may OOM; shrink "
                    "-session-capacity or the board.",
                    [f"gol_device_hbm_bytes_in_use {used:.3g} / {cap:.3g}"],
                    [], label,
                ))
    return out


def _find_checkpoint(statuses) -> List[dict]:
    out = []
    for label, payload in statuses.items():
        snap = payload.get("metrics") or {}
        errs = _scalar(snap, "gol_engine_checkpoint_errors_total") or 0
        ck = _series_map(snap, "gol_ckpt_verify_total")
        bad = (ck.get(("fail",)) or {}).get("value") or 0
        if not errs and not bad:
            continue
        evidence = []
        if errs:
            evidence.append(f"{int(errs)} periodic checkpoint write failure(s)")
        if bad:
            evidence.append(f"{int(bad)} checkpoint digest verification failure(s)")
        out.append(_finding(
            "warn", 60.0,
            "checkpoint trouble: crash-recovery coverage is degraded",
            "the run continues, but a crash now may lose more turns than "
            "-auto-checkpoint promises; check disk space and the "
            "-ckpt-keep generations.",
            evidence, [], label,
        ))
    return out


#: where _find_journal reads persisted segments from — module-level so
#: tests (and the selfcheck's tmp dir) can point it elsewhere
_JOURNAL_DIR = "out"


def _find_journal(statuses) -> List[dict]:
    """Journal-fed findings that SURVIVE restarts: unlike every other
    heuristic (which reads live Status payloads), this one reads the
    on-disk journal segments (obs/journal.py) — so a worker that flapped
    three times YESTERDAY, under a broker that has since restarted and
    forgotten, still surfaces. Two findings:

    * repeat-loss/flap correlation: an address with repeated
      lost->readmitted cycles across the whole persisted history;
    * torn/corrupted records: crc-detected damage in the segments
      themselves (a SIGKILL mid-append) — loud, never silent."""
    from . import journal as _jn

    events, problems = _jn.read_segments(_JOURNAL_DIR)
    # fold in live in-memory tails when the polled processes ship them
    # (events not yet flushed to a segment)
    for label, payload in statuses.items():
        jw = payload.get("journal")
        if isinstance(jw, dict) and isinstance(jw.get("events"), list):
            events.extend(e for e in jw["events"] if isinstance(e, dict))
    out = []
    losses: Dict[str, int] = {}
    readmits: Dict[str, int] = {}
    seen_ev = set()
    for e in events:
        key = (_jn.event_node(e), e.get("seq"), e.get("kind"))
        if key in seen_ev:
            continue  # an event in both a live tail and a segment
        seen_ev.add(key)
        if e.get("kind") == "worker.lost":
            losses[e.get("name", "?")] = losses.get(e.get("name", "?"), 0) + 1
        elif e.get("kind") == "worker.readmit":
            readmits[e.get("name", "?")] = (
                readmits.get(e.get("name", "?"), 0) + 1
            )
    for addr, n in sorted(losses.items(), key=lambda kv: -kv[1]):
        if n < 2:
            continue
        back = readmits.get(addr, 0)
        out.append(_finding(
            "warn", 75.0 + min(15.0, 3.0 * n),
            f"worker {addr} flapped: {n} losses / {back} readmissions "
            "across the persisted journal history",
            "repeat lost->readmitted cycles — a flapper taxes every turn "
            "a deadline when admitted. This evidence comes from the "
            "on-disk journal segments, so it survives broker restarts "
            "that reset the live loss counters. Quarantine backoff is "
            "escalating (worker.quarantine events); consider draining "
            "the host.",
            [f"journal: {n} worker.lost, {back} worker.readmit for {addr}"],
            [addr], "journal",
        ))
    if problems:
        out.append(_finding(
            "warn", 55.0,
            f"{len(problems)} damaged journal record(s)/segment(s) "
            "detected (crc)",
            "torn tails are expected after a SIGKILL mid-append — the "
            "surviving records still reconstruct; repeated damage on a "
            "LIVE process suggests disk trouble.",
            problems[:8], [], "journal",
        ))
    return out


def _find_hotspot(statuses) -> List[dict]:
    """The profile x decomposition join (obs/profiler.py x obs/perf.py):
    when a ``-profile`` target's busy samples concentrate in one frame,
    NAME it — and when the PR 12 segment decomposition also has a
    dominant segment, say which wall that code is ("host_prep 58% of the
    turn; 71% of busy samples in pickle.dumps"). Idle leaves (accept/
    select/wait) are excluded: a parked server thread is not a hotspot."""
    from .perf import decomposition_summary
    from .profiler import is_idle_frame

    out = []
    for label, payload in statuses.items():
        pw = payload.get("profile")
        if not isinstance(pw, dict):
            continue
        stacks = pw.get("stacks") or 0
        busy = [
            r for r in pw.get("frames") or []
            if isinstance(r, dict) and (r.get("self") or 0) > 0
            and not is_idle_frame(
                str(r.get("func", "")), str(r.get("file", ""))
            )
        ]
        if stacks < 20 or not busy:
            continue  # too few samples to name anything honestly
        busy_total = sum(r.get("self") or 0 for r in busy)
        if not busy_total:
            continue
        top = busy[0]  # windows ship hottest-self-first
        share = (top.get("self") or 0) / busy_total
        if share < 0.25:
            continue
        func = str(top.get("func", "?"))
        where = f"{top.get('file', '?')}:{top.get('line', '?')}"
        evidence = [
            f"{top.get('self')} of {busy_total} busy sample(s) "
            f"({share:.0%}) at {func} ({where}); {stacks} stacks total "
            f"@ {pw.get('period_ms', '?')}ms cadence"
        ]
        for hs in pw.get("hot_stacks") or []:
            # caller context: the hottest leaf path through this frame —
            # a leaf alone (e.g. a helper) can be ambiguous
            if isinstance(hs, dict) and func in str(hs.get("stack", "")):
                evidence.append(
                    f"hot path ({hs.get('self')} hit(s)): {hs['stack']}"
                )
                break
        seg_note = ""
        decomp = decomposition_summary(payload.get("metrics") or {})
        hot_seg, hot_share, hot_comp = None, 0.0, None
        for comp, segs in decomp.items():
            for seg, e in segs.items():
                if not seg.startswith("_") and isinstance(e, dict) \
                        and e.get("share", 0) > hot_share:
                    hot_seg, hot_share, hot_comp = seg, e["share"], comp
        if hot_seg and hot_share >= 0.4:
            seg_note = (
                f" while segment '{hot_seg}' holds {hot_share:.0%} of "
                f"{hot_comp}'s decomposed wall"
            )
            evidence.append(
                f"gol_turn_segment_seconds: {hot_comp}/{hot_seg} "
                f"share {hot_share:.0%}"
            )
        out.append(_finding(
            "warn", 40.0 + 55.0 * share,
            f"hotspot: {func} holds {share:.0%} of busy samples",
            f"the continuous profiler names {func} ({where}) as the "
            f"dominant busy frame{seg_note}. If this is unexpected, "
            "diff against a clean run: python -m "
            "gol_distributed_final_tpu.obs.flame -diff OLD NEW.",
            evidence, [], label,
        ))
    return out


def _find_fleet_targets(statuses) -> List[dict]:
    """Fleet scrape-health findings (obs/fleet.py collector payloads): a
    STALE target is a dead process named WITH its scrape evidence —
    last-success age, consecutive-failure count, the last error string —
    and a stale BROKER outranks every other page (a broker the fleet
    lost is the first thing to fix). Failing-but-not-yet-stale targets
    and merge-excluded (version-skewed) snapshots warn."""
    out = []
    for label, payload in statuses.items():
        fl = payload.get("fleet")
        if not isinstance(fl, dict):
            continue
        for t in fl.get("targets") or []:
            state = t.get("state")
            if state not in ("stale", "failing"):
                continue
            addr = str(t.get("address", "?"))
            kind = "worker" if t.get("worker") else "broker"
            fails = int(t.get("consecutive_failures") or 0)
            age = t.get("last_success_age_s")
            evidence = [
                f"scrape health: {fails} consecutive failure(s), "
                f"{int(t.get('ok_total') or 0)} ok / "
                f"{int(t.get('err_total') or 0)} error(s) lifetime",
                "last successful scrape: "
                + (f"{age:.1f}s ago" if isinstance(age, (int, float))
                   else "never"),
            ]
            if t.get("error"):
                evidence.append(f"last scrape error: {t['error']}")
            if state == "stale":
                out.append(_finding(
                    "page" if kind == "broker" else "warn",
                    110.0 + fails,
                    f"fleet target {kind} {addr} is DOWN (stale)",
                    "no successful Status scrape past the staleness "
                    f"bound ({fl.get('stale_after_s', '?')}s): its "
                    "metrics left the merged registry (the fleet sums "
                    "now cover the survivors only) and the "
                    "'target-down' fleet rule pages on the "
                    "gol_fleet_targets_down gauge.",
                    evidence, [addr], label,
                ))
            else:
                out.append(_finding(
                    "warn", 58.0 + fails,
                    f"fleet target {kind} {addr} failing scrapes",
                    "recent scrapes failed but the last success is "
                    "still inside the staleness bound — a blip, or the "
                    "start of an outage.",
                    evidence, [addr], label,
                ))
        for eaddr, why in sorted((fl.get("merge_excluded") or {}).items()):
            out.append(_finding(
                "warn", 57.0,
                f"fleet target {eaddr} EXCLUDED from the merged registry",
                "its snapshot could not be merged exactly (version skew "
                "across the fleet); it was dropped and counted "
                "(gol_fleet_merge_failures_total), never averaged in.",
                [why], [eaddr], label,
            ))
    return out


def _find_fleet_share(statuses) -> List[dict]:
    """The cross-broker balance findings (fleet payloads only): one
    broker holding a disproportionate share of the fleet's
    device-seconds, and one tenant riding far past its fair share on a
    single broker (the merged-ledger skew the gol_fleet_tenant_skew
    gauge tracks)."""
    out = []
    for label, payload in statuses.items():
        fl = payload.get("fleet")
        if not isinstance(fl, dict):
            continue
        dev: Dict[str, float] = {}
        for addr, bp in (fl.get("broker_status") or {}).items():
            totals = (bp.get("accounting") or {}).get("totals") or {}
            ds = totals.get("device_seconds")
            if isinstance(ds, (int, float)) and ds > 0:
                dev[addr] = float(ds)
        if len(dev) >= 2:
            total = sum(dev.values())
            hot = max(dev, key=dev.get)
            share = dev[hot] / total
            if share > max(0.6, 2.0 / len(dev)):
                out.append(_finding(
                    "warn", 62.0 + 20.0 * share,
                    f"broker {hot} holds {100 * share:.0f}% of fleet "
                    "device-seconds",
                    "the fleet's device time is concentrated on one "
                    "broker while the rest idle — the load view the "
                    "ROADMAP's session-router tier will route against.",
                    [f"{a}: {v:.3f} dev-s ({100 * v / total:.0f}%)"
                     for a, v in sorted(dev.items(), key=lambda kv: -kv[1])],
                    [hot], label,
                ))
        sk = fl.get("tenant_skew") or {}
        val = sk.get("value")
        if isinstance(val, (int, float)) and val > 3.0:
            out.append(_finding(
                "warn", 61.0,
                f"tenant '{sk.get('tenant')}' rides {val:.1f}x its fair "
                f"share on broker {sk.get('address')}",
                "cross-broker tenant skew from the merged ledgers: this "
                "tenant's device-seconds pile onto one broker instead "
                "of spreading — respread it, or the hot broker's "
                "co-tenants pay its admission waits.",
                [f"gol_fleet_tenant_skew = {val:.2f} "
                 "(the fleet-tenant-skew rule warns past 3.0)"],
                [str(sk.get("address"))], label,
            ))
    return out


def _find_fleet_provenance(statuses) -> List[dict]:
    """Divergent provenance across fleet targets: brokers that disagree
    on the Status payload schema, the metrics snapshot schema, or the
    backend class are running different code or config. Merged sums
    stay exact either way, but cross-broker comparisons stop meaning
    one thing — and schema skew is the usual root of a merge
    exclusion."""
    out = []
    for label, payload in statuses.items():
        fl = payload.get("fleet")
        if not isinstance(fl, dict):
            continue
        brokers = fl.get("broker_status") or {}
        if len(brokers) < 2:
            continue
        stamps = {
            addr: (
                str(bp.get("schema")),
                str((bp.get("metrics") or {}).get("schema")),
                str(bp.get("backend")),
            )
            for addr, bp in brokers.items()
        }
        if len(set(stamps.values())) > 1:
            out.append(_finding(
                "warn", 59.0,
                "divergent provenance across fleet brokers",
                "targets report different status/metrics schemas or "
                "backend classes — a mixed-version or mixed-config "
                "fleet.",
                [f"{a}: status {s[0]}, metrics {s[1]}, backend {s[2]}"
                 for a, s in sorted(stamps.items())],
                sorted(stamps), label,
            ))
    return out


_HEURISTICS = (
    _find_unreachable,
    _find_fleet_targets,
    _find_lost_workers,
    _find_integrity,
    _find_alerts,
    _find_error_ratio,
    _find_straggler,
    _find_tenant_skew,
    _find_stall,
    _find_hbm,
    _find_checkpoint,
    _find_journal,
    _find_hotspot,
    _find_fleet_share,
    _find_fleet_provenance,
)


def diagnose(statuses: Dict[str, dict]) -> List[dict]:
    """The correlation core: pure function of the fetched payloads.
    Returns findings ranked severity-then-score, deduplicated by
    (severity, title); ALWAYS non-empty — a clean bill of health is
    itself a finding (the smoke gate's renderable-diagnosis contract)."""
    findings: List[dict] = []
    seen = set()
    for heuristic in _HEURISTICS:
        try:
            batch = heuristic(statuses)
        except Exception as exc:  # a probe bug must not sink the triage
            batch = [_finding(
                "info", 0.0, f"heuristic {heuristic.__name__} failed",
                str(exc), [], [], "-",
            )]
        for f in batch:
            key = (f["severity"], f["title"])
            if key not in seen:
                seen.add(key)
                findings.append(f)
    if not findings:
        polled = sum(1 for p in statuses.values() if "error" not in p)
        findings.append(_finding(
            "info", 0.0, "no anomalies detected",
            f"{polled}/{len(statuses)} target(s) answered Status; no lost "
            "workers, no firing alerts, no integrity failures, no error "
            "ratio past 1%.",
            [], [], "-",
        ))
    findings.sort(
        key=lambda f: (_SEVERITY_ORDER.get(f["severity"], 9), -f["score"])
    )
    for rank, f in enumerate(findings, 1):
        f["rank"] = rank
    return findings


def render(findings: List[dict], statuses: Dict[str, dict]) -> str:
    """Terminal report — pure function of the diagnosis (testable without
    a cluster, the obs/watch.py renderer posture)."""
    stamp = time.strftime("%Y-%m-%d %H:%M:%S")
    lines = [f"gol doctor — {stamp}   ({len(statuses)} target(s) polled)"]
    for label, payload in statuses.items():
        state = (
            f"UNREACHABLE — {payload['error']}"
            if "error" in payload
            else f"ok (pid {payload.get('pid', '?')}"
            + (
                "" if payload.get("metrics_enabled")
                else ", metrics DISABLED"
            )
            + ")"
        )
        lines.append(f"  {label}: {state}")
    lines.append("")
    for f in findings:
        lines.append(
            f"#{f['rank']} [{f['severity'].upper()}] {f['title']}"
        )
        if f.get("detail"):
            lines.append(f"    {f['detail']}")
        for e in f.get("evidence", []):
            lines.append(f"    - {e}")
        if f.get("suspects"):
            lines.append(f"    suspects: {', '.join(f['suspects'])}")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def write_report(
    findings: List[dict], statuses: Dict[str, dict], out_dir="out"
) -> pathlib.Path:
    """``out/doctor_<ts>.json``: diagnosis + per-target identity (NOT the
    full payloads — flight rings and timelines would bloat the artifact;
    the evidence strings carry what mattered). Temp-name + atomic rename
    like every other artifact writer."""
    path = pathlib.Path(out_dir) / f"doctor_{int(time.time())}.json"
    path.parent.mkdir(parents=True, exist_ok=True)
    targets = {}
    for label, payload in statuses.items():
        if "error" in payload:
            targets[label] = {"error": payload["error"]}
        else:
            targets[label] = {
                "pid": payload.get("pid"),
                "role": payload.get("role"),
                "metrics_enabled": payload.get("metrics_enabled"),
                "firing_alerts": [
                    a.get("rule") for a in payload.get("alerts") or []
                    if a.get("state") == "firing"
                ],
            }
    report = {
        "schema": SCHEMA,
        "generated_unix": time.time(),
        "targets": targets,
        "findings": findings,
    }
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(report, indent=1, default=str))
    tmp.replace(path)
    return path


# artifact globs a bundle collects out of the artifact directory — the
# files post-hoc triage used to mean hand-gathering. Newest-first per
# pattern, capped (keep=N) so a long-lived out/ does not balloon the
# bundle — EXCEPT the journal segments (keep=None: unlimited): the
# lifecycle journal is the causal event history, and a rotated .g2
# segment may hold exactly the loss/recovery sequence being triaged, so
# EVERY generation of every process's journal is collected. Whatever a
# cap drops is recorded in the manifest's ``dropped`` list — a bundle
# must never look more complete than it is. The accounting ledger has
# no on-disk artifact of its own: it rides each target's FULL Status
# payload, which the bundle writes verbatim.
_BUNDLE_GLOBS = (
    ("trace", "trace_*.json", 3),
    ("flight", "flight_*.jsonl", 3),
    ("report", "report_*.json", 3),
    ("doctor", "doctor_*.json", 3),
    ("history", "history_*.json", 3),
    ("journal", "journal_*.jsonl", None),
    # continuous-profiler artifacts (obs/profiler.py): the run-end and
    # crash profiles of every process — the flame/diff feedstock; 6
    # keeps both forms for a broker + a couple of workers
    ("profile", "profile_*.collapsed", 6),
    ("profile", "profile_*.speedscope.json", 6),
    ("analysis", "analysis.json", 1),
)


def write_bundle(
    findings: List[dict], statuses: Dict[str, dict], out_dir="out"
) -> pathlib.Path:
    """One ``out/bundle_<ts>/`` incident directory: the diagnosis, every
    target's FULL Status payload (metrics + timeline + flight ring +
    accounting — the live evidence), and copies of the existing on-disk
    artifacts (traces, flight dumps, run reports, prior diagnoses, the
    analysis posture), indexed by a ``manifest.json`` — so post-hoc
    triage is one directory to attach, not five files to hand-gather."""
    import shutil

    out = pathlib.Path(out_dir)
    bdir = out / f"bundle_{int(time.time())}"
    bdir.mkdir(parents=True, exist_ok=True)
    entries = []

    def _write(name: str, payload, source: str) -> None:
        path = bdir / name
        path.write_text(json.dumps(payload, indent=1, default=str))
        entries.append({
            "file": name, "source": source, "bytes": path.stat().st_size,
        })

    _write(
        "doctor.json",
        {"schema": SCHEMA, "generated_unix": time.time(),
         "findings": findings},
        "diagnosis",
    )
    for label, payload in statuses.items():
        slug = label.replace(" ", "_").replace(":", "").replace("/", "_")
        _write(f"status_{slug}.json", payload, f"live Status poll: {label}")
    dropped = []
    for kind, pattern, keep in _BUNDLE_GLOBS:
        found = [
            p for p in sorted(
                out.glob(pattern),
                key=lambda p: p.stat().st_mtime, reverse=True,
            )
            if bdir not in p.parents  # never re-collect this bundle's own
        ]
        take = found if keep is None else found[:keep]
        for src in found[len(take):]:
            # capped out: the manifest NAMES what the bundle left behind,
            # so an incomplete bundle never masquerades as the full record
            dropped.append({
                "file": src.name, "kind": kind,
                "why": f"newest-{keep} cap for {kind} artifacts",
            })
        for src in take:
            dst = bdir / src.name
            try:
                shutil.copy2(src, dst)
            except OSError as exc:
                # a copy failure is ALSO a dropped file: stamp it into
                # the same manifest list with its family and reason (the
                # cap-drop shape, applied uniformly), so a postmortem
                # reads ONE list of what this bundle is missing and why
                dropped.append({
                    "file": src.name, "kind": kind,
                    "why": f"copy failed: {exc}",
                })
                continue
            entries.append({
                "file": src.name, "source": f"{kind} artifact ({src})",
                "bytes": dst.stat().st_size,
            })
    manifest = {
        "schema": "gol-bundle/1",
        "generated_unix": time.time(),
        "targets": sorted(statuses),
        "entries": entries,
        "dropped": dropped,
    }
    (bdir / "manifest.json").write_text(
        json.dumps(manifest, indent=1, default=str)
    )
    return bdir


def _selfcheck(out_dir: str) -> int:
    """The ``scripts/check --doctor`` smoke: loopback broker, tiny run,
    poll + diagnose + render + write, fail on empty/unrenderable."""
    import numpy as np

    from ..obs import metrics as _metrics
    from ..obs import timeline as _timeline
    from ..rpc.broker import serve
    from ..rpc.client import RpcClient
    from ..rpc.protocol import Methods, Request

    _metrics.enable()
    _timeline.enable(period=0.1)
    server, _service = serve(port=0)
    try:
        addr = f"127.0.0.1:{server.port}"
        rng = np.random.default_rng(7)
        board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
        client = RpcClient(addr)
        try:
            client.call(
                Methods.BROKER_RUN,
                Request(world=board, turns=8, image_width=64,
                        image_height=64, threads=1),
                timeout=120.0,
            )
        finally:
            client.close()
        time.sleep(0.3)  # at least two sampler ticks land
        statuses = collect(addr, [])
        findings = diagnose(statuses)
        text = render(findings, statuses)
        path = write_report(findings, statuses, out_dir)
        sys.stdout.write(text)
        tl = statuses.get(f"broker {addr}", {}).get("timeline") or {}
        if not findings or not text.strip():
            print("doctor selfcheck FAILED: empty diagnosis", file=sys.stderr)
            return 1
        if not tl.get("series"):
            print(
                "doctor selfcheck FAILED: broker shipped no timeline window",
                file=sys.stderr,
            )
            return 1
        print(f"doctor selfcheck ok: report at {path}")
        return 0
    finally:
        _timeline.disable()
        server.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="one-shot cluster triage over the read-only Status verb"
    )
    parser.add_argument(
        "address", nargs="?", default=None,
        help="broker host:port (tcp:// prefix and :port shorthand accepted)",
    )
    parser.add_argument(
        "-worker", action="append", default=[], metavar="HOST:PORT",
        help="extra worker to poll beyond the broker's worker_health "
             "roster, which is auto-discovered (repeatable)",
    )
    parser.add_argument(
        "-timeout", type=float, default=5.0, metavar="SECONDS",
        help="per-target poll bound (default 5); an unreachable target "
             "becomes evidence, not a hang",
    )
    parser.add_argument(
        "-out", default="out", metavar="DIR",
        help="directory for doctor_<ts>.json (default out)",
    )
    parser.add_argument(
        "-json", action="store_true",
        help="print the JSON report to stdout instead of the terminal text",
    )
    parser.add_argument(
        "-bundle", action="store_true",
        help="also collect a full incident bundle: out/bundle_<ts>/ with "
             "the diagnosis, every target's full Status payload (metrics "
             "+ timeline + flight + accounting), and copies of the "
             "existing trace/flight/report/analysis artifacts, indexed "
             "by manifest.json",
    )
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="spin a loopback broker, run a tiny job, diagnose it, and "
             "fail on an empty diagnosis (the scripts/check --doctor gate)",
    )
    args = parser.parse_args(argv)
    if args.selfcheck:
        return _selfcheck(args.out)
    if not args.address:
        parser.error("an address is required (or --selfcheck)")
    statuses = collect(args.address, args.worker, timeout=args.timeout)
    findings = diagnose(statuses)
    path = write_report(findings, statuses, args.out)
    if args.bundle:
        bdir = write_bundle(findings, statuses, args.out)
        print(f"incident bundle collected at {bdir}", file=sys.stderr)
    if args.json:
        print(json.dumps(
            {"findings": findings, "report_path": str(path)},
            indent=1, default=str,
        ))
    else:
        sys.stdout.write(render(findings, statuses))
        print(f"report written to {path}")
    broker_label = next(iter(statuses), None)
    broker_ok = broker_label is not None and "error" not in statuses[broker_label]
    return 0 if broker_ok else 1


if __name__ == "__main__":
    sys.exit(main())
