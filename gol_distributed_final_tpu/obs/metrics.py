"""Dependency-free metrics registry: counters, gauges, histograms.

Design constraints, in priority order:

* **Cheap when off.** The engine run loop and the RPC dispatch path call
  these per chunk / per request; with the registry disabled every
  instrument method is one attribute load and a branch — no clock reads,
  no locking, no allocation. The global default registry starts disabled
  and is switched on by the ``-metrics`` CLI flags (``enable()``).
* **Exact cross-host merge.** Histograms use FIXED bucket edges declared
  at registration (monotonic-clock seconds by default), so merging two
  hosts' snapshots is element-wise addition of bucket counts — no
  re-binning error, ever. ``merge_snapshots`` refuses mismatched edges
  instead of approximating.
* **No dependencies.** Pure stdlib: the RPC layer (which must import this)
  stays importable in a worker process that never loads jax or numpy.

Exposition: ``Registry.snapshot()`` is a plain-JSON dict (the wire/report
format); ``snapshot_to_prometheus`` renders the standard text format
(cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``), and
``parse_prometheus_text`` reads that text back into ``{sample: value}``
for round-trip checks and scrapers without a real Prometheus.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

# Default histogram edges (seconds), spanning a 10 us kernel dispatch to a
# multi-minute checkpoint. FIXED at registration so cross-host merges are
# exact; change requires bumping the README metric table (obs/lint.py).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-5, 2.5e-5, 5e-5,
    1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5,
    1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0,
)


def clock() -> float:
    """The one timestamp source for every instrument: monotonic seconds."""
    return time.monotonic()


class _Child:
    """One labelled series. ``_reg`` is consulted on every mutation so a
    disabled registry records nothing regardless of when the instrument
    was created."""

    __slots__ = ("_reg", "labels_values")

    def __init__(self, reg: "Registry", labels_values: Tuple[str, ...]):
        self._reg = reg
        self.labels_values = labels_values


class Counter(_Child):
    __slots__ = ("value",)

    def __init__(self, reg, labels_values):
        super().__init__(reg, labels_values)
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not self._reg.enabled:
            return
        if amount < 0:
            raise ValueError(f"counters only go up, got {amount}")
        with self._reg._lock:
            self.value += amount


class Gauge(_Child):
    __slots__ = ("value",)

    def __init__(self, reg, labels_values):
        super().__init__(reg, labels_values)
        self.value = 0.0

    def set(self, value: float) -> None:
        if not self._reg.enabled:
            return
        with self._reg._lock:
            self.value = float(value)


class Histogram(_Child):
    """Fixed-edge histogram. ``counts`` is NON-cumulative per bucket with a
    trailing +inf overflow slot (len(edges) + 1 entries); exposition
    cumulates on the way out, merge adds element-wise."""

    __slots__ = ("edges", "counts", "sum", "count")

    def __init__(self, reg, labels_values, edges: Tuple[float, ...]):
        super().__init__(reg, labels_values)
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.observe_n(value, 1)

    def observe_n(self, value: float, n: int) -> None:
        """Record ``n`` identical observations in one call — the engine's
        chunked dispatch records a whole chunk's per-turn time at once, so
        the histogram count still equals the TURN count."""
        if not self._reg.enabled or n <= 0:
            return
        i = bisect.bisect_left(self.edges, value)
        with self._reg._lock:
            self.counts[i] += n
            self.sum += value * n
            self.count += n


class _Family:
    """One named metric and its labelled children. With no labelnames the
    family owns a single default child and proxies its mutators, so
    ``FAMILY.inc()`` / ``FAMILY.observe()`` work directly."""

    def __init__(self, reg, name, kind, help_text, labelnames, edges=None):
        self.reg = reg
        self.name = name
        self.kind = kind  # "counter" | "gauge" | "histogram"
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self.edges = edges
        self.children: Dict[Tuple[str, ...], _Child] = {}
        if not self.labelnames:
            self._default = self.labels()

    def _make_child(self, values: Tuple[str, ...]) -> _Child:
        if self.kind == "counter":
            return Counter(self.reg, values)
        if self.kind == "gauge":
            return Gauge(self.reg, values)
        return Histogram(self.reg, values, self.edges)

    def labels(self, *values: str) -> _Child:
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got {values}"
            )
        child = self.children.get(values)
        if child is None:
            with self.reg._lock:
                child = self.children.setdefault(values, self._make_child(values))
        return child

    # unlabelled convenience surface
    def inc(self, amount: float = 1.0) -> None:
        self._default.inc(amount)

    def set(self, value: float) -> None:
        self._default.set(value)

    def observe(self, value: float) -> None:
        self._default.observe(value)

    def observe_n(self, value: float, n: int) -> None:
        self._default.observe_n(value, n)

    @property
    def value(self) -> float:
        return self._default.value


class Registry:
    """A set of metric families. Registration is idempotent by name (the
    instruments module may be imported from several entry points); a
    re-registration with a DIFFERENT kind/labels/edges is a programming
    error and raises."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _register(self, name, kind, help_text, labelnames, edges=None):
        fam = self._families.get(name)
        if fam is not None:
            if (fam.kind, fam.labelnames, fam.edges) != (
                kind, tuple(labelnames), edges,
            ):
                raise ValueError(
                    f"metric {name} re-registered with a different signature"
                )
            return fam
        fam = _Family(self, name, kind, help_text, labelnames, edges)
        self._families[name] = fam
        return fam

    def counter(self, name, help_text="", labelnames=()):
        return self._register(name, "counter", help_text, labelnames)

    def gauge(self, name, help_text="", labelnames=()):
        return self._register(name, "gauge", help_text, labelnames)

    def histogram(self, name, help_text="", labelnames=(), buckets=DEFAULT_BUCKETS):
        edges = tuple(float(b) for b in buckets)
        if list(edges) != sorted(set(edges)):
            raise ValueError(f"bucket edges must be strictly increasing: {edges}")
        return self._register(name, "histogram", help_text, labelnames, edges)

    def families(self) -> List[_Family]:
        return list(self._families.values())

    def reset(self) -> None:
        """Zero every series (keeps registrations) — test/bench isolation."""
        with self._lock:
            for fam in self._families.values():
                for child in fam.children.values():
                    if isinstance(child, Histogram):
                        child.counts = [0] * (len(child.edges) + 1)
                        child.sum = 0.0
                        child.count = 0
                    else:
                        child.value = 0.0

    def snapshot(self) -> dict:
        """Plain-JSON state of every family — the wire/report format, and
        the merge operand."""
        fams = []
        with self._lock:
            for fam in self._families.values():
                series = []
                for values, child in sorted(fam.children.items()):
                    if isinstance(child, Histogram):
                        series.append({
                            "labels": list(values),
                            "buckets": list(child.counts),
                            "sum": child.sum,
                            "count": child.count,
                        })
                    else:
                        series.append({
                            "labels": list(values),
                            "value": child.value,
                        })
                entry = {
                    "name": fam.name,
                    "type": fam.kind,
                    "help": fam.help,
                    "labelnames": list(fam.labelnames),
                    "series": series,
                }
                if fam.edges is not None:
                    entry["le"] = list(fam.edges)
                fams.append(entry)
        return {"schema": "gol-metrics/1", "families": fams}


# -- snapshot algebra --------------------------------------------------------


def merge_snapshots(a: dict, b: dict) -> dict:
    """Element-wise merge of two snapshots (e.g. two hosts of an SPMD job):
    counters and histogram buckets/sum/count ADD (exact, because edges are
    fixed and must match), gauges take the MAX (commutative and meaningful
    for high-water readings like chunk size). Families or series present
    on one side only pass through."""
    out = {"schema": "gol-metrics/1", "families": []}
    b_fams = {f["name"]: f for f in b.get("families", [])}
    seen = set()
    for fa in a.get("families", []):
        fb = b_fams.get(fa["name"])
        seen.add(fa["name"])
        if fb is None:
            out["families"].append(_copy_family(fa))
            continue
        if fa["type"] != fb["type"] or fa.get("le") != fb.get("le"):
            raise ValueError(
                f"cannot merge {fa['name']}: type/bucket-edge mismatch "
                "(fixed edges are the exactness contract)"
            )
        merged = _copy_family(fa)
        index = {tuple(s["labels"]): s for s in merged["series"]}
        for sb in fb["series"]:
            key = tuple(sb["labels"])
            sa = index.get(key)
            if sa is None:
                merged["series"].append(dict(sb))
                continue
            if fa["type"] == "histogram":
                sa["buckets"] = [
                    x + y for x, y in zip(sa["buckets"], sb["buckets"])
                ]
                sa["sum"] += sb["sum"]
                sa["count"] += sb["count"]
            elif fa["type"] == "counter":
                sa["value"] += sb["value"]
            else:  # gauge
                sa["value"] = max(sa["value"], sb["value"])
        out["families"].append(merged)
    for name, fb in b_fams.items():
        if name not in seen:
            out["families"].append(_copy_family(fb))
    return out


def merge_many(snaps) -> dict:
    """Left fold of ``merge_snapshots`` over N per-process snapshots —
    the fleet collector's cluster-registry primitive. The merged result
    keeps the exactness contract: every counter equals the ARITHMETIC
    SUM of its per-process values, every histogram bucket the per-bucket
    sum. An empty iterable yields an empty snapshot; a single snapshot
    comes back as a deep-ish copy (same shape as a merge result), so
    callers may mutate it without aliasing a target's cached payload."""
    merged = {"schema": "gol-metrics/1", "families": []}
    for snap in snaps:
        merged = merge_snapshots(merged, snap)
    return merged


def _copy_family(fam: dict) -> dict:
    out = {k: v for k, v in fam.items() if k != "series"}
    out["series"] = [dict(s, labels=list(s["labels"])) for s in fam["series"]]
    for s in out["series"]:
        if "buckets" in s:
            s["buckets"] = list(s["buckets"])
    return out


# -- Prometheus text exposition ---------------------------------------------


def _label_str(labelnames: Iterable[str], values: Iterable[str],
               extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [(n, v) for n, v in zip(labelnames, values)]
    if extra is not None:
        pairs.append(extra)
    if not pairs:
        return ""
    body = ",".join(f'{n}="{_escape(v)}"' for n, v in pairs)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt(x: float) -> str:
    if x == float("inf"):
        return "+Inf"
    if float(x) == int(x) and abs(x) < 1e15:
        return str(int(x))
    return repr(float(x))


def snapshot_to_prometheus(snap: dict) -> str:
    """Render a snapshot in the Prometheus text format (histograms go out
    CUMULATIVE with a +Inf bucket, per the format's contract)."""
    lines: List[str] = []
    for fam in snap.get("families", []):
        name, kind = fam["name"], fam["type"]
        if fam.get("help"):
            lines.append(f"# HELP {name} {_escape(fam['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        labelnames = fam.get("labelnames", [])
        for s in fam["series"]:
            if kind == "histogram":
                cum = 0
                for edge, n in zip(
                    list(fam["le"]) + [float("inf")], s["buckets"]
                ):
                    cum += n
                    ls = _label_str(labelnames, s["labels"], ("le", _fmt(edge)))
                    lines.append(f"{name}_bucket{ls} {cum}")
                ls = _label_str(labelnames, s["labels"])
                lines.append(f"{name}_sum{ls} {_fmt(s['sum'])}")
                lines.append(f"{name}_count{ls} {_fmt(s['count'])}")
            else:
                ls = _label_str(labelnames, s["labels"])
                lines.append(f"{name}{ls} {_fmt(s['value'])}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Minimal reader for the text format THIS module emits: returns
    ``{sample_line_without_value: value}`` — enough for exposition
    round-trip tests and for a scraper-less operator to diff two Status
    snapshots. Not a general Prometheus parser."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, value = line.rpartition(" ")
        out[key] = float("inf") if value == "+Inf" else float(value)
    return out


# -- the process-global default registry ------------------------------------

# Disabled until an entry point opts in (-metrics / -report / enable()):
# every instrument bound to it is a no-op flag check until then.
_DEFAULT = Registry(enabled=False)


def registry() -> Registry:
    return _DEFAULT


def enable(on: bool = True) -> None:
    _DEFAULT.enabled = on


def enabled() -> bool:
    return _DEFAULT.enabled
