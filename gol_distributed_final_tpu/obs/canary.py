"""Blackbox canary prober — continuous end-to-end CORRECTNESS probing.

    python -m gol_distributed_final_tpu.obs.canary :8040            # forever
    python -m gol_distributed_final_tpu.obs.canary :8040 -once
    python -m gol_distributed_final_tpu.obs.canary :8040 -verb run
    python -m gol_distributed_final_tpu.obs.canary --selfcheck

Every defense so far is WHITE-box: integrity digests verify what the
workers claim, SLO rules watch the metrics the code emits. None of them
would notice a serving path that is *silently wrong end to end* — a stale
kernel, a bad resplit, a session demux bug that hands tenant A tenant B's
board. The canary is the blackbox closure: a daemon that continuously
drives a tiny KNOWN-ORACLE universe through the full client path —
admission → turns → tagged mid-flight retrieve → final board — and
verifies **bit-exactness** against an independent numpy oracle (the same
``np.roll`` math as ``tests/oracle.vector_step``, inlined so the prober
ships with the package). A wrong bit anywhere pages within one probe
period (the ``canary-failure`` SLO rule) instead of being discovered by a
user.

Probe verbs:

* ``session`` (default) — ``Operations.SessionRun`` tagged with the
  canary's tenant (``CANARY_TENANT`` high bits, see obs/accounting.py),
  with a concurrent tagged ``RetrieveCurrentData`` mid-flight: the
  retrieve's ``(turn, alive)`` must match the oracle's count AT that
  turn (the per-session demux contract), and the final board must be
  bit-exact. Safe to run against a serving broker: sessions never
  conflict with client traffic.
* ``run`` — the classic blocking ``Operations.Run``: exercises the
  backend data plane itself (scatter / resident strips on a workers
  broker). Opt-in: a broker serves ONE Run at a time, so this verb
  would collide with real single-board traffic.

Metrics (lint-enforced, README "Canary & load harness"):
``gol_canary_probes_total{result}`` (``ok`` / ``corrupt`` — wrong bits
served — / ``error`` — the path failed loudly) and
``gol_canary_latency_seconds`` (probe round-trip). Failures also land a
``canary.fail`` flight event for the doctor.

The broker's ``-canary [SECS]`` flag runs this prober in-process against
its own loopback port (full RPC path through the real server socket);
``scripts/check --canary`` runs ``--selfcheck`` — one loopback probe,
bit-exact or nonzero exit.
"""

from __future__ import annotations

import argparse
import itertools
import json
import logging
import sys
import threading
import time
from typing import List, Optional, Tuple

from . import flight as _flight
from . import instruments as _ins

logger = logging.getLogger(__name__)

#: the canary's tenant id (the ``session_id`` high bits — 0xCA): its
#: usage shows up in the accounting ledger like any tenant's
CANARY_TENANT = 0xCA

#: stable result-label set of ``gol_canary_probes_total``
RESULTS = ("ok", "corrupt", "error")

_nonce = itertools.count(1)


def _oracle_evolve(board, turns: int) -> Tuple[object, List[int]]:
    """``(final board, alive count per turn 0..turns)`` by the
    independent numpy oracle (tests/oracle.vector_step's math, inlined:
    obs/ must not import the test tree)."""
    import numpy as np

    b = (np.asarray(board) != 0).astype(np.int32)
    counts = [int(b.sum())]
    for _ in range(turns):
        n = sum(
            np.roll(np.roll(b, dy, 0), dx, 1)
            for dy in (-1, 0, 1)
            for dx in (-1, 0, 1)
            if (dy, dx) != (0, 0)
        )
        b = ((n == 3) | ((b == 1) & (n == 2))).astype(np.int32)
        counts.append(int(b.sum()))
    return (b * 255).astype(np.uint8), counts


def canary_board(size: int, seed: int, round_no: int):
    """Deterministic probe universe: same (seed, round) → same board, so
    a failing probe replays exactly."""
    import numpy as np

    rng = np.random.default_rng((seed << 20) ^ round_no)
    return np.where(rng.random((size, size)) < 0.35, 255, 0).astype(np.uint8)


class CanaryProber:
    """One prober: a reusable client plus an optional daemon loop."""

    def __init__(
        self,
        address: str,
        *,
        period: float = 5.0,
        size: int = 16,
        turns: int = 16,
        verb: str = "session",
        timeout: float = 60.0,
        seed: int = 0,
        tenant: int = CANARY_TENANT,
    ):
        from .status import norm_address

        if verb not in ("session", "run"):
            raise ValueError(f"verb must be 'session' or 'run', got {verb!r}")
        if period <= 0:
            raise ValueError(f"period must be > 0, got {period}")
        self.address = norm_address(address)
        self.period = period
        self.size = size
        self.turns = turns
        self.verb = verb
        self.timeout = timeout
        self.seed = seed
        self.tenant = tenant
        self._round = 0
        self._client = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _connect(self):
        from ..rpc.client import RpcClient

        if self._client is None:
            self._client = RpcClient(
                self.address, timeout=10.0, reconnect=True
            )
        return self._client

    # -- one probe ---------------------------------------------------------

    def probe_once(self) -> dict:
        """Drive one known-oracle universe through the full path and
        verify it. Returns ``{"result", "verb", "round", "latency_s",
        "detail"}`` and meters ``gol_canary_probes_total{result}`` +
        ``gol_canary_latency_seconds`` either way."""
        self._round += 1
        round_no = self._round
        board = canary_board(self.size, self.seed, round_no)
        want, counts = _oracle_evolve(board, self.turns)
        t0 = time.monotonic()
        try:
            if self.verb == "session":
                result, detail = self._probe_session(board, want, counts)
            else:
                result, detail = self._probe_run(board, want, counts)
        except Exception as exc:  # transport/reply failure: loud, not wrong
            result, detail = "error", f"{type(exc).__name__}: {exc}"
        latency = time.monotonic() - t0
        _ins.CANARY_PROBES_TOTAL.labels(result).inc()
        _ins.CANARY_LATENCY_SECONDS.observe(latency)
        if result != "ok":
            _flight.record(
                "canary.fail", self.address, result=result,
                detail=str(detail)[:200],
            )
            logger.error(
                "CANARY %s (%s verb, round %d): %s",
                result, self.verb, round_no, detail,
            )
        return {
            "result": result,
            "verb": self.verb,
            "round": round_no,
            "latency_s": round(latency, 6),
            "detail": detail,
        }

    def _verify_board(self, got, want) -> Optional[str]:
        import numpy as np

        if got is None:
            return "final board missing from the reply"
        got = np.asarray(got)
        if got.shape != want.shape:
            return f"final board shape {got.shape} != {want.shape}"
        if not np.array_equal(got, want):
            bad = int(np.count_nonzero(got != want))
            return (
                f"final board diverges from the oracle in {bad} cell(s) "
                f"after {self.turns} turns"
            )
        return None

    def _probe_session(self, board, want, counts) -> Tuple[str, str]:
        """SessionRun + a concurrent tagged retrieve: the blocking call
        parks a helper thread while this one polls the per-session
        snapshot — exactly the two-threaded client shape real tenants
        use. Mid-flight ``(turn, alive)`` must match the oracle AT that
        turn; the final board must be bit-exact."""
        from . import accounting as _acct
        from ..rpc.client import RpcError
        from ..rpc.protocol import Methods, Request

        client = self._connect()
        tag = _acct.make_tag(self.tenant, next(_nonce))
        req = Request(
            world=board, turns=self.turns,
            image_height=self.size, image_width=self.size,
            threads=1, session_id=tag,
        )
        box: dict = {}

        def runner():
            try:
                box["res"] = client.call(
                    Methods.SESSION_RUN, req, timeout=self.timeout
                )
            except Exception as exc:
                box["exc"] = exc

        t = threading.Thread(target=runner, name="gol-canary-run", daemon=True)
        t.start()
        midflight = None
        deadline = time.monotonic() + self.timeout
        # head start: the SessionRun frame must reach the scheduler
        # before the first tagged poll, or the poll eats an expected
        # "no session with tag" error reply — noise in the very
        # error-ratio budget the canary exists to protect
        t.join(timeout=0.02)
        while t.is_alive() and time.monotonic() < deadline:
            try:
                snap = client.call(
                    Methods.RETRIEVE,
                    Request(include_world=False, session_id=tag),
                    timeout=5.0,
                )
            except RpcError:
                # not yet admitted, or already finished: both fine — a
                # tiny universe can drain between our two calls
                pass
            else:
                turn = snap.turns_completed
                if not 0 <= turn <= self.turns:
                    midflight = (
                        f"tagged retrieve reports turn {turn} outside "
                        f"[0, {self.turns}]"
                    )
                elif snap.alive_count != counts[turn]:
                    midflight = (
                        f"tagged retrieve at turn {turn} counts "
                        f"{snap.alive_count} alive, oracle says "
                        f"{counts[turn]}"
                    )
            t.join(timeout=0.005)
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            return "error", f"SessionRun did not return within {self.timeout}s"
        if "exc" in box:
            raise box["exc"]
        res = box.get("res")
        bad = self._verify_board(getattr(res, "world", None), want)
        if bad is None and res.alive_count != counts[self.turns]:
            bad = (
                f"final alive count {res.alive_count} != oracle "
                f"{counts[self.turns]}"
            )
        if bad is None and midflight is not None:
            bad = midflight
        return ("corrupt", bad) if bad else ("ok", "")

    def _probe_run(self, board, want, counts) -> Tuple[str, str]:
        """The classic blocking Run — the backend data plane end to end
        (on a workers broker: scatter / resident strips, the path an
        ``-integrity off`` deployment leaves undefended)."""
        from ..rpc.protocol import Methods, Request

        client = self._connect()
        res = client.call(
            Methods.BROKER_RUN,
            Request(
                world=board, turns=self.turns,
                image_height=self.size, image_width=self.size, threads=0,
            ),
            timeout=self.timeout,
        )
        bad = self._verify_board(getattr(res, "world", None), want)
        if bad is None and res.alive_count != counts[self.turns]:
            bad = (
                f"final alive count {res.alive_count} != oracle "
                f"{counts[self.turns]}"
            )
        return ("corrupt", bad) if bad else ("ok", "")

    # -- the daemon loop ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="gol-canary", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)
        if self._client is not None:
            self._client.close()
            self._client = None

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            try:
                self.probe_once()
            except Exception:  # the prober must outlive any probe bug
                logger.exception("canary probe crashed")


def _selfcheck() -> int:
    """The ``scripts/check --canary`` smoke: loopback broker, ONE probe
    round-trip, bit-exact or nonzero exit — with the probe counters
    checked so a silently-unmetered canary cannot pass."""
    from . import metrics as _metrics
    from .status import series_map
    from ..rpc.broker import serve

    _metrics.registry().reset()
    _metrics.enable()
    server, service = serve(port=0)
    try:
        prober = CanaryProber(
            f"127.0.0.1:{server.port}", size=16, turns=16, verb="session"
        )
        try:
            out = prober.probe_once()
        finally:
            prober.stop()
        print(json.dumps(out))
        snap = _metrics.registry().snapshot()
        probes = series_map(snap, "gol_canary_probes_total")
        metered = (probes.get(("ok",)) or {}).get("value") or 0
        if out.get("result") != "ok":
            print(f"canary selfcheck FAILED: {out}", file=sys.stderr)
            return 1
        if metered != 1:
            print(
                "canary selfcheck FAILED: probe not metered "
                f"(gol_canary_probes_total{{ok}}={metered})",
                file=sys.stderr,
            )
            return 1
        print("canary selfcheck ok: one loopback probe, bit-exact")
        return 0
    finally:
        service._shutdown()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="blackbox canary prober: known-oracle universes "
        "through the full RPC + session path, bit-exact or paged"
    )
    parser.add_argument(
        "address", nargs="?", default=None,
        help="broker host:port (tcp:// prefix and :port shorthand accepted)",
    )
    parser.add_argument(
        "-period", type=float, default=5.0, metavar="SECS",
        help="seconds between probes (default 5)",
    )
    parser.add_argument(
        "-count", type=int, default=0, metavar="N",
        help="stop after N probes (0 = forever); nonzero exit if any failed",
    )
    parser.add_argument(
        "-once", action="store_true", help="exactly one probe (== -count 1)",
    )
    parser.add_argument(
        "-verb", choices=("session", "run"), default="session",
        help="probe path: SessionRun + tagged retrieve (default; safe "
             "beside live traffic) or the classic blocking Run (opt-in: "
             "one Run at a time per broker)",
    )
    parser.add_argument("-size", type=int, default=16, metavar="CELLS")
    parser.add_argument("-turns", type=int, default=16)
    parser.add_argument("-timeout", type=float, default=60.0, metavar="SECS")
    parser.add_argument("-seed", type=int, default=0)
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="loopback broker + one probe (the scripts/check --canary gate)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.selfcheck:
        return _selfcheck()
    if not args.address:
        parser.error("an address is required (or --selfcheck)")
    from . import metrics as _metrics

    _metrics.enable()  # the probe counters must record
    prober = CanaryProber(
        args.address, period=args.period, size=args.size, turns=args.turns,
        verb=args.verb, timeout=args.timeout, seed=args.seed,
    )
    count = 1 if args.once else args.count
    failures = 0
    try:
        n = 0
        while True:
            out = prober.probe_once()
            print(json.dumps(out), flush=True)
            if out.get("result") != "ok":
                failures += 1
            n += 1
            if count and n >= count:
                break
            time.sleep(args.period)
    except KeyboardInterrupt:
        pass
    finally:
        prober.stop()
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
