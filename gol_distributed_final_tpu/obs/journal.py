"""Durable universe lifecycle journal — HLC-stamped, crc-framed, rotated.

Every evidence store before this one is volatile and process-local: the
flight ring, the timeline rings, the tenant ledger and the critical-path
EWMAs all die with their process, so "what happened to universe X,
across its whole life, in what order across the broker and its workers?"
is unanswerable the moment a run ends. This module is the durable
substrate the persistent-universes tier (ROADMAP) admits against:

* **An append-only on-disk journal per process.** ``enable(role=...)``
  opens ``out/journal_<role>_<pid>.jsonl`` and a buffered writer thread;
  ``record(kind, name, **args)`` is the only hot-path surface — one
  global load and a branch while disabled, one lock + two deque appends
  while enabled (the Podracer posture, arXiv:2104.06272: history lives
  on the control path, never in the kernel hot loop). The bench prices
  it like timeline/attribution before it (``journal_overhead_pct``,
  gated <= 2% beyond the fits' noise band).
* **crc32-framed records** (rpc/integrity.py's frame-word API): each
  line is ``<crc32-hex> <json>`` with the crc computed over the json
  bytes, so a record torn by a crash mid-write — or a flipped byte in a
  cold segment — is DETECTED and skipped loudly by the reader
  (``read_segment`` returns the problems beside the events), never
  mis-parsed into a silently-wrong history.
* **Hybrid logical clock stamps.** Every record carries ``[physical_ms,
  logical, node]``; the process clock ticks on local events and merges
  remote stamps carried on the ``Request.hlc`` / ``Response.hlc``
  extension fields (rpc/client.py + rpc/server.py stamp every call both
  ways, getattr-skew-safe like ``trace_ctx``), so events from all
  processes merge into ONE causal order: a broker-side ``worker.lost``
  is always ordered after the worker events that caused it, even under
  wall-clock skew or regression between hosts. ``HLC_ORDER``/
  ``hlc_key`` are the shared sort contract (obs/history.py).
* **Bounded retention, drops metered never silent.** Segments rotate at
  ``rotate_bytes`` (active -> ``.g1`` -> ``.g2`` ..., the checkpoint
  generation-chain naming), keeping ``keep`` generations; a retired
  segment's record count and any write-queue overflow are counted on
  ``gol_journal_drops_total`` — bounded disk can lose history, but it
  can never lose it silently.
* **Incremental Status windows.** ``window(since=seq)`` ships only the
  tail events a poller has not seen (the ``Request.journal_since``
  extension field — the ``timeline_since`` pattern), so live processes
  are queryable (obs/history.py, the watch JOURNAL panel) and dead ones
  leave their segments for the same reader.

``EVENT_KINDS`` is the declared vocabulary: every lifecycle event kind
emitted anywhere in the tree must appear here (the registry-drift lint,
obs/lint.py ``lint-journal-kinds``) with a one-line meaning — the table
the README section and the history renderer share.
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..rpc import integrity as _integrity
from ..utils import locksan as _locksan
from . import instruments as _ins

SCHEMA = "gol-journal/1"

#: rotate the active segment past this many bytes (4 MiB: ~20k records)
DEFAULT_ROTATE_BYTES = 4 << 20
#: generations kept per process (active + keep-1 rotated)
DEFAULT_KEEP = 4
#: in-memory tail ring shipped through Status windows
DEFAULT_TAIL_CAPACITY = 512
#: bounded write queue: a wedged disk drops (metered), never blocks
DEFAULT_QUEUE_CAPACITY = 4096
#: background writer drain cadence (seconds)
FLUSH_INTERVAL = 0.2

#: the declared event-kind vocabulary: kind -> one-line meaning. The
#: registry-drift lint (obs/lint.py) fails when a ``journal.record``
#: site anywhere in the tree emits a kind missing from this table, and
#: the README event-kind table must name every row.
EVENT_KINDS: Dict[str, str] = {
    "run.start": "an engine/broker run began (geometry, turns, wire mode)",
    "run.end": "a run completed (turns done, alive count)",
    "session.admit": "a universe was admitted into the session batch",
    "session.reject": "an admission was refused (tenant + reason)",
    "session.final": "a session reached FinalTurnComplete",
    "chunk.commit": "a turn chunk committed (turn range, alive, route)",
    "snapshot": "a mid-run snapshot was served (Retrieve)",
    "ckpt.write": "a checkpoint (full or delta) was written",
    "ckpt.verify": "a checkpoint digest verification (ok/fail)",
    "ckpt.replay": "a resume replayed state from a checkpoint",
    "worker.lost": "a worker was marked lost (address, error)",
    "worker.quarantine": "a lost worker entered the probe/backoff cycle",
    "worker.readmit": "a lost worker was probed alive and readmitted",
    "recovery.resplit": "surviving workers were re-split over the board",
    "integrity.fail": "an integrity check caught corruption",
    "early.exit": "a run short-circuited (still/period2/dead)",
    "slo.fire": "an SLO burn-rate rule started firing",
    "slo.clear": "a firing SLO rule resolved",
    "canary.verdict": "a blackbox canary probe verdict (ok/fail)",
    "journal.drop": "journal retention retired a segment (count, path)",
    "crash": "an unhandled exception dumped this process's evidence",
}

_SEGMENT_RE = re.compile(
    r"^journal_(?P<role>[A-Za-z0-9-]+)_(?P<pid>\d+)(?:\.g(?P<gen>\d+))?\.jsonl$"
)


# -- the hybrid logical clock -------------------------------------------------


class HLC:
    """A hybrid logical clock (Kulkarni et al.): stamps are
    ``[physical_ms, logical, node]`` — physical tracks the max wall
    clock observed (ms), logical breaks ties within one ms, node breaks
    ties between processes deterministically. ``tick`` stamps a local
    event; ``merge`` folds a remote stamp in on message receipt, so a
    stamp issued after a merge always orders AFTER the remote event that
    carried it — causality survives wall-clock skew and regression.

    Stamps are plain lists of (int, int, str): they cross the restricted
    unpickler on ``Request.hlc``/``Response.hlc`` and serialise to JSON
    in journal records without help."""

    _GUARDED_BY = {"_physical": "_lock", "_logical": "_lock"}

    def __init__(self, node: Optional[str] = None, now=time.time):
        self.node = node or f"{socket.gethostname() or 'localhost'}-{os.getpid()}"
        self._now = now  # injectable: the skew/regression property tests
        self._lock = _locksan.lock("HLC._lock")
        self._physical = 0
        self._logical = 0

    def tick(self) -> List:
        """Stamp a local event: physical never goes backwards even when
        the wall clock does (logical advances instead)."""
        wall = int(self._now() * 1000)
        with self._lock:
            if wall > self._physical:
                self._physical, self._logical = wall, 0
            else:
                self._logical += 1
            return [self._physical, self._logical, self.node]

    def merge(self, remote) -> Optional[List]:
        """Fold a remote stamp in (message receipt). Malformed stamps —
        a skewed peer without the field sends None — are ignored: skew
        means "no causality hint", never an exception."""
        try:
            rp, rl = int(remote[0]), int(remote[1])
        except (TypeError, ValueError, IndexError):
            return None
        wall = int(self._now() * 1000)
        with self._lock:
            if wall > self._physical and wall > rp:
                self._physical, self._logical = wall, 0
            elif self._physical == rp:
                self._physical = rp
                self._logical = max(self._logical, rl) + 1
            elif self._physical > rp:
                self._logical += 1
            else:
                self._physical, self._logical = rp, rl + 1
            return [self._physical, self._logical, self.node]

    def read(self) -> List:
        """The current stamp WITHOUT advancing the clock (diagnostics)."""
        with self._lock:
            return [self._physical, self._logical, self.node]


def event_node(event: dict) -> str:
    """The emitting process's identity for one journal event: segment
    records carry it inside the HLC stamp (``[physical, logical,
    node]``); window-level consumers may have stamped it top-level;
    role-pid is the last resort for foreign records."""
    node = event.get("node")
    if node:
        return str(node)
    stamp = event.get("hlc")
    if isinstance(stamp, (list, tuple)) and len(stamp) == 3 and stamp[2]:
        return str(stamp[2])
    return f"{event.get('role', '?')}-{event.get('pid', '?')}"


def hlc_key(event: dict) -> Tuple[int, int, str]:
    """The total-order sort key of one journal event: (physical,
    logical, node) — deterministic tie-break by node id, so two merges
    of the same segments always render the same timeline. Events without
    a usable stamp (foreign records) fall back to wall-clock ms, which
    orders them best-effort without poisoning the stamped order."""
    stamp = event.get("hlc")
    try:
        return int(stamp[0]), int(stamp[1]), str(stamp[2])
    except (TypeError, ValueError, IndexError):
        return int(float(event.get("t_unix") or 0.0) * 1000), 0, ""


# -- the per-process journal --------------------------------------------------


def _frame(record_json: bytes) -> bytes:
    """One framed line: ``<crc32-hex> <json>\\n`` — the crc is the
    rpc/integrity.py frame word over the json bytes, so the reader
    detects a torn or flipped record with the same primitive the wire
    plane trusts."""
    crc = _integrity.crc_add(_integrity.crc_new(), record_json)
    return _integrity.crc_pack(crc).hex().encode() + b" " + record_json + b"\n"


def _unframe(line: bytes):
    """One line back to its record dict, or a string describing why it
    cannot be trusted (torn tail, flipped byte, foreign content)."""
    parts = line.rstrip(b"\n").split(b" ", 1)
    if len(parts) != 2 or len(parts[0]) != 8:
        return "unframed line (no crc word)"
    word, payload = parts
    try:
        crc = _integrity.crc_add(_integrity.crc_new(), payload)
        _integrity.crc_check(crc, bytes.fromhex(word.decode()), "journal record")
    except (ValueError, _integrity.IntegrityError):
        return "crc mismatch (torn or corrupted record)"
    try:
        record = json.loads(payload)
    except ValueError:
        return "crc ok but unparseable json (framing bug)"
    if not isinstance(record, dict):
        return "record is not an object"
    return record


class Journal:
    """One process's durable event journal: a buffered writer draining a
    bounded queue into crc-framed, size-rotated segments, plus an
    in-memory tail ring for incremental Status windows. ``record`` is
    the only hot surface; everything else is control-path."""

    # tail/queue/seq/counters move together under the lock; the writer
    # thread owns the file handle exclusively (single consumer)
    _GUARDED_BY = {
        "_tail": "_lock",
        "_queue": "_lock",
        "_seq": "_lock",
        "_dropped": "_lock",
        "_counts": "_lock",
        "_writing": "_lock",
    }

    def __init__(
        self,
        out_dir="out",
        role: str = "engine",
        rotate_bytes: int = DEFAULT_ROTATE_BYTES,
        keep: int = DEFAULT_KEEP,
        tail_capacity: int = DEFAULT_TAIL_CAPACITY,
        queue_capacity: int = DEFAULT_QUEUE_CAPACITY,
        clock: Optional[HLC] = None,
    ):
        if rotate_bytes < 1024:
            raise ValueError(f"rotate_bytes must be >= 1024, got {rotate_bytes}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.out_dir = pathlib.Path(out_dir)
        self.role = str(role)
        self.rotate_bytes = int(rotate_bytes)
        self.keep = int(keep)
        self.clock = clock if clock is not None else HLC()
        self._lock = _locksan.lock("Journal._lock")
        self._tail: deque = deque(maxlen=tail_capacity)
        self._queue: deque = deque()
        self._queue_capacity = int(queue_capacity)
        self._seq = 0
        self._dropped = 0
        self._writing = False
        self._counts: Dict[str, int] = {}
        self._bytes_written = 0
        self._rotations = 0
        # records per on-disk generation (gen 0 = active), so retention
        # can meter exactly how many events a retired segment took away
        self._gen_records: Dict[int, int] = {0: 0}
        self._file = None
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.out_dir.mkdir(parents=True, exist_ok=True)
        self._thread = threading.Thread(
            target=self._run, name="gol-journal", daemon=True
        )
        self._thread.start()

    # -- paths ---------------------------------------------------------------

    @property
    def path(self) -> pathlib.Path:
        """The active segment (generation 0)."""
        return self.out_dir / f"journal_{self.role}_{os.getpid()}.jsonl"

    def _gen_path(self, gen: int) -> pathlib.Path:
        p = self.path
        return p if gen == 0 else p.with_name(
            p.name[: -len(".jsonl")] + f".g{gen}.jsonl"
        )

    # -- the hot surface -----------------------------------------------------

    def record(self, kind: str, name: str, /, **args) -> None:
        """Append one lifecycle event: HLC tick, tail ring, write queue.
        A full queue drops the event METERED (``gol_journal_drops_total``)
        — a wedged disk must never block a chunk commit. ``kind`` and
        ``name`` are positional-only so event args may reuse those
        names (``ckpt.verify`` carries the error's ``kind=``)."""
        event = {
            "kind": kind,
            "name": name,
            "t_unix": time.time(),
            "hlc": self.clock.tick(),
            "pid": os.getpid(),
            "role": self.role,
            "args": args,
        }
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._tail.append(event)
            self._counts[kind] = self._counts.get(kind, 0) + 1
            if len(self._queue) >= self._queue_capacity:
                self._dropped += 1
                _ins.JOURNAL_DROPS_TOTAL.inc()
            else:
                self._queue.append(event)
        _ins.JOURNAL_EVENTS_TOTAL.labels(kind).inc()
        self._wake.set()

    # -- the writer thread ---------------------------------------------------

    def _run(self) -> None:
        while True:
            self._wake.wait(FLUSH_INTERVAL)
            self._wake.clear()
            try:
                self._drain()
            # gol: allow(hygiene): the journal writer must survive disk
            # errors — the drop meter is the loud evidence, and the next
            # drain retries with a fresh open
            except Exception:  # pragma: no cover - depends on disk state
                pass
            if self._stop.is_set():
                with self._lock:
                    remaining = len(self._queue)
                if remaining == 0:
                    break
        f, self._file = self._file, None
        if f is not None:
            try:
                f.close()
            except OSError:  # pragma: no cover
                pass

    def _drain(self) -> None:
        """Write every queued event (writer thread only)."""
        while True:
            with self._lock:
                if not self._queue:
                    # in-flight flag cleared only once the last batch (and
                    # anything it enqueued, e.g. journal.drop on rotation)
                    # is on disk — flush() barriers on it, not just on an
                    # empty queue
                    self._writing = False
                    return
                batch = list(self._queue)
                self._queue.clear()
                self._writing = True
            for event in batch:
                if self._file is None:  # lazy (re)open after a rotation
                    self._file = open(self.path, "ab")
                    self._bytes_written = self.path.stat().st_size
                line = _frame(
                    json.dumps(event, separators=(",", ":"), default=str).encode()
                )
                self._file.write(line)
                self._bytes_written += len(line)
                self._gen_records[0] = self._gen_records.get(0, 0) + 1
                _ins.JOURNAL_BYTES_TOTAL.inc(len(line))
                # per-record, not per-batch: one giant drain must not
                # blow the segment past its size cap
                if self._bytes_written >= self.rotate_bytes:
                    self._file.flush()
                    self._rotate()
            if self._file is not None:
                self._file.flush()

    def _rotate(self) -> None:
        """Retire the active segment down the generation chain (writer
        thread only): active -> .g1 -> ... -> .g<keep-1>, the oldest
        beyond ``keep`` unlinked with its record count metered on the
        drop counter — retention is bounded, never silent."""
        self._file.close()
        self._file = None
        retired = self._gen_path(self.keep - 1)
        if self.keep > 1 and retired.exists():
            lost = self._gen_records.get(self.keep - 1)
            if lost is None:  # a segment from a previous process lifetime
                lost = sum(1 for _ in retired.open("rb"))
            with self._lock:
                self._dropped += lost
            _ins.JOURNAL_DROPS_TOTAL.inc(lost)
            self.record("journal.drop", str(retired), records=lost)
            retired.unlink()
        elif self.keep == 1:
            lost = self._gen_records.get(0, 0)
            with self._lock:
                self._dropped += lost
            _ins.JOURNAL_DROPS_TOTAL.inc(lost)
            self.path.unlink(missing_ok=True)
            self._gen_records[0] = 0
            self._bytes_written = 0
            self._rotations += 1
            _ins.JOURNAL_ROTATIONS_TOTAL.inc()
            return
        for gen in range(self.keep - 2, -1, -1):
            src = self._gen_path(gen)
            if src.exists():
                src.replace(self._gen_path(gen + 1))
                self._gen_records[gen + 1] = self._gen_records.pop(gen, 0)
        self._gen_records[0] = 0
        self._bytes_written = 0
        self._rotations += 1
        _ins.JOURNAL_ROTATIONS_TOTAL.inc()

    # -- control-path queries ------------------------------------------------

    def window(self, since: int = 0) -> dict:
        """The Status payload form: tail events with seq > ``since``
        (the poller echoes the last seq it saw — ``journal_since``).
        Plain JSON-able throughout: the payload crosses the restricted
        unpickler."""
        with self._lock:
            events = [e for e in self._tail if e["seq"] > since]
            seq = self._seq
            dropped = self._dropped
        return {
            "schema": SCHEMA,
            "seq": seq,
            "role": self.role,
            "node": self.clock.node,
            "dropped": dropped,
            "events": events,
        }

    def summary(self) -> dict:
        """Counts by kind + retention state — the RunReport embed."""
        with self._lock:
            counts = dict(self._counts)
            dropped = self._dropped
            total = self._seq
        return {
            "schema": SCHEMA,
            "role": self.role,
            "node": self.clock.node,
            "events_total": total,
            "by_kind": counts,
            "dropped": dropped,
            "rotations": self._rotations,
            "segments": [str(p) for p in self.segments()],
        }

    def segments(self) -> List[pathlib.Path]:
        """This journal's on-disk segments, oldest generation first."""
        out = [
            self._gen_path(gen)
            for gen in range(self.keep - 1, -1, -1)
            if self._gen_path(gen).exists()
        ]
        return out

    def flush(self, timeout: float = 2.0) -> None:
        """Block until everything queued so far is on disk (bounded)."""
        deadline = time.monotonic() + timeout
        self._wake.set()
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._writing:
                    return
            self._wake.set()
            time.sleep(0.01)

    def close(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout)


# -- segment readers (history, doctor, tests) ---------------------------------


def read_segment(path) -> Tuple[List[dict], List[str]]:
    """One segment -> (events, problems). Every record that fails its
    crc frame or parse — a torn tail from a SIGKILL mid-write, a flipped
    byte in cold storage — lands in ``problems`` with its line number
    and is SKIPPED: detected loudly, never mis-parsed, never a crash."""
    path = pathlib.Path(path)
    events: List[dict] = []
    problems: List[str] = []
    try:
        raw = path.read_bytes()
    except OSError as exc:
        return [], [f"{path}: unreadable ({exc})"]
    for lineno, line in enumerate(raw.split(b"\n"), 1):
        if not line:
            continue
        record = _unframe(line)
        if isinstance(record, dict):
            events.append(record)
        else:
            problems.append(f"{path}:{lineno}: {record} — record skipped")
    return events, problems


def segment_paths(out_dir="out") -> List[pathlib.Path]:
    """Every journal segment under ``out_dir`` (all roles, all pids,
    all generations), sorted by name — the dead-process read surface."""
    out_dir = pathlib.Path(out_dir)
    if not out_dir.is_dir():
        return []
    return sorted(
        p for p in out_dir.iterdir() if _SEGMENT_RE.match(p.name)
    )


def read_segments(paths_or_dir) -> Tuple[List[dict], List[str]]:
    """Many segments (or a directory of them) -> (events merged in HLC
    order, problems). The merge is deterministic: ``hlc_key`` breaks
    ties by node id, so the same segments always render the same
    timeline."""
    if isinstance(paths_or_dir, (str, pathlib.Path)):
        # a directory (possibly absent: no segments yet -> empty), never
        # a char-by-char iteration of the string
        paths = segment_paths(paths_or_dir)
    else:
        paths = [pathlib.Path(p) for p in paths_or_dir]
    events: List[dict] = []
    problems: List[str] = []
    for p in paths:
        ev, pr = read_segment(p)
        events.extend(ev)
        problems.extend(pr)
    events.sort(key=hlc_key)
    return events, problems


# -- the process-global default journal + clock -------------------------------

#: the process HLC: ALWAYS live (stamping/merging costs a few integer
#: compares under a lock), so causality survives even between processes
#: whose journals are off — rpc/client.py and rpc/server.py stamp every
#: call both ways unconditionally
_CLOCK = HLC()

_JOURNAL: Optional[Journal] = None


def clock() -> HLC:
    return _CLOCK


def stamp() -> List:
    """An outbound HLC stamp (rpc/client.py request, rpc/server.py
    reply): one tick of the process clock."""
    return _CLOCK.tick()


def observe(remote) -> None:
    """Merge a received stamp (getattr-read from the ``hlc`` extension
    field; None from a skewed peer is a no-op)."""
    if remote is not None:
        _CLOCK.merge(remote)


def journal() -> Optional[Journal]:
    return _JOURNAL


def enabled() -> bool:
    return _JOURNAL is not None


def enable(
    out_dir="out",
    role: str = "engine",
    rotate_bytes: int = DEFAULT_ROTATE_BYTES,
    keep: int = DEFAULT_KEEP,
) -> Journal:
    """Open the process journal (the ``-journal`` CLI flags). The global
    HLC is shared with the RPC stamping surface, so journal records and
    wire stamps advance one clock."""
    global _JOURNAL
    if _JOURNAL is not None:
        _JOURNAL.close()
    _JOURNAL = Journal(
        out_dir=out_dir, role=role, rotate_bytes=rotate_bytes, keep=keep,
        clock=_CLOCK,
    )
    return _JOURNAL


def disable() -> None:
    global _JOURNAL
    j, _JOURNAL = _JOURNAL, None
    if j is not None:
        j.close()


def record(kind: str, name: str, /, **args) -> None:
    """The module-level hot surface: one global load and a branch while
    disabled (the flight.record posture). ``kind``/``name`` are
    positional-only so event args may reuse those names."""
    j = _JOURNAL
    if j is not None:
        j.record(kind, name, **args)


def window(since: int = 0) -> Optional[dict]:
    """The Status payload section, or None while disabled."""
    j = _JOURNAL
    return j.window(since) if j is not None else None


def summary() -> Optional[dict]:
    j = _JOURNAL
    return j.summary() if j is not None else None


def flush_on_crash(exc: Optional[BaseException] = None) -> None:
    """Best-effort final flush for an unhandled exception (the crash
    hooks in engine/broker/worker): records the crash as the journal's
    final event, then drains the queue to disk. Never raises — a broken
    disk must not mask the original exception."""
    j = _JOURNAL
    if j is None:
        return
    try:
        if exc is not None:
            j.record("crash", type(exc).__name__, message=str(exc)[:500])
        j.flush()
    # gol: allow(hygiene): the crash hook must never mask the original
    # exception with a secondary disk/teardown failure — best-effort
    except Exception:  # pragma: no cover - depends on disk state
        pass
