"""Every metric the codebase records, declared in ONE place.

The names are a stable operator-facing contract: they appear in RunReport
JSON, in ``Status`` RPC payloads, and in Prometheus scrapes, so renaming
one is a breaking change. The README's "Observability" table documents
them all, and ``obs/lint.py`` (run by ``tests/test_obs.py``) fails the
build if this module and that table drift apart.

Conventions: seconds for every duration histogram (fixed DEFAULT_BUCKETS
edges — the exact-merge contract), ``_total`` suffix on counters,
``method``/``plane``/``site`` labels kept low-cardinality (RPC verb names,
plane kinds, compile-cache sites — never per-board values).

All instruments bind to the process-global default registry, which starts
DISABLED: importing this module from a hot path costs nothing until an
entry point calls ``metrics.enable()`` (the ``-metrics`` flags).
"""

from __future__ import annotations

from .metrics import registry

_R = registry()

# -- engine run loop (engine/engine.py) -------------------------------------

ENGINE_STEP_SECONDS = _R.histogram(
    "gol_engine_step_seconds",
    "Per-turn step time, dispatch wall / chunk turns (near-zero for "
    "pipelined async chunks; growth-phase chunks are synchronous and "
    "accurate). Count == turns evolved.",
)
ENGINE_DISPATCH_SECONDS = _R.histogram(
    "gol_engine_dispatch_seconds",
    "Per-chunk dispatch wall time (block_until_ready during chunk growth, "
    "enqueue-only once pipelined).",
)
ENGINE_PARK_SECONDS = _R.histogram(
    "gol_engine_park_seconds",
    "Time the run loop spent parked in the pause gate, per park.",
)
ENGINE_CHECKPOINT_SECONDS = _R.histogram(
    "gol_engine_checkpoint_seconds",
    "Periodic checkpoint write time (including failed attempts).",
)
ENGINE_TURNS_TOTAL = _R.counter(
    "gol_engine_turns_total", "Turns evolved by this process's engine."
)
ENGINE_CHUNKS_TOTAL = _R.counter(
    "gol_engine_chunks_total", "Chunk dispatches issued by the run loop."
)
ENGINE_CHUNK_SIZE = _R.gauge(
    "gol_engine_chunk_size", "Current turns-per-dispatch chunk size."
)
ENGINE_CHECKPOINT_ERRORS_TOTAL = _R.counter(
    "gol_engine_checkpoint_errors_total",
    "Periodic checkpoint attempts that failed (run continues).",
)

# -- controller / ticker (engine/controller.py) -----------------------------

CONTROLLER_TICK_SECONDS = _R.histogram(
    "gol_controller_tick_seconds",
    "Ticker count-only retrieve latency (the 2 s AliveCellsCount path).",
)
CONTROLLER_KEY_SECONDS = _R.histogram(
    "gol_controller_key_seconds",
    "Keypress handling latency, per key.",
    labelnames=("key",),
)
CONTROLLER_EMIT_SECONDS = _R.histogram(
    "gol_controller_emit_seconds",
    "Event-queue put latency on the controller's emit paths.",
)
CONTROLLER_EVENTS_TOTAL = _R.counter(
    "gol_controller_events_total",
    "Events emitted by the controller, by event type.",
    labelnames=("event",),
)

# -- RPC, both sides (rpc/client.py, rpc/server.py) -------------------------

RPC_CLIENT_REQUESTS_TOTAL = _R.counter(
    "gol_rpc_client_requests_total",
    "Outbound RPC calls issued, by verb.",
    labelnames=("method",),
)
RPC_CLIENT_ERRORS_TOTAL = _R.counter(
    "gol_rpc_client_errors_total",
    "Outbound RPC calls that raised RpcError, by verb.",
    labelnames=("method",),
)
RPC_CLIENT_REQUEST_SECONDS = _R.histogram(
    "gol_rpc_client_request_seconds",
    "Outbound RPC round-trip latency (send to reply), by verb.",
    labelnames=("method",),
)
RPC_CLIENT_SENT_BYTES_TOTAL = _R.counter(
    "gol_rpc_client_sent_bytes_total",
    "Request frame bytes (header + pickle payload) sent, by verb.",
    labelnames=("method",),
)
RPC_CLIENT_RECEIVED_BYTES_TOTAL = _R.counter(
    "gol_rpc_client_received_bytes_total",
    "Reply frame bytes received, by verb.",
    labelnames=("method",),
)
RPC_SERVER_REQUESTS_TOTAL = _R.counter(
    "gol_rpc_server_requests_total",
    "Inbound RPC calls dispatched, by verb.",
    labelnames=("method",),
)
RPC_SERVER_ERRORS_TOTAL = _R.counter(
    "gol_rpc_server_errors_total",
    "Inbound RPC calls answered with an error reply, by verb.",
    labelnames=("method",),
)
RPC_SERVER_REQUEST_SECONDS = _R.histogram(
    "gol_rpc_server_request_seconds",
    "Inbound RPC handler latency (dispatch to reply written), by verb.",
    labelnames=("method",),
)
RPC_SERVER_RECEIVED_BYTES_TOTAL = _R.counter(
    "gol_rpc_server_received_bytes_total",
    "Request frame bytes received, by verb.",
    labelnames=("method",),
)
RPC_SERVER_SENT_BYTES_TOTAL = _R.counter(
    "gol_rpc_server_sent_bytes_total",
    "Reply frame bytes sent, by verb.",
    labelnames=("method",),
)

# -- wire data plane (rpc/protocol.py frames, rpc/broker.py wire modes) -----

WIRE_BYTES_TOTAL = _R.counter(
    "gol_wire_bytes_total",
    "Frame bytes this process's RPC clients moved, by verb and direction "
    "(sent/received) — the data-plane comms meter the wire-mode bench "
    "cases embed and scripts/bench_diff gates.",
    labelnames=("verb", "direction"),
)
TURN_BATCH_SIZE = _R.histogram(
    "gol_turn_batch_size",
    "Turns advanced per workers-backend RPC batch (resident wire mode: K "
    "turns per StripStep round-trip; full/haloed: always 1).",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
)
STRIP_RESYNC_TOTAL = _R.counter(
    "gol_strip_resync_total",
    "Resident-mode full strip re-syncs (StripFetch gathers): -sync-interval "
    "expiry, snapshot/pause/checkpoint/run-end boundaries, and loss "
    "recovery.",
)

# -- multi-universe serving (engine/sessions.py, rpc/broker.py scheduler) ---

SESSIONS_ACTIVE = _R.gauge(
    "gol_sessions_active",
    "Universes currently packed (or pending admission) in this process's "
    "device-batched session table.",
)
SESSIONS_ADMITTED_TOTAL = _R.counter(
    "gol_sessions_admitted_total",
    "Sessions admitted into the batched session table since start.",
)
SESSIONS_REJECTED_TOTAL = _R.counter(
    "gol_sessions_rejected_total",
    "Session admissions refused, by reason: 'capacity' (table full), "
    "'geometry' (board shape differs from the batch's), 'rule' (rule "
    "differs from the batch's), 'turns' (non-positive budget), 'tag' "
    "(client session tag already in use).",
    labelnames=("reason",),
)
SESSION_TURNS_TOTAL = _R.counter(
    "gol_session_turns_total",
    "Universe-turns evolved by the batched session driver (each k-turn "
    "batched dispatch adds k x active universes).",
)

# -- serving SLOs (obs/timeline.py sampler, obs/slo.py rules) ---------------

SESSION_TURN_SECONDS = _R.histogram(
    "gol_session_turn_seconds",
    "Per-universe-turn serving latency of the batched session driver "
    "(engine/sessions.py): each k-turn batched dispatch records its wall "
    "normalized per universe-turn, count == universe-turns — the "
    "latency objective the 'session-turn-latency' SLO rule evaluates.",
)
SESSION_ADMIT_WAIT_SECONDS = _R.histogram(
    "gol_session_admit_wait_seconds",
    "SessionRun admission latency (rpc/broker.SessionScheduler.submit "
    "entry to the session joining the table) — the 'session-admit-"
    "latency' SLO rule's feed; growth means the driver thread is "
    "starved or the table lock is contended.",
)
RPC_DISPATCH_SECONDS = _R.histogram(
    "gol_rpc_dispatch_seconds",
    "Inbound RPC HANDLER time only (fn(request) inside the dispatch, "
    "excluding frame parse and reply serialisation — "
    "gol_rpc_server_request_seconds covers the whole dispatch), by verb "
    "— the 'rpc-dispatch-latency' SLO rule's feed. Verbs that BLOCK by "
    "contract (Run, SessionRun: rpc/protocol.BLOCKING_METHODS) are "
    "excluded; their handler wall is the run length, not a latency.",
    labelnames=("method",),
)
SCATTER_DEADLINE_SECONDS = _R.gauge(
    "gol_scatter_deadline_seconds",
    "The workers backend's current per-scatter reply deadline (pinned "
    "by -rpc-deadline, else adaptive ~20x the turn-time EWMA): the "
    "'scatter-deadline-growth' SLO rule alerts on its drift — the "
    "cluster getting slower before anything has failed.",
)
SLO_ALERTS_TOTAL = _R.counter(
    "gol_slo_alerts_total",
    "SLO rule firings (obs/slo.py RuleBook transitions to firing), by "
    "rule name and severity (page/warn). Active alert STATE lives in "
    "the Status payload's 'alerts' field; this counter is the "
    "scrape-able history.",
    labelnames=("rule", "severity"),
)

# -- blackbox canary + open-loop load harness (obs/canary.py,
#    obs/loadgen.py) ---------------------------------------------------------

CANARY_PROBES_TOTAL = _R.counter(
    "gol_canary_probes_total",
    "Blackbox canary probes (obs/canary.py: a known-oracle universe "
    "through the full RPC + session path), by result: 'ok' (bit-exact), "
    "'corrupt' (the serving path returned WRONG bits — the silent class "
    "the 'canary-failure' SLO rule pages on), 'error' (the path failed "
    "loudly: transport/reply error or timeout).",
    labelnames=("result",),
)
CANARY_LATENCY_SECONDS = _R.histogram(
    "gol_canary_latency_seconds",
    "End-to-end canary probe latency (submit to verified final board), "
    "success or failure — a slow canary is an early latency signal from "
    "the exact path tenants use.",
)
LOADGEN_ADMIT_TO_FIRST_TURN_SECONDS = _R.histogram(
    "gol_loadgen_admit_to_first_turn_seconds",
    "CLIENT-side admission-to-first-turn latency measured by the "
    "open-loop load generator (obs/loadgen.py): session arrival to the "
    "first turn visible via the tagged retrieve poller (quantized by "
    "the poll cadence; a session that drains before the first poll "
    "records its end-to-end wall) — the ROADMAP front-door objective.",
)
LOADGEN_SESSION_SECONDS = _R.histogram(
    "gol_loadgen_session_seconds",
    "CLIENT-side end-to-end session latency measured by the open-loop "
    "load generator: arrival to final board.",
)
LOADGEN_SESSIONS_TOTAL = _R.counter(
    "gol_loadgen_sessions_total",
    "Load-generator session outcomes, by 'ok' / 'rejected' (structured "
    "SessionRejected reply — reasons break out in the loadgen summary "
    "and the per-tenant accounting ledger) / 'error'.",
    labelnames=("outcome",),
)

# -- data integrity (rpc/integrity.py: checked frames, attestation,
#    verified checkpoints) ---------------------------------------------------

INTEGRITY_CHECKS_TOTAL = _R.counter(
    "gol_integrity_checks_total",
    "Integrity verifications performed: in-header frame crc words verified, "
    "resident-strip digest-chain / edge-digest / halo cross-attestation "
    "comparisons on the broker.",
)
INTEGRITY_FAILURES_TOTAL = _R.counter(
    "gol_integrity_failures_total",
    "Integrity verifications that FAILED, by kind: 'frame' (checksum "
    "mismatch — the frame was never parsed), 'strip' (a resident strip's "
    "pre-batch digest broke the committed chain: in-place corruption), "
    "'edges' (reply edge rows disagree with their attested digest), "
    "'attest' (neighbouring strips' redundant boundary-band digests "
    "disagree: wrong compute), 'fetch' (a gathered strip does not hash to "
    "the committed chain). Every failure routes the suspect through the "
    "loss/quarantine machinery.",
    labelnames=("kind",),
)
CKPT_VERIFY_TOTAL = _R.counter(
    "gol_ckpt_verify_total",
    "Checkpoint digest verifications (engine/checkpoint.py "
    "load_verified_checkpoint), by result (ok/fail) — every -resume "
    "attempt and -ckpt-keep fallback probe counts here.",
    labelnames=("result",),
)

# -- fault tolerance (rpc/client.py reconnect, rpc/broker.py recovery) ------

RPC_RETRIES_TOTAL = _R.counter(
    "gol_rpc_retries_total",
    "RPC client transport reconnect attempts (capped jittered exponential "
    "backoff). In-flight calls fail and are never silently re-sent; only "
    "the transport is retried.",
)
WORKER_LOST_TOTAL = _R.counter(
    "gol_worker_lost_total",
    "Workers dropped from the broker's scatter set mid-run (connection "
    "loss or scatter-deadline expiry) — each loss re-splits the rows over "
    "the survivors.",
)
WORKER_READMITTED_TOTAL = _R.counter(
    "gol_worker_readmitted_total",
    "Lost or never-connected roster addresses readmitted by the broker's "
    "background probe (a full worker Status round-trip); the row split "
    "re-expands at the next turn.",
)
TURN_RETRY_TOTAL = _R.counter(
    "gol_turn_retry_total",
    "Scatter/gather turns recomputed after losing workers (the same turn "
    "is retried from the committed pre-turn world — never a skipped or "
    "half-applied turn).",
)
AUTO_CHECKPOINT_TOTAL = _R.counter(
    "gol_auto_checkpoint_total",
    "Periodic broker auto-checkpoints written (-auto-checkpoint; "
    "tmp-then-rename, failures logged and excluded).",
)

# -- kernel-tier selection + compile cache (ops/auto.py, parallel/*) --------

OPS_PLANE_SELECTED_TOTAL = _R.counter(
    "gol_ops_plane_selected_total",
    "Automatic data-plane routing decisions, by selected tier "
    "(bitplane / sparse_bitplane / roll_stencil / pallas_bit_step / "
    "packed_xla_step, plus the batched family's batch_bitplane / "
    "batch_roll_stencil). Cached per (rule, shape): counts DECISIONS, "
    "not admissions.",
    labelnames=("plane",),
)
COMPILE_CACHE_REQUESTS_TOTAL = _R.counter(
    "gol_compile_cache_requests_total",
    "Compiled-program cache lookups on the mesh step paths, by site.",
    labelnames=("site",),
)
COMPILE_CACHE_MISSES_TOTAL = _R.counter(
    "gol_compile_cache_misses_total",
    "Cache lookups that traced+compiled a new program (hits = requests "
    "- misses), by site.",
    labelnames=("site",),
)

# -- halo-exchange data planes (parallel/halo.py, parallel/bit_halo.py) -----

HALO_DISPATCH_SECONDS = _R.histogram(
    "gol_halo_dispatch_seconds",
    "Host-side wall time of one mesh step_n dispatch (trace/compile on "
    "first call, enqueue after; device-side exchange time lives in the "
    "jax.profiler trace), by plane.",
    labelnames=("plane",),
)
HALO_EXCHANGES_TOTAL = _R.counter(
    "gol_halo_exchanges_total",
    "Halo exchanges (one rows+cols ppermute pair) issued inside mesh "
    "dispatches, by plane.",
    labelnames=("plane",),
)

# -- device / XLA telemetry (obs/device.py) ---------------------------------

COMPILE_SECONDS = _R.histogram(
    "gol_compile_seconds",
    "Wall time of one explicit XLA lower+compile at an instrumented "
    "compile site (obs/device.instrument_jit), by site.",
    labelnames=("site",),
)
KERNEL_FLOPS = _R.gauge(
    "gol_kernel_flops",
    "XLA cost-analysis FLOP estimate of the most recently compiled "
    "program at a site (Lowered.cost_analysis).",
    labelnames=("site",),
)
KERNEL_BYTES_ACCESSED = _R.gauge(
    "gol_kernel_bytes_accessed",
    "XLA cost-analysis bytes-accessed estimate of the most recently "
    "compiled program at a site.",
    labelnames=("site",),
)
HBM_BYTES_IN_USE = _R.gauge(
    "gol_device_hbm_bytes_in_use",
    "Device memory in use (memory_stats bytes_in_use), sampled per "
    "turn-chunk and at checkpoints; absent on backends without memory "
    "stats (CPU).",
    labelnames=("device",),
)
HBM_PEAK_BYTES = _R.gauge(
    "gol_device_hbm_peak_bytes",
    "Device-reported peak memory in use (memory_stats peak_bytes_in_use).",
    labelnames=("device",),
)
HBM_BYTES_LIMIT = _R.gauge(
    "gol_device_hbm_bytes_limit",
    "Device memory capacity (memory_stats bytes_limit).",
    labelnames=("device",),
)

# -- performance attribution (obs/perf.py roofline, obs/critical.py
#    straggler/critical-path, dispatch-wall decomposition) -------------------

KERNEL_DISPATCH_SECONDS = _R.histogram(
    "gol_kernel_dispatch_seconds",
    "Host-side wall of one instrumented compiled-executable call "
    "(obs/device.py AOT path), by site — the measured-dispatch-wall half "
    "of the roofline join (gol_kernel_flops / _bytes_accessed are the "
    "cost half). Pipelined callers that only enqueue record enqueue "
    "time; callers that sync (growth chunks, count reductions) record "
    "real device wall — the honest-caveat split the README documents.",
    labelnames=("site",),
)
KERNEL_ACHIEVED_FLOPS = _R.gauge(
    "gol_kernel_achieved_flops",
    "Achieved FLOP/s at a kernel site: XLA cost-analysis flops executed "
    "divided by measured dispatch wall, over every instrumented call so "
    "far (obs/perf.refresh_metrics sets it on Status polls and report "
    "writes).",
    labelnames=("site",),
)
KERNEL_ACHIEVED_BYTES = _R.gauge(
    "gol_kernel_achieved_bytes_per_s",
    "Achieved memory throughput at a kernel site: cost-analysis bytes "
    "accessed divided by measured dispatch wall (gol_kernel_achieved_"
    "flops's memory twin).",
    labelnames=("site",),
)
KERNEL_BOUND = _R.gauge(
    "gol_kernel_bound",
    "Roofline classification of a kernel site against the calibrated "
    "device ceilings (obs/perf.py): 1 on the site's current class "
    "(compute-bound / memory-bound / launch-bound), 0 on the others.",
    labelnames=("site", "class"),
)
TURN_SEGMENT_SECONDS = _R.histogram(
    "gol_turn_segment_seconds",
    "Dispatch-wall decomposition: each turn-chunk/K-batch's wall split "
    "into host_prep (planning, encode, request assembly), "
    "device_compute (kernel/worker compute — block_until_ready delta "
    "on the engine, the gating worker's reported service time on the "
    "broker), wire (round-trip wall minus service, workers backend "
    "only), and demux (reply validation, commit, event fan-out), by "
    "component (engine / sessions / broker) and segment — the "
    "WHERE-TIME-GOES panel's feed.",
    labelnames=("component", "segment"),
)
STRIP_STEP_SECONDS = _R.histogram(
    "gol_strip_step_seconds",
    "Per-worker StripStep round-trip wall as the broker measured it "
    "(resident wire mode), by worker address — the straggler/critical-"
    "path feed (obs/critical.py): per K-batch the slowest of these "
    "gated the gather.",
    labelnames=("addr",),
)
WORKER_SKEW_RATIO = _R.gauge(
    "gol_worker_skew_ratio",
    "Worst per-worker service-time skew: the slowest worker's "
    "service-time EWMA over the roster median (obs/critical.py), "
    "updated per K-batch — 1.0 is a balanced roster; the 'worker-skew' "
    "SLO GrowthRule alerts on its drift.",
)

# -- activity-sparse stepping (ops/sparse.py, rpc/ dirty-tile deltas,
#    engine early exits) ------------------------------------------------------

ACTIVE_TILES = _R.gauge(
    "gol_active_tiles",
    "Active tiles after the most recent sparse step chunk (ops/sparse."
    "SparseBitPlane) — or, on a resident-wire broker, dirty tiles "
    "reported by the roster's latest StripStep batch. The frontier size "
    "the SPARSITY watch panel tracks.",
)
TILE_SKIPS_TOTAL = _R.counter(
    "gol_tile_skips_total",
    "Tiles NOT computed by the sparse stepper (total tiles minus active, "
    "summed per turn): the work the activity bitmap saved vs the dense "
    "path.",
)
SPARSE_FRAME_BYTES_TOTAL = _R.counter(
    "gol_sparse_frame_bytes_total",
    "Payload bytes of dirty-tile delta frames shipped instead of full "
    "gathers (resident-wire StripFetch deltas: flat tile buffer + dirty "
    "bitmap) — the sparse-wire meter bench embeds as "
    "sparse_frame_bytes_per_sync and bench_diff gates.",
)
EARLY_EXIT_TOTAL = _R.counter(
    "gol_early_exit_total",
    "Runs short-circuited arithmetically instead of computed, by kind: "
    "'still' (activity bitmap drained — a still life's remaining turns "
    "are no-ops), 'period2' (board(t+2) == board(t): blinker-stable, "
    "remaining turns resolve by parity), 'dead' (a batched session "
    "universe's alive count hit 0 under a non-B0 rule: retired at the "
    "next boundary with its full budget credited).",
    labelnames=("kind",),
)

# -- 2-D tile data plane (rpc/broker.py -grid, rpc/worker.py tile batches) ---

# terse help by design: every registered family's help rides EVERY
# Status reply, which tests/test_tenants.py budgets at 64 KiB — the full
# semantics live in README "## 2-D tiles" (lint-tile-names enforces it)
HALO_BYTES_TOTAL = _R.counter(
    "gol_halo_bytes_total",
    "Resident-wire halo bytes moved, both directions, by axis "
    "(row/col edge bands, corner KxK blocks).",
    labelnames=("axis",),
)
TILE_EDGE_CELLS = _R.gauge(
    "gol_tile_edge_cells",
    "Cells in one K-batch halo exchange for the largest active tile "
    "(2K(th+tw) + 4K^2; a 1-column grid counts its 2KW strip rows).",
)
TILE_GRID_ROWS = _R.gauge(
    "gol_tile_grid_rows",
    "Row bands of the active resident tile layout (N for strips).",
)
TILE_GRID_COLS = _R.gauge(
    "gol_tile_grid_cols",
    "Column bands of the active resident tile layout (1 for strips).",
)

# -- fused K-turns-per-launch stepping (ops/fused.py, rpc/worker.py) ---------

FUSED_LAUNCHES_TOTAL = _R.counter(
    "gol_fused_launches_total",
    "Device kernel launches issued by the fused K-turns-per-launch tier "
    "(ops/fused.py: whole-board/tiled/batched ladders, fused step+count "
    "programs, the worker's fused strip batch). The denominator of the "
    "launch-amortisation story: turns advanced / launches issued is the "
    "effective K.",
)
FUSED_TURNS_PER_LAUNCH = _R.histogram(
    "gol_fused_turns_per_launch",
    "Turns advanced per fused device launch (the K distribution): full-K "
    "ladder launches observe K, pow2 remainder launches their size, and "
    "one-dispatch step+count programs the whole chunk. A collapse toward "
    "1 means the fusion is being bypassed — the launch floor is back.",
)
STRIP_ROWS_SKIPPED_TOTAL = _R.counter(
    "gol_strip_rows_skipped_total",
    "Row-steps the resident worker's dead-band skip did NOT compute "
    "(rows outside the live frontier's K-deep dependency cone, summed "
    "over the batch's steps — rpc/worker.strip_step_batch): the work the "
    "frontier bound saved vs stepping the full strip.",
)

# -- lifecycle journal (obs/journal.py) ---------------------------------------

JOURNAL_EVENTS_TOTAL = _R.counter(
    "gol_journal_events_total",
    "Lifecycle events appended to the durable journal (obs/journal.py), "
    "by event kind (the journal's declared EVENT_KINDS table: "
    "session.admit, chunk.commit, worker.lost, ckpt.write, ...). The "
    "per-process tally of the durable, HLC-stamped history that "
    "obs/history.py reconstructs cross-process timelines from.",
    labelnames=("kind",),
)
JOURNAL_BYTES_TOTAL = _R.counter(
    "gol_journal_bytes_total",
    "Bytes the journal's buffered writer appended to on-disk segments "
    "(crc-framed record lines, out/journal_<role>_<pid>*.jsonl).",
)
JOURNAL_ROTATIONS_TOTAL = _R.counter(
    "gol_journal_rotations_total",
    "Active journal segments retired down the generation chain when the "
    "size cap (rotate_bytes) was reached — the bounded-retention knob "
    "at work.",
)
JOURNAL_DROPS_TOTAL = _R.counter(
    "gol_journal_drops_total",
    "Journal records LOST to bounding — write-queue overflow on a "
    "wedged disk, plus every record inside a segment retired past the "
    "keep cap. Bounded retention may lose history; this meter is the "
    "contract that it never loses it silently.",
)

# -- continuous profiler (obs/profiler.py) -----------------------------------

PROFILE_SAMPLES_TOTAL = _R.counter(
    "gol_profile_samples_total",
    "Sampling ticks the continuous profiler (obs/profiler.py, the "
    "-profile [MS] flags) completed — each walks every thread's stack "
    "into the bounded call-tree trie. Rate vs the configured cadence "
    "shows adaptive backoff in action.",
)
PROFILE_BACKOFFS_TOTAL = _R.counter(
    "gol_profile_backoffs_total",
    "Times the profiler DOUBLED its own cadence because sampling cost "
    "exceeded its budget share (default 1%) of the period — the "
    "profiler refusing to become the hotspot it exists to find. A "
    "climbing value means the process has too many/too-deep threads "
    "for the configured -profile cadence.",
)

# -- GC observability (obs/profiler.py gc.callbacks hook) --------------------

GC_PAUSE_SECONDS = _R.histogram(
    "gol_gc_pause_seconds",
    "Stop-the-world garbage-collection pause walls (gc.callbacks "
    "start->stop), metered while the profiler runs. Feeds the "
    "'gc-pause' SLO rule: a pause is wall time no turn-segment "
    "decomposition can name, and past ~50 ms it IS the p99.",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
)
GC_COLLECTIONS_TOTAL = _R.counter(
    "gol_gc_collections_total",
    "Garbage-collection passes by generation ('0'/'1'/'2'), metered "
    "while the profiler runs. A hot gen-2 rate alongside gc-pause "
    "spikes usually means a reference-cycle churn in the serving path.",
    labelnames=("gen",),
)

# -- fleet collector (obs/fleet.py) -------------------------------------------
# Published only inside the COLLECTOR process (its own registry, its own
# Status verb): scrape health and merge cost of the control plane itself,
# kept off every data-plane process's registry by construction — the
# registrations below run lazily, on ``obs.fleet`` import, so a broker's
# or worker's Status payload never carries the fleet families' (empty)
# series and help text. The incremental-reply size budget
# (tests/test_tenants.py) counts every registered family.

FLEET_SCRAPES_TOTAL = None
FLEET_TARGETS_TOTAL = None
FLEET_TARGETS_DOWN = None
FLEET_SCRAPE_SECONDS = None
FLEET_MERGE_FAILURES_TOTAL = None
FLEET_SESSIONS_ACTIVE = None
FLEET_CAPACITY_TOTAL = None
FLEET_TENANT_SKEW = None


def register_fleet_instruments() -> None:
    """Register the gol_fleet_* families (idempotent — the registry
    refuses only signature CHANGES). obs/fleet.py calls this at import,
    the only module that publishes these series."""
    global FLEET_SCRAPES_TOTAL, FLEET_TARGETS_TOTAL, FLEET_TARGETS_DOWN
    global FLEET_SCRAPE_SECONDS, FLEET_MERGE_FAILURES_TOTAL
    global FLEET_SESSIONS_ACTIVE, FLEET_CAPACITY_TOTAL, FLEET_TENANT_SKEW
    FLEET_SCRAPES_TOTAL = _R.counter(
        "gol_fleet_scrapes_total",
        "Per-target Status scrape attempts by the fleet collector "
        "(obs/fleet.py), by outcome: 'ok' for a payload, 'error' for a "
        "timeout/refused/skew failure. The error rate per address is the "
        "scrape-health signal fleet doctor findings cite as evidence.",
        labelnames=("outcome",),
    )
    FLEET_TARGETS_TOTAL = _R.gauge(
        "gol_fleet_targets_total",
        "Targets the collector currently scrapes (configured brokers plus "
        "workers auto-discovered from their worker_health rosters).",
    )
    FLEET_TARGETS_DOWN = _R.gauge(
        "gol_fleet_targets_down",
        "Targets currently marked STALE: consecutive scrape failures pushed "
        "the last-success age past the staleness bound (3 intervals). The "
        "'target-down' fleet SLO rule pages on this going nonzero — a dead "
        "broker is a first-class finding, not a timeout traceback.",
    )
    FLEET_SCRAPE_SECONDS = _R.histogram(
        "gol_fleet_scrape_seconds",
        "Wall seconds per fleet poll sweep (parallel fan-out across all "
        "targets + exact merge + fleet timeline sample). bench.py embeds "
        "its p99 as fleet_scrape_p99_us and gates the data-plane tax of "
        "being scraped at <=2% beyond the noise band.",
    )
    FLEET_MERGE_FAILURES_TOTAL = _R.counter(
        "gol_fleet_merge_failures_total",
        "Target snapshots EXCLUDED from the merged cluster registry because "
        "merge_snapshots refused them (type or histogram-edge mismatch — "
        "version skew across the fleet). Skew degrades loudly, never "
        "wrongly: the exactness contract means a non-mergeable snapshot is "
        "dropped and counted, not averaged in.",
    )
    FLEET_SESSIONS_ACTIVE = _R.gauge(
        "gol_fleet_sessions_active",
        "Sum of gol_sessions_active across all live broker targets — the "
        "numerator of the fleet capacity-headroom rule (denominator: summed "
        "session_capacity from each broker's Status).",
    )
    FLEET_CAPACITY_TOTAL = _R.gauge(
        "gol_fleet_capacity_total",
        "Sum of session_capacity across all live broker targets. 0 while no "
        "broker has reported (keeps the headroom rule silent rather than "
        "dividing by a lie).",
    )
    FLEET_TENANT_SKEW = _R.gauge(
        "gol_fleet_tenant_skew",
        "Worst cross-broker tenant skew from the merged ledgers: for each "
        "tenant, its hottest broker's share of that tenant's fleet "
        "device-seconds, times the broker count (1.0 = perfectly spread, "
        "N = all load on one broker). Only computed once >=2 brokers ship "
        "ledgers; the 'fleet-tenant-skew' rule warns past 3x fair share.",
    )

# -- lock sanitizer (utils/locksan.py) ---------------------------------------

LOCKSAN_VIOLATIONS_TOTAL = _R.counter(
    "gol_locksan_violations_total",
    "Lock-sanitizer incidents under GOL_LOCKSAN=1 (utils/locksan.py), "
    "by kind: 'order' for an observed acquisition inverting the "
    "recorded lock order (the acquiring thread also aborts with both "
    "stacks), 'watchdog' for a lock held past GOL_LOCKSAN_DEADLINE "
    "with waiters queued (all-thread tracebacks dumped to "
    "out/locksan_<ts>.txt). Always 0 in production: the wrappers are "
    "never installed without the env knob.",
    labelnames=("kind",),
)
