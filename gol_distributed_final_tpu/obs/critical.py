"""Straggler / critical-path attribution for the fan-out data plane.

    python -m gol_distributed_final_tpu.obs.critical :8040   # live table
    python -m gol_distributed_final_tpu.obs.critical --selfcheck

Every workers-backend turn is a barrier: the broker's gather completes
when the SLOWEST worker replies, so one persistently slow worker sets
the whole cluster's turn rate — invisibly, because nothing fails. This
module makes the gating visible: the broker records each worker's
per-call round-trip wall (``gol_strip_step_seconds{addr}`` for resident
StripStep batches; scatter Update calls feed the tracker too), and per
K-batch the tracker attributes the gather to the worker that gated it,
keeping per-address service-time EWMAs, gated counts, and a roster skew
ratio (slowest EWMA / roster median) published on
``gol_worker_skew_ratio`` — the 'worker-skew' SLO GrowthRule's feed.

The tracker's ``snapshot()`` rides the broker's Status payload
(``critical_path``), so the doctor's ``straggler`` heuristic and the
watch dashboard name the gating worker with per-address evidence rows —
within one K-batch of the skew appearing, because attribution happens at
every batch commit, not on a sampling window.

Pure stdlib; the hot-loop feed is guarded by ``metrics.enabled()`` AND
``perf.attribution_enabled()`` (the bench's decomposition-overhead gate
A/Bs the latter).
"""

from __future__ import annotations

import argparse
import statistics
import sys
import threading
from typing import Dict, List, Optional, Tuple

from . import instruments as _ins
from ..utils import locksan as _locksan

#: EWMA smoothing for per-address service times (one K-batch is one step)
EWMA_ALPHA = 0.2
#: a worker is a STRAGGLER when it gated more than this share of batches...
STRAGGLER_GATED_SHARE = 0.5
#: ...AND its service-time EWMA exceeds the roster median by this ratio
STRAGGLER_SKEW_RATIO = 2.0


class _WorkerStat:
    __slots__ = ("ewma_s", "last_s", "calls", "gated")

    def __init__(self):
        self.ewma_s: Optional[float] = None
        self.last_s = 0.0
        self.calls = 0
        self.gated = 0


class CriticalPathTracker:
    """Per-address service-time EWMAs + per-batch gating attribution.

    ``record_batch`` is called once per committed K-batch from the
    broker's turn loop (single-threaded per run, but Status polls read
    concurrently — every touch is locked)."""

    _GUARDED_BY = {
        "_stats": "_lock",
        "_batches": "_lock",
        "_last_gating": "_lock",
    }

    def __init__(self):
        self._lock = _locksan.lock("CriticalPathTracker._lock")
        self._stats: Dict[str, _WorkerStat] = {}
        self._batches = 0
        self._last_gating: Optional[str] = None

    def record_batch(
        self,
        entries: List[Tuple[str, float, Optional[float]]],
        turn: int = 0,
        k: int = 1,
    ) -> Optional[str]:
        """Fold one batch's per-worker walls: ``entries`` is
        ``[(addr, round_trip_s, service_s | None)]`` (service is the
        worker-reported handler wall when the reply carried it — version
        skew degrades to the round trip). Returns the gating address.
        Updates the skew gauge; flight-records a gating change only when
        the skew is material (the ring must not churn per batch)."""
        if len(entries) < 1:
            return None
        gating_addr, gating_wall = None, -1.0
        with self._lock:
            for addr, rt, service in entries:
                wall = service if service else rt
                st = self._stats.setdefault(addr, _WorkerStat())
                st.last_s = wall
                st.calls += 1
                st.ewma_s = (
                    wall
                    if st.ewma_s is None
                    else (1 - EWMA_ALPHA) * st.ewma_s + EWMA_ALPHA * wall
                )
                if rt > gating_wall:
                    gating_addr, gating_wall = addr, rt
            self._batches += 1
            self._stats[gating_addr].gated += 1
            skew, _ = self._skew_locked()
            changed = gating_addr != self._last_gating
            self._last_gating = gating_addr
        _ins.WORKER_SKEW_RATIO.set(skew)
        if changed and skew >= STRAGGLER_SKEW_RATIO:
            from . import flight as _flight

            _flight.record(
                "critical.gate", gating_addr, turn=turn, k=k,
                skew=round(skew, 2),
            )
        return gating_addr

    def _skew_locked(self) -> Tuple[float, Optional[str]]:  # gol: holds(_lock)
        """(worst skew ratio, its address): slowest EWMA over the roster
        median. 1.0 for rosters of fewer than two measured workers (a
        lone worker cannot be skewed against anyone)."""
        ewmas = [
            (addr, st.ewma_s)
            for addr, st in self._stats.items()
            if st.ewma_s is not None
        ]
        if len(ewmas) < 2:
            return 1.0, None
        med = statistics.median(e for _, e in ewmas)
        if med <= 0:
            return 1.0, None
        addr, worst = max(ewmas, key=lambda p: p[1])
        return worst / med, addr

    def snapshot(self) -> dict:
        """JSON-able state for the Status payload: per-address evidence
        rows + the straggler verdict (None when the roster is
        balanced)."""
        with self._lock:
            batches = self._batches
            rows = [
                {
                    "addr": addr,
                    "ewma_s": round(st.ewma_s, 6) if st.ewma_s is not None else None,
                    "last_s": round(st.last_s, 6),
                    "calls": st.calls,
                    "gated": st.gated,
                    "gated_share": (
                        round(st.gated / batches, 4) if batches else 0.0
                    ),
                }
                for addr, st in sorted(self._stats.items())
            ]
            skew, skew_addr = self._skew_locked()
        out = {
            "batches": batches,
            "skew_ratio": round(skew, 3),
            "workers": rows,
            "straggler": None,
        }
        if batches and skew_addr is not None and skew >= STRAGGLER_SKEW_RATIO:
            row = next(r for r in rows if r["addr"] == skew_addr)
            if row["gated_share"] > STRAGGLER_GATED_SHARE:
                out["straggler"] = dict(row, skew=round(skew, 3))
        return out

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._batches = 0
            self._last_gating = None


# the process-global tracker (one broker per process — the obs posture)
_TRACKER = CriticalPathTracker()


def tracker() -> CriticalPathTracker:
    return _TRACKER


def attribute_batches(matrix: List[Dict[str, float]]) -> dict:
    """Pure-function attribution over a canned timing matrix
    (``[{addr: seconds}]`` per batch) through a FRESH tracker — the
    synthetic-fixture surface the tests pin the straggler math on."""
    t = CriticalPathTracker()
    for batch in matrix:
        t.record_batch([(a, s, None) for a, s in batch.items()])
    return t.snapshot()


def render(cp: dict) -> str:
    """Terminal table — pure function of a critical_path snapshot."""
    head = (
        f"critical path — {cp.get('batches', 0)} batch(es), roster skew "
        f"{cp.get('skew_ratio', 1.0):.2f}x"
    )
    cols = (
        f"{'worker':<24} {'ewma':>10} {'last':>10} {'calls':>6} "
        f"{'gated':>6} {'share':>7}"
    )
    lines = [head, cols, "-" * len(cols)]
    for r in cp.get("workers") or []:
        ewma = r.get("ewma_s")
        lines.append(
            f"{r.get('addr', '?'):<24} "
            f"{(f'{ewma * 1e3:.2f}ms' if ewma is not None else '-'):>10} "
            f"{r.get('last_s', 0.0) * 1e3:>8.2f}ms "
            f"{r.get('calls', 0):>6} {r.get('gated', 0):>6} "
            f"{100 * (r.get('gated_share') or 0.0):>6.1f}%"
        )
    s = cp.get("straggler")
    if s:
        lines.append(
            f"STRAGGLER: {s.get('addr')} gates {100 * s['gated_share']:.0f}% "
            f"of batches at {s.get('skew', 0):.1f}x the roster median"
        )
    return "\n".join(lines)


def _selfcheck() -> int:
    """The ``scripts/check --perf`` straggler smoke: a synthetic
    4-worker timing matrix with one 6x-slow worker must be attributed
    to that worker — and a balanced matrix must NOT name anyone."""
    slow = [
        {":8030": 0.010, ":8031": 0.011, ":8032": 0.060, ":8033": 0.009}
        for _ in range(5)
    ]
    cp = attribute_batches(slow)
    print(render(cp))
    s = cp.get("straggler")
    if not s or s.get("addr") != ":8032":
        print("critical selfcheck FAILED: straggler not attributed to "
              ":8032", file=sys.stderr)
        return 1
    balanced = [
        {":8030": 0.010, ":8031": 0.011, ":8032": 0.010, ":8033": 0.009}
        for _ in range(5)
    ]
    if attribute_batches(balanced).get("straggler") is not None:
        print("critical selfcheck FAILED: balanced roster produced a "
              "straggler", file=sys.stderr)
        return 1
    print("critical selfcheck ok: straggler attribution exact on the "
          "synthetic matrix")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="critical-path / straggler attribution over the "
        "read-only Status verb"
    )
    parser.add_argument(
        "address", nargs="?", default=None,
        help="broker host:port (or :port)",
    )
    parser.add_argument(
        "-timeout", type=float, default=5.0, metavar="SECONDS",
        help="poll reply bound (default 5)",
    )
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="synthetic-matrix attribution smoke (the scripts/check "
             "--perf gate)",
    )
    args = parser.parse_args(argv)
    if args.selfcheck:
        return _selfcheck()
    if not args.address:
        parser.error("an address is required (or --selfcheck)")
    from .status import StatusUnavailable, fetch_status

    try:
        payload = fetch_status(args.address, timeout=args.timeout)
    except StatusUnavailable as exc:
        print(f"critical: no status — {exc}", file=sys.stderr)
        return 1
    except Exception as exc:
        print(f"critical: poll failed — {exc}", file=sys.stderr)
        return 1
    cp = payload.get("critical_path")
    if not cp or not cp.get("batches"):
        print("critical: the broker has recorded no fan-out batches "
              "(tpu backend, or the run has not started)", file=sys.stderr)
        return 1
    print(render(cp))
    return 0


if __name__ == "__main__":
    sys.exit(main())
