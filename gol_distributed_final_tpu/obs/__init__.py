"""Observability — the metrics/report layer the perf work attributes to.

The reference's only runtime signals are the 2-second ``AliveCellsCount``
tick and a ``runtime/trace`` test wrapper (count_test.go, trace_test.go);
our port adds the ``jax.profiler`` shim in ``utils/trace.py``. Neither says
*where* a run's wall clock goes — dispatch vs. halo exchange vs. host
transfer vs. RPC — which is the first question every perf round asks
(BENCH_r*.json measures only end-to-end time).

This package is the answer, in eight parts:

* ``metrics``     — a dependency-free registry (counters, gauges,
                    fixed-bucket histograms) with JSON and Prometheus-text
                    exposition and EXACT cross-host merge;
* ``instruments`` — the single declaration site for every metric the
                    codebase records (engine, controller, RPC, ops,
                    parallel) — the stable-name contract the README
                    documents and ``lint`` enforces;
* ``report``      — the ``RunReport`` writer (registry + device inventory
                    + memory stats -> ``out/report_<W>x<H>x<Turns>.json``)
                    and the ``Status`` RPC payload builder;
* ``tracing``     — the cross-process span tracer (trace_id propagated
                    over ``Request.trace_ctx``) with Chrome trace-event
                    export (``out/trace_<W>x<H>x<Turns>.json``, Perfetto-
                    loadable) and the ``jax.profiler`` device-trace
                    fold-in (``-trace-device`` routes ``utils/trace.py``'s
                    profiler shim into the same out dir, span names pushed
                    as ``TraceAnnotation``s);
* ``flight``      — the hang flight-recorder: a bounded per-process ring
                    of the last structured events (span open/close, RPC
                    send/recv, checkpoint votes), shipped in ``Status``
                    replies and dumped to ``out/flight_<host>.jsonl`` on
                    unhandled engine exceptions;
* ``device``      — XLA-level telemetry: timed explicit lower/compile with
                    ``cost_analysis`` (FLOPs, bytes accessed) at every
                    kernel compile site, and per-device ``memory_stats``
                    HBM gauges sampled per turn-chunk (null-guarded on
                    CPU) with a process-local peak high-water mark;
* ``watch``       — the live terminal dashboard: polls broker/worker
                    ``Status`` and renders throughput, RPC latency,
                    compile-cache hit rate, HBM, and the flight tail — a
                    cluster ``top`` on the read-only verb;
* ``regress``     — the noise-aware perf-regression gate over two bench
                    JSON outputs (``scripts/bench_diff``): per-case
                    verdicts using each case's recorded endpoint spread,
                    provenance-checked, nonzero exit past the threshold.

Everything is process-local and OFF by default: with metrics and tracing
disabled each instrument call is a flag check, so the hot paths cost
nothing until an operator passes ``-metrics``/``-report``/``-trace`` (or
calls ``metrics.enable()`` / ``tracing.enable()``).
"""

from . import metrics  # noqa: F401
