"""Cross-process postmortem reconstruction: one causally-ordered timeline.

    python -m gol_distributed_final_tpu.obs.history myrun
    python -m gol_distributed_final_tpu.obs.history crash -dir out
    python -m gol_distributed_final_tpu.obs.history live -broker :8040 \
        -worker :8030 -worker :8031
    python -m gol_distributed_final_tpu.obs.history t7 -tenant 7
    python -m gol_distributed_final_tpu.obs.history w0 -address 127.0.0.1:8030

Every ``-journal`` process (broker, workers, engine) appends its
lifecycle events to its own ``out/journal_<role>_<pid>.jsonl`` segment,
each event stamped with a hybrid logical clock (obs/journal.py). This
CLI is the merge: it reads the on-disk segments of DEAD processes,
optionally fetches the live in-memory tails of RUNNING ones (the
incremental Status window, ``Request.journal_since`` — the
timeline_since pattern), dedups events that appear in both a live
window and a flushed segment, sorts everything by HLC key, and renders
the universe's history as one causal timeline: admission -> chunk
commits -> worker lost -> recovery/resplit -> readmission -> final.

Causality is what makes the merge meaningful: wall clocks across the
processes may disagree by seconds, but every RPC carries an HLC stamp
both ways (rpc/client.py / rpc/server.py), so a broker-side event
CAUSED by a worker's reply always sorts after the worker-side events
that produced it — no NTP assumption anywhere.

Torn or corrupted records (a SIGKILL mid-append) are crc-detected,
skipped, and reported LOUDLY in the ``problems`` section — never a
crash, never a silent gap.

Output: a terminal report plus ``out/history_<tag>.json`` (schema
``gol-history/1``), written tmp-then-rename like every other artifact.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time
from typing import List, Optional, Tuple

from . import journal as _journal

SCHEMA = "gol-history/1"

#: terminal render cap — the JSON artifact always carries everything
DEFAULT_SHOW = 200


#: the emitting process's identity (segment records carry it inside the
#: HLC stamp) — shared with doctor's journal heuristic
_node = _journal.event_node


def _dedup_key(event: dict) -> tuple:
    """Identity of one event across sources: the same record can arrive
    via a live Status window AND (later) the flushed on-disk segment —
    (node, seq) is unique per process journal, with the HLC stamp as a
    fallback for events from a pre-seq source."""
    node = _node(event)
    seq = event.get("seq")
    if isinstance(seq, int):
        return (node, seq)
    hlc = event.get("hlc")
    return (node, tuple(hlc) if isinstance(hlc, list) else event.get("t_unix"))


def merge_events(
    *sources: List[dict],
) -> List[dict]:
    """Merge event lists from any number of sources (segments, live
    windows) into ONE list in HLC order, deduplicating records seen via
    more than one source. Ties (same physical+logical) break on node id
    — deterministic regardless of input order."""
    seen = set()
    out: List[dict] = []
    for events in sources:
        for ev in events:
            if not isinstance(ev, dict):
                continue
            k = _dedup_key(ev)
            if k in seen:
                continue
            seen.add(k)
            out.append(ev)
    out.sort(key=_journal.hlc_key)
    return out


def _matches(
    event: dict,
    tenant: Optional[str],
    address: Optional[str],
    since_ms: Optional[int] = None,
    until_ms: Optional[int] = None,
) -> bool:
    if tenant is not None:
        args = event.get("args") or {}
        if str(args.get("tenant", "")) != tenant:
            return False
    if address is not None:
        # worker-address filter: matches the event NAME (loss/readmit/
        # quarantine events name the address) or the source node
        if address not in str(event.get("name", "")) and address not in _node(
            event
        ):
            return False
    if since_ms is not None or until_ms is not None:
        # time-window filter on the HLC's PHYSICAL milliseconds (the
        # sort key's first component) — the same clock the render
        # stamps, so a window cut from a rendered timeline round-trips.
        # Both bounds inclusive; an event with no usable stamp (hlc_key
        # falls back to 0) only survives an unbounded-below window.
        phys = _journal.hlc_key(event)[0]
        if since_ms is not None and phys < since_ms:
            return False
        if until_ms is not None and phys > until_ms:
            return False
    return True


def fetch_live_events(
    brokers: List[str], workers: List[str], timeout: float
) -> Tuple[List[dict], List[str]]:
    """Fetch the in-memory journal tails of live processes via Status
    (full window: since=0). A dead or journal-less process is a note,
    not a failure — its on-disk segments still tell its story."""
    from .status import fetch_status

    events: List[dict] = []
    problems: List[str] = []
    for addr, worker in [(a, False) for a in brokers] + [
        (a, True) for a in workers
    ]:
        role = "worker" if worker else "broker"
        try:
            payload = fetch_status(addr, worker=worker, timeout=timeout)
        except Exception as exc:  # dead process: its segments still tell
            problems.append(f"{role} {addr}: live fetch failed ({exc})")
            continue
        jw = payload.get("journal")
        if not isinstance(jw, dict):
            problems.append(
                f"{role} {addr}: answered Status but ships no journal "
                "window (started without -journal, or version skew)"
            )
            continue
        evs = jw.get("events")
        if isinstance(evs, list):
            events.extend(e for e in evs if isinstance(e, dict))
        dropped = jw.get("dropped", 0)
        if dropped:
            problems.append(
                f"{role} {addr}: journal reports {dropped} dropped "
                "event(s) (queue overflow or rotation past -journal keep)"
            )
    return events, problems


def build_history(
    tag: str,
    out_dir: str = "out",
    brokers: Optional[List[str]] = None,
    workers: Optional[List[str]] = None,
    tenant: Optional[str] = None,
    address: Optional[str] = None,
    timeout: float = 5.0,
    since_ms: Optional[int] = None,
    until_ms: Optional[int] = None,
) -> dict:
    """The full reconstruction: segments + live windows -> one merged,
    filtered, HLC-ordered history dict (schema ``gol-history/1``).
    ``since_ms``/``until_ms`` bound the window on HLC physical
    milliseconds (unix epoch ms, both inclusive)."""
    seg_paths = _journal.segment_paths(out_dir)
    seg_events, problems = _journal.read_segments(seg_paths)
    live_events: List[dict] = []
    if brokers or workers:
        live_events, live_problems = fetch_live_events(
            brokers or [], workers or [], timeout
        )
        problems.extend(live_problems)
    merged = merge_events(seg_events, live_events)
    filtered = [
        e for e in merged
        if _matches(e, tenant, address, since_ms, until_ms)
    ]
    by_kind: dict = {}
    nodes = set()
    for e in filtered:
        by_kind[e.get("kind", "?")] = by_kind.get(e.get("kind", "?"), 0) + 1
        nodes.add(_node(e))
    return {
        "schema": SCHEMA,
        "tag": tag,
        "time_unix": time.time(),
        "segments": [str(p) for p in seg_paths],
        "nodes": sorted(nodes),
        "events_total": len(filtered),
        "by_kind": dict(sorted(by_kind.items())),
        "filters": {
            "tenant": tenant, "address": address,
            "since_ms": since_ms, "until_ms": until_ms,
        },
        "problems": problems,
        "events": filtered,
    }


def _fmt_event(event: dict) -> str:
    hlc = event.get("hlc")
    if isinstance(hlc, list) and len(hlc) == 3:
        ts = time.strftime("%H:%M:%S", time.localtime(hlc[0] / 1000.0))
        stamp = f"{ts}.{int(hlc[0]) % 1000:03d}+{hlc[1]}"
    else:
        t = event.get("t_unix")
        stamp = (
            time.strftime("%H:%M:%S", time.localtime(t))
            if isinstance(t, (int, float)) else "--:--:--"
        )
    node = _node(event)
    kind = event.get("kind", "?")
    name = event.get("name", "")
    args = event.get("args") or {}
    detail = " ".join(f"{k}={v}" for k, v in args.items())
    return f"{stamp}  {node:<24} {kind:<18} {name} {detail}".rstrip()


def render(history: dict, show: int = DEFAULT_SHOW) -> str:
    lines = [
        f"history '{history['tag']}': {history['events_total']} event(s) "
        f"across {len(history['nodes'])} process(es)",
    ]
    for node in history["nodes"]:
        lines.append(f"  node {node}")
    if history["by_kind"]:
        kinds = ", ".join(f"{k}x{n}" for k, n in history["by_kind"].items())
        lines.append(f"  kinds: {kinds}")
    events = history["events"]
    shown = events[-show:] if show and len(events) > show else events
    if len(shown) < len(events):
        lines.append(
            f"  ... showing the last {len(shown)} of {len(events)} "
            "(the JSON artifact carries all)"
        )
    lines.append("")
    for e in shown:
        lines.append("  " + _fmt_event(e))
    if history["problems"]:
        lines.append("")
        lines.append(f"PROBLEMS ({len(history['problems'])}):")
        for p in history["problems"]:
            lines.append(f"  !! {p}")
    return "\n".join(lines)


def write_history(history: dict, out_dir: str = "out") -> pathlib.Path:
    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"history_{history['tag']}.json"
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(history, indent=1, default=str))
    tmp.replace(path)
    return path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="merge journal segments + live tails into one "
                    "causally-ordered (HLC) cross-process timeline"
    )
    parser.add_argument(
        "tag", help="artifact tag: writes out/history_<tag>.json"
    )
    parser.add_argument(
        "-dir", default="out", metavar="DIR",
        help="directory holding journal_<role>_<pid>[.gN].jsonl segments "
             "(default out/) — also where the history artifact lands",
    )
    parser.add_argument(
        "-broker", action="append", default=[], metavar="ADDR",
        help="also fetch a LIVE broker's in-memory journal tail via "
             "Status (repeatable)",
    )
    parser.add_argument(
        "-worker", action="append", default=[], metavar="ADDR",
        help="also fetch a LIVE worker's in-memory journal tail via "
             "Status (repeatable)",
    )
    parser.add_argument(
        "-tenant", default=None,
        help="filter: only events attributed to this tenant id",
    )
    parser.add_argument(
        "-address", default=None,
        help="filter: only events naming this worker address (losses, "
             "readmissions, quarantines) or emitted by it",
    )
    parser.add_argument(
        "-since", type=int, default=None, metavar="MS",
        help="filter: only events whose HLC physical stamp is at or "
             "after this unix-epoch millisecond (the merge's sort key — "
             "skew-safe across processes, unlike per-host wall clocks)",
    )
    parser.add_argument(
        "-until", type=int, default=None, metavar="MS",
        help="filter: only events whose HLC physical stamp is at or "
             "before this unix-epoch millisecond (pairs with -since to "
             "cut an incident window out of a long run)",
    )
    parser.add_argument(
        "-show", type=int, default=DEFAULT_SHOW, metavar="N",
        help=f"terminal rows rendered (default {DEFAULT_SHOW}; 0 = all); "
             "the JSON artifact always carries every event",
    )
    parser.add_argument(
        "-timeout", type=float, default=5.0, metavar="SECS",
        help="bound per live Status fetch (default 5)",
    )
    args = parser.parse_args(argv)
    history = build_history(
        args.tag,
        out_dir=args.dir,
        brokers=args.broker,
        workers=args.worker,
        tenant=args.tenant,
        address=args.address,
        timeout=args.timeout,
        since_ms=args.since,
        until_ms=args.until,
    )
    print(render(history, show=args.show))
    path = write_history(history, args.dir)
    print(f"\nwrote {path}")
    # problems are loud but not fatal: a torn tail is EXPECTED after a
    # SIGKILL — the report names it and the surviving records still
    # reconstruct; only a totally empty reconstruction fails the run
    if not history["events"]:
        print("no journal events found", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
