"""RunReport writer + Status payload builder.

A RunReport is the per-run attribution artifact: the full metrics snapshot
plus the device inventory (``jax.local_devices()``) and per-device memory
stats, dumped to ``out/report_<W>x<H>x<Turns>.json`` when the controller
reaches ``FinalTurnComplete``. BENCH rounds embed its compact
``stage_timings`` so every published number carries its own breakdown
(bench.py), instead of the ad-hoc timers earlier rounds hand-rolled.

The Status payload is the same registry snapshot without the jax imports —
served by the broker's and worker's read-only ``Status`` RPC verb, so an
operator can interrogate a RUNNING process without disturbing it
(``python -m gol_distributed_final_tpu.obs.status host:port``).
"""

from __future__ import annotations

import json
import os
import pathlib
import time
from typing import Optional

from . import accounting, flight, metrics, timeline, tracing
from . import critical as _critical
from . import journal as _journal
from . import perf as _perf
from . import profiler as _profiler

SCHEMA = "gol-run-report/1"


def status_payload(
    timeline_since: int = 0, accounting_since: int = 0,
    journal_since: int = 0, profile_since: int = 0, **extra
) -> dict:
    """The ``Status`` verb's reply body: registry snapshot + identity.

    Deliberately jax-free: a worker process that never imported jax must
    answer Status without paying that import, and the verb must stay
    cheap enough to poll.

    With tracing on, the payload also carries the span ring (the material
    a controller's Chrome-trace export is built from) and the flight
    recorder's last-events ring — so a WEDGED process can be post-mortemed
    live over one read-only RPC.

    With the timeline sampler on (``-timeline``), the payload carries an
    INCREMENTAL metric-timeline window — only samples past the caller's
    ``timeline_since`` seq (the ``Request.timeline_since`` extension
    field), with server-computed rates/quantiles in its ``summary`` —
    plus the SLO rulebook's alert states (obs/slo.py), so one poll sees
    cluster health without client-side reconstruction."""
    reg = metrics.registry()
    # refresh the roofline gauges BEFORE the snapshot: a process with
    # instrumented kernel dispatches publishes achieved FLOP/s, bytes/s,
    # and bound classes on its own poll (obs/perf.py; no-op — and still
    # jax-free — in a process that never dispatched)
    _perf.refresh_metrics()
    payload = {
        "schema": "gol-status/1",
        "pid": os.getpid(),
        "time_unix": time.time(),
        "metrics_enabled": reg.enabled,
        "metrics": reg.snapshot(),
    }
    cp = _critical.tracker().snapshot()
    if cp.get("batches"):
        # straggler/critical-path attribution (obs/critical.py) — the
        # doctor's 'straggler' heuristic and the watch panel read this
        payload["critical_path"] = cp
    if tracing.enabled():
        payload["trace_spans"] = tracing.tracer().snapshot()
    if flight.enabled():
        payload["flight"] = flight.recorder().snapshot()
    sampler = timeline.sampler()
    if sampler is not None:
        # opportunistic tick: a GIL-saturated (or just-started) process
        # whose background thread has not run still answers the poll
        # with a due sample instead of a stale ring
        sampler.maybe_sample()
        payload["timeline"] = sampler.window(since=timeline_since)
        if sampler.rulebook is not None:
            payload["alerts"] = sampler.rulebook.snapshot()
    ledger = accounting.ledger()
    if ledger.has_data:
        # the per-tenant usage ledger (obs/accounting.py) — incremental
        # past the caller's accounting_since seq, bounded at top-K
        # tenants + the 'other' bucket either way
        payload["accounting"] = ledger.window(since=accounting_since)
    jw = _journal.window(since=journal_since)
    if jw is not None:
        # the lifecycle journal's incremental tail (obs/journal.py) —
        # the live half of `python -m ..obs.history` and the watch
        # JOURNAL panel; only events past the caller's journal_since
        payload["journal"] = jw
    pw = _profiler.window(since=profile_since)
    if pw is not None:
        # the continuous profiler's incremental window (obs/profiler.py)
        # — only frames whose hits moved past the caller's profile_since
        # seq; the doctor's hotspot join, the watch PROFILE panel, and
        # obs/flame.py's live lane all read this
        payload["profile"] = pw
    payload.update(extra)
    return payload


def stage_timings(snap: Optional[dict] = None) -> dict:
    """Compact per-stage attribution from a snapshot: every nonzero
    histogram series as ``{count, sum_s, mean_s}`` and every nonzero
    counter as its value, keyed ``name{label=value,...}``. The form BENCH
    rounds embed (bench.py) — small enough to diff across rounds."""
    if snap is None:
        snap = metrics.registry().snapshot()
    out: dict = {}
    for fam in snap.get("families", []):
        labelnames = fam.get("labelnames", [])
        for s in fam["series"]:
            pairs = ",".join(
                f"{n}={v}" for n, v in zip(labelnames, s["labels"])
            )
            key = fam["name"] + (f"{{{pairs}}}" if pairs else "")
            if fam["type"] == "histogram":
                if s["count"]:
                    out[key] = {
                        "count": s["count"],
                        "sum_s": round(s["sum"], 6),
                        "mean_s": round(s["sum"] / s["count"], 9),
                    }
            elif s["value"]:
                out[key] = s["value"]
    return out


def device_inventory() -> dict:
    """``jax.local_devices()`` identity + per-device memory stats, each
    guarded: a backend without memory_stats (CPU) reports null, and a
    failing jax import degrades to an error note instead of sinking the
    report that exists to explain the run.

    ``hbm_peak_observed_bytes`` is the high-water ``bytes_in_use`` across
    every sample this process took (per turn-chunk and at every
    checkpoint — obs/device.py), NOT just the final reading: a mid-run
    HBM spike that subsided before FinalTurnComplete still shows here."""
    try:
        import jax
    except Exception as exc:  # pragma: no cover - jax is baked in
        return {"error": f"jax unavailable: {exc}"}
    from . import device as _device

    peaks = _device.hbm_peak_observed()
    devices = []
    for dev in jax.local_devices():
        entry = {
            "id": dev.id,
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", ""),
            "process_index": getattr(dev, "process_index", 0),
        }
        try:
            entry["memory_stats"] = dev.memory_stats()
        # gol: allow(hygiene): inventory decoration — a device
        # without memory_stats() reports null, not a failed report
        except Exception:
            entry["memory_stats"] = None
        entry["hbm_peak_observed_bytes"] = peaks.get(str(dev.id))
        devices.append(entry)
    return {
        "backend": devices[0]["platform"] if devices else "none",
        "process_count": getattr(jax, "process_count", lambda: 1)(),
        "local_devices": devices,
    }


def report_path(params, out_dir="out") -> pathlib.Path:
    # rides the load-bearing <W>x<H>x<Turns> naming convention
    # (params.output_filename, gol/distributor.go:165)
    return pathlib.Path(out_dir) / f"report_{params.output_filename}.json"


def write_run_report(
    params,
    out_dir="out",
    *,
    wall_seconds: Optional[float] = None,
    extra: Optional[dict] = None,
) -> pathlib.Path:
    """Dump the registry + device inventory for a finished run. Written to
    a temp name then renamed, like the checkpoint writer, so a crash
    mid-dump never leaves a half-parseable report."""
    _perf.refresh_metrics()  # achieved/bound gauges land in the snapshot
    snap = metrics.registry().snapshot()
    report = {
        "schema": SCHEMA,
        "params": {
            "image_width": params.image_width,
            "image_height": params.image_height,
            "turns": params.turns,
            "threads": params.threads,
        },
        "time_unix": time.time(),
        "wall_seconds": wall_seconds,
        "metrics_enabled": metrics.enabled(),
        "devices": device_inventory(),
        "metrics": snap,
        "stage_timings": stage_timings(snap),
    }
    sampler = timeline.sampler()
    if sampler is not None:
        # the run-health verdict rides in the final artifact: a timeline
        # summary (rate/mean/p50/p99 per active series) plus every SLO
        # rule's state and fire count — "was this run healthy" without
        # replaying logs
        report["timeline"] = sampler.summary()
        if sampler.rulebook is not None:
            alerts = sampler.rulebook.snapshot()
            report["alerts"] = alerts
            report["alerts_fired"] = sorted(
                a["rule"] for a in alerts if a.get("fired_total")
            )
    ledger = accounting.ledger()
    if ledger.has_data:
        # who spent this run's capacity: the bounded per-tenant ledger
        # rides the final artifact beside the timeline verdict
        report["accounting"] = ledger.window()
    js = _journal.summary()
    if js is not None:
        # what HAPPENED this run: the lifecycle journal's by-kind totals
        # and drop/rotation accounting (the segments on disk hold the
        # full causally-stamped history)
        report["journal"] = js
    ps = _profiler.summary()
    if ps is not None:
        # WHICH CODE the wall went to: the profiler's head + top frames
        # (the full trie lands in the collapsed/speedscope artifacts the
        # mains write at run end — obs/flame.py renders those)
        report["profile"] = ps
    decomp = _perf.decomposition_summary(snap)
    if decomp:
        # WHERE the wall went: the dispatch-wall decomposition breakdown
        # (host_prep / device_compute / wire / demux per component)
        report["where_time_goes"] = decomp
    cp = _critical.tracker().snapshot()
    if cp.get("batches"):
        report["critical_path"] = cp
    if extra:
        report.update(extra)
    path = report_path(params, out_dir)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(report, indent=1, default=str))
    tmp.replace(path)
    return path
