"""Open-loop traffic generator — the arrival process the front door will
be admitted against.

    python -m gol_distributed_final_tpu.obs.loadgen :8040 \\
        -rate 100 -sessions 500 -tenants 30 -arrival poisson
    python -m gol_distributed_final_tpu.obs.loadgen --loopback -rate 200
    python -m gol_distributed_final_tpu.obs.loadgen --selfcheck

``bench.py`` replays CLOSED-loop batches: the next unit of work waits for
the previous one, so the measured system is never behind — which is
exactly the regime real serving is not in. The ROADMAP's front-door gate
("p99 admission-to-first-turn at 10k+ concurrent sessions") needs an
**open-loop** generator: arrivals fire on the wall clock regardless of
completions (a deterministic seeded schedule — Poisson exponential
inter-arrivals or periodic bursts), so queueing delay is *measured*, not
hidden.

Each arrival is one ``Operations.SessionRun`` with a tenant-packed
``session_id`` (obs/accounting.py convention: tenant id in the high 32
bits, drawn uniform or zipf over ``-tenants``), issued on its own worker
thread over ONE multiplexed RpcClient. Two client-side latency
histograms merge into the registry (lint-enforced, README "Canary & load
harness"):

* ``gol_loadgen_admit_to_first_turn_seconds`` — arrival to the first
  turn being VISIBLE via the tagged retrieve poller (one shared thread
  round-robins the in-flight tags at ``think_s`` cadence; a session that
  drains before the poller sees it records its end-to-end wall — the
  honest upper bound, quantized by the poll cadence);
* ``gol_loadgen_session_seconds`` — arrival to the final board.

``gol_loadgen_sessions_total{outcome}`` counts ``ok`` / ``rejected`` /
``error``; rejects classify by the STRUCTURED reason the error envelope
now carries (``RpcError.reason`` — no string matching).

``--selfcheck`` is the ``scripts/check --loadgen`` gate: a loopback
broker, 30 tenants, mixed Poisson + burst arrivals, then the
reconciliation assert — the accounting ledger's per-tenant turn and
session totals must agree exactly with ``gol_session_turns_total`` /
``gol_sessions_admitted_total``, and its device-seconds with the
``gol_session_turn_seconds`` sum.
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools
import json
import random
import sys
import threading
import time
from typing import Dict, List, Optional

from . import accounting as _acct
from . import instruments as _ins

#: loadgen outcome labels (``gol_loadgen_sessions_total{outcome}``)
OUTCOMES = ("ok", "rejected", "error")

#: process-global session-nonce stream: tags must be unique across EVERY
#: run this process issues against a broker — a per-run index would
#: collide with the broker's finished-session snapshot cache, and the
#: first-turn poller would record a PREVIOUS run's final snapshot as a
#: near-zero admission latency
_nonce = itertools.count(1)


@dataclasses.dataclass
class LoadConfig:
    """One load shape. ``rate`` paces the arrival clock (sessions/s);
    ``arrival`` picks the process: ``poisson`` (exponential
    inter-arrivals) or ``burst`` (``burst`` simultaneous arrivals every
    ``burst/rate`` seconds). ``max_inflight`` is a safety bound on
    concurrent worker threads — past it the generator BLOCKS the arrival
    clock (documented closed-loop degradation; raise it rather than let
    a wedged broker spawn unbounded threads). ``tenant_dist`` spreads
    tags over ``tenants`` ids: ``uniform`` or ``zipf`` (weight 1/rank —
    the skew shape the doctor's hot-tenant finding exists for)."""

    rate: float = 50.0
    sessions: int = 100
    arrival: str = "poisson"  # "poisson" | "burst"
    burst: int = 10
    tenants: int = 4
    tenant_dist: str = "uniform"  # "uniform" | "zipf"
    size: int = 16
    turns: int = 16
    think_s: float = 0.002  # first-turn poll cadence
    timeout: float = 120.0
    seed: int = 0
    max_inflight: int = 1024

    def validate(self) -> None:
        if self.rate <= 0:
            raise ValueError(f"rate must be > 0, got {self.rate}")
        if self.sessions < 1:
            raise ValueError(f"sessions must be >= 1, got {self.sessions}")
        if self.arrival not in ("poisson", "burst"):
            raise ValueError(f"arrival must be poisson|burst, got {self.arrival!r}")
        if self.burst < 1:
            raise ValueError(f"burst must be >= 1, got {self.burst}")
        if self.tenants < 1:
            raise ValueError(f"tenants must be >= 1, got {self.tenants}")
        if self.tenant_dist not in ("uniform", "zipf"):
            raise ValueError(
                f"tenant_dist must be uniform|zipf, got {self.tenant_dist!r}"
            )
        if self.max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {self.max_inflight}"
            )


def _quantiles_us(samples: List[float]) -> dict:
    """Exact client-side quantiles of a latency sample list, in µs (the
    embedded-bench form: p99_admit_to_first_turn_us etc.)."""
    if not samples:
        return {"n": 0}
    s = sorted(samples)

    def q(p: float) -> float:
        return s[min(len(s) - 1, int(p * len(s)))]

    return {
        "n": len(s),
        "mean_us": round(sum(s) / len(s) * 1e6, 1),
        "p50_us": round(q(0.50) * 1e6, 1),
        "p90_us": round(q(0.90) * 1e6, 1),
        "p99_us": round(q(0.99) * 1e6, 1),
        "max_us": round(s[-1] * 1e6, 1),
    }


class LoadGenerator:
    """One run of one ``LoadConfig`` against one broker address."""

    def __init__(self, address: str, config: LoadConfig):
        from .status import norm_address

        config.validate()
        self.address = norm_address(address)
        self.config = config
        self._lock = threading.Lock()
        self._outstanding: Dict[int, float] = {}  # tag -> submit t_mono
        self._first_turn: Dict[int, float] = {}  # tag -> latency_s
        self._e2e: List[float] = []
        self._outcomes: Dict[str, int] = {o: 0 for o in OUTCOMES}
        self._rejects: Dict[str, int] = {}
        self._per_tenant_issued: Dict[int, int] = {}

    # -- the arrival schedule (deterministic per seed) ---------------------

    def _schedule(self) -> List[float]:
        cfg = self.config
        rng = random.Random(cfg.seed)
        times: List[float] = []
        if cfg.arrival == "poisson":
            t = 0.0
            for _ in range(cfg.sessions):
                t += rng.expovariate(cfg.rate)
                times.append(t)
        else:  # burst: `burst` simultaneous arrivals, rate-paced groups
            interval = cfg.burst / cfg.rate
            for i in range(cfg.sessions):
                times.append((i // cfg.burst) * interval)
        return times

    def _tenants_for(self) -> List[int]:
        cfg = self.config
        rng = random.Random(cfg.seed ^ 0x7E7A)
        ids = list(range(1, cfg.tenants + 1))
        if cfg.tenant_dist == "uniform":
            return [rng.choice(ids) for _ in range(cfg.sessions)]
        weights = [1.0 / rank for rank in range(1, cfg.tenants + 1)]
        return rng.choices(ids, weights=weights, k=cfg.sessions)

    def _board_for(self, i: int):
        import numpy as np

        cfg = self.config
        rng = np.random.default_rng((cfg.seed << 16) ^ i)
        return np.where(
            rng.random((cfg.size, cfg.size)) < 0.3, 255, 0
        ).astype(np.uint8)

    # -- one session -------------------------------------------------------

    def _session(self, client, i: int, tenant: int, slots) -> None:
        from ..rpc.client import RpcError
        from ..rpc.protocol import Methods, Request

        cfg = self.config
        tag = _acct.make_tag(tenant, next(_nonce))
        t0 = time.monotonic()
        with self._lock:
            self._outstanding[tag] = t0
        try:
            client.call(
                Methods.SESSION_RUN,
                Request(
                    world=self._board_for(i), turns=cfg.turns,
                    image_height=cfg.size, image_width=cfg.size,
                    threads=1, session_id=tag,
                ),
                timeout=cfg.timeout,
            )
        except RpcError as exc:
            with self._lock:
                self._outstanding.pop(tag, None)
                if exc.kind == "SessionRejected":
                    # the structured reject reason (the error_reason
                    # envelope key): classification without string-matching
                    reason = exc.reason or "unknown"
                    self._outcomes["rejected"] += 1
                    self._rejects[reason] = self._rejects.get(reason, 0) + 1
                else:
                    self._outcomes["error"] += 1
            _ins.LOADGEN_SESSIONS_TOTAL.labels(
                "rejected" if exc.kind == "SessionRejected" else "error"
            ).inc()
            return
        except Exception:
            with self._lock:
                self._outstanding.pop(tag, None)
                self._outcomes["error"] += 1
            _ins.LOADGEN_SESSIONS_TOTAL.labels("error").inc()
            return
        finally:
            slots.release()
        e2e = time.monotonic() - t0
        with self._lock:
            self._outstanding.pop(tag, None)
            self._e2e.append(e2e)
            self._outcomes["ok"] += 1
            if tag not in self._first_turn:
                # drained before the poller saw turn 1: the end-to-end
                # wall is the honest (poll-cadence-quantized) upper bound
                self._first_turn[tag] = e2e
        _ins.LOADGEN_SESSIONS_TOTAL.labels("ok").inc()
        _ins.LOADGEN_SESSION_SECONDS.observe(e2e)
        _ins.LOADGEN_ADMIT_TO_FIRST_TURN_SECONDS.observe(
            self._first_turn[tag]
        )

    def _first_turn_poller(self, client, done: threading.Event) -> None:
        """ONE shared thread round-robins the outstanding tags with
        count-only tagged retrieves: the first poll that sees
        ``turns_completed >= 1`` records that session's
        admission-to-first-turn latency. A completed tag still answers
        (the scheduler's finished-snapshot cache); only a
        NOT-YET-ADMITTED tag errors, and those polls back off per tag —
        the generator's own probing must not burn the server's
        rpc-error-ratio budget."""
        from ..rpc.client import RpcError
        from ..rpc.protocol import Methods, Request

        cfg = self.config
        not_before: Dict[int, float] = {}  # tag -> (next poll, backoff)
        backoff: Dict[int, float] = {}
        while not done.wait(cfg.think_s):
            now = time.monotonic()
            with self._lock:
                pending = [
                    (tag, t0) for tag, t0 in self._outstanding.items()
                    if tag not in self._first_turn
                    and not_before.get(tag, 0.0) <= now
                ]
            for tag, t0 in pending:
                try:
                    snap = client.call(
                        Methods.RETRIEVE,
                        Request(include_world=False, session_id=tag),
                        timeout=5.0,
                    )
                except RpcError:
                    # not yet admitted: back this tag off (25 ms
                    # doubling to 200 ms) instead of erroring every round
                    b = min(0.2, backoff.get(tag, 0.0125) * 2)
                    backoff[tag] = b
                    not_before[tag] = time.monotonic() + b
                    continue
                except OSError:
                    return
                backoff.pop(tag, None)
                not_before.pop(tag, None)
                if snap.turns_completed >= 1:
                    with self._lock:
                        if tag not in self._first_turn:
                            self._first_turn[tag] = time.monotonic() - t0

    # -- the run -----------------------------------------------------------

    def run(self) -> dict:
        """Issue the whole schedule, wait for every session, and return
        the summary dict (also printed as the CLI's JSON line)."""
        from ..rpc.client import RpcClient

        cfg = self.config
        client = RpcClient(self.address, timeout=10.0)
        done = threading.Event()
        poller = threading.Thread(
            target=self._first_turn_poller, args=(client, done),
            name="gol-loadgen-poll", daemon=True,
        )
        poller.start()
        slots = threading.Semaphore(cfg.max_inflight)
        schedule = self._schedule()
        tenants = self._tenants_for()
        threads: List[threading.Thread] = []
        t_start = time.monotonic()
        try:
            for i, (at, tenant) in enumerate(zip(schedule, tenants)):
                # open loop: sleep to the ARRIVAL time, never to a completion
                delay = t_start + at - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                slots.acquire()  # the documented safety bound
                self._per_tenant_issued[tenant] = (
                    self._per_tenant_issued.get(tenant, 0) + 1
                )
                t = threading.Thread(
                    target=self._session, args=(client, i, tenant, slots),
                    name=f"gol-loadgen-{i}", daemon=True,
                )
                t.start()
                threads.append(t)
            deadline = time.monotonic() + cfg.timeout
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            wall = time.monotonic() - t_start
        finally:
            done.set()
            poller.join(timeout=2.0)
            client.close()
        hung = sum(1 for t in threads if t.is_alive())
        with self._lock:
            completed = self._outcomes["ok"]
            summary = {
                "schema": "gol-loadgen/1",
                "address": self.address,
                "config": dataclasses.asdict(cfg),
                "issued": len(threads),
                "completed": completed,
                "rejected": dict(sorted(self._rejects.items())),
                "rejected_total": self._outcomes["rejected"],
                "errors": self._outcomes["error"] + hung,
                "hung": hung,
                "wall_s": round(wall, 4),
                "sessions_per_s": round(completed / wall, 2) if wall > 0 else None,
                "universe_turns": completed * cfg.turns,
                "admit_to_first_turn": _quantiles_us(
                    list(self._first_turn.values())
                ),
                "session_e2e": _quantiles_us(list(self._e2e)),
                "per_tenant_issued": {
                    str(t): n
                    for t, n in sorted(self._per_tenant_issued.items())
                },
            }
        return summary


def _selfcheck() -> int:
    """``scripts/check --loadgen``: loopback broker, 30 tenants, mixed
    Poisson + burst arrival, then the ledger-vs-metrics reconciliation
    (the acceptance contract: per-tenant turn/session totals agree
    EXACTLY with the session counters; device-seconds with the chunk
    walls the latency histogram recorded)."""
    from . import metrics as _metrics
    from .status import scalar_value, series_map
    from ..rpc.broker import serve

    _metrics.registry().reset()
    _acct.ledger().reset()
    _metrics.enable()
    server, service = serve(port=0, session_capacity=256)
    addr = f"127.0.0.1:{server.port}"
    failures: List[str] = []
    try:
        for arrival in ("poisson", "burst"):
            cfg = LoadConfig(
                rate=400.0, sessions=40, arrival=arrival, burst=8,
                tenants=30, tenant_dist="zipf", size=16, turns=8,
                seed=3 if arrival == "poisson" else 4,
            )
            summary = LoadGenerator(addr, cfg).run()
            print(json.dumps(summary), flush=True)
            if summary["completed"] + summary["rejected_total"] + summary[
                "errors"
            ] != summary["issued"]:
                failures.append(f"{arrival}: outcomes do not sum to issued")
            if summary["errors"]:
                failures.append(
                    f"{arrival}: {summary['errors']} session error(s)"
                )
        snap = _metrics.registry().snapshot()
        win = _acct.ledger().window()
        totals = win.get("totals") or {}
        turns_metric = scalar_value(snap, "gol_session_turns_total") or 0
        admitted = scalar_value(snap, "gol_sessions_admitted_total") or 0
        if totals.get("turns") != int(turns_metric):
            failures.append(
                f"ledger turns {totals.get('turns')} != "
                f"gol_session_turns_total {int(turns_metric)}"
            )
        if totals.get("sessions") != int(admitted):
            failures.append(
                f"ledger sessions {totals.get('sessions')} != "
                f"gol_sessions_admitted_total {int(admitted)}"
            )
        hist = series_map(snap, "gol_session_turn_seconds").get(()) or {}
        dev = totals.get("device_seconds") or 0.0
        hsum = hist.get("sum") or 0.0
        if abs(dev - hsum) > 1e-6 + 1e-6 * max(dev, hsum):
            failures.append(
                f"ledger device-seconds {dev} != "
                f"gol_session_turn_seconds sum {hsum}"
            )
        tracked = win.get("tracked") or 0
        if tracked > _acct.ledger().top_k:
            failures.append(f"ledger tracked {tracked} tenants past top_k")
        if not (win.get("other") or {}).get("sessions"):
            failures.append(
                "30 tenants at top_k=16 left the 'other' bucket empty — "
                "the cardinality bound did not engage"
            )
        if failures:
            for f in failures:
                print(f"loadgen selfcheck FAILED: {f}", file=sys.stderr)
            return 1
        print(
            f"loadgen selfcheck ok: {int(admitted)} sessions over 30 "
            f"tenants, ledger reconciles ({totals.get('turns')} turns, "
            f"{dev:.4f} device-seconds, {tracked} tracked + other)"
        )
        return 0
    finally:
        service._shutdown()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="open-loop session traffic generator (Poisson/burst "
        "arrivals, tenant-tagged, client-side latency histograms)"
    )
    parser.add_argument(
        "address", nargs="?", default=None,
        help="broker host:port (tcp:// prefix and :port shorthand accepted)",
    )
    parser.add_argument("-rate", type=float, default=50.0, metavar="PER_S")
    parser.add_argument("-sessions", type=int, default=100, metavar="N")
    parser.add_argument(
        "-arrival", choices=("poisson", "burst"), default="poisson",
    )
    parser.add_argument("-burst", type=int, default=10, metavar="N")
    parser.add_argument("-tenants", type=int, default=4, metavar="N")
    parser.add_argument(
        "-tenant-dist", dest="tenant_dist", choices=("uniform", "zipf"),
        default="uniform",
    )
    parser.add_argument("-size", type=int, default=16, metavar="CELLS")
    parser.add_argument("-turns", type=int, default=16)
    parser.add_argument(
        "-think", dest="think_s", type=float, default=0.002, metavar="SECS",
        help="first-turn poll cadence (default 2 ms)",
    )
    parser.add_argument("-timeout", type=float, default=120.0, metavar="SECS")
    parser.add_argument("-seed", type=int, default=0)
    parser.add_argument(
        "-max-inflight", dest="max_inflight", type=int, default=1024,
    )
    parser.add_argument(
        "--loopback", action="store_true",
        help="spin an in-process broker and run the load against it",
    )
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="loopback smoke + ledger reconciliation (the scripts/check "
             "--loadgen gate)",
    )
    args = parser.parse_args(argv)
    if args.selfcheck:
        return _selfcheck()
    from . import metrics as _metrics

    _metrics.enable()  # the client-side histograms must record
    server = service = None
    address = args.address
    if args.loopback:
        from ..rpc.broker import serve

        server, service = serve(port=0, session_capacity=1024)
        address = f"127.0.0.1:{server.port}"
    elif not address:
        parser.error("an address is required (or --loopback / --selfcheck)")
    cfg = LoadConfig(
        rate=args.rate, sessions=args.sessions, arrival=args.arrival,
        burst=args.burst, tenants=args.tenants,
        tenant_dist=args.tenant_dist, size=args.size, turns=args.turns,
        think_s=args.think_s, timeout=args.timeout, seed=args.seed,
        max_inflight=args.max_inflight,
    )
    try:
        summary = LoadGenerator(address, cfg).run()
    finally:
        if service is not None:
            service._shutdown()
    print(json.dumps(summary))
    return 0 if not summary["errors"] else 1


if __name__ == "__main__":
    sys.exit(main())
