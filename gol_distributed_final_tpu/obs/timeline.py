"""In-process metric timelines: a time-series ring over the registry.

The registry (obs/metrics.py) answers "how much, ever"; the watch
dashboard (obs/watch.py) reconstructs "how fast, lately" CLIENT-side from
successive polls — which loses all history between polls, dies with the
poller, and cannot feed server-side alerting. Production serving stacks
keep the history where the work happens (Podracer, arXiv:2104.06272,
keeps the controller off the hot path for exactly this reason): this
module is that history.

* **A cheap background sampler.** ``enable(period)`` (the ``-timeline
  [SECS]`` CLI flags, default cadence 1 s) starts a daemon thread that
  snapshots every registered counter/gauge/histogram into fixed-size
  per-series rings — bounded memory (``DEFAULT_CAPACITY`` samples per
  series), monotonic timestamps for rate math, wall clocks for display.
  ``maybe_sample()`` sites (the engine chunk loop) opportunistically
  advance the clock when due, so a GIL-saturated process still samples.
* **Counter-reset detection.** Each series keeps an adjusted MONOTONE
  value: when the raw value goes backwards (a registry reset, a
  restarted subprocess merged in), the previous raw total folds into a
  base instead of producing a negative rate — the Prometheus ``rate()``
  posture. ``counter_delta`` exposes the same logic to client-side
  pollers (obs/watch.py rides it).
* **Server-side rates and quantiles.** ``rate``/``increase``/
  ``quantile`` compute over the ring's real timestamps; histogram
  quantiles interpolate within the fixed bucket edges (exact against a
  numpy oracle to bucket resolution — tests/test_slo.py).
* **Incremental Status windows.** ``window(since=seq)`` ships only the
  samples a poller has not seen (the poller echoes the last ``seq`` it
  received via the ``Request.timeline_since`` extension field — getattr-
  skew-safe like ``trace_ctx``), plus a server-computed ``summary`` of
  rates/p50/p99 per series, so ONE poll answers "how fast, lately"
  without client-side reconstruction.
* **Chrome counter tracks.** ``chrome_counter_samples()`` renders the
  rings as trace-event counter samples; ``tracing.write_chrome_trace``
  folds them in so Perfetto shows throughput/HBM/queue depth on the same
  timeline as the spans.

Like the registry, the tracer, and the flight recorder: pure stdlib,
OFF by default, one global-load-and-branch per ``maybe_sample`` site
until an entry point opts in. SLO evaluation (obs/slo.py) attaches a
rulebook that runs after every tick.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import metrics as _metrics
from ..utils import locksan as _locksan

logger = logging.getLogger(__name__)

#: default sampling cadence (seconds) — the ``-timeline`` flags' implied
#: value; one registry snapshot per tick
DEFAULT_PERIOD = 1.0
#: samples retained per series: 6 minutes of history at the default
#: cadence — enough for every default SLO window (obs/slo.py) with slack
DEFAULT_CAPACITY = 360
#: wall-clock history enable() guarantees the rings cover regardless of
#: cadence: the default rulebook's longest window (120 s) plus slack. A
#: sub-second ``-timeline 0.2`` would otherwise span 360 x 0.2 = 72 s and
#: silently collapse the slow burn-rate window onto the fast one — the
#: very blip-suppression the two-window design exists for.
RULE_HORIZON_S = 150.0

SCHEMA = "gol-timeline/1"

#: summary/rate window (seconds) the Status payload computes over
SUMMARY_WINDOW_S = 60.0


def counter_delta(prev: float, new: float) -> float:
    """Non-negative counter increase across one poll, reset-aware: a
    value that went BACKWARDS means the process restarted (or its
    registry was reset), so everything the new total holds happened
    since — the Prometheus ``rate()`` posture. Shared with client-side
    pollers (obs/watch.py) so server rings and dashboards agree."""
    return new if new < prev else new - prev


def quantile_from_buckets(
    edges: Tuple[float, ...], counts: List[float], q: float
) -> Optional[float]:
    """The ``q``-quantile of a fixed-edge histogram (non-cumulative
    ``counts`` with a trailing overflow slot, the obs/metrics.py layout):
    linear interpolation within the containing bucket, lower bound 0 for
    the first, clamped to the last finite edge for overflow — the
    ``histogram_quantile`` contract. None on an empty histogram."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    for i, n in enumerate(counts):
        if n <= 0:
            continue
        if cum + n >= target:
            lo = edges[i - 1] if i > 0 else 0.0
            if i >= len(edges):
                return float(edges[-1])  # overflow: the honest clamp
            hi = edges[i]
            return float(lo + (hi - lo) * (target - cum) / n)
        cum += n
    return float(edges[-1])


class _SeriesRing:
    """One series' bounded history. Counter/gauge samples are
    ``(seq, t_mono, t_unix, value)``; histogram samples are
    ``(seq, t_mono, t_unix, count, sum, buckets_tuple)``. Counter and
    histogram values are stored ADJUSTED (monotone across resets, see
    ``counter_delta``); ``resets`` counts the backwards jumps seen."""

    __slots__ = ("kind", "edges", "samples", "resets", "_last_raw", "_base")

    def __init__(self, kind: str, capacity: int, edges=None):
        self.kind = kind
        self.edges = edges
        self.samples: deque = deque(maxlen=capacity)
        self.resets = 0
        self._last_raw = None  # last RAW observation (reset detection)
        self._base = None  # accumulated pre-reset totals

    def push_scalar(self, seq: int, t_mono: float, t_unix: float, raw: float):
        if self.kind == "gauge":
            self.samples.append((seq, t_mono, t_unix, float(raw)))
            return
        if self._base is None:
            self._base = 0.0
        if self._last_raw is not None and raw < self._last_raw:
            self._base += self._last_raw
            self.resets += 1
        self._last_raw = raw
        self.samples.append((seq, t_mono, t_unix, self._base + raw))

    def push_hist(self, seq, t_mono, t_unix, count, total, buckets):
        if self._base is None:
            self._base = (0, 0.0, (0,) * len(buckets))
        # reset detection per BUCKET, not just the count: a restart
        # followed by heavy traffic can push the new count past the old
        # total, but no individual bucket can shrink without a reset
        if self._last_raw is not None and (
            count < self._last_raw[0]
            or any(b < pb for b, pb in zip(buckets, self._last_raw[2]))
        ):
            pc, ps, pb = self._last_raw
            bc, bs, bb = self._base
            self._base = (bc + pc, bs + ps,
                          tuple(x + y for x, y in zip(bb, pb)))
            self.resets += 1
        self._last_raw = (count, total, tuple(buckets))
        bc, bs, bb = self._base
        self.samples.append((
            seq, t_mono, t_unix, bc + count, bs + total,
            tuple(x + y for x, y in zip(bb, buckets)),
        ))

    # -- window queries ----------------------------------------------------

    def pair(self, window_s: float):
        """(oldest-in-window, newest) sample pair, or None with fewer
        than two samples. The oldest is the last sample at or BEFORE the
        window start, so a window slightly longer than the ring still
        uses the full ring instead of returning nothing."""
        if len(self.samples) < 2:
            return None
        newest = self.samples[-1]
        cutoff = newest[1] - window_s
        oldest = None
        for s in self.samples:
            if s[1] <= cutoff:
                oldest = s
            else:
                if oldest is None:
                    oldest = s
                break
        if oldest is None or oldest is newest:
            oldest = self.samples[0]
        if oldest is newest:
            return None
        return oldest, newest


class TimelineSampler:
    """The per-process timeline: rings for every series of a registry,
    advanced by ``sample_once`` (the background thread, or an
    opportunistic ``maybe_sample`` site). All public queries take the
    internal lock; sampling is O(registry snapshot)."""

    # the ring state mutates under _lock during ticks while Status polls
    # iterate it — the exact 'deque mutated during iteration' race the
    # PR 8 review fixed, now machine-enforced (analysis/locks.py)
    _GUARDED_BY = {
        "_series": "_lock",
        "_labelnames": "_lock",
        "_seq": "_lock",
        "_prev_stamp": "_lock",
    }

    def __init__(
        self,
        registry=None,
        period: float = DEFAULT_PERIOD,
        capacity: int = DEFAULT_CAPACITY,
    ):
        if period <= 0:
            raise ValueError(f"period must be > 0, got {period}")
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self._registry = registry if registry is not None else _metrics.registry()
        self.period = float(period)
        self.capacity = int(capacity)
        # RLock: every reader holds it for its WHOLE computation (ring
        # deques mutate under it during sample ticks — an unlocked
        # iteration would race a concurrent append), and window() nests
        # summary() under the same lock
        self._lock = _locksan.rlock("TimelineSampler._lock")
        # serialises ticks + rule evaluation: concurrent maybe_sample
        # sites (engine chunk loop, Status polls, the background thread)
        # must produce ONE tick and ONE rulebook pass, or a single
        # worker-lost transition could double-increment the alert meter
        self._tick_lock = _locksan.lock("TimelineSampler._tick_lock")
        self._series: Dict[Tuple[str, Tuple[str, ...]], _SeriesRing] = {}
        self._labelnames: Dict[str, Tuple[str, ...]] = {}
        self._seq = 0
        self._last_t = 0.0
        self._prev_stamp: Optional[Tuple[float, float]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rulebook = None  # obs/slo.RuleBook, attached by enable()
        # rulebook-failure tally (tick-lock serialised): paces the
        # warning log so a per-tick rule bug doesn't flood stderr
        self._rule_errors = 0

    # -- sampling ----------------------------------------------------------

    def attach_rulebook(self, rulebook) -> None:
        self._rulebook = rulebook

    @property
    def rulebook(self):
        return self._rulebook

    def sample_once(self, now: Optional[float] = None,
                    wall: Optional[float] = None) -> int:
        """Snapshot every series into the rings; returns the tick's seq.
        ``now``/``wall`` are injectable for deterministic tests."""
        with self._tick_lock:
            return self._sample_locked(now, wall)

    def _sample_locked(self, now: Optional[float] = None,
                       wall: Optional[float] = None) -> int:
        t_mono = time.monotonic() if now is None else now
        t_unix = time.time() if wall is None else wall
        snap = self._registry.snapshot()
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._last_t = t_mono
            for fam in snap.get("families", []):
                name, kind = fam["name"], fam["type"]
                self._labelnames[name] = tuple(fam.get("labelnames", ()))
                edges = tuple(fam["le"]) if kind == "histogram" else None
                for s in fam["series"]:
                    key = (name, tuple(s["labels"]))
                    ring = self._series.get(key)
                    if ring is None:
                        ring = self._series[key] = _SeriesRing(
                            kind, self.capacity, edges
                        )
                        if self._prev_stamp is not None and kind != "gauge":
                            # a series BORN mid-window (first labelled
                            # observation — e.g. the first SessionRun's
                            # dispatch histogram) was truthfully zero at
                            # the previous tick: seed that zero so its
                            # first value counts as an increase instead
                            # of an invisible flat line
                            pm, pw = self._prev_stamp
                            if kind == "histogram":
                                ring.push_hist(
                                    seq, pm, pw, 0, 0.0,
                                    (0,) * (len(edges) + 1),
                                )
                            else:
                                ring.push_scalar(seq, pm, pw, 0.0)
                    if kind == "histogram":
                        ring.push_hist(
                            seq, t_mono, t_unix,
                            s["count"], s["sum"], s["buckets"],
                        )
                    else:
                        ring.push_scalar(seq, t_mono, t_unix, s["value"])
            self._prev_stamp = (t_mono, t_unix)
        rb = self._rulebook
        if rb is not None:
            # after the tick, outside the ring lock: rules read back
            # through the public query surface
            try:
                rb.evaluate(self, now=t_mono, wall=t_unix)
            except Exception as exc:
                # an alert bug must never kill the sampler — but it must
                # leave evidence UNCONDITIONALLY: the flight ring only
                # records when the trace flags enabled it, so the log
                # line (paced: first failure, then every 60th — the
                # broker's outage-log posture, since this fires per tick)
                # is what guarantees a broken rulebook is visible instead
                # of silently never paging again
                self._rule_errors += 1
                if self._rule_errors == 1 or self._rule_errors % 60 == 0:
                    logger.warning(
                        "SLO rulebook evaluation failed (%d time(s)): %s",
                        self._rule_errors, exc,
                    )
                from . import flight

                flight.record(
                    "slo.error", "rulebook", error=str(exc)[:200]
                )
        return seq

    def maybe_sample(self) -> bool:
        """Sample if a full period has elapsed — the opportunistic form
        hot loops call so a GIL-saturated process still gets ticks. The
        cheap unlocked check runs first (the hot-path cost); the due
        path re-checks under the tick lock so racing sites produce one
        tick, not one each."""
        if time.monotonic() - self._last_t < self.period:
            return False
        with self._tick_lock:
            if time.monotonic() - self._last_t < self.period:
                return False
            self._sample_locked()
            return True

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="gol-timeline", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            try:
                # an opportunistic site may have just ticked; don't double
                if time.monotonic() - self._last_t >= 0.5 * self.period:
                    self.sample_once()
            # gol: allow(hygiene): the 1 Hz sampler loop must survive
            # registry bugs; recording each period would churn the
            # flight ring — the rulebook path above records once
            except Exception:  # pragma: no cover - registry bugs
                pass

    # -- queries (the obs/slo.py rule surface) -----------------------------

    def _rings(self, name: str, labels=None) -> List[_SeriesRing]:  # gol: holds(_lock)
        """Matching rings. Caller must hold ``self._lock`` across BOTH
        this call and any iteration of the returned rings' deques — a
        sample tick appends under the same lock (every query above/below
        wraps this call in ``with self._lock`` — the holds() marker
        declares that caller contract to analysis/locks.py)."""
        return [
            ring
            for (n, lv), ring in self._series.items()
            if n == name and (labels is None or lv == tuple(labels))
        ]

    def increase(self, name: str, window_s: float, labels=None) -> Optional[float]:
        """Summed adjusted increase across matching series over the
        window; None when no series has two samples yet. Histograms
        count their observation COUNT."""
        total, seen = 0.0, False
        with self._lock:
            for ring in self._rings(name, labels):
                pair = ring.pair(window_s)
                if pair is None:
                    continue
                old, new = pair
                total += new[3] - old[3]
                seen = True
        return total if seen else None

    def rate(self, name: str, window_s: float, labels=None) -> Optional[float]:
        """Per-second rate over the window's REAL elapsed time."""
        best_dt = 0.0
        total, seen = 0.0, False
        with self._lock:
            for ring in self._rings(name, labels):
                pair = ring.pair(window_s)
                if pair is None:
                    continue
                old, new = pair
                total += new[3] - old[3]
                best_dt = max(best_dt, new[1] - old[1])
                seen = True
        if not seen or best_dt <= 0:
            return None
        return total / best_dt

    def quantile(self, name: str, q: float, window_s: float,
                 labels=None) -> Optional[float]:
        """Histogram quantile over the window: element-wise bucket deltas
        summed across matching series, interpolated within the fixed
        edges. None without histogram data in the window."""
        edges = None
        acc: Optional[List[float]] = None
        with self._lock:
            for ring in self._rings(name, labels):
                if ring.kind != "histogram" or ring.edges is None:
                    continue
                pair = ring.pair(window_s)
                if pair is None:
                    continue
                old, new = pair
                delta = [x - y for x, y in zip(new[5], old[5])]
                if edges is None:
                    edges, acc = ring.edges, delta
                elif ring.edges == edges:
                    acc = [a + d for a, d in zip(acc, delta)]
        if acc is None:
            return None
        return quantile_from_buckets(edges, acc, q)

    def gauge_values(self, name: str) -> Dict[Tuple[str, ...], float]:
        """Latest value per labelled gauge series."""
        out = {}
        with self._lock:
            for (n, lv), ring in self._series.items():
                if n == name and ring.kind == "gauge" and ring.samples:
                    out[lv] = ring.samples[-1][3]
        return out

    def gauge_window(self, name: str, window_s: float,
                     labels=None) -> Optional[Tuple[float, float]]:
        """(earliest-in-window, latest) gauge value — the growth-rule
        surface (e.g. the scatter-deadline EWMA)."""
        with self._lock:
            for ring in self._rings(name, labels):
                if ring.kind != "gauge":
                    continue
                pair = ring.pair(window_s)
                if pair is not None:
                    return pair[0][3], pair[1][3]
        return None

    # -- exposition --------------------------------------------------------

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def reset_count(self, name: str, labels=None) -> int:
        with self._lock:
            return sum(r.resets for r in self._rings(name, labels))

    def summary(self, window_s: float = SUMMARY_WINDOW_S) -> dict:
        """Server-computed rates/quantiles per series over ``window_s``,
        keyed ``name{label=value,...}`` like report.stage_timings:
        counters -> rate; histograms -> count rate + mean + p50/p99;
        gauges -> latest/min/max over the window. Zero-activity series
        are skipped (the stage_timings posture)."""
        out: dict = {}
        with self._lock:
            for (name, lv), ring in self._series.items():
                pairs = ",".join(
                    f"{n}={v}"
                    for n, v in zip(self._labelnames.get(name, ()), lv)
                )
                key = name + (f"{{{pairs}}}" if pairs else "")
                pair = ring.pair(window_s)
                if pair is None:
                    continue
                old, new = pair
                dt = new[1] - old[1]
                if ring.kind == "counter":
                    inc = new[3] - old[3]
                    if inc:
                        out[key] = {
                            "rate_per_s": round(inc / dt, 6) if dt > 0 else None,
                            "increase": inc,
                        }
                elif ring.kind == "histogram":
                    dcount = new[3] - old[3]
                    if not dcount:
                        continue
                    dsum = new[4] - old[4]
                    delta = [x - y for x, y in zip(new[5], old[5])]
                    out[key] = {
                        "rate_per_s": round(dcount / dt, 6) if dt > 0 else None,
                        "count": dcount,
                        "mean_s": round(dsum / dcount, 9),
                        "p50_s": quantile_from_buckets(ring.edges, delta, 0.50),
                        "p99_s": quantile_from_buckets(ring.edges, delta, 0.99),
                    }
                else:  # gauge
                    window = [
                        s[3] for s in ring.samples if s[1] >= new[1] - window_s
                    ]
                    if new[3] or any(window):
                        out[key] = {
                            "value": new[3],
                            "min": min(window) if window else new[3],
                            "max": max(window) if window else new[3],
                        }
        return out

    def window(self, since: int = 0, window_s: float = SUMMARY_WINDOW_S) -> dict:
        """The Status payload form: every sample with seq > ``since``
        (the poller echoes the last seq it saw — incremental windows),
        plus the server-computed ``summary``. Counter/gauge samples ship
        ``[seq, t_unix, value]``; histograms ``[seq, t_unix, count,
        sum]`` (quantiles are server business — the summary carries
        them, so windows stay small). Plain JSON-able throughout: the
        payload must cross the restricted unpickler."""
        series = []
        with self._lock:
            seq = self._seq
            for (name, lv), ring in self._series.items():
                if ring.kind == "histogram":
                    samples = [
                        [s[0], round(s[2], 3), s[3], round(s[4], 6)]
                        for s in ring.samples if s[0] > since
                    ]
                else:
                    samples = [
                        [s[0], round(s[2], 3), s[3]]
                        for s in ring.samples if s[0] > since
                    ]
                if not samples:
                    continue
                series.append({
                    "name": name,
                    "labels": list(lv),
                    "labelnames": list(self._labelnames.get(name, ())),
                    "kind": ring.kind,
                    "resets": ring.resets,
                    "samples": samples,
                })
        return {
            "schema": SCHEMA,
            "seq": seq,
            "period_s": self.period,
            "summary_window_s": window_s,
            "series": series,
            "summary": self.summary(window_s),
        }

    def chrome_counter_samples(self) -> List[dict]:
        """Trace-event counter samples (``ph: "C"`` feedstock for
        tracing.write_chrome_trace): counters as per-second rates between
        consecutive ticks, gauges as raw values — so Perfetto shows
        throughput/HBM/queue depth ON the span timeline. Histograms are
        summarised elsewhere and skipped here."""
        out: List[dict] = []
        with self._lock:
            items = [
                (name, lv, ring.kind, list(ring.samples))
                for (name, lv), ring in self._series.items()
            ]
            labelnames = dict(self._labelnames)
        for name, lv, kind, samples in items:
            if kind == "histogram":
                continue
            pairs = ",".join(
                f"{n}={v}" for n, v in zip(labelnames.get(name, ()), lv)
            )
            track = name + (f"{{{pairs}}}" if pairs else "")
            if kind == "gauge":
                if not any(s[3] for s in samples):
                    continue
                for s in samples:
                    out.append({
                        "name": track, "ts_us": int(s[2] * 1e6),
                        "value": s[3],
                    })
            else:
                if len(samples) < 2 or samples[-1][3] == samples[0][3]:
                    continue
                for prev, cur in zip(samples, samples[1:]):
                    dt = cur[1] - prev[1]
                    if dt <= 0:
                        continue
                    out.append({
                        "name": track + " /s", "ts_us": int(cur[2] * 1e6),
                        "value": (cur[3] - prev[3]) / dt,
                    })
        return out


# -- the process-global default sampler --------------------------------------

_SAMPLER: Optional[TimelineSampler] = None


def sampler() -> Optional[TimelineSampler]:
    return _SAMPLER


def enabled() -> bool:
    return _SAMPLER is not None


def enable(
    period: float = DEFAULT_PERIOD,
    capacity: Optional[int] = None,
    rules=None,
    start_thread: bool = True,
) -> TimelineSampler:
    """Start the global timeline (the ``-timeline [SECS]`` flags).
    Implies ``metrics.enable()`` — a timeline over a disabled registry
    would record a flat zero forever. Attaches the default SLO rulebook
    (obs/slo.py) unless ``rules`` overrides it (pass ``rules=[]`` for a
    timeline with no alerting). Default capacity scales with the period
    so the rings always span ``RULE_HORIZON_S`` of wall clock — the slow
    SLO windows must be real windows at any cadence."""
    global _SAMPLER
    if _SAMPLER is not None:
        disable()
    _metrics.enable()
    if capacity is None:
        capacity = max(DEFAULT_CAPACITY, int(RULE_HORIZON_S / period) + 2)
    s = TimelineSampler(period=period, capacity=capacity)
    from . import slo as _slo  # lazy: slo imports this module's helpers

    s.attach_rulebook(_slo.RuleBook(
        _slo.default_rules() if rules is None else rules
    ))
    _SAMPLER = s
    if start_thread:
        s.start()
    return s


def disable() -> None:
    global _SAMPLER
    s, _SAMPLER = _SAMPLER, None
    if s is not None:
        s.stop()


def maybe_sample() -> None:
    """Hot-loop hook (engine chunk boundaries): one global load and a
    branch when the timeline is off; an opportunistic due-tick when on."""
    s = _SAMPLER
    if s is not None:
        s.maybe_sample()
