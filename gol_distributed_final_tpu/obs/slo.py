"""Serving SLOs: declarative rules evaluated in-process on the timeline.

The Gemma-on-TPU serving comparison (arXiv:2605.25645) frames every
serving result as a latency/throughput OBJECTIVE; this module makes those
objectives executable against the obs/timeline.py rings, where the
history already lives — server-side, off the poller, surviving dashboard
detach (the Podracer controller-off-the-hot-path posture).

Rule kinds (each a small class with one ``evaluate(timeline) -> (firing,
value, detail)``):

* ``BurnRateRule`` — Google-SRE multi-window burn rate on an error
  RATIO (numerator/denominator counter rates): fires only when the
  ratio exceeds ``factor x (1 - objective)`` over BOTH the fast window
  (catches a fresh outage quickly) and the slow window (a brief blip
  de-asserts instead of paging) — the two-window recipe from the SRE
  workbook, scaled to in-process window lengths.
* ``QuantileRule`` — a latency objective: the histogram's q-quantile
  above the threshold over both windows (with a minimum observation
  count, so three slow requests at 3 a.m. don't page).
* ``IncreaseRule`` — an any-increase-is-an-event counter (worker losses,
  integrity failures): fires while the window contains an increase.
* ``GaugeRatioRule`` — a headroom bound on a gauge pair per label set
  (HBM in-use / limit).
* ``GrowthRule`` — a drift detector on a gauge (the scatter-deadline
  EWMA): fires when the latest value grew past ``factor x`` the value a
  window ago — the "cluster is getting slower" signal before any
  absolute threshold trips.

``RuleBook`` owns the state machine: a rule TRANSITIONING to firing
increments ``gol_slo_alerts_total{rule,severity}``, lands a structured
``slo.fire`` event in the flight recorder (PR 2), and appears in the
``Status`` payload (rendered as obs/watch.py's ALERTS panel) until it
clears. Rule NAMES are a stable operator contract like metric names:
``DEFAULT_RULE_NAMES`` is documented in the README "SLOs & alerting"
table and linted by obs/lint.py.

Thresholds are deliberately serving-loose defaults (CPU loopback must
not page); operators tune by passing their own rule list to
``timeline.enable(rules=...)``.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from . import flight as _flight
from . import instruments as _ins

SEVERITIES = ("page", "warn")


class Rule:
    """Base: ``name`` and ``severity`` are the alert's stable identity
    (the ``gol_slo_alerts_total`` label pair)."""

    def __init__(self, name: str, severity: str):
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}")
        self.name = name
        self.severity = severity

    def evaluate(self, tl) -> Tuple[bool, Optional[float], str]:
        raise NotImplementedError

    def describe(self) -> str:
        return self.name


class BurnRateRule(Rule):
    """Multi-window burn rate on ``numerator``/``denominator`` counter
    rates. Burn threshold = ``factor x (1 - objective)``; fires when the
    ratio exceeds it over BOTH windows."""

    def __init__(self, name, severity, numerator, denominator, *,
                 objective=0.999, factor=14.4, fast_s=30.0, slow_s=120.0):
        super().__init__(name, severity)
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        self.numerator = numerator
        self.denominator = denominator
        self.objective = objective
        self.factor = factor
        self.fast_s = fast_s
        self.slow_s = slow_s

    @property
    def threshold(self) -> float:
        return self.factor * (1.0 - self.objective)

    def _ratio(self, tl, window_s) -> Optional[float]:
        num = tl.increase(self.numerator, window_s)
        den = tl.increase(self.denominator, window_s)
        if num is None or not den:
            return None
        return num / den

    def evaluate(self, tl):
        fast = self._ratio(tl, self.fast_s)
        slow = self._ratio(tl, self.slow_s)
        firing = (
            fast is not None and slow is not None
            and fast > self.threshold and slow > self.threshold
        )
        value = fast if fast is not None else slow
        return firing, value, (
            f"{self.numerator}/{self.denominator} "
            f"{'?' if fast is None else f'{fast:.4f}'} fast / "
            f"{'?' if slow is None else f'{slow:.4f}'} slow "
            f"(burn threshold {self.threshold:.4f})"
        )


class QuantileRule(Rule):
    """Histogram p``q`` over ``threshold`` seconds in both windows, with
    at least ``min_count`` observations in the fast window."""

    def __init__(self, name, severity, metric, *, q=0.99, threshold=0.25,
                 fast_s=30.0, slow_s=120.0, min_count=10):
        super().__init__(name, severity)
        self.metric = metric
        self.q = q
        self.threshold = threshold
        self.fast_s = fast_s
        self.slow_s = slow_s
        self.min_count = min_count

    def evaluate(self, tl):
        fast = tl.quantile(self.metric, self.q, self.fast_s)
        slow = tl.quantile(self.metric, self.q, self.slow_s)
        count = tl.increase(self.metric, self.fast_s) or 0
        firing = (
            fast is not None and slow is not None
            and count >= self.min_count
            and fast > self.threshold and slow > self.threshold
        )
        return firing, fast, (
            f"{self.metric} p{int(self.q * 100)} "
            f"{'?' if fast is None else f'{fast:.4f}s'} fast / "
            f"{'?' if slow is None else f'{slow:.4f}s'} slow "
            f"(> {self.threshold}s, n={int(count)})"
        )


class IncreaseRule(Rule):
    """Fires while the window holds a counter increase above
    ``threshold`` (default: ANY increase — worker losses, integrity
    failures). The alert self-clears once the increase ages out of the
    window.

    ``labels`` optionally restricts the count to specific label-value
    tuples of the family, summed — the canary-failure rule watches only
    ``gol_canary_probes_total``'s failure results, never the ``ok``
    stream that moves on every healthy probe."""

    def __init__(self, name, severity, metric, *, threshold=0.0,
                 window_s=60.0, labels=None):
        super().__init__(name, severity)
        self.metric = metric
        self.threshold = threshold
        self.window_s = window_s
        self.labels = [tuple(l) for l in labels] if labels else None

    def evaluate(self, tl):
        if self.labels is None:
            inc = tl.increase(self.metric, self.window_s)
        else:
            seen = [
                v
                for l in self.labels
                for v in (tl.increase(self.metric, self.window_s, labels=l),)
                if v is not None
            ]
            inc = sum(seen) if seen else None
        where = (
            "{" + "|".join(",".join(l) for l in self.labels) + "}"
            if self.labels else ""
        )
        firing = inc is not None and inc > self.threshold
        return firing, inc, (
            f"{self.metric}{where} +{0 if inc is None else int(inc)} over "
            f"{int(self.window_s)}s (> {int(self.threshold)})"
        )


class GaugeRatioRule(Rule):
    """Fires when any label set's ``num/den`` exceeds ``max_ratio`` —
    the headroom bound (HBM in-use vs limit, per device)."""

    def __init__(self, name, severity, num_metric, den_metric, *,
                 max_ratio=0.92):
        super().__init__(name, severity)
        self.num_metric = num_metric
        self.den_metric = den_metric
        self.max_ratio = max_ratio

    def evaluate(self, tl):
        nums = tl.gauge_values(self.num_metric)
        dens = tl.gauge_values(self.den_metric)
        worst, worst_labels = None, None
        for labels, num in nums.items():
            den = dens.get(labels)
            if not den:
                continue
            ratio = num / den
            if worst is None or ratio > worst:
                worst, worst_labels = ratio, labels
        firing = worst is not None and worst > self.max_ratio
        where = ",".join(worst_labels) if worst_labels else "-"
        return firing, worst, (
            f"{self.num_metric}/{self.den_metric} "
            f"{'?' if worst is None else f'{worst:.2f}'} at [{where}] "
            f"(> {self.max_ratio})"
        )


class GaugeAboveRule(Rule):
    """Fires when any label set's latest gauge value exceeds an absolute
    ``threshold`` — the simplest possible bound, used where the gauge
    itself already encodes the judgement (the fleet collector's
    ``gol_fleet_targets_down`` count: ANY nonzero value is a dead
    target)."""

    def __init__(self, name, severity, metric, *, threshold=0.0):
        super().__init__(name, severity)
        self.metric = metric
        self.threshold = threshold

    def evaluate(self, tl):
        vals = tl.gauge_values(self.metric)
        worst, worst_labels = None, None
        for labels, v in vals.items():
            if worst is None or v > worst:
                worst, worst_labels = v, labels
        firing = worst is not None and worst > self.threshold
        where = ",".join(worst_labels) if worst_labels else "-"
        return firing, worst, (
            f"{self.metric} {'?' if worst is None else f'{worst:.3g}'} "
            f"at [{where}] (> {self.threshold:.3g})"
        )


class GrowthRule(Rule):
    """Fires when a gauge's latest value grew past ``factor x`` its
    value a window ago (both nonzero) — drift, not an absolute bound
    (the scatter-deadline EWMA's 'cluster is getting slower')."""

    def __init__(self, name, severity, metric, *, factor=3.0,
                 window_s=120.0, floor=0.0):
        super().__init__(name, severity)
        self.metric = metric
        self.factor = factor
        self.window_s = window_s
        self.floor = floor  # ignore growth below this absolute value

    def evaluate(self, tl):
        pair = tl.gauge_window(self.metric, self.window_s)
        if pair is None:
            return False, None, f"{self.metric}: no window yet"
        earlier, latest = pair
        firing = (
            earlier > 0 and latest > self.floor
            and latest >= self.factor * earlier
        )
        growth = latest / earlier if earlier > 0 else None
        return firing, growth, (
            f"{self.metric} {earlier:.3g} -> {latest:.3g} over "
            f"{int(self.window_s)}s "
            f"({'?' if growth is None else f'{growth:.1f}x'}, "
            f"fires at {self.factor}x)"
        )


def default_rules() -> List[Rule]:
    """The default serving rulebook — one rule per objective on the
    README "SLOs & alerting" table (names are the stable contract,
    ``DEFAULT_RULE_NAMES`` below; obs/lint.py enforces the docs)."""
    return [
        # losing a worker mid-run is the page: recovery machinery (PR 4)
        # hides the latency cost, so an operator would otherwise only
        # notice at the Nth loss of a flapping transport
        IncreaseRule(
            "worker-lost", "page", "gol_worker_lost_total", window_s=60.0,
        ),
        # any integrity failure is a caught corruption — page immediately
        IncreaseRule(
            "integrity-failures", "page", "gol_integrity_failures_total",
            window_s=120.0,
        ),
        # the blackbox closure (obs/canary.py): a probe that came back
        # WRONG ('corrupt') or failed loudly ('error') means the serving
        # path itself is broken end to end — page within one probe
        # period instead of waiting for a user to notice. The 'ok'
        # stream is excluded: a healthy canary must never arm the rule.
        IncreaseRule(
            "canary-failure", "page", "gol_canary_probes_total",
            window_s=120.0, labels=[("corrupt",), ("error",)],
        ),
        # 99.9% availability objective at 14.4x burn (the SRE workbook's
        # fast-burn page): >1.44% of RPCs erroring in both windows
        BurnRateRule(
            "rpc-error-ratio", "page",
            "gol_rpc_server_errors_total", "gol_rpc_server_requests_total",
            objective=0.999, factor=14.4, fast_s=30.0, slow_s=120.0,
        ),
        # per-universe-turn serving latency (engine/sessions.py): the
        # batch is supposed to amortise dispatch — p99 above 250 ms per
        # chunk-normalized turn means it is not
        QuantileRule(
            "session-turn-latency", "page", "gol_session_turn_seconds",
            q=0.99, threshold=0.25, fast_s=30.0, slow_s=120.0,
        ),
        # admission should be near-instant (a lock + a table append);
        # waiting a second means the driver thread is starved or wedged
        QuantileRule(
            "session-admit-latency", "warn",
            "gol_session_admit_wait_seconds",
            q=0.99, threshold=1.0, fast_s=30.0, slow_s=120.0, min_count=3,
        ),
        # per-verb handler latency on the serving surface
        QuantileRule(
            "rpc-dispatch-latency", "warn", "gol_rpc_dispatch_seconds",
            q=0.99, threshold=1.0, fast_s=30.0, slow_s=120.0,
        ),
        # HBM headroom: past 92% in-use the next admission OOMs
        GaugeRatioRule(
            "hbm-headroom", "page",
            "gol_device_hbm_bytes_in_use", "gol_device_hbm_bytes_limit",
            max_ratio=0.92,
        ),
        # the adaptive scatter deadline (rpc/broker.py) tracks the
        # turn-time EWMA: 3x growth means the cluster is getting slower
        # even though nothing has failed yet
        GrowthRule(
            "scatter-deadline-growth", "warn",
            "gol_scatter_deadline_seconds", factor=3.0, window_s=120.0,
            floor=1.0,
        ),
        # per-worker service-time skew (obs/critical.py: slowest EWMA /
        # roster median, updated per K-batch): every fan-out turn lands
        # at the slowest worker's pace, so a skew that DOUBLES means one
        # host quietly started setting the whole cluster's turn rate —
        # the straggler signal before anything fails. floor 1.5 keeps a
        # balanced roster's jitter (~1.0) from ever arming it.
        GrowthRule(
            "worker-skew", "warn",
            "gol_worker_skew_ratio", factor=2.0, window_s=120.0,
            floor=1.5,
        ),
        # stop-the-world GC pauses (obs/profiler.py's gc.callbacks
        # hook): a 50 ms pause under a 250 ms turn budget IS the p99,
        # and no segment decomposition will ever name it — the rule
        # only arms while a -profile run is metering pauses
        QuantileRule(
            "gc-pause", "warn", "gol_gc_pause_seconds",
            q=0.99, threshold=0.05, fast_s=30.0, slow_s=120.0,
            min_count=3,
        ),
    ]


def fleet_rules() -> List[Rule]:
    """Fleet-scope rules the collector (obs/fleet.py) adds ON TOP of the
    re-instantiated default rulebook — each reads a ``gol_fleet_*`` gauge
    the collector maintains in its OWN registry from scrape health and
    the merged ledgers, so the rules ride the same timeline surface as
    every other objective (names documented in the README "Fleet" rule
    table, ``FLEET_RULE_NAMES`` below; obs/lint.py enforces the docs)."""
    return [
        # a target whose last-success age crossed the staleness bound is
        # DOWN — the page every other fleet reading depends on, since a
        # dead broker's sessions silently vanish from the merged sums
        GaugeAboveRule(
            "target-down", "page", "gol_fleet_targets_down", threshold=0.0,
        ),
        # summed live sessions vs summed broker capacity: past 90% the
        # fleet has no room to reshard a dead broker's tenants into
        GaugeRatioRule(
            "fleet-capacity-headroom", "warn",
            "gol_fleet_sessions_active", "gol_fleet_capacity_total",
            max_ratio=0.90,
        ),
        # a tenant whose device-seconds pile onto ONE broker at >3x its
        # fair share defeats the sharding the fleet exists to provide
        GaugeAboveRule(
            "fleet-tenant-skew", "warn", "gol_fleet_tenant_skew",
            threshold=3.0,
        ),
    ]


#: the stable rule-name contract (README "SLOs & alerting", obs/lint.py)
DEFAULT_RULE_NAMES = (
    "worker-lost",
    "integrity-failures",
    "canary-failure",
    "rpc-error-ratio",
    "session-turn-latency",
    "session-admit-latency",
    "rpc-dispatch-latency",
    "hbm-headroom",
    "scatter-deadline-growth",
    "worker-skew",
    "gc-pause",
)

#: the fleet collector's rule-name contract (README "Fleet", obs/lint.py)
FLEET_RULE_NAMES = (
    "target-down",
    "fleet-capacity-headroom",
    "fleet-tenant-skew",
)


class _AlertState:
    __slots__ = ("firing", "since_mono", "since_unix", "value", "detail",
                 "fired_total")

    def __init__(self):
        self.firing = False
        self.since_mono = 0.0
        self.since_unix = 0.0
        self.value = None
        self.detail = ""
        self.fired_total = 0


class RuleBook:
    """Rule states + transition side effects. ``evaluate`` runs after
    every timeline tick (TimelineSampler calls it); ``snapshot`` is the
    JSON-able list the Status payload ships (the ALERTS panel's feed and
    the doctor's correlation input)."""

    def __init__(self, rules: List[Rule]):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self.rules = list(rules)
        self._states = {r.name: _AlertState() for r in self.rules}

    def evaluate(self, tl, now: Optional[float] = None,
                 wall: Optional[float] = None) -> List[dict]:
        """Evaluate every rule; returns the transitions that happened
        this tick (fired/cleared) for callers that want them."""
        now = time.monotonic() if now is None else now
        wall = time.time() if wall is None else wall
        transitions = []
        for rule in self.rules:
            state = self._states[rule.name]
            try:
                firing, value, detail = rule.evaluate(tl)
            except Exception as exc:  # a rule bug must not kill the tick
                firing, value, detail = False, None, f"rule error: {exc}"
            state.value, state.detail = value, detail
            if firing and not state.firing:
                state.firing = True
                state.since_mono, state.since_unix = now, wall
                state.fired_total += 1
                _ins.SLO_ALERTS_TOTAL.labels(rule.name, rule.severity).inc()
                _flight.record(
                    "slo.fire", rule.name, severity=rule.severity,
                    value=value, detail=detail[:200],
                )
                transitions.append({"rule": rule.name, "event": "fire"})
            elif state.firing and not firing:
                state.firing = False
                state.since_mono, state.since_unix = now, wall
                _flight.record("slo.clear", rule.name, severity=rule.severity)
                transitions.append({"rule": rule.name, "event": "clear"})
        return transitions

    def active(self) -> List[dict]:
        return [a for a in self.snapshot() if a["state"] == "firing"]

    def snapshot(self) -> List[dict]:
        """Every rule's current state, firing first — plain JSON-able
        (the Status payload form; crosses the restricted unpickler)."""
        out = []
        for rule in self.rules:
            s = self._states[rule.name]
            out.append({
                "rule": rule.name,
                "severity": rule.severity,
                "state": "firing" if s.firing else "ok",
                "since_unix": s.since_unix or None,
                "value": s.value,
                "detail": s.detail,
                "fired_total": s.fired_total,
            })
        out.sort(key=lambda a: (a["state"] != "firing",
                                SEVERITIES.index(a["severity"])
                                if a["severity"] in SEVERITIES else 9))
        return out


def active_alerts() -> List[dict]:
    """The global sampler's firing alerts ([] when the timeline — and so
    alerting — is off). The doctor and report surfaces read this."""
    from . import timeline as _timeline

    s = _timeline.sampler()
    if s is None or s.rulebook is None:
        return []
    return s.rulebook.active()


def alerts_snapshot() -> Optional[List[dict]]:
    """Every rule state, or None when alerting is off — the Status
    payload's ``alerts`` field."""
    from . import timeline as _timeline

    s = _timeline.sampler()
    if s is None or s.rulebook is None:
        return None
    return s.rulebook.snapshot()
