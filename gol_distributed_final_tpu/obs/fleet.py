"""Fleet collector: ONE address that speaks for many brokers.

    python -m gol_distributed_final_tpu.obs.fleet tcp://hostA:8040 \
        tcp://hostB:8040 [-port 8050] [-interval SECS]

Every observability consumer before this module (obs/watch.py,
obs/doctor.py, the SLO rulebook, timeline rings, tenant ledger) polls
exactly one process at a time. The collector gives the CLUSTER its own
control-plane process, Podracer-style — out of the data plane's hot
loop — that scrapes every broker's read-only ``Status`` verb on a fixed
cadence, auto-discovers each broker's workers from the
``worker_health`` roster the broker already ships, and folds the fleet
into one model:

- **Exact metric merge.** ``metrics.merge_snapshots`` is the primitive:
  merged counters equal the arithmetic SUM of per-process snapshots,
  histograms per-bucket (fixed edges are the exactness contract). Only
  the CURRENT sweep's successful scrapes are merged, so a dead target
  leaves the merged totals within one sweep — the sums stay exactly
  equal to the sum of the SURVIVING targets' own snapshots. A snapshot
  the merge refuses (type/edge mismatch = version skew) is dropped and
  counted in ``gol_fleet_merge_failures_total``: skew degrades loudly,
  never wrongly.
- **Scrape health.** Per target: last-success age, consecutive-failure
  count, ok/error totals, last error string. A target whose
  last-success age passes ``STALE_INTERVALS`` sweeps is STALE — a dead
  broker is first-class data (a finding, a gauge, a firing rule), not a
  timeout traceback.
- **Fleet timeline + SLOs.** A private ``TimelineSampler`` samples the
  merged registry each sweep, and a ``RuleBook`` of the standard rules
  PLUS the fleet rules (``target-down``, ``fleet-capacity-headroom``,
  ``fleet-tenant-skew`` — obs/slo.py ``fleet_rules``) evaluates over
  the merged series.
- **Incremental cursors.** The four ``*_since`` cursors
  (timeline/accounting/journal/profile) are tracked and echoed PER
  TARGET, so N targets ship deltas, not full windows, every sweep. A
  target restart (pid change) resets its cursors to 0.

The collector serves its own read-only Status verb (same
``Operations.Status`` surface, ``role="fleet"``), so ``obs/watch.py``
and ``obs/doctor.py`` pointed at this ONE address render/diagnose the
whole fleet."""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from . import instruments as _ins
from . import metrics as _metrics
from .status import fetch_many, norm_address, scalar_value
from .timeline import RULE_HORIZON_S, TimelineSampler

# the gol_fleet_* families exist only where a collector can live: their
# registration rides this import so a plain broker/worker Status payload
# never carries them (the incremental-reply size budget counts every
# registered family, empty or not)
_ins.register_fleet_instruments()

SCHEMA = "gol-fleet/1"
DEFAULT_PORT = 8050
DEFAULT_INTERVAL = 5.0
# sweeps a target may miss before it is STALE (gol_fleet_targets_down,
# the target-down page) — one slow scrape is noise, three is an outage
STALE_INTERVALS = 3
_CURSOR_KEYS = (
    "timeline_since", "accounting_since", "journal_since", "profile_since",
)
_CURSOR_SOURCES = ("timeline", "accounting", "journal", "profile")


class _TargetHealth:
    """Scrape-health bookkeeping for one target address."""

    __slots__ = (
        "address", "worker", "via", "last_success_unix", "last_attempt_unix",
        "consecutive_failures", "ok_total", "err_total", "error", "pid",
    )

    def __init__(self, address: str, worker: bool, via: str):
        self.address = address
        self.worker = worker
        self.via = via  # "configured" or the discovering broker's address
        self.last_success_unix: Optional[float] = None
        self.last_attempt_unix: Optional[float] = None
        self.consecutive_failures = 0
        self.ok_total = 0
        self.err_total = 0
        self.error: Optional[str] = None
        self.pid: Optional[int] = None

    def state(self, now: float, stale_after: float) -> str:
        """``ok`` | ``failing`` | ``stale`` | ``pending`` — the operator
        word for this target. ``stale`` means the last-success age passed
        the bound (or it NEVER succeeded despite attempts): the fleet no
        longer has current truth about it."""
        if self.last_attempt_unix is None:
            return "pending"
        if self.consecutive_failures == 0:
            return "ok"
        if self.last_success_unix is None:
            return "stale"
        if now - self.last_success_unix > stale_after:
            return "stale"
        return "failing"

    def row(self, now: float, stale_after: float, cursors: dict) -> dict:
        return {
            "address": self.address,
            "worker": self.worker,
            "via": self.via,
            "state": self.state(now, stale_after),
            "last_success_age_s": (
                None if self.last_success_unix is None
                else round(now - self.last_success_unix, 3)
            ),
            "consecutive_failures": self.consecutive_failures,
            "ok_total": self.ok_total,
            "err_total": self.err_total,
            "error": self.error,
            # the incremental cursors echoed per address: what this
            # collector will send on the NEXT scrape of this target
            "cursors": dict(cursors),
        }


class _CompositeRegistry:
    """Registry-shaped adapter over the collector's merged cluster
    snapshot, so a stock ``TimelineSampler`` (which only needs
    ``.snapshot()``) can ring-buffer the FLEET's series."""

    def __init__(self, collector: "FleetCollector"):
        self._collector = collector

    def snapshot(self) -> dict:
        return self._collector.composite_snapshot()


class FleetCollector:
    """Scrapes many Status endpoints, merges them into one cluster
    model, and answers Status for the whole fleet.

    ``sweep(now=None, wall=None)`` is one full poll: fan-out fetch
    (``status.fetch_many`` — parallel, per-target timeout), roster
    auto-discovery, exact merge, fleet gauges, timeline sample, rule
    evaluation. The clock args are injectable so tests drive staleness
    and rule transitions deterministically."""

    def __init__(
        self,
        brokers,
        extra_workers=(),
        interval: float = DEFAULT_INTERVAL,
        timeout: float = 5.0,
    ):
        self.brokers = [norm_address(b) for b in brokers]
        self.extra_workers = [norm_address(w) for w in extra_workers]
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.stale_after = STALE_INTERVALS * self.interval
        self._lock = threading.Lock()
        self._health: Dict[str, _TargetHealth] = {}
        self._cursors: Dict[str, Dict[str, int]] = {}
        # workers each broker's roster named, kept across sweeps so a
        # dead broker's workers stay scraped (their health still matters)
        self._discovered: Dict[str, str] = {}  # worker addr -> via broker
        # latest SUCCESSFUL broker payloads from the CURRENT sweep only:
        # what watch renders per-broker panels from (a dead broker gets a
        # health row, not a panel of stale numbers)
        self._broker_status: Dict[str, dict] = {}
        # cumulative per-broker per-tenant device-seconds: the tenant
        # ledger ships INCREMENTAL windows (only tenants whose seq
        # moved), so skew needs last-known cumulative values cached
        self._tenant_dev: Dict[str, Dict[str, float]] = {}
        self._merge_excluded: Dict[str, str] = {}
        self._merged: dict = {"schema": "gol-metrics/1", "families": []}
        self._sweeps = 0
        from .slo import RuleBook, default_rules, fleet_rules

        self._timeline = TimelineSampler(
            registry=_CompositeRegistry(self),
            period=self.interval,
            capacity=max(360, int(RULE_HORIZON_S / self.interval) + 2),
        )
        # fleet-scope SLOs: the standard rulebook re-instantiated over
        # the MERGED series, plus the fleet-only rules
        self._timeline.attach_rulebook(
            RuleBook(list(default_rules()) + list(fleet_rules()))
        )

    @property
    def sweeps(self) -> int:
        """Completed sweep count (bench embeds it beside the scrape p99)."""
        with self._lock:
            return self._sweeps

    # -- target bookkeeping --------------------------------------------------

    def _target_specs(self) -> List[dict]:
        """Current scrape set: configured brokers, then extra workers,
        then roster-discovered workers — each with its echoed cursors."""
        specs = []
        seen = set()
        for addr in self.brokers:
            if addr in seen:
                continue
            seen.add(addr)
            self._health.setdefault(addr, _TargetHealth(addr, False, "configured"))
            specs.append({"address": addr, "worker": False,
                          **self._cursors.get(addr, {})})
        for addr, via in list(
            [(w, "configured") for w in self.extra_workers]
            + sorted(self._discovered.items())
        ):
            if addr in seen:
                continue
            seen.add(addr)
            self._health.setdefault(addr, _TargetHealth(addr, True, via))
            specs.append({"address": addr, "worker": True,
                          **self._cursors.get(addr, {})})
        return specs

    def _note_result(self, addr: str, payload, fetched_at, error) -> None:
        h = self._health[addr]
        h.last_attempt_unix = fetched_at
        if error is None:
            h.consecutive_failures = 0
            h.last_success_unix = fetched_at
            h.error = None
            h.ok_total += 1
            _ins.FLEET_SCRAPES_TOTAL.labels("ok").inc()
            pid = payload.get("pid")
            if isinstance(pid, int) and pid != h.pid:
                if h.pid is not None:
                    # restart: the server's seqs began again at 0 — a
                    # stale cursor would silently suppress its windows
                    self._cursors.pop(addr, None)
                h.pid = pid
            cur = self._cursors.setdefault(addr, {})
            for key, source in zip(_CURSOR_KEYS, _CURSOR_SOURCES):
                part = payload.get(source)
                seq = part.get("seq") if isinstance(part, dict) else None
                if isinstance(seq, int):
                    cur[key] = seq
        else:
            h.consecutive_failures += 1
            h.err_total += 1
            h.error = error
            _ins.FLEET_SCRAPES_TOTAL.labels("error").inc()

    def _discover(self, broker_addr: str, payload: dict) -> None:
        """Fold the broker's ``worker_health`` roster into the scrape
        set. LOST workers are kept: a worker the broker cannot reach may
        still answer Status, and its scrape health is exactly the
        evidence the doctor wants."""
        roster = payload.get("workers")
        if not isinstance(roster, list):
            return
        for entry in roster:
            if not isinstance(entry, dict):
                continue
            addr = entry.get("address")
            if not isinstance(addr, str) or ":" not in addr:
                continue
            addr = norm_address(addr)
            if addr in self.brokers or addr in self.extra_workers:
                continue
            self._discovered.setdefault(addr, broker_addr)

    # -- the sweep -----------------------------------------------------------

    def sweep(self, now: Optional[float] = None,
              wall: Optional[float] = None) -> dict:
        """One poll of the whole fleet. Returns the fleet section of the
        Status payload (handy for tests and ``-once``)."""
        wall = time.time() if wall is None else wall
        t0 = time.monotonic()
        with self._lock:
            specs = self._target_specs()
        results = fetch_many(specs, timeout=self.timeout)
        with self._lock:
            payloads: Dict[str, dict] = {}
            for spec in specs:
                addr = spec["address"]
                payload, fetched_at, error = results.get(
                    addr, (None, wall, "no result"))
                self._note_result(addr, payload, fetched_at, error)
                if payload is not None:
                    payloads[addr] = payload
                    if not self._health[addr].worker:
                        self._discover(addr, payload)
            self._merge(payloads)
            self._set_fleet_gauges(payloads, wall)
            self._broker_status = {
                a: p for a, p in payloads.items()
                if not self._health[a].worker
            }
            self._sweeps += 1
            _ins.FLEET_SCRAPE_SECONDS.observe(time.monotonic() - t0)
            fleet = self._fleet_section(wall)
        # sample OUTSIDE the collector lock: the sampler snapshots the
        # composite (which re-takes the lock) and runs the rulebook
        self._timeline.sample_once(now=now, wall=wall)
        return fleet

    def _merge(self, payloads: Dict[str, dict]) -> None:
        """Exact merge of the CURRENT sweep's snapshots. Exclusions
        (missing metrics = version skew, merge refusal = edge/type
        skew) are counted and named, never averaged in."""
        merged = {"schema": "gol-metrics/1", "families": []}
        excluded: Dict[str, str] = {}
        for addr in sorted(payloads):
            snap = payloads[addr].get("metrics")
            if not isinstance(snap, dict) or "families" not in snap:
                excluded[addr] = "payload carries no metrics snapshot (skew)"
                _ins.FLEET_MERGE_FAILURES_TOTAL.inc()
                continue
            try:
                merged = _metrics.merge_snapshots(merged, snap)
            except (ValueError, KeyError, TypeError) as exc:
                excluded[addr] = str(exc)
                _ins.FLEET_MERGE_FAILURES_TOTAL.inc()
        self._merged = merged
        self._merge_excluded = excluded

    def _set_fleet_gauges(self, payloads: Dict[str, dict],
                          wall: float) -> None:
        states = [
            h.state(wall, self.stale_after) for h in self._health.values()
        ]
        _ins.FLEET_TARGETS_TOTAL.set(float(len(states)))
        _ins.FLEET_TARGETS_DOWN.set(
            float(sum(1 for s in states if s == "stale")))
        sessions = 0.0
        capacity = 0.0
        for addr, payload in payloads.items():
            if self._health[addr].worker:
                continue
            v = scalar_value(payload.get("metrics") or {},
                             "gol_sessions_active")
            if isinstance(v, (int, float)):
                sessions += v
            cap = payload.get("session_capacity")
            if isinstance(cap, (int, float)):
                capacity += cap
            acct = payload.get("accounting")
            if isinstance(acct, dict):
                dev = self._tenant_dev.setdefault(addr, {})
                for row in acct.get("tenants") or []:
                    if isinstance(row, dict) and "tenant" in row:
                        ds = row.get("device_seconds")
                        if isinstance(ds, (int, float)):
                            dev[str(row["tenant"])] = float(ds)
        _ins.FLEET_SESSIONS_ACTIVE.set(sessions)
        _ins.FLEET_CAPACITY_TOTAL.set(capacity)
        _ins.FLEET_TENANT_SKEW.set(self._tenant_skew()[0])

    def _tenant_skew(self):
        """Worst cross-broker tenant skew from the cached cumulative
        ledgers: hottest broker's share of a tenant's fleet
        device-seconds, times the ledger-shipping broker count (1.0 =
        perfectly spread, N = all on one broker). ``(value, tenant,
        address)``; 0 until >=2 brokers have shipped ledgers."""
        ledgers = {a: d for a, d in self._tenant_dev.items() if d}
        if len(ledgers) < 2:
            return 0.0, None, None
        n = len(ledgers)
        worst = (0.0, None, None)
        tenants = set()
        for dev in ledgers.values():
            tenants.update(dev)
        for tenant in tenants:
            per = {a: d.get(tenant, 0.0) for a, d in ledgers.items()}
            total = sum(per.values())
            if total <= 0.0:
                continue
            hot = max(per, key=per.get)
            skew = per[hot] / total * n
            if skew > worst[0]:
                worst = (skew, tenant, hot)
        return worst

    # -- the cluster model, read out -----------------------------------------

    def composite_snapshot(self) -> dict:
        """The fleet registry: merged data-plane families from the
        targets, plus the collector's OWN ``gol_fleet_*`` families.
        Stripping ``gol_fleet_*`` from the merged side keeps the split
        clean even when a scraped process shares this registry (the
        in-process selfcheck); dropping the collector's other families
        keeps its own RPC-server counters out of the data-plane sums —
        scraping the fleet must not perturb the fleet's numbers."""
        with self._lock:
            merged = self._merged
        own = _metrics.registry().snapshot()
        families = [
            f for f in merged.get("families", [])
            if not str(f.get("name", "")).startswith("gol_fleet_")
        ]
        families.extend(
            f for f in own.get("families", [])
            if str(f.get("name", "")).startswith("gol_fleet_")
        )
        return {"schema": "gol-metrics/1", "families": families}

    def _fleet_section(self, now: float) -> dict:
        rows = [
            h.row(now, self.stale_after, self._cursors.get(a, {}))
            for a, h in sorted(self._health.items())
        ]
        skew, tenant, hot = self._tenant_skew()
        return {
            "schema": SCHEMA,
            "interval_s": self.interval,
            "stale_after_s": self.stale_after,
            "sweeps": self._sweeps,
            "targets": rows,
            "merge_excluded": dict(self._merge_excluded),
            "tenant_skew": {"value": skew, "tenant": tenant, "address": hot},
            "broker_status": dict(self._broker_status),
        }

    def status_payload(self, timeline_since: int = 0) -> dict:
        """The collector's own Status payload: ``role="fleet"``, the
        merged registry as ``metrics``, the fleet timeline window +
        alert states, and the ``fleet`` section (scrape health, cursors,
        per-broker payloads). Same ``gol-status/1`` envelope every
        Status consumer already parses."""
        with self._lock:
            fleet = self._fleet_section(time.time())
        payload = {
            "schema": "gol-status/1",
            "pid": os.getpid(),
            "time_unix": time.time(),
            "role": "fleet",
            "metrics_enabled": True,
            "metrics": self.composite_snapshot(),
            "timeline": self._timeline.window(since=timeline_since),
            "fleet": fleet,
        }
        rb = self._timeline.rulebook
        if rb is not None:
            payload["alerts"] = rb.snapshot()
        return payload


def serve(collector: FleetCollector, host: str = "127.0.0.1",
          port: int = DEFAULT_PORT):
    """Expose the collector's Status on its own RPC port. Both the
    broker-surface and worker-surface Status verbs are registered (and
    nothing else — the collector is read-only by construction), so any
    existing poller reaches it unchanged."""
    from ..rpc.protocol import Methods, Response
    from ..rpc.server import RpcServer

    server = RpcServer(host=host, port=port)

    def _status(req) -> Response:
        since = getattr(req, "timeline_since", 0)
        return Response(status=collector.status_payload(
            timeline_since=since if isinstance(since, int) else 0))

    server.register(Methods.STATUS, _status)
    server.register(Methods.WORKER_STATUS, _status)
    server.serve_background()
    return server


def _selfcheck() -> int:
    """The ``scripts/check --fleet`` smoke: two loopback brokers, a tiny
    run on one, two collector sweeps, then every fleet consumer — exact
    merge pinned against the scraped payloads, watch renders the FLEET
    panel through the collector's OWN Status port, fleet doctor
    diagnoses through it."""
    import numpy as np

    from ..rpc.broker import serve as broker_serve
    from ..rpc.client import RpcClient
    from ..rpc.protocol import Methods, Request

    _metrics.enable()
    server_a, _svc_a = broker_serve(port=0)
    server_b, _svc_b = broker_serve(port=0)
    fleet_server = None
    try:
        addr_a = f"127.0.0.1:{server_a.port}"
        addr_b = f"127.0.0.1:{server_b.port}"
        rng = np.random.default_rng(11)
        board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
        client = RpcClient(addr_a)
        try:
            client.call(
                Methods.BROKER_RUN,
                Request(world=board, turns=8, image_width=64,
                        image_height=64, threads=1),
                timeout=120.0,
            )
        finally:
            client.close()
        collector = FleetCollector([addr_a, addr_b], interval=0.2,
                                   timeout=10.0)
        collector.sweep()
        collector.sweep()
        payload = collector.status_payload()
        fleet = payload.get("fleet") or {}
        scraped = fleet.get("broker_status") or {}
        if set(scraped) != {addr_a, addr_b}:
            print("fleet selfcheck FAILED: not all brokers scraped: "
                  f"{sorted(scraped)}", file=sys.stderr)
            return 1
        # exactness: merged counter == arithmetic sum of the scraped
        # per-target snapshots (both brokers share this process's
        # registry, so the merged value is exactly 2x either)
        want = sum(
            scalar_value(p.get("metrics") or {}, "gol_engine_turns_total")
            or 0.0
            for p in scraped.values()
        )
        got = scalar_value(payload.get("metrics") or {},
                           "gol_engine_turns_total")
        if not want or got != want:
            print(f"fleet selfcheck FAILED: merged gol_engine_turns_total "
                  f"{got} != sum of targets {want}", file=sys.stderr)
            return 1
        fleet_server = serve(collector, port=0)
        fleet_addr = f"127.0.0.1:{fleet_server.port}"
        from .watch import Watcher

        frame, ok = Watcher(fleet_addr, [], timeout=10.0).frame()
        sys.stdout.write(frame + "\n")
        if not ok or "FLEET" not in frame:
            print("fleet selfcheck FAILED: watch at the collector did not "
                  "render a FLEET panel", file=sys.stderr)
            return 1
        from . import doctor as _doctor

        statuses = _doctor.collect(fleet_addr, [], timeout=10.0)
        findings = _doctor.diagnose(statuses)
        text = _doctor.render(findings, statuses)
        sys.stdout.write(text)
        if not findings or not text.strip():
            print("fleet selfcheck FAILED: empty fleet diagnosis",
                  file=sys.stderr)
            return 1
        print("fleet selfcheck ok")
        return 0
    finally:
        if fleet_server is not None:
            fleet_server.stop()
        server_a.stop()
        server_b.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="fleet Status collector: scrape many brokers, merge "
                    "exactly, serve one cluster-level Status"
    )
    parser.add_argument(
        "brokers", nargs="*",
        help="broker Status addresses (tcp://host:port, host:port, :port)",
    )
    parser.add_argument(
        "-worker", action="append", default=[], metavar="HOST:PORT",
        help="extra worker target beyond roster auto-discovery (repeatable)",
    )
    parser.add_argument(
        "-port", type=int, default=DEFAULT_PORT,
        help=f"port the collector's own Status listens on "
             f"(default {DEFAULT_PORT})",
    )
    parser.add_argument("-host", default="127.0.0.1")
    parser.add_argument(
        "-interval", type=float, default=DEFAULT_INTERVAL, metavar="SECS",
        help=f"scrape cadence (default {DEFAULT_INTERVAL}); staleness is "
             f"{STALE_INTERVALS} missed intervals",
    )
    parser.add_argument(
        "-timeout", type=float, default=5.0, metavar="SECONDS",
        help="per-target scrape bound (default 5); a wedged target costs "
             "one timeout, in parallel with the rest of the sweep",
    )
    parser.add_argument(
        "-once", action="store_true",
        help="one sweep, print the fleet Status payload as JSON, exit",
    )
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="loopback smoke over two in-process brokers (scripts/check "
             "--fleet)",
    )
    args = parser.parse_args(argv)
    if args.selfcheck:
        return _selfcheck()
    if not args.brokers:
        parser.error("at least one broker address is required")
    _metrics.enable()
    collector = FleetCollector(
        args.brokers, extra_workers=args.worker,
        interval=args.interval, timeout=args.timeout,
    )
    if args.once:
        collector.sweep()
        print(json.dumps(collector.status_payload(), indent=1, default=str))
        return 0
    server = serve(collector, host=args.host, port=args.port)
    print(
        f"fleet collector on {args.host}:{server.port} scraping "
        f"{len(collector.brokers)} broker(s) every {args.interval}s",
        file=sys.stderr,
    )
    try:
        while True:
            t0 = time.time()
            collector.sweep()
            time.sleep(max(0.0, args.interval - (time.time() - t0)))
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
