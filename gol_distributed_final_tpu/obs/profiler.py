"""Continuous sampling profiler: names per wall, dependency-free.

The PR 12 decomposition (obs/perf.py) prices WHERE a turn's wall went —
``host_prep`` vs ``device_compute`` vs ``wire`` vs ``demux`` — but not
WHICH CODE. The ROADMAP's next tier (pod-scale sharding, a 10k-session
front door) lives or dies on host-side orchestration overhead, exactly
the controller-off-the-hot-path concern Podracer (arXiv:2104.06272)
architects around: this module turns "58% of the turn is host_prep"
into "71% of host samples are in ``pickle.dumps`` via rpc/protocol.py".

* **A daemon sampler over ``sys._current_frames()``.** ``enable(ms)``
  (the ``-profile [MS]`` CLI flags, default cadence 10 ms) walks every
  thread's stack each tick and folds it twice: into a bounded per-thread
  call-tree TRIE (self/cumulative hits per node — the artifact form) and
  into a bounded FLAT frame table (the Status/doctor/diff form). Both
  are capped — past ``max_nodes``/``max_frames`` new frames fold into a
  single ``<other>`` bucket, so a pathological stack set cannot grow
  memory without bound.
* **Adaptive cadence.** Each tick meters its own cost into an EWMA;
  when sampling itself exceeds ``budget`` (default 1%) of the period,
  the period doubles (up to ``max_period_ms``) and
  ``gol_profile_backoffs_total`` ticks — the profiler is the one obs
  layer that must never become the hotspot it exists to find. When the
  cost falls back, the period decays toward the configured base.
* **GC pauses.** ``gc.callbacks`` metering (on by default with the
  profiler; the callback is REMOVED on disable — analysis/hygiene.py
  checks the pairing) feeds ``gol_gc_pause_seconds`` +
  ``gol_gc_collections_total{gen}`` and the ``gc-pause`` SLO rule: a
  stop-the-world pause is wall time no segment decomposition can name.
* **Allocation snapshots.** Opt-in tracemalloc top-N (``alloc_top_n``)
  rides the same window/summary payloads.
* **Three shipping lanes.** Incremental Status windows
  (``window(since=seq)`` — only frames whose counts moved since the
  poller's echoed seq, the ``timeline_since``/``journal_since`` twin,
  via ``Request.profile_since``); on-disk artifacts in collapsed-stack
  and speedscope-JSON form at run end and on crash
  (``flush_on_crash`` — the obs/journal.py posture: never raises); and
  the obs/flame.py CLI, which renders/merges/diffs either lane.

Like every obs layer: pure stdlib, OFF by default, one global load per
call site until an entry point opts in.
"""

from __future__ import annotations

import gc
import json
import logging
import pathlib
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils import locksan as _locksan

logger = logging.getLogger(__name__)

SCHEMA = "gol-profile/1"

#: default sampling cadence (milliseconds) — the ``-profile`` flags'
#: implied value. 10 ms ~ 100 stacks/s/thread: enough to name a frame
#: holding >=5% of the wall within a couple of Status polls.
DEFAULT_PERIOD_MS = 10.0
#: adaptive-backoff ceiling: a GIL-saturated 100-thread process degrades
#: to 10 stacks/s rather than stealing the wall it is measuring
MAX_PERIOD_MS = 200.0
#: fraction of wall clock sampling may consume before backing off
DEFAULT_BUDGET = 0.01
#: call-tree trie node cap (all threads pooled) before the <other> fold
DEFAULT_MAX_NODES = 4096
#: flat frame-table cap before the <other> fold
DEFAULT_MAX_FRAMES = 2048
#: stack depth cap — deeper stacks keep the LEAF side (the hot end)
MAX_DEPTH = 64
#: frames shipped per Status window / rendered per artifact summary
WINDOW_TOP = 80
#: hot leaf-paths shipped in every window (the doctor's caller context)
HOT_STACKS_TOP = 5

#: the fold bucket: where frames land once a bound is hit
OTHER_FRAME = ("<other>", "", 0)

#: leaf frames that mean "parked, not working": a wall-clock sampler
#: sees idle server threads blocked in accept/select/wait forever, and
#: a hotspot report that names ``Event.wait`` as the top frame would be
#: noise. Shared with obs/doctor.py and obs/flame.py (-active).
_IDLE_FUNCS = frozenset((
    "wait", "select", "poll", "accept", "recv", "recv_into", "readinto",
    "read", "readline", "get", "sleep", "_wait_for_tstate_lock", "join",
    "flush", "epoll",
    # the rpc/protocol.py frame pump: these loops spend their wall parked
    # in sock.recv/sendall (C frames the sampler cannot see past), so the
    # Python leaf is the loop itself — a resident-wire worker would
    # otherwise report its own idle connection as the process hotspot.
    # Serialize/deserialize cost is priced by the perf decomposition
    # (host_prep/wire segments), not by wall-clock stack sampling.
    "recv_frame_sized", "recv_frame", "send_frame",
    "_recv_exact", "_recv_into_exact",
))
_IDLE_FILES = (
    "threading.py", "selectors.py", "socket.py", "socketserver.py",
    "queue.py", "ssl.py", "connection.py", "subprocess.py",
    # the obs samplers' own loops: self-profiles would otherwise list
    # the measurement as the workload
    "timeline.py", "profiler.py",
)


def is_idle_frame(func: str, file: str) -> bool:
    """True when a LEAF frame means the thread was parked (blocking
    accept/select/wait) or inside an obs sampler loop — the frames the
    hotspot heuristics and ``flame -active`` exclude from shares."""
    return func in _IDLE_FUNCS or str(file).endswith(_IDLE_FILES)


def short_file(path: str) -> str:
    """Render a code path relative to the package (or the last two
    components for foreign code) — stable across checkouts, so collapsed
    goldens and cross-host diffs line up."""
    s = str(path).replace("\\", "/")
    marker = "gol_distributed_final_tpu/"
    i = s.find(marker)
    if i >= 0:
        return s[i:]
    parts = s.rsplit("/", 2)
    return "/".join(parts[-2:]) if len(parts) > 1 else s


def frame_name(func: str, file: str, line: int) -> str:
    """One frame's collapsed-stack token: ``func (file:line)``. Parsed
    back by obs/flame.py with rsplit on the final space-count split, so
    the embedded space is safe within this toolchain."""
    if not file and not line:
        return func
    return f"{func} ({short_file(file)}:{line})"


class _Node:
    """One call-tree trie node: children keyed by (func, file, line)."""

    __slots__ = ("self_hits", "cum_hits", "children")

    def __init__(self):
        self.self_hits = 0
        self.cum_hits = 0
        self.children: Dict[Tuple[str, str, int], "_Node"] = {}


class ContinuousProfiler:
    """The per-process profile: a bounded trie + flat frame table over
    ``sys._current_frames()``, advanced by ``sample_once`` (the daemon
    thread, or a test injecting stacks). All public queries take the
    internal lock; one tick is O(threads x depth)."""

    # the trie/table mutate under _lock during ticks while Status polls
    # and artifact writers iterate them — the timeline's posture,
    # machine-enforced (analysis/locks.py)
    _GUARDED_BY = {
        "_roots": "_lock",
        "_frames": "_lock",
        "_seq": "_lock",
        "_nodes": "_lock",
        "_stacks": "_lock",
        # NOTE: the _gc_* tallies are deliberately NOT lock-guarded.
        # They are mutated only inside the gc callback, which can
        # preempt ANY thread at ANY allocation — including one already
        # holding this lock or the metrics registry lock — so the
        # callback must never acquire a lock (observed: a worker's
        # Status thread self-deadlocking when gc fired inside
        # metrics.snapshot()). The collecting thread holds the GIL for
        # the whole callback, which is all the synchronisation plain
        # counter bumps need.
    }

    def __init__(
        self,
        period_ms: float = DEFAULT_PERIOD_MS,
        *,
        budget: float = DEFAULT_BUDGET,
        max_period_ms: float = MAX_PERIOD_MS,
        max_nodes: int = DEFAULT_MAX_NODES,
        max_frames: int = DEFAULT_MAX_FRAMES,
        track_gc: bool = True,
        alloc_top_n: int = 0,
    ):
        if period_ms <= 0:
            raise ValueError(f"period_ms must be > 0, got {period_ms}")
        if max_nodes < 8 or max_frames < 8:
            raise ValueError("max_nodes/max_frames must be >= 8")
        self.base_period_s = period_ms / 1000.0
        self.period_s = self.base_period_s
        self.max_period_s = max(max_period_ms, period_ms) / 1000.0
        self.budget = float(budget)
        self.max_nodes = int(max_nodes)
        self.max_frames = int(max_frames)
        self.alloc_top_n = int(alloc_top_n)
        # RLock: readers (window/artifacts) hold it across whole walks
        # of structures a concurrent tick mutates
        self._lock = _locksan.rlock("ContinuousProfiler._lock")
        # serialises ticks: the thread and a test's sample_once must
        # produce one fold each, never interleaved
        self._tick_lock = _locksan.lock("ContinuousProfiler._tick_lock")
        self._roots: Dict[str, _Node] = {}  # thread name -> trie root
        # (func, file, line) -> [self_hits, cum_hits, last_seq]
        self._frames: Dict[Tuple[str, str, int], List[int]] = {}
        self._seq = 0
        self._nodes = 0
        self._stacks = 0  # stack samples folded (threads x ticks)
        self._cost_ewma_s = 0.0
        self._backoffs = 0
        self._started_unix = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # gc-pause metering (callback installed by enable())
        self._gc_t0: Optional[float] = None
        self._gc_installed = False
        self._gc_pauses = 0
        self._gc_pause_s = 0.0
        self._gc_max_s = 0.0
        # (pause_s, generation) rows the callback defers; the sampler
        # (or a window build) flushes them into the metrics registry
        # from a thread that is NOT inside a collection
        self._gc_pending: List[Tuple[float, str]] = []
        self._tracemalloc_started = False

    # -- sampling ----------------------------------------------------------

    def _extract_stacks(self) -> List[Tuple[str, List[Tuple[str, str, int]]]]:
        """(thread_name, root-first frame list) per thread, skipping the
        sampler's own thread — a profiler that profiles itself walking
        stacks reports its own overhead as the workload."""
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        out = []
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack: List[Tuple[str, str, int]] = []
            f = frame
            while f is not None and len(stack) < MAX_DEPTH:
                code = f.f_code
                stack.append(
                    (code.co_name, code.co_filename, code.co_firstlineno)
                )
                f = f.f_back
            stack.reverse()  # leaf-up walk -> root-first fold
            out.append((names.get(ident, f"tid-{ident}"), stack))
        return out

    def _fold(  # gol: holds(_lock)
        self, thread: str, stack: List[Tuple[str, str, int]], seq: int
    ) -> None:
        """Fold one root-first stack into the trie and the flat table.
        Caller holds ``self._lock`` (the holds() marker declares the
        contract to analysis/locks.py)."""
        node = self._roots.get(thread)
        if node is None:
            node = self._roots[thread] = _Node()
            self._nodes += 1
        for key in stack:
            child = node.children.get(key)
            if child is None:
                if self._nodes >= self.max_nodes:
                    key = OTHER_FRAME
                    child = node.children.get(key)
                if child is None:
                    child = node.children[key] = _Node()
                    self._nodes += 1
            child.cum_hits += 1
            node = child
        node.self_hits += 1
        leaf = stack[-1] if stack else OTHER_FRAME
        for key in dict.fromkeys(stack):  # unique: recursion counts once
            row = self._frames.get(key)
            if row is None:
                if len(self._frames) >= self.max_frames:
                    key = OTHER_FRAME
                    row = self._frames.get(key)
                if row is None:
                    row = self._frames[key] = [0, 0, 0]
            row[1] += 1
            row[2] = seq
        # the leaf's self hit: a leaf that overflowed the table above
        # lands in <other> like its cum hit did
        lrow = self._frames.get(leaf)
        if lrow is None:
            lrow = self._frames.setdefault(OTHER_FRAME, [0, 0, 0])
        lrow[0] += 1
        lrow[2] = seq
        self._stacks += 1

    def sample_once(self, cost: Optional[float] = None,
                    stacks=None) -> int:
        """One tick: walk every thread's stack, fold, meter own cost,
        adapt the cadence. Both knobs are injectable for deterministic
        tests: ``stacks`` as ``[(thread_name, [(func, file, line),
        ...root-first])]``, ``cost`` as the tick's claimed sampling cost
        in seconds (drives ``_adapt``). Returns the tick's seq."""
        with self._tick_lock:
            t0 = time.perf_counter()
            extracted = self._extract_stacks() if stacks is None else stacks
            with self._lock:
                self._seq += 1
                seq = self._seq
                for thread, stack in extracted:
                    if stack:
                        self._fold(thread, list(stack), seq)
            if cost is None:
                cost = time.perf_counter() - t0
            self._cost_ewma_s = 0.8 * self._cost_ewma_s + 0.2 * cost
            self._adapt()
            from . import instruments

            instruments.PROFILE_SAMPLES_TOTAL.inc()
            self._flush_gc_metrics()
            return seq

    def _adapt(self) -> None:
        """Back the cadence off when sampling exceeds its budget share
        of the period; decay back toward the base once it is cheap
        again. Tick-lock serialised (only sample_once calls this)."""
        if self._cost_ewma_s > self.budget * self.period_s:
            new = min(self.period_s * 2.0, self.max_period_s)
            if new > self.period_s:
                self.period_s = new
                self._backoffs += 1
                from . import instruments

                instruments.PROFILE_BACKOFFS_TOTAL.inc()
        elif (
            self.period_s > self.base_period_s
            and self._cost_ewma_s < 0.25 * self.budget * self.period_s
        ):
            self.period_s = max(self.base_period_s, self.period_s / 2.0)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="gol-profiler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.sample_once()
            # gol: allow(hygiene): the 100 Hz sampler loop must survive
            # interpreter-shutdown races in _current_frames; recording
            # each period would churn the flight ring
            except Exception:  # pragma: no cover - shutdown races
                pass

    # -- gc-pause metering -------------------------------------------------

    def _gc_callback(self, phase: str, info: dict) -> None:
        """gc.callbacks hook: pause = start->stop wall.

        MUST NOT acquire any lock or touch the metrics registry: a
        collection can trigger at any allocation, so this hook can
        preempt a thread that already holds ``self._lock`` or the
        registry lock — taking either here self-deadlocks that thread
        and wedges the whole process (every later metric op parks on
        the dead lock). Plain attribute ops suffice: the collecting
        thread holds the GIL for the entire callback. The histogram
        observations are deferred to ``_flush_gc_metrics``."""
        if phase == "start":
            self._gc_t0 = time.perf_counter()
            return
        t0, self._gc_t0 = self._gc_t0, None
        if t0 is None:
            return
        dt = time.perf_counter() - t0
        self._gc_pauses += 1
        self._gc_pause_s += dt
        if dt > self._gc_max_s:
            self._gc_max_s = dt
        self._gc_pending.append((dt, str(info.get("generation", "?"))))

    def _flush_gc_metrics(self) -> None:
        """Drain callback-deferred gc pauses into the registry. Runs on
        the sampler thread (every tick) and on window builds — never
        inside a collection, so taking the registry lock is safe here.
        Atomic ``list.pop(0)`` keeps this drain lock-free against the
        callback's concurrent ``append``."""
        if not self._gc_pending:
            return
        from . import instruments

        while True:
            try:
                dt, gen = self._gc_pending.pop(0)
            except IndexError:
                break
            instruments.GC_PAUSE_SECONDS.observe(dt)
            instruments.GC_COLLECTIONS_TOTAL.labels(gen).inc()

    def install_gc(self) -> None:
        if not self._gc_installed:
            gc.callbacks.append(self._gc_callback)
            self._gc_installed = True

    def remove_gc(self) -> None:
        if self._gc_installed:
            self._gc_installed = False
            try:
                gc.callbacks.remove(self._gc_callback)
            except ValueError:  # pragma: no cover - external clear
                pass

    # -- allocation snapshots ----------------------------------------------

    def start_alloc(self) -> None:
        """Opt-in tracemalloc: started here only if not already tracing
        (an outer harness may own it), remembered so close() stops only
        what it started."""
        import tracemalloc

        if not tracemalloc.is_tracing():
            tracemalloc.start()
            self._tracemalloc_started = True

    def alloc_top(self) -> List[dict]:
        """Top-N allocation sites by live bytes (empty when alloc
        tracking is off) — JSON-able rows for windows/summaries."""
        if self.alloc_top_n <= 0:
            return []
        import tracemalloc

        if not tracemalloc.is_tracing():
            return []
        stats = tracemalloc.take_snapshot().statistics("lineno")
        return [
            {
                "site": f"{short_file(s.traceback[0].filename)}:"
                        f"{s.traceback[0].lineno}",
                "kib": round(s.size / 1024.0, 1),
                "count": s.count,
            }
            for s in stats[: self.alloc_top_n]
        ]

    def close(self) -> None:
        """Stop the thread, unhook gc, stop tracemalloc if owned."""
        self.stop()
        self.remove_gc()
        if self._tracemalloc_started:
            import tracemalloc

            self._tracemalloc_started = False
            tracemalloc.stop()

    # -- queries -----------------------------------------------------------

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def hot_frames(self, top: int = WINDOW_TOP,
                   since: int = 0) -> List[dict]:
        """The flat frame table, hottest self first. ``since`` keeps it
        incremental: only frames whose counts moved past that seq."""
        with self._lock:
            rows = [
                {
                    "func": k[0], "file": short_file(k[1]), "line": k[2],
                    "self": v[0], "cum": v[1],
                }
                for k, v in self._frames.items()
                if v[2] > since
            ]
        rows.sort(key=lambda r: (-r["self"], -r["cum"], r["func"]))
        return rows[:top]

    def hot_stacks(self, top: int = HOT_STACKS_TOP) -> List[dict]:
        """Hottest LEAF PATHS (collapsed frame strings + self hits),
        merged across threads — the caller context a flat table loses,
        and what the doctor names when a leaf alone is ambiguous."""
        acc: Dict[str, int] = {}
        with self._lock:
            for root in self._roots.values():
                self._walk_leaves(root, [], acc)
        rows = [
            {"stack": k, "self": v}
            for k, v in sorted(acc.items(), key=lambda kv: -kv[1])[:top]
        ]
        return rows

    def _walk_leaves(  # gol: holds(_lock)
        self, node: _Node, path: List[str], acc: Dict[str, int]
    ) -> None:
        if node.self_hits:
            key = ";".join(path) if path else "<root>"
            acc[key] = acc.get(key, 0) + node.self_hits
        for k, child in node.children.items():
            path.append(frame_name(*k))
            self._walk_leaves(child, path, acc)
            path.pop()

    def window(self, since: int = 0) -> dict:
        """The Status payload form: counters plus only the frames whose
        hits moved past the poller's echoed ``since`` seq (empty when
        nothing was sampled since — the incremental contract that keeps
        a 2 s poll over a 10 ms sampler cheap). Plain JSON-able: the
        payload must cross the restricted unpickler."""
        self._flush_gc_metrics()
        with self._lock:
            seq = self._seq
            stacks = self._stacks
            nodes = self._nodes
            gc_sect = {
                "pauses": self._gc_pauses,
                "pause_s": round(self._gc_pause_s, 6),
                "max_pause_s": round(self._gc_max_s, 6),
                "tracked": self._gc_installed,
            }
            threads = sorted(self._roots)
        out = {
            "schema": SCHEMA,
            "seq": seq,
            "period_ms": round(self.period_s * 1000.0, 3),
            "base_period_ms": round(self.base_period_s * 1000.0, 3),
            "overhead_ewma_ms": round(self._cost_ewma_s * 1000.0, 4),
            "backoffs": self._backoffs,
            "stacks": stacks,
            "nodes": nodes,
            "threads": threads,
            "gc": gc_sect,
            "frames": self.hot_frames(WINDOW_TOP, since=since),
            "hot_stacks": self.hot_stacks(),
        }
        if self.alloc_top_n > 0:
            try:
                out["alloc"] = self.alloc_top()
            except Exception as exc:  # pragma: no cover - tracemalloc off
                out["alloc_error"] = str(exc)[:200]
        return out

    def summary(self) -> dict:
        """The RunReport-embedded form: the window head plus only the
        top-10 frames — bounded, artifact-friendly."""
        w = self.window(since=0)
        w["frames"] = w["frames"][:10]
        return w

    # -- artifacts ---------------------------------------------------------

    def collapsed_lines(self) -> List[str]:
        """Brendan Gregg collapsed-stack form, one line per unique leaf
        path: ``thread;frame;frame... count`` — flamegraph.pl and
        speedscope both ingest it; obs/flame.py diffs it."""
        acc: Dict[str, int] = {}
        with self._lock:
            for thread, root in sorted(self._roots.items()):
                self._walk_leaves(root, [thread], acc)
        return [f"{path} {hits}" for path, hits in sorted(acc.items())]

    def speedscope_dict(self, name: str = "gol-profile") -> dict:
        """The speedscope JSON file format (``type: sampled``): one
        profile per thread, each unique leaf path one weighted sample.
        https://www.speedscope.app/file-format-schema.json"""
        frames: List[dict] = []
        index: Dict[Tuple[str, str, int], int] = {}
        profiles: List[dict] = []
        with self._lock:
            items = sorted(self._roots.items())
            for thread, root in items:
                samples: List[List[int]] = []
                weights: List[int] = []
                self._speedscope_walk(root, [], frames, index,
                                      samples, weights)
                total = sum(weights)
                profiles.append({
                    "type": "sampled",
                    "name": thread,
                    "unit": "none",
                    "startValue": 0,
                    "endValue": total,
                    "samples": samples,
                    "weights": weights,
                })
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "name": name,
            "exporter": f"gol-profiler ({SCHEMA})",
            "activeProfileIndex": 0,
            "shared": {"frames": frames},
            "profiles": profiles,
        }

    def _speedscope_walk(self, node, path, frames, index, samples,
                         weights) -> None:  # gol: holds(_lock)
        if node.self_hits and path:
            samples.append(list(path))
            weights.append(node.self_hits)
        for k, child in node.children.items():
            i = index.get(k)
            if i is None:
                i = index[k] = len(frames)
                frames.append({
                    "name": k[0] or "?",
                    "file": short_file(k[1]),
                    "line": k[2],
                })
            path.append(i)
            self._speedscope_walk(child, path, frames, index,
                                  samples, weights)
            path.pop()

    def write_artifacts(self, out_dir: str = "out",
                        tag: str = "run") -> List[pathlib.Path]:
        """Both artifact forms, tmp-then-rename like every other obs
        artifact: ``profile_<tag>.collapsed`` + ``.speedscope.json``."""
        out = pathlib.Path(out_dir)
        out.mkdir(parents=True, exist_ok=True)
        paths = []
        collapsed = out / f"profile_{tag}.collapsed"
        tmp = collapsed.with_name(collapsed.name + ".tmp")
        tmp.write_text("\n".join(self.collapsed_lines()) + "\n")
        tmp.replace(collapsed)
        paths.append(collapsed)
        scope = out / f"profile_{tag}.speedscope.json"
        tmp = scope.with_name(scope.name + ".tmp")
        tmp.write_text(json.dumps(self.speedscope_dict(tag)))
        tmp.replace(scope)
        paths.append(scope)
        return paths


# -- the process-global default profiler --------------------------------------

_PROFILER: Optional[ContinuousProfiler] = None
#: where run-end/crash artifacts land (enable() records the CLI's -dir)
_OUT_DIR = "out"
_TAG = "run"


def profiler() -> Optional[ContinuousProfiler]:
    return _PROFILER


def enabled() -> bool:
    return _PROFILER is not None


def enable(
    period_ms: float = DEFAULT_PERIOD_MS,
    *,
    budget: float = DEFAULT_BUDGET,
    max_nodes: int = DEFAULT_MAX_NODES,
    max_frames: int = DEFAULT_MAX_FRAMES,
    track_gc: bool = True,
    alloc_top_n: int = 0,
    out_dir: str = "out",
    tag: str = "run",
    start_thread: bool = True,
) -> ContinuousProfiler:
    """Start the global profiler (the ``-profile [MS]`` flags). Implies
    ``metrics.enable()`` — the gc/pause/backoff meters must land
    somewhere. ``start_thread=False`` gives tests a profiler they tick
    by hand."""
    global _PROFILER, _OUT_DIR, _TAG
    if _PROFILER is not None:
        disable()
    from . import metrics as _metrics

    _metrics.enable()
    p = ContinuousProfiler(
        period_ms,
        budget=budget,
        max_nodes=max_nodes,
        max_frames=max_frames,
        track_gc=track_gc,
        alloc_top_n=alloc_top_n,
    )
    if track_gc:
        p.install_gc()
    if alloc_top_n > 0:
        p.start_alloc()
    _OUT_DIR = out_dir
    _TAG = tag
    _PROFILER = p
    if start_thread:
        p.start()
    return p


def disable() -> None:
    global _PROFILER
    p, _PROFILER = _PROFILER, None
    if p is not None:
        p.close()


def summary() -> Optional[dict]:
    """The RunReport hook: None when the profiler is off."""
    p = _PROFILER
    return p.summary() if p is not None else None


def window(since: int = 0) -> Optional[dict]:
    """The Status hook: None when the profiler is off."""
    p = _PROFILER
    return p.window(since=since) if p is not None else None


def write_artifacts(tag: Optional[str] = None) -> List[pathlib.Path]:
    """Run-end artifact write (mains call it on clean shutdown)."""
    p = _PROFILER
    if p is None:
        return []
    return p.write_artifacts(_OUT_DIR, tag or _TAG)


def shutdown() -> None:
    """Clean-exit hook for the mains' finally blocks: best-effort
    run-end artifact write, then disable. Never raises — the serving
    process's own exit status is the prize."""
    p = _PROFILER
    if p is None:
        return
    try:
        p.stop()
        p.write_artifacts(_OUT_DIR, _TAG)
    except Exception as exc:  # pragma: no cover - disk-full path
        logger.warning("profiler run-end artifact write failed: %s", exc)
    disable()


def flush_on_crash(exc: BaseException) -> None:
    """Crash-path artifact write, riding the mains' dump_on_crash hook
    next to flight/journal. NEVER raises — the original traceback is
    the prize; losing it to a profiler bug would be absurd."""
    p = _PROFILER
    if p is None:
        return
    try:
        p.stop()
        paths = p.write_artifacts(_OUT_DIR, f"crash_{_TAG}")
        print(
            f"[obs] crash profile: {', '.join(str(x) for x in paths)} "
            f"({type(exc).__name__})",
            file=sys.stderr,
        )
    # gol: allow(hygiene): crash path — the original traceback is the
    # prize; a raising (or even printing-failure) handler here would
    # mask it
    except BaseException:  # pragma: no cover - crash path must not raise
        pass
