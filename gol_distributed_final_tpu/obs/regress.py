"""Noise-aware bench diff — the perf-regression gate over BENCH JSON.

    python -m gol_distributed_final_tpu.obs.regress BENCH_r04.json BENCH_r05.json
    python -m gol_distributed_final_tpu.obs.regress --latest
    scripts/bench_diff A.json B.json          # the same thing

Compares two bench outputs case-by-case using each case's OWN recorded
noise: ``bench.py``'s marginal fit stores the min-estimator endpoint
spread (``spread_s``) and the endpoint distance (``n_hi - n_lo``), so the
per-turn uncertainty of each measurement is ``spread_s / (n_hi - n_lo)``
— the same quantity the bench's NOISE_MARGIN publication gate is built
on. A delta between two rounds is only a verdict when it exceeds the
COMBINED noise of both sides (scaled by ``--noise-k``); inside that band
it is ``jitter`` regardless of how large the percentage looks. Past the
noise band, a slowdown must also exceed ``--threshold`` (relative) to be
``REGRESSED`` — the nonzero-exit verdict ``scripts/check --bench-diff``
enforces in CI.

Inputs, per file (auto-detected):

* bench.py's own JSON line (``{"metric": ..., "extra": {cases...}}``);
* the driver wrapper (``{"n", "cmd", "rc", "tail", "parsed"}``) around a
  BENCH_r*.json round. The wrapper's ``tail`` keeps only the last 2000
  characters of stdout, which can cut the JSON line's HEAD off — the
  loader then SALVAGES every complete per-case object out of the
  truncated text (case dicts are flat, so balanced-brace extraction is
  exact) and reports how many cases survived.

Verdicts per case: ``REGRESSED`` (the only one that fails the gate),
``slower``, ``jitter``, ``faster``, ``improved``, ``new``, ``removed``,
and ``incomparable`` (a side without a usable per-turn fit).

Environment provenance: bench.py stamps ``jax.__version__``, device
kind/count, and the git SHA into its line; when both sides carry it and
the jax version or device fleet differ, the comparison REFUSES (exit 2)
unless ``--force`` — a number from a different chip is not a regression.

No jax import — runnable anywhere, including the lint-only CI leg.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import re
import sys
from typing import Dict, List, Optional, Tuple

# provenance keys that must agree for per-turn times to be comparable
_PROVENANCE_KEYS = ("jax_version", "device_kind", "device_count")

# a complete flat JSON object assigned to a quoted key: the salvage unit
_CASE_RE = re.compile(r'"(\w+)":\s*(\{[^{}]*\})')


class BenchLoadError(RuntimeError):
    """The file held nothing comparable (not even salvageable cases)."""


def _cases_from_extra(extra: dict) -> Dict[str, dict]:
    """The measurement cases of a bench line: every extra entry that is a
    dict carrying a marginal fit (stage_timings etc. filter out)."""
    return {
        name: case
        for name, case in extra.items()
        if isinstance(case, dict) and "per_turn_us" in case
    }


def _find_bench_line(text: str) -> Optional[dict]:
    """The first parseable bench JSON line (``{"metric": ...}``) among the
    lines of a stdout capture, or None — shared by the raw-capture and
    driver-wrapper loaders so their line detection cannot drift."""
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _salvage_cases(text: str) -> Dict[str, dict]:
    """Every complete ``"name": {...}`` case object in a (possibly
    head-truncated) text — the driver wrapper keeps only the tail of
    stdout, so the bench line's opening may be gone while most case
    objects survive intact."""
    out: Dict[str, dict] = {}
    for name, body in _CASE_RE.findall(text):
        try:
            case = json.loads(body)
        except ValueError:
            continue
        if isinstance(case, dict) and "per_turn_us" in case:
            out[name] = case
    return out


def load_bench(path) -> dict:
    """Read one bench output file into ``{label, cases, provenance,
    salvaged}``. Accepts bench.py's own JSON line or the driver wrapper
    (salvaging from a truncated tail when needed)."""
    path = pathlib.Path(path)
    text = path.read_text()
    try:
        doc = json.loads(text)
    except ValueError:
        # raw stdout capture: find the bench line among the lines
        doc = _find_bench_line(text)
    result = {
        "label": path.name,
        "cases": {},
        "provenance": None,
        "salvaged": False,
    }
    if isinstance(doc, dict) and "extra" in doc:
        result["cases"] = _cases_from_extra(doc.get("extra") or {})
        result["provenance"] = doc.get("provenance")
        return result
    if isinstance(doc, dict) and "tail" in doc:
        # driver wrapper: prefer a parsed payload if the driver kept one
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "extra" in parsed:
            result["cases"] = _cases_from_extra(parsed.get("extra") or {})
            result["provenance"] = parsed.get("provenance")
            return result
        tail = doc.get("tail") or ""
        line_match = _find_bench_line(tail)
        if isinstance(line_match, dict) and "extra" in line_match:
            result["cases"] = _cases_from_extra(line_match.get("extra") or {})
            result["provenance"] = line_match.get("provenance")
            return result
        result["cases"] = _salvage_cases(tail)
        result["salvaged"] = True
        if result["cases"]:
            return result
    if isinstance(doc, dict):
        # a bare extra-shaped dict (the test fixture form)
        cases = _cases_from_extra(doc)
        if cases:
            result["cases"] = cases
            return result
    # last resort: salvage from the raw text
    result["cases"] = _salvage_cases(text)
    result["salvaged"] = True
    if result["cases"]:
        return result
    raise BenchLoadError(f"{path}: no bench cases found (even by salvage)")


def _per_turn_noise_us(case: dict, noise_k: float) -> Optional[float]:
    """One side's per-turn uncertainty in µs: the min-estimator endpoint
    spread divided over the marginal turn distance, scaled by noise_k.
    None when the case predates the spread fields (old rounds)."""
    spread = case.get("spread_s")
    n_lo, n_hi = case.get("n_lo"), case.get("n_hi")
    if spread is None or not n_lo or not n_hi or n_hi <= n_lo:
        return None
    return noise_k * spread * 1e6 / (n_hi - n_lo)


def compare_case(
    old: Optional[dict],
    new: Optional[dict],
    *,
    threshold: float = 0.05,
    noise_k: float = 2.0,
) -> dict:
    """One case's verdict: ``REGRESSED`` / ``slower`` / ``faster`` /
    ``improved`` / ``jitter`` / ``new`` / ``removed`` / ``incomparable``
    (a side present but without a usable per_turn_us — e.g. a zero or
    missing fit on a salvaged fragment; reported, never gating).

    The delta must clear the combined per-turn noise of BOTH sides to be
    a verdict at all (inside: ``jitter``); a slowdown past the noise must
    also exceed ``threshold`` relative to the old time to be the gating
    ``REGRESSED`` (between: ``slower``, reported but not failing)."""
    if old is None:
        return {"verdict": "new", "new_us": new.get("per_turn_us")}
    if new is None:
        return {"verdict": "removed", "old_us": old.get("per_turn_us")}
    old_us, new_us = old.get("per_turn_us"), new.get("per_turn_us")
    out = {"old_us": old_us, "new_us": new_us}
    # symmetric: a zero/missing fit on EITHER side is a broken
    # measurement, never an infinite improvement or regression. The byte
    # gate still applies below: byte accounting survives a broken
    # wall-clock fit (e.g. a salvaged round), and a deterministic comms
    # regression must not hide behind an unusable timing.
    if not old_us or not new_us:
        out["verdict"] = "incomparable"
        out = _apply_roofline_gate(old, new, out, threshold, 0.0)
        out = _apply_sparse_gates(old, new, out, threshold, 0.0)
        out = _apply_fused_gate(old, new, out, threshold)
        out = _apply_journal_gate(old, new, out, threshold)
        out = _apply_profile_gate(old, new, out, threshold)
        out = _apply_fleet_gate(old, new, out, threshold)
        out = _apply_wire_bytes_gate(old, new, out, threshold)
        return _apply_halo_bytes_gate(old, new, out, threshold)
    delta = new_us - old_us
    rel = delta / old_us
    noises = [
        n
        for n in (
            _per_turn_noise_us(old, noise_k),
            _per_turn_noise_us(new, noise_k),
        )
        if n is not None
    ]
    noise_us = sum(noises) if noises else 0.0
    out["delta_pct"] = 100.0 * rel
    out["noise_pct"] = 100.0 * noise_us / old_us
    if abs(delta) <= noise_us:
        out["verdict"] = "jitter"
    elif delta > 0:
        out["verdict"] = "REGRESSED" if rel > threshold else "slower"
    else:
        out["verdict"] = "improved" if -rel > threshold else "faster"
    out = _apply_roofline_gate(old, new, out, threshold, noise_us / old_us)
    out = _apply_sparse_gates(old, new, out, threshold, noise_us / old_us)
    out = _apply_fused_gate(old, new, out, threshold)
    out = _apply_journal_gate(old, new, out, threshold)
    out = _apply_profile_gate(old, new, out, threshold)
    out = _apply_fleet_gate(old, new, out, threshold)
    out = _apply_wire_bytes_gate(old, new, out, threshold)
    return _apply_halo_bytes_gate(old, new, out, threshold)


def _apply_roofline_gate(
    old: dict, new: dict, out: dict, threshold: float, noise_rel: float
) -> dict:
    """The achieved-throughput gate (obs/perf.py roofline fields embedded
    per kernel case from bench.py): a per-SITE drop in achieved FLOP/s
    past the threshold AND the noise band is REGRESSED in its own units.
    HONESTY NOTE: while both rounds' fields come from the analytic cost
    model over the same case's fit (the current bench), this is
    mathematically redundant with the wall-clock gate (flops ∝ 1/wall,
    same constant) — it becomes load-bearing when the sides' throughput
    sources diverge: a future bench embedding MEASURED dispatch-stats
    throughput, a model-constant change between rounds, or a salvaged
    side whose wall-clock fit broke but whose embedded fields survived.
    A bound-class flip (e.g. memory-bound -> launch-bound) is always
    REPORTED; it only gates when the throughput drop does (a class is a
    coarse call and a flip alone can be a utilization hovering at the
    boundary)."""
    old_f, new_f = old.get("achieved_flops"), new.get("achieved_flops")
    if old_f and new_f:
        drop_rel = (old_f - new_f) / old_f
        out["old_achieved_flops"] = old_f
        out["new_achieved_flops"] = new_f
        out["achieved_delta_pct"] = -100.0 * drop_rel
        if drop_rel > threshold + noise_rel:
            out["verdict"] = "REGRESSED"
            out["why"] = (
                "achieved FLOP/s fell past threshold beyond the noise band"
            )
    old_c, new_c = old.get("bound_class"), new.get("bound_class")
    if old_c and new_c and old_c != new_c:
        out["bound_class_change"] = f"{old_c} -> {new_c}"
    return out


def _apply_sparse_gates(
    old: dict, new: dict, out: dict, threshold: float, noise_rel: float
) -> dict:
    """The activity-sparse gates (ISSUE 14 satellite): per-ACTIVE-cell
    throughput (``cell_updates_per_s_active`` on the sparse-board cases)
    gates like achieved FLOP/s — a drop past threshold AND the noise
    band is REGRESSED in its own units — and delta-sync byte growth
    (``sparse_frame_bytes_per_sync``) gates like wire bytes: byte
    accounting is deterministic, so no noise band applies."""
    old_a, new_a = (
        old.get("cell_updates_per_s_active"),
        new.get("cell_updates_per_s_active"),
    )
    if old_a and new_a:
        drop_rel = (old_a - new_a) / old_a
        out["old_active_updates_per_s"] = old_a
        out["new_active_updates_per_s"] = new_a
        out["active_delta_pct"] = -100.0 * drop_rel
        if drop_rel > threshold + noise_rel:
            out["verdict"] = "REGRESSED"
            out["why"] = (
                "per-active-cell throughput fell past threshold beyond "
                "the noise band"
            )
    old_b, new_b = (
        old.get("sparse_frame_bytes_per_sync"),
        new.get("sparse_frame_bytes_per_sync"),
    )
    if old_b and new_b:
        bytes_rel = (new_b - old_b) / old_b
        out["old_sparse_sync_bytes"] = old_b
        out["new_sparse_sync_bytes"] = new_b
        out["sparse_sync_delta_pct"] = 100.0 * bytes_rel
        if bytes_rel > threshold:
            out["verdict"] = "REGRESSED"
            out["why"] = "sparse sync bytes grew past threshold"
    return out


def _apply_fused_gate(
    old: dict, new: dict, out: dict, threshold: float
) -> dict:
    """The launch-floor gate (ISSUE 15 satellite): the fused bench pair
    embeds ``dispatches_per_turn`` (device launches per turn — 1.0 for
    the serial chain, 1/K fused). Launch accounting is DETERMINISTIC
    like byte accounting — no noise band — so growth past the threshold
    gates even when the wall-clock verdict is clean or unusable: a
    routing regression that quietly un-fuses the ladder fails bench_diff
    here, not in a later wall-clock drift."""
    old_d, new_d = old.get("dispatches_per_turn"), new.get("dispatches_per_turn")
    if old_d and new_d:
        rel = (new_d - old_d) / old_d
        out["old_dispatches_per_turn"] = old_d
        out["new_dispatches_per_turn"] = new_d
        out["dispatches_delta_pct"] = 100.0 * rel
        if rel > threshold:
            out["verdict"] = "REGRESSED"
            out["why"] = "dispatches per turn grew past threshold"
    return out


def _apply_journal_gate(
    old: dict, new: dict, out: dict, threshold: float
) -> dict:
    """The journal-cost trajectory gate (ISSUE 16 satellite): the wire
    bench's journal pair embeds ``journal_overhead_pct`` (journal-on vs
    journal-off resident K=8). bench.py's own run-time gate holds each
    round under 2% beyond its noise band; THIS gate is the cross-round
    backstop — overhead creeping up by more than ``100 * threshold``
    percentage points between rounds (default 5 points) is REGRESSED
    even if a loosened or noisy per-round band let it through, so a
    hot-path record() regression cannot ratchet in across rounds."""
    old_j, new_j = old.get("journal_overhead_pct"), new.get("journal_overhead_pct")
    if old_j is not None and new_j is not None:
        out["old_journal_overhead_pct"] = old_j
        out["new_journal_overhead_pct"] = new_j
        out["journal_overhead_delta_pts"] = round(new_j - old_j, 2)
        if new_j - old_j > 100.0 * threshold:
            out["verdict"] = "REGRESSED"
            out["why"] = (
                "journal overhead grew past the cross-round threshold"
            )
    return out


def _apply_profile_gate(
    old: dict, new: dict, out: dict, threshold: float
) -> dict:
    """The profiler-cost trajectory gate (ISSUE 17): the wire bench's
    profiler pair embeds ``profile_overhead_pct`` (profiler-on vs
    profiler-off resident K=8). bench.py's own run-time gate holds each
    round under 2% beyond its noise band; THIS gate is the cross-round
    backstop — sampling overhead creeping up by more than
    ``100 * threshold`` percentage points between rounds is REGRESSED
    even if a loosened per-round band let it through (the journal gate's
    pattern, applied to the sampler's hot path: _extract_stacks and
    _fold). The embedded ``profile_hot`` table (top busy frames with
    ``self_share``) also rides along: the top mover between rounds is
    always REPORTED, and a frame's share growing by more than 0.35
    absolute gates — sampling shares jitter, so only a wholesale shift
    of the profile's center of mass (a new dominant frame) is a verdict,
    not a few points of drift."""
    old_p, new_p = old.get("profile_overhead_pct"), new.get("profile_overhead_pct")
    if old_p is not None and new_p is not None:
        out["old_profile_overhead_pct"] = old_p
        out["new_profile_overhead_pct"] = new_p
        out["profile_overhead_delta_pts"] = round(new_p - old_p, 2)
        if new_p - old_p > 100.0 * threshold:
            out["verdict"] = "REGRESSED"
            out["why"] = (
                "profiler overhead grew past the cross-round threshold"
            )
    old_h, new_h = old.get("profile_hot"), new.get("profile_hot")
    if isinstance(old_h, list) and isinstance(new_h, list) and new_h:
        shares_old = {
            r.get("frame"): r.get("self_share") or 0.0
            for r in old_h if isinstance(r, dict)
        }
        movers = sorted(
            (
                (
                    (r.get("self_share") or 0.0)
                    - shares_old.get(r.get("frame"), 0.0),
                    str(r.get("frame")),
                )
                for r in new_h
                if isinstance(r, dict) and r.get("frame")
            ),
            reverse=True,
        )
        if movers:
            delta_share, frame = movers[0]
            out["profile_top_mover"] = frame
            out["profile_top_mover_delta_share"] = round(delta_share, 3)
            if delta_share > 0.35:
                out["verdict"] = "REGRESSED"
                out["why"] = (
                    "the profile's dominant frame shifted between rounds"
                )
    return out


def _apply_fleet_gate(
    old: dict, new: dict, out: dict, threshold: float
) -> dict:
    """The fleet scrape-tax trajectory gate (ISSUE 18 satellite): the
    wire bench's collector pair embeds ``fleet_overhead_pct``
    (collector-on vs collector-off resident K=8, a FleetCollector
    sweeping the workers at a 1 s cadence). bench.py's own run-time
    gate holds each round under 2% beyond its noise band; THIS gate is
    the cross-round backstop — the data-plane tax of being scraped
    creeping up by more than ``100 * threshold`` percentage points
    between rounds is REGRESSED even if a loosened per-round band let it
    through (the journal/profiler gates' pattern, applied to the Status
    serve path + the collector's fan-out). The embedded
    ``fleet_scrape_p99_us`` (p99 of gol_fleet_scrape_seconds) rides
    along as REPORTED context — sweep latency is the collector's own
    cost, already bounded by its cadence, so it informs but never
    gates."""
    old_f, new_f = old.get("fleet_overhead_pct"), new.get("fleet_overhead_pct")
    if old_f is not None and new_f is not None:
        out["old_fleet_overhead_pct"] = old_f
        out["new_fleet_overhead_pct"] = new_f
        out["fleet_overhead_delta_pts"] = round(new_f - old_f, 2)
        if new_f - old_f > 100.0 * threshold:
            out["verdict"] = "REGRESSED"
            out["why"] = (
                "fleet scrape tax grew past the cross-round threshold"
            )
    old_p, new_p = old.get("fleet_scrape_p99_us"), new.get("fleet_scrape_p99_us")
    if old_p is not None and new_p is not None:
        out["old_fleet_scrape_p99_us"] = old_p
        out["new_fleet_scrape_p99_us"] = new_p
    return out


def _apply_wire_bytes_gate(
    old: dict, new: dict, out: dict, threshold: float
) -> dict:
    """The comms meter rides along on wire-mode cases
    (``wire_bytes_per_turn`` from gol_wire_bytes_total): byte accounting
    is deterministic — no noise band — so growth past the threshold gates
    even when the wall-clock verdict was clean OR unusable. The comms win
    is a contract, not a side effect."""
    old_b, new_b = old.get("wire_bytes_per_turn"), new.get("wire_bytes_per_turn")
    if old_b and new_b:
        bytes_rel = (new_b - old_b) / old_b
        out["old_bytes"] = old_b
        out["new_bytes"] = new_b
        out["bytes_delta_pct"] = 100.0 * bytes_rel
        if bytes_rel > threshold:
            out["verdict"] = "REGRESSED"
            out["why"] = "wire bytes/turn grew past threshold"
    return out


def _apply_halo_bytes_gate(
    old: dict, new: dict, out: dict, threshold: float
) -> dict:
    """The wire-bytes gate's resident-halo twin: tile/strip bench cases
    embed ``halo_bytes_per_turn`` (gol_halo_bytes_total summed over
    axes), and halo accounting is exactly as deterministic — a change
    that quietly grows the halo cone (wider bands, unpacked corners, a
    worse layout) gates here even when wall-clock looks fine."""
    old_b, new_b = old.get("halo_bytes_per_turn"), new.get("halo_bytes_per_turn")
    if old_b and new_b:
        halo_rel = (new_b - old_b) / old_b
        out["old_halo_bytes"] = old_b
        out["new_halo_bytes"] = new_b
        out["halo_bytes_delta_pct"] = 100.0 * halo_rel
        if halo_rel > threshold:
            out["verdict"] = "REGRESSED"
            out["why"] = "halo bytes/turn grew past threshold"
    return out


def compare(
    old: dict, new: dict, *, threshold: float = 0.05, noise_k: float = 2.0
) -> Dict[str, dict]:
    """Per-case verdicts over the union of both sides' case names."""
    names = sorted(set(old["cases"]) | set(new["cases"]))
    return {
        name: compare_case(
            old["cases"].get(name),
            new["cases"].get(name),
            threshold=threshold,
            noise_k=noise_k,
        )
        for name in names
    }


def provenance_conflicts(old: dict, new: dict) -> List[str]:
    """Human-readable mismatches between two provenance stamps; empty when
    compatible or when either side predates provenance stamping."""
    a, b = old.get("provenance"), new.get("provenance")
    if not a or not b:
        return []
    out = []
    for key in _PROVENANCE_KEYS:
        va, vb = a.get(key), b.get(key)
        if va is not None and vb is not None and va != vb:
            out.append(f"{key}: {va!r} vs {vb!r}")
    return out


def _fmt_us(v) -> str:
    return f"{v:.5f}" if isinstance(v, (int, float)) else "-"


def render_table(verdicts: Dict[str, dict]) -> str:
    header = (
        f"{'case':<28} {'old µs/t':>10} {'new µs/t':>10} "
        f"{'Δ%':>8} {'noise±%':>8}  verdict"
    )
    lines = [header, "-" * len(header)]
    for name, v in verdicts.items():
        delta = v.get("delta_pct")
        noise = v.get("noise_pct")
        tail = v["verdict"]
        if v.get("bound_class_change"):
            tail += f"  [{v['bound_class_change']}]"
        lines.append(
            f"{name:<28} {_fmt_us(v.get('old_us')):>10} "
            f"{_fmt_us(v.get('new_us')):>10} "
            f"{(f'{delta:+.1f}' if delta is not None else '-'):>8} "
            f"{(f'{noise:.1f}' if noise is not None else '-'):>8}  "
            f"{tail}"
        )
    return "\n".join(lines)


# a bench ROUND and nothing else: MULTICHIP_r*.json and friends share the
# _r<N>.json suffix and a lax pattern would sort them into the rounds —
# the exact-name match is the selection contract (test-pinned)
_ROUND_RE = re.compile(r"^BENCH_r(\d+)\.json$")


def latest_bench_files(directory=".") -> List[pathlib.Path]:
    """The BENCH_r*.json rounds of a repo, oldest to newest by round
    number (lexical sort breaks at r10 without the numeric key).
    STRICTLY ``BENCH_r<number>.json``: other result files in the same
    directory (``MULTICHIP_r*.json``, a stray ``BENCH_rX.json``) are
    ignored, never sorted into the rounds ``--latest`` gates on."""
    out = []
    for p in pathlib.Path(directory).glob("BENCH_r*.json"):
        m = _ROUND_RE.match(p.name)
        if m:
            out.append((int(m.group(1)), p))
    return [p for _, p in sorted(out)]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="noise-aware diff of two bench JSON outputs "
        "(nonzero exit on a regression past the threshold)"
    )
    parser.add_argument(
        "files", nargs="*", metavar="JSON",
        help="OLD.json NEW.json (bench.py line or driver BENCH_r*.json)",
    )
    parser.add_argument(
        "--latest", action="store_true",
        help="compare the two newest BENCH_r*.json in --dir instead of "
             "naming files (no-op exit 0 when fewer than two exist)",
    )
    parser.add_argument("--dir", default=".", help="where --latest looks")
    parser.add_argument(
        "--threshold", type=float, default=0.05, metavar="FRAC",
        help="relative slowdown past the noise band that fails the gate "
             "(default 0.05 = 5%%)",
    )
    parser.add_argument(
        "--noise-k", type=float, default=2.0, metavar="K",
        help="noise-band scale: delta must exceed K x (old + new per-turn "
             "spread) to be a verdict at all (default 2)",
    )
    parser.add_argument(
        "--force", action="store_true",
        help="compare despite a provenance mismatch (different jax / "
             "device fleet)",
    )
    args = parser.parse_args(argv)

    if args.latest:
        rounds = latest_bench_files(args.dir)
        if len(rounds) < 2:
            print(
                f"bench-diff: fewer than two BENCH_r*.json in {args.dir!r} "
                "— nothing to gate", file=sys.stderr,
            )
            return 0
        old_path, new_path = rounds[-2], rounds[-1]
    elif len(args.files) == 2:
        old_path, new_path = args.files
    else:
        parser.error("need OLD.json NEW.json, or --latest")

    try:
        old, new = load_bench(old_path), load_bench(new_path)
    except (OSError, BenchLoadError) as exc:
        print(f"bench-diff: {exc}", file=sys.stderr)
        return 2

    for side in (old, new):
        if side["salvaged"]:
            print(
                f"note: {side['label']} was salvaged from a truncated "
                f"tail — {len(side['cases'])} case(s) recovered, "
                "provenance unknown", file=sys.stderr,
            )
    conflicts = provenance_conflicts(old, new)
    if conflicts:
        msg = (
            f"provenance mismatch between {old['label']} and "
            f"{new['label']}: " + "; ".join(conflicts)
        )
        if not args.force:
            print(
                f"bench-diff: REFUSING to compare — {msg} (use --force "
                "to override)", file=sys.stderr,
            )
            return 2
        print(f"warning: {msg} (forced)", file=sys.stderr)
    elif not (old.get("provenance") and new.get("provenance")):
        print(
            "note: provenance absent on at least one side (pre-stamping "
            "round) — environment compatibility unverified", file=sys.stderr,
        )

    verdicts = compare(
        old, new, threshold=args.threshold, noise_k=args.noise_k
    )
    print(f"bench diff: {old['label']} -> {new['label']}")
    print(render_table(verdicts))
    regressed = [n for n, v in verdicts.items() if v["verdict"] == "REGRESSED"]
    if regressed:
        print(
            f"\nFAIL: {len(regressed)} case(s) regressed past "
            f"{100 * args.threshold:.0f}% beyond noise: "
            + ", ".join(regressed)
        )
        return 1
    print("\nok: no regression beyond the noise band and threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
