"""XLA-level device telemetry: compile wall time, kernel cost analysis,
and per-device HBM gauges.

The host-side registry (obs/metrics.py) answers "where did WALL time go";
this module answers the three questions that actually bound a TPU stack
and were invisible until something OOM'd:

* **How long did each compile take?** Every instrumented compile site goes
  through an explicit ``lower() -> compile()`` with the wall clock around
  it (``gol_compile_seconds{site}``), instead of paying the compile
  silently inside the first dispatch.
* **What does the compiled program cost?** ``Lowered.cost_analysis()``
  gives XLA's own FLOP and bytes-accessed estimates for the program
  (``gol_kernel_flops{site}`` / ``gol_kernel_bytes_accessed{site}``) — the
  roofline inputs, per kernel site, without a profiler run.
* **How close is HBM to the ceiling?** ``Device.memory_stats()`` sampled
  per turn-chunk in the engine (``gol_device_hbm_bytes_in_use{device}``,
  ``..._peak_bytes``, ``..._bytes_limit``) plus a process-local high-water
  mark (``hbm_peak_observed``) that the RunReport publishes, so a mid-run
  spike is visible even after it subsides.

Everything flows through the existing registry, so the numbers ride the
``Status`` verb, the RunReport, Prometheus exposition, and the live watch
dashboard (obs/watch.py) with no new plumbing.

Guards, in the same spirit as obs/report.py's device inventory: jax is
imported lazily (this module must stay importable from jax-free
processes); a backend without ``memory_stats`` (CPU) is discovered ONCE
and sampling becomes a near-free early return; any failure inside the
AOT lower/compile path falls back to the plain jitted call — telemetry
must never change what executes, only observe it.

Instrumentation sites are a stable, low-cardinality label set (README
"Device telemetry" table; obs/lint.py):

    pallas.vmem_byte   whole-board VMEM byte kernel   (ops/pallas_stencil)
    pallas.vmem_bit    whole-board VMEM bit kernel    (ops/pallas_stencil)
    pallas.tiled       grid-tiled bit kernel          (ops/pallas_tiled)
    bitpack.xla_step   XLA bitboard fori_loop step    (ops/bitpack, ops/plane)
    halo.byte          byte-plane mesh step           (parallel/halo)
    halo.bit           bit-plane mesh step            (parallel/bit_halo)
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from . import instruments as _ins
from . import metrics as _metrics

# sentinel distinct from None ("AOT failed / decided plain"), so a key's
# first-call decision is taken exactly once
_UNSEEN = object()

# jax.core.Tracer, resolved lazily on first use (this module must import
# without jax)
_TRACER_CLS = None


def _is_traced(args) -> bool:
    """True when any argument is a jax tracer — the call site is being
    TRACED into an enclosing program (e.g. the tiled kernel inside
    shard_map), where an AOT lower/compile of the inner function would be
    a wasted standalone compile. Such calls pass straight through."""
    global _TRACER_CLS
    if _TRACER_CLS is None:
        try:
            from jax.core import Tracer as _TRACER_CLS  # noqa: F811
        except Exception:
            return False
    return any(isinstance(a, _TRACER_CLS) for a in args)

# (site, id(jitted), abstract key) -> _Entry | None for compile_and_call
_CALL_CACHE: Dict[tuple, object] = {}

# per-site dispatch accounting (obs/perf.py's roofline join): every
# instrumented executable call adds its program's cost-analysis flops /
# bytes and its measured host-side wall, so achieved FLOP/s and bytes/s
# are exact even when a site mixes programs (different chunk sizes)
_DISPATCH_STATS: Dict[str, list] = {}  # site -> [flops, bytes, wall_s, calls]
_DISPATCH_LOCK = threading.Lock()


class _Entry:
    """One cached AOT decision: the compiled executable plus the program's
    cost-analysis estimates (0.0 when the backend reported none), so the
    dispatch path can attribute flops/bytes per executed call."""

    __slots__ = ("compiled", "flops", "bytes_accessed")

    def __init__(self, compiled, flops: float, bytes_accessed: float):
        self.compiled = compiled
        self.flops = flops
        self.bytes_accessed = bytes_accessed


def _note_dispatch(site: str, entry: "_Entry", wall_s: float) -> None:
    """Fold one executed call into the per-site roofline accumulators and
    the gol_kernel_dispatch_seconds histogram."""
    _ins.KERNEL_DISPATCH_SECONDS.labels(site).observe(wall_s)
    with _DISPATCH_LOCK:
        stats = _DISPATCH_STATS.setdefault(site, [0.0, 0.0, 0.0, 0])
        stats[0] += entry.flops
        stats[1] += entry.bytes_accessed
        stats[2] += wall_s
        stats[3] += 1


def dispatch_stats() -> Dict[str, dict]:
    """Per-site dispatch totals: ``{site: {flops, bytes_accessed, wall_s,
    calls}}`` — obs/perf.py's achieved-throughput input."""
    with _DISPATCH_LOCK:
        return {
            site: {
                "flops": s[0],
                "bytes_accessed": s[1],
                "wall_s": s[2],
                "calls": s[3],
            }
            for site, s in _DISPATCH_STATS.items()
        }


def reset_dispatch() -> None:
    """Forget the dispatch accumulators (tests / bench isolation)."""
    with _DISPATCH_LOCK:
        _DISPATCH_STATS.clear()

# per-device high-water mark of bytes_in_use, across every sample this
# process ever took — what the RunReport publishes as the peak SEEN, not
# just the peak at the final sample
_PEAK_OBSERVED: Dict[str, int] = {}
_PEAK_LOCK = threading.Lock()

# tri-state discovery: None = never probed, False = backend has no
# memory_stats (CPU) — later samples return immediately, True = supported
_HBM_SUPPORTED: Optional[bool] = None


def _abstract_key(args) -> tuple:
    """Hashable (shape, dtype) signature of a call — arrays by aval,
    non-array statics by value (they select the compiled program)."""
    key = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            key.append((tuple(shape), str(getattr(a, "dtype", ""))))
        else:
            key.append(a)
    return tuple(key)


def _timed_compile(site: str, jitted, args):
    """Explicit AOT lower+compile with the wall clock around it, recording
    compile seconds and the lowered cost analysis. Returns an ``_Entry``
    (executable + its cost estimates), or None if anything failed (caller
    falls back to the plain jitted call — which re-raises any REAL
    compile error)."""
    try:
        t0 = time.monotonic()
        lowered = jitted.lower(*args)
        compiled = lowered.compile()
        _ins.COMPILE_SECONDS.labels(site).observe(time.monotonic() - t0)
    except Exception:
        return None
    flops = accessed = 0.0
    try:
        ca = lowered.cost_analysis()
        # older jax versions return a per-device list, newer a flat dict
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if hasattr(ca, "get"):
            flops = ca.get("flops")
            if flops is not None:
                _ins.KERNEL_FLOPS.labels(site).set(flops)
            accessed = ca.get("bytes accessed")
            if accessed is not None:
                _ins.KERNEL_BYTES_ACCESSED.labels(site).set(accessed)
    # gol: allow(hygiene): cost analysis is best-effort decoration —
    # the compile itself already counted, and a backend without
    # cost_analysis() support would log every single compile
    except Exception:
        pass
    return _Entry(compiled, float(flops or 0.0), float(accessed or 0.0))


def instrument_jit(site: str, jitted):
    """Wrap a jitted callable so its FIRST call per argument signature goes
    through a timed explicit lower/compile (+ cost analysis), and every
    later call hits the cached executable directly.

    The first call for each signature decides ONCE: with the registry
    enabled it takes the AOT path; disabled, it pins that signature to the
    plain jit path (so enabling metrics later never triggers a duplicate
    compile of an already-compiled program). Any AOT failure — lower,
    compile, or a mismatched executable call — falls back to the plain
    jitted call, which re-raises real errors with their original type
    (the BitPlane VMEM-gate fallback depends on that)."""
    if getattr(jitted, "lower", None) is None:
        return jitted  # duck-typed fake or plain fn: nothing to instrument
    cache: Dict[tuple, object] = {}

    def call(*args):
        if _is_traced(args):
            return jitted(*args)  # inlining into an enclosing trace
        key = _abstract_key(args)
        entry = cache.get(key, _UNSEEN)
        if entry is _UNSEEN:
            if not _metrics.enabled():
                cache[key] = None
                return jitted(*args)
            entry = _timed_compile(site, jitted, args)
            cache[key] = entry
            if entry is None:
                return jitted(*args)
        if entry is None:
            return jitted(*args)
        try:
            t0 = time.monotonic()
            out = entry.compiled(*args)
        except (TypeError, ValueError):
            # the executable's ARGUMENT checks (input pytree / committed
            # sharding mismatch) reject before anything runs: route this
            # signature to the plain jit path rather than fail dispatch
            # over telemetry. Runtime failures (XlaRuntimeError, OOM)
            # propagate as-is — re-running a failing multi-second program
            # through the fallback would double time-to-failure and drop
            # the original traceback.
            cache[key] = None
            return jitted(*args)
        _note_dispatch(site, entry, time.monotonic() - t0)
        return out

    call.__wrapped__ = jitted
    return call


def compile_and_call(site: str, jitted, *args, static_argnums=()):
    """One-shot form of ``instrument_jit`` for direct call sites of a
    module-level jitted function (e.g. ``bitpack.bit_step_n``): same
    decide-once-per-signature semantics through a module-global cache.

    ``static_argnums`` must mirror the jitted function's own — an AOT
    executable is called WITHOUT its static arguments (they are burned
    into the program)."""
    if _is_traced(args):
        return jitted(*args)  # inlining into an enclosing trace
    key = (site, id(jitted), _abstract_key(args))
    entry = _CALL_CACHE.get(key, _UNSEEN)
    if entry is _UNSEEN:
        if not _metrics.enabled() or getattr(jitted, "lower", None) is None:
            _CALL_CACHE[key] = None
            return jitted(*args)
        entry = _timed_compile(site, jitted, args)
        _CALL_CACHE[key] = entry
        if entry is None:
            return jitted(*args)
    if entry is None:
        return jitted(*args)
    dynamic = tuple(a for i, a in enumerate(args) if i not in static_argnums)
    try:
        t0 = time.monotonic()
        out = entry.compiled(*dynamic)
    except (TypeError, ValueError):
        # argument-check rejection only — runtime failures propagate
        # (see instrument_jit's call path for the rationale)
        _CALL_CACHE[key] = None
        return jitted(*args)
    _note_dispatch(site, entry, time.monotonic() - t0)
    return out


# -- HBM sampling -------------------------------------------------------------


def sample_hbm(devices=None) -> Dict[str, dict]:
    """One ``memory_stats()`` sweep over the local devices: sets the HBM
    gauges and advances the process-local peak-observed high-water mark.

    Returns ``{device_id: {bytes_in_use, peak_bytes_in_use, bytes_limit}}``
    — empty on a backend without memory stats (CPU returns None, like the
    guarded null in obs/report.device_inventory). The unsupported
    discovery is cached, so the engine can call this per turn-chunk and a
    CPU run pays one probe total. ``devices`` overrides the
    ``jax.local_devices()`` default (the test hook)."""
    global _HBM_SUPPORTED
    probed_default = devices is None
    if probed_default:
        if _HBM_SUPPORTED is False:
            return {}
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            _HBM_SUPPORTED = False
            return {}
    out: Dict[str, dict] = {}
    for dev in devices:
        try:
            stats = dev.memory_stats()
        # gol: allow(hygiene): per-device probe — a backend without
        # memory_stats() degrades to 'no gauge', by design
        except Exception:
            stats = None
        if not stats:
            continue
        label = str(getattr(dev, "id", len(out)))
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use", in_use)
        limit = stats.get("bytes_limit")
        if in_use is not None:
            _ins.HBM_BYTES_IN_USE.labels(label).set(in_use)
            with _PEAK_LOCK:
                _PEAK_OBSERVED[label] = max(
                    _PEAK_OBSERVED.get(label, 0), int(in_use)
                )
        if peak is not None:
            _ins.HBM_PEAK_BYTES.labels(label).set(peak)
            with _PEAK_LOCK:
                _PEAK_OBSERVED[label] = max(
                    _PEAK_OBSERVED.get(label, 0), int(peak)
                )
        if limit is not None:
            _ins.HBM_BYTES_LIMIT.labels(label).set(limit)
        out[label] = {
            "bytes_in_use": in_use,
            "peak_bytes_in_use": peak,
            "bytes_limit": limit,
        }
    if probed_default:
        # an explicit device list (the test hook) never writes the
        # discovery — only a real local_devices() probe decides it. The
        # latch is one-way up: the FIRST probe may declare the backend
        # unsupported (CPU), but once a sweep has produced stats, a
        # transient all-devices-failed sweep must not silently disable
        # every future sample (the gauges would freeze mid-run).
        if out:
            _HBM_SUPPORTED = True
        elif _HBM_SUPPORTED is None:
            _HBM_SUPPORTED = False
    return out


def hbm_peak_observed() -> Dict[str, int]:
    """Per-device high-water ``bytes_in_use`` across every sample this
    process took — what the RunReport publishes so a mid-run spike is
    visible in the final artifact even after it subsides."""
    with _PEAK_LOCK:
        return dict(_PEAK_OBSERVED)


def reset_hbm() -> None:
    """Forget peaks and the supported/unsupported discovery (tests)."""
    global _HBM_SUPPORTED
    with _PEAK_LOCK:
        _PEAK_OBSERVED.clear()
    _HBM_SUPPORTED = None
