"""Hang flight-recorder: a bounded ring of the last N structured events.

The SPMD rank-desync hang (ADVICE r5) and broker->worker fan-out stalls
leave no artifact: the process is alive, the metrics counters have simply
stopped moving, and the interesting question — what was the LAST thing
each process did — is unanswerable after the fact. This module answers it:

* every process keeps a ring buffer (``deque(maxlen=N)``) of structured
  events — span open/close (obs/tracing.py feeds these), RPC send/recv
  (rpc/client.py + rpc/server.py), checkpoint agreement votes
  (engine/engine.py) — each stamped with wall + monotonic clocks, pid,
  thread id, and a monotonically increasing sequence number;
* the ring is snapshotted into the ``Status`` verb payload, so a WEDGED
  run can be interrogated live from any surviving rank
  (``python -m gol_distributed_final_tpu.obs.status host:port``);
* an unhandled engine exception dumps the ring to
  ``out/flight_<host>.jsonl`` before propagating (``dump_on_crash``), so
  a crashed rank leaves its last-events record on disk for post-mortem.

Like the registry and the tracer, recording is **off by default** and every
``record`` call is one flag check until the ``-trace`` flags opt in.
Events are plain JSON-able dicts: the ring must cross the restricted
unpickler inside Status replies and serialise to JSONL without help.
"""

from __future__ import annotations

import json
import os
import pathlib
import socket
import threading
import time
from collections import deque

from ..utils import locksan as _locksan
from typing import List, Optional

DEFAULT_CAPACITY = 512


class FlightRecorder:
    """Thread-safe bounded event ring. ``record`` is the only hot-path
    surface: one flag check when disabled, one lock + deque append when
    enabled (the deque's maxlen does the eviction — no manual trimming)."""

    # ring + seq move together under the lock (analysis/locks.py)
    _GUARDED_BY = {"_ring": "_lock", "_seq": "_lock"}

    def __init__(self, capacity: int = DEFAULT_CAPACITY, enabled: bool = False):
        self.enabled = enabled
        self._lock = _locksan.lock("FlightRecorder._lock")
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0

    @property
    def capacity(self) -> int:
        # gol: allow(lock-discipline): maxlen is fixed at construction —
        # reading it races nothing
        return self._ring.maxlen

    def record(self, kind: str, name: str, **args) -> None:
        """Append one event. ``kind`` is the event class (``span.open``,
        ``rpc.send``, ``ckpt.vote``, ...), ``name`` the specific site or
        verb, ``args`` small JSON-able details (never boards or frames)."""
        if not self.enabled:
            return
        event = {
            "kind": kind,
            "name": name,
            "t_unix": time.time(),
            "t_mono": time.monotonic(),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": args,
        }
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._ring.append(event)

    def snapshot(self) -> List[dict]:
        """The ring's current contents, oldest first — what the Status
        verb embeds. Copies are shallow: events are append-only records."""
        with self._lock:
            return list(self._ring)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0

    def dump(self, path) -> pathlib.Path:
        """Write the ring as JSONL (one event per line, oldest first).
        Temp-name + atomic rename, like every other artifact writer."""
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        events = self.snapshot()
        tmp = path.with_name(path.name + ".tmp")
        with tmp.open("w") as f:
            for event in events:
                f.write(json.dumps(event, default=str) + "\n")
        tmp.replace(path)
        return path


# -- the process-global default recorder -------------------------------------

_DEFAULT = FlightRecorder(enabled=False)

# where dump_on_crash writes; entry points with an -out notion may redirect
_DUMP_DIR = "out"


def recorder() -> FlightRecorder:
    return _DEFAULT


def enable(on: bool = True) -> None:
    _DEFAULT.enabled = on


def enabled() -> bool:
    return _DEFAULT.enabled


def record(kind: str, name: str, **args) -> None:
    _DEFAULT.record(kind, name, **args)


def set_dump_dir(path) -> None:
    global _DUMP_DIR
    _DUMP_DIR = str(path)


def crash_dump_path(out_dir: Optional[str] = None) -> pathlib.Path:
    host = socket.gethostname() or "localhost"
    return pathlib.Path(out_dir or _DUMP_DIR) / f"flight_{host}.jsonl"


def dump_on_crash(exc: BaseException, out_dir: Optional[str] = None):
    """Best-effort post-mortem dump for an unhandled engine exception: the
    exception itself is recorded as the ring's final event, then the ring
    goes to ``out/flight_<host>.jsonl``. Never raises (a broken disk must
    not mask the original exception) and is a no-op while disabled.
    Returns the written path, or None."""
    if not _DEFAULT.enabled:
        return None
    try:
        _DEFAULT.record(
            "crash", type(exc).__name__, message=str(exc)[:500]
        )
        return _DEFAULT.dump(crash_dump_path(out_dir))
    except Exception as dump_exc:  # pragma: no cover - depends on disk state
        print(f"flight-recorder dump failed: {dump_exc}")
        return None
