"""Operator one-liner: snapshot a RUNNING broker or worker's metrics.

    python -m gol_distributed_final_tpu.obs.status 127.0.0.1:8040
    python -m gol_distributed_final_tpu.obs.status -worker 127.0.0.1:8030
    python -m gol_distributed_final_tpu.obs.status -format prom :8040

Read-only: the ``Status`` verb snapshots the server's registry under its
lock and replies — it never touches the engine, the board, or the run
loop, so polling it mid-run is safe (unlike ``RetrieveCurrentData``, whose
full-world form costs a device->host transfer)."""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Optional


def norm_address(address: str) -> str:
    """Accept ``tcp://host:port``, ``host:port``, and ``:port`` — the one
    normalization every Status-surface CLI shares (doctor, canary,
    loadgen, watch, this module)."""
    if address.startswith("tcp://"):
        address = address[len("tcp://"):]
    if address.startswith(":"):
        address = "127.0.0.1" + address
    return address


def series_map(snap: dict, name: str) -> Dict[tuple, dict]:
    """``{labels_tuple: series_dict}`` for one family of a registry
    snapshot — the skew-safe reader every Status consumer (obs/watch.py
    panels, obs/doctor.py heuristics) shares: an absent family reads as
    empty, never a KeyError."""
    for fam in snap.get("families", []):
        if fam.get("name") == name:
            return {tuple(s.get("labels", ())): s for s in fam.get("series", [])}
    return {}


def scalar_value(snap: dict, name: str, labels: tuple = ()) -> Optional[float]:
    """One series' value from a snapshot, or None when absent."""
    s = series_map(snap, name).get(labels)
    return None if s is None else s.get("value")


class StatusUnavailable(RuntimeError):
    """The server answered, but no usable status payload came back. The
    message distinguishes the two distinct situations an operator needs
    to tell apart: an OLD server whose Response pickle predates the
    ``status`` field entirely, vs a current server that replied with an
    EMPTY payload."""


def extract_status(res) -> dict:
    """Classify a Status reply: the payload dict, or StatusUnavailable
    with a message naming WHICH failure mode this is. A missing attribute
    (old server's Response pickle) and a present-but-None field (handler
    never populated it) are the same operator situation — no payload —
    and share a message; an EMPTY dict is a different, current-server
    situation and gets its own."""
    status = getattr(res, "status", None)
    if status is None:
        raise StatusUnavailable(
            "server predates the Status payload (reply carries no status "
            "field) — upgrade the server, or you are polling a non-Status "
            "verb"
        )
    if not status:
        raise StatusUnavailable(
            "server knows the Status verb but replied with an EMPTY "
            "payload — unexpected server state, not version skew"
        )
    return status


def fetch_status(
    address: str,
    worker: bool = False,
    timeout: float = 10.0,
    timeline_since: int = 0,
    accounting_since: int = 0,
    journal_since: int = 0,
    profile_since: int = 0,
) -> dict:
    """One Status round-trip against a broker (default) or worker.

    ``timeline_since`` echoes the last timeline seq this poller received
    (``payload["timeline"]["seq"]``) so a ``-timeline`` server ships
    only NEWER samples — the incremental-window contract; 0 asks for the
    full ring, and a pre-timeline server ignores the field entirely.
    ``accounting_since`` is the tenant ledger's twin (broker only): a
    ``-accounting`` broker ships only ledger deltas past this seq.
    ``journal_since`` is the lifecycle journal's twin (obs/journal.py):
    a ``-journal`` server ships only events past this seq.
    ``profile_since`` is the continuous profiler's twin
    (obs/profiler.py): a ``-profile`` server ships only frames whose
    hit counts moved past this seq.

    Raises ``StatusUnavailable`` (with a mode-specific message, see
    ``extract_status``) instead of returning an empty dict, so callers
    and operators can tell "old server" from "empty reply" apart."""
    from ..rpc.client import RpcClient
    from ..rpc.protocol import Methods, Request

    address = norm_address(address)
    client = RpcClient(address, timeout=timeout)
    try:
        # timeout bounds the REPLY wait too, not just the connect: a
        # wedged server must fail this poller, never hang it
        res = client.call(
            Methods.WORKER_STATUS if worker else Methods.STATUS,
            Request(
                timeline_since=timeline_since,
                accounting_since=accounting_since,
                journal_since=journal_since,
                profile_since=profile_since,
            ),
            timeout=timeout,
        )
    finally:
        client.close()
    return extract_status(res)


def fetch_many(
    targets,
    timeout: float = 10.0,
) -> Dict[str, tuple]:
    """Parallel Status fan-out: one thread per target, each bounded by
    its own ``timeout``, so a single wedged target costs ONE timeout
    instead of stacking sequentially across the whole poll (the failure
    mode a fleet-of-N collector cannot afford).

    ``targets`` is an iterable of dicts, each at least
    ``{"address": ...}`` plus optional ``worker`` (bool) and the four
    ``*_since`` cursor fields — the same kwargs ``fetch_status`` takes.

    Returns ``{address: (payload, fetched_at, error)}`` keyed by the
    NORMALIZED address: exactly one of ``payload``/``error`` is non-None,
    and ``fetched_at`` is the local wall clock at reply (or failure) —
    the raw material for scrape-health bookkeeping (last-success age,
    consecutive failures). Errors are captured as strings, never raised:
    a dead target is DATA to a fleet consumer, not an exception."""
    import threading
    import time as _time

    specs = []
    for t in targets:
        spec = dict(t)
        spec["address"] = norm_address(spec["address"])
        specs.append(spec)
    results: Dict[str, tuple] = {}
    lock = threading.Lock()

    def one(spec: dict) -> None:
        addr = spec["address"]
        try:
            payload = fetch_status(
                addr,
                worker=bool(spec.get("worker", False)),
                timeout=timeout,
                timeline_since=int(spec.get("timeline_since", 0)),
                accounting_since=int(spec.get("accounting_since", 0)),
                journal_since=int(spec.get("journal_since", 0)),
                profile_since=int(spec.get("profile_since", 0)),
            )
            with lock:
                results[addr] = (payload, _time.time(), None)
        except Exception as exc:
            with lock:
                results[addr] = (None, _time.time(), str(exc) or type(exc).__name__)

    threads = [
        threading.Thread(target=one, args=(s,), daemon=True) for s in specs
    ]
    for th in threads:
        th.start()
    for th in threads:
        # join is bounded: fetch_status itself times out, so a small
        # grace on top covers thread scheduling, never a hung socket
        th.join(timeout + 5.0)
    with lock:
        for s in specs:
            # a thread that somehow outlived its bounded join still
            # yields a result row — consumers never KeyError on a target
            results.setdefault(
                s["address"], (None, _time.time(), "fetch thread timed out")
            )
        return dict(results)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="snapshot a running broker/worker's metrics registry"
    )
    parser.add_argument("address", help="host:port (or :port for loopback)")
    parser.add_argument(
        "-worker", action="store_true",
        help="query a worker's GameOfLifeOperations.Status instead of the "
             "broker's Operations.Status",
    )
    parser.add_argument(
        "-format", choices=("json", "prom"), default="json",
        help="json: the full status payload; prom: Prometheus text "
             "exposition of the metrics snapshot",
    )
    parser.add_argument(
        "-timeout", type=float, default=10.0, metavar="SECONDS",
        help="bound on connect AND reply wait (default 10); a wedged "
             "server fails the poll after this instead of hanging it",
    )
    args = parser.parse_args(argv)
    try:
        status = fetch_status(
            args.address, worker=args.worker, timeout=args.timeout
        )
    except StatusUnavailable as exc:
        print(f"no status: {exc}", file=sys.stderr)
        return 1
    except Exception as exc:
        print(f"status fetch failed: {exc}", file=sys.stderr)
        return 1
    if args.format == "prom":
        from .metrics import snapshot_to_prometheus

        sys.stdout.write(snapshot_to_prometheus(status.get("metrics", {})))
    else:
        print(json.dumps(status, indent=1, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
