from .checkpoint import (
    load_checkpoint,
    load_packed_checkpoint,
    save_checkpoint,
    save_packed_checkpoint,
)
from .engine import Engine, RunResult, Snapshot

__all__ = [
    "Engine",
    "RunResult",
    "Snapshot",
    "load_checkpoint",
    "load_packed_checkpoint",
    "save_checkpoint",
    "save_packed_checkpoint",
]
