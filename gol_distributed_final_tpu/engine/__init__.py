from .engine import Engine, RunResult, Snapshot

__all__ = ["Engine", "RunResult", "Snapshot"]
