from .checkpoint import (
    CheckpointError,
    load_checkpoint,
    load_packed_checkpoint,
    load_resume_checkpoint,
    load_verified_checkpoint,
    save_checkpoint,
    save_packed_checkpoint,
)
from .engine import Engine, RunResult, Snapshot
from .sessions import Session, SessionRejected, SessionTable

__all__ = [
    "CheckpointError",
    "Engine",
    "RunResult",
    "Session",
    "SessionRejected",
    "SessionTable",
    "Snapshot",
    "load_checkpoint",
    "load_packed_checkpoint",
    "load_resume_checkpoint",
    "load_verified_checkpoint",
    "save_checkpoint",
    "save_packed_checkpoint",
]
