from .checkpoint import (
    CheckpointError,
    load_checkpoint,
    load_packed_checkpoint,
    load_resume_checkpoint,
    load_verified_checkpoint,
    save_checkpoint,
    save_packed_checkpoint,
)
from .engine import Engine, RunResult, Snapshot

__all__ = [
    "CheckpointError",
    "Engine",
    "RunResult",
    "Snapshot",
    "load_checkpoint",
    "load_packed_checkpoint",
    "load_resume_checkpoint",
    "load_verified_checkpoint",
    "save_checkpoint",
    "save_packed_checkpoint",
]
