"""Checkpoint / resume — a superset of the reference's snapshot mechanism.

The reference's only "checkpoint" is the PGM snapshot ('s' writes
out/<W>x<H>x<Turns>.pgm, gol/distributor.go:78-90); there is no resume —
input is always images/<W>x<H>.pgm and the turn counter starts at 0
(SURVEY.md §5). Here a checkpoint carries the board, the turn counter, and
the rule, so a run can continue exactly where it stopped: bit-identical to
an uninterrupted run (tests/test_checkpoint.py).

Resume ≡ uninterrupted run is proven bit-identical by
tests/test_aux.py::test_resume_equals_uninterrupted_run.

Format: a plain .npz — board (uint8 [H, W]), turn (int), rulestring (str).
"""

from __future__ import annotations

import pathlib

import numpy as np

from ..models import CONWAY, LifeRule


def npz_path(path) -> pathlib.Path:
    """The path ``np.savez_compressed`` actually writes: ``.npz`` is
    appended whenever the name doesn't already end with it (so e.g.
    ``ck.backup`` lands at ``ck.backup.npz``)."""
    path = pathlib.Path(path)
    return path if path.suffix == ".npz" else path.with_name(path.name + ".npz")


def _save_npz(path, **arrays) -> pathlib.Path:
    """Write a compressed npz, returning the path actually written."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    np.savez_compressed(path, **arrays)
    return npz_path(path)


def save_checkpoint(path, world, turn: int, rule: LifeRule = CONWAY) -> pathlib.Path:
    return _save_npz(
        path,
        board=np.asarray(world, np.uint8),
        turn=np.int64(turn),
        rulestring=np.str_(rule.rulestring),
    )


def load_checkpoint(path) -> tuple[np.ndarray, int, LifeRule]:
    with np.load(path, allow_pickle=False) as data:
        if "packed" in data:
            raise ValueError(
                f"{path} is a packed-bitboard checkpoint; use "
                "load_packed_checkpoint (unpacking a config-5-scale board "
                "to bytes would materialise 32x the state on host)"
            )
        board = data["board"].astype(np.uint8)
        turn = int(data["turn"])
        rule = LifeRule.from_rulestring(str(data["rulestring"]))
    return board, turn, rule


def save_packed_checkpoint(
    path, packed, turn: int, rule: LifeRule = CONWAY, word_axis: int = 0
) -> pathlib.Path:
    """Checkpoint a bit-packed board WITHOUT decoding it: the int32 words
    cross the device boundary once and land compressed on disk (a 65536^2
    board is 512 MiB packed vs 4 GiB as bytes — and a sparse one
    compresses to almost nothing). The reference has no analogue; this is
    the big-board (bigboard.py / BASELINE config 5) snapshot path."""
    return _save_npz(
        path,
        packed=np.asarray(packed, np.int32),
        word_axis=np.int64(word_axis),
        turn=np.int64(turn),
        rulestring=np.str_(rule.rulestring),
    )


def checkpoint_shard_path(path, rank: int, num_processes: int) -> pathlib.Path:
    """Where rank ``rank``'s shard of a multi-host checkpoint lives:
    ``<stem>.rank<k>of<n>.npz`` next to the configured path. Works on a
    shared filesystem (all shards side by side) and on per-host disks
    (each rank only ever touches its own name)."""
    p = npz_path(path)
    return p.with_name(f"{p.stem}.rank{rank}of{num_processes}.npz")


def local_packed_rows(state) -> tuple[int, np.ndarray]:
    """This process's contiguous block of packed word rows, assembled from
    the global array's addressable shards -> (first_global_row, rows).

    Requires the process to own whole contiguous rows of the packed array
    (the canonical process-major ('rows', 'cols') placement —
    parallel/multihost.host_row_range makes the same demand of byte
    boards); raises if the addressable shards leave gaps."""
    shards = list(state.addressable_shards)
    if not shards:
        raise ValueError("state has no addressable shards on this process")
    n_rows, n_cols = state.shape
    row0 = min(s.index[0].start or 0 for s in shards)
    row1 = max(
        n_rows if s.index[0].stop is None else s.index[0].stop for s in shards
    )
    out = np.zeros((row1 - row0, n_cols), np.int32)
    filled = np.zeros((row1 - row0, n_cols), bool)
    for s in shards:
        r0 = s.index[0].start or 0
        c0 = s.index[1].start or 0
        data = np.asarray(s.data)
        out[r0 - row0 : r0 - row0 + data.shape[0], c0 : c0 + data.shape[1]] = data
        filled[r0 - row0 : r0 - row0 + data.shape[0], c0 : c0 + data.shape[1]] = True
    if not filled.all():
        raise ValueError(
            "this process's shards do not cover a contiguous whole-row "
            "block; use a process-major ('rows', 'cols') mesh placement"
        )
    return row0, out


def save_packed_checkpoint_sharded(
    path, state, turn: int, rule: LifeRule = CONWAY, word_axis: int = 0
) -> pathlib.Path:
    """One checkpoint shard per process for a multi-host packed board:
    each rank writes ONLY its own word rows (the 65536^2 board never
    materialises anywhere), to a temp name atomically renamed so a crash
    mid-write leaves the previous shard intact. Every shard stamps the
    turn / rule / global shape / process count, so the loader can refuse
    mismatched reassembly."""
    import jax

    rank, nprocs = jax.process_index(), jax.process_count()
    row0, rows = local_packed_rows(state)
    final = checkpoint_shard_path(path, rank, nprocs)
    tmp = final.with_name(final.name + ".tmp")
    written = _save_npz(
        tmp,
        packed=rows,
        row0=np.int64(row0),
        global_rows=np.int64(state.shape[0]),
        global_cols=np.int64(state.shape[1]),
        num_processes=np.int64(nprocs),
        process_index=np.int64(rank),
        word_axis=np.int64(word_axis),
        turn=np.int64(turn),
        rulestring=np.str_(rule.rulestring),
    )
    written.replace(final)
    return final


def load_packed_checkpoint_sharded(path, sharding):
    """Each rank loads ITS shard of a multi-host packed checkpoint and
    re-places it onto the mesh -> (global array, turn, rule, word_axis).

    ``sharding`` is the target NamedSharding (parallel/bit_halo
    ``packed_sharding(mesh)``). Validates that the shard was written by a
    job of the same process count, that this rank's stored row offset
    matches where the sharding will place its local block, and (via the
    global shape) that the board geometry is unchanged. COLLECTIVE in a
    multi-process job: ranks allgather their shard turns and refuse a
    mixed set — resuming ranks from different turns would desynchronise
    every later collective (a crash between two ranks' shard renames can
    leave exactly that on disk)."""
    import jax

    rank, nprocs = jax.process_index(), jax.process_count()
    p = checkpoint_shard_path(path, rank, nprocs)
    if nprocs == 1 and not p.exists() and npz_path(path).exists():
        # single-process runs write the plain packed format (the state is
        # fully addressable, engine/_write_checkpoint's other branch) —
        # accept it here so one-host and pod checkpoints interoperate
        packed, turn, rule, word_axis = load_packed_checkpoint(npz_path(path))
        arr = jax.make_array_from_process_local_data(
            sharding, packed, packed.shape
        )
        return arr, turn, rule, word_axis
    # Per-rank load + validation is caught, NOT raised: one rank raising
    # here while its peers proceed into the collective below strands them
    # in the allgather — a distributed hang instead of a clean error
    # (ADVICE r4). Every rank always reaches the agreement crossing with
    # an ok/turn word, mirroring the save path's protocol.
    err = None
    rows = turn = rule = word_axis = gshape = None
    try:
        with np.load(p, allow_pickle=False) as data:
            if "packed" not in data or "row0" not in data:
                raise ValueError(f"{p} is not a sharded packed checkpoint")
            if int(data["num_processes"]) != nprocs:
                raise ValueError(
                    f"{p} was written by {int(data['num_processes'])} "
                    f"processes; this job has {nprocs}"
                )
            rows = data["packed"].astype(np.int32)
            row0 = int(data["row0"])
            word_axis = int(data["word_axis"])
            turn = int(data["turn"])
            rule = LifeRule.from_rulestring(str(data["rulestring"]))
            gshape = (int(data["global_rows"]), int(data["global_cols"]))
        idx_map = sharding.addressable_devices_indices_map(gshape)
        want_row0 = min(idx[0].start or 0 for idx in idx_map.values())
        if row0 != want_row0:
            raise ValueError(
                f"shard {p} holds rows from {row0} but this rank's mesh "
                f"placement starts at {want_row0}: process/mesh order "
                "changed since the checkpoint was written"
            )
    except Exception as exc:
        err = exc
    if nprocs > 1:
        from jax.experimental import multihost_utils

        word = np.array(
            [0, -1] if err is not None else [1, turn], dtype=np.int64
        )
        agreed = multihost_utils.process_allgather(word)  # (nprocs, 2)
        if err is not None:
            raise err
        failed = int(nprocs - agreed[:, 0].sum())
        if failed:
            raise ValueError(
                f"checkpoint load: shard validation failed on {failed} "
                f"other rank(s); the job cannot resume from {path}"
            )
        turns = agreed[:, 1]
        if int(turns.min()) != int(turns.max()):
            raise ValueError(
                f"checkpoint shards disagree on the turn "
                f"({int(turns.min())}..{int(turns.max())}): a crash "
                "between per-rank writes left a mixed set; restore from "
                "an older consistent checkpoint"
            )
    elif err is not None:
        raise err
    arr = jax.make_array_from_process_local_data(sharding, rows, gshape)
    return arr, turn, rule, word_axis


def load_packed_checkpoint(path) -> tuple[np.ndarray, int, LifeRule, int]:
    """-> (packed int32 array, turn, rule, word_axis) — the byte loader's
    (board, turn, rule) shape with word_axis appended, so the two loaders
    never swap the bare-int positions of turn and word_axis."""
    with np.load(path, allow_pickle=False) as data:
        if "packed" not in data:
            raise ValueError(
                f"{path} is a byte-board checkpoint; use load_checkpoint"
            )
        packed = data["packed"].astype(np.int32)
        word_axis = int(data["word_axis"])
        turn = int(data["turn"])
        rule = LifeRule.from_rulestring(str(data["rulestring"]))
    return packed, turn, rule, word_axis
