"""Checkpoint / resume — a superset of the reference's snapshot mechanism.

The reference's only "checkpoint" is the PGM snapshot ('s' writes
out/<W>x<H>x<Turns>.pgm, gol/distributor.go:78-90); there is no resume —
input is always images/<W>x<H>.pgm and the turn counter starts at 0
(SURVEY.md §5). Here a checkpoint carries the board, the turn counter, and
the rule, so a run can continue exactly where it stopped: bit-identical to
an uninterrupted run (tests/test_checkpoint.py).

Resume ≡ uninterrupted run is proven bit-identical by
tests/test_aux.py::test_resume_equals_uninterrupted_run.

Format: a plain .npz — board (uint8 [H, W]), turn (int), rulestring (str),
plus (format v2) an embedded blake2b digest over (geometry, turn, rule,
board bytes) and a format-version stamp, so a truncated, corrupt, or
mislabelled file is a LOUD typed :class:`CheckpointError` at load time
instead of a silently-wrong resume. ``-resume`` surfaces only go through
:func:`load_verified_checkpoint` / :func:`load_resume_checkpoint` (the
latter falls back across ``-ckpt-keep`` generations to the newest file
that verifies); the plain :func:`load_checkpoint` stays lenient for
callers that accept pre-integrity files.
"""

from __future__ import annotations

import hashlib
import pathlib

import numpy as np

from ..models import CONWAY, LifeRule
from ..obs import instruments as _ins
from ..obs import journal as _journal

CKPT_FORMAT_VERSION = 2


class CheckpointError(ValueError):
    """A checkpoint that must not be resumed from. ``kind`` narrows the
    failure: ``unreadable`` (not an npz / truncated zip), ``truncated``
    (an npz missing checkpoint fields), ``format`` (a packed-bitboard
    file on the byte surface), ``unverified`` (a pre-integrity file with
    no embedded digest), ``digest`` (contents do not hash to the embedded
    digest), ``exhausted`` (every ``-ckpt-keep`` generation failed). The
    message always says what to do next."""

    def __init__(self, message: str, kind: str = "corrupt"):
        super().__init__(message)
        self.kind = kind


def checkpoint_digest(
    board, turn: int, rulestring: str,
    format_version: int = CKPT_FORMAT_VERSION,
) -> str:
    """blake2b-128 hex digest binding the board BYTES to its metadata —
    geometry, turn, and rule — so a bit flip in any of them (or a
    board/metadata swap between files) fails verification.

    ``format_version`` is the version stamped IN the file being written
    or verified, not this module's constant: a version bump must not
    retroactively flip every existing valid file to kind="digest"."""
    board = np.ascontiguousarray(board, np.uint8)
    h = hashlib.blake2b(digest_size=16)
    h.update(
        f"gol-ckpt:v{int(format_version)}:{board.shape[0]}x{board.shape[1]}"
        f":{int(turn)}:{rulestring}:".encode()
    )
    h.update(board.data)
    return h.hexdigest()


def npz_path(path) -> pathlib.Path:
    """The path ``np.savez_compressed`` actually writes: ``.npz`` is
    appended whenever the name doesn't already end with it (so e.g.
    ``ck.backup`` lands at ``ck.backup.npz``)."""
    path = pathlib.Path(path)
    return path if path.suffix == ".npz" else path.with_name(path.name + ".npz")


def _save_npz(path, **arrays) -> pathlib.Path:
    """Write a compressed npz, returning the path actually written."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    np.savez_compressed(path, **arrays)
    return npz_path(path)


def save_checkpoint(path, world, turn: int, rule: LifeRule = CONWAY) -> pathlib.Path:
    board = np.asarray(world, np.uint8)
    return _save_npz(
        path,
        board=board,
        turn=np.int64(turn),
        rulestring=np.str_(rule.rulestring),
        # format v2: the verification surface (load_verified_checkpoint).
        # Older loaders ignore the extra keys — forward-compatible.
        format_version=np.int64(CKPT_FORMAT_VERSION),
        digest=np.str_(checkpoint_digest(board, turn, rule.rulestring)),
    )


def load_checkpoint(path) -> tuple[np.ndarray, int, LifeRule]:
    with np.load(path, allow_pickle=False) as data:
        if "packed" in data:
            raise ValueError(
                f"{path} is a packed-bitboard checkpoint; use "
                "load_packed_checkpoint (unpacking a config-5-scale board "
                "to bytes would materialise 32x the state on host)"
            )
        board = data["board"].astype(np.uint8)
        turn = int(data["turn"])
        rule = LifeRule.from_rulestring(str(data["rulestring"]))
    return board, turn, rule


def _load_for_verification(
    path,
) -> tuple[np.ndarray, int, LifeRule, str | None, int]:
    """The typed-error load: every way an npz can be wrong becomes a
    CheckpointError whose message says what happened and what to do —
    never a raw zipfile/KeyError/ValueError traceback."""
    path = pathlib.Path(path)
    try:
        with np.load(path, allow_pickle=False) as data:
            if "packed" in data:
                raise CheckpointError(
                    f"{path} is a packed-bitboard checkpoint; the -resume "
                    "surface takes byte checkpoints (the bigboard surface "
                    "loads packed ones)",
                    kind="format",
                )
            missing = [
                k for k in ("board", "turn", "rulestring") if k not in data
            ]
            if missing:
                raise CheckpointError(
                    f"{path} is missing checkpoint field(s) "
                    f"{', '.join(missing)}: not a checkpoint, or one cut "
                    "short mid-write — fall back to an older generation "
                    "(-ckpt-keep) or start the run fresh",
                    kind="truncated",
                )
            board = data["board"].astype(np.uint8)
            turn = int(data["turn"])
            rulestring = str(data["rulestring"])
            stored = str(data["digest"]) if "digest" in data else None
            # the version the FILE claims; digests began at v2, so a
            # digested file without the stamp verifies as v2
            version = (
                int(data["format_version"])
                if "format_version" in data else CKPT_FORMAT_VERSION
            )
    except CheckpointError:
        raise
    except Exception as exc:
        raise CheckpointError(
            f"{path} is not a readable checkpoint "
            f"({type(exc).__name__}: {exc}): the file is truncated or "
            "corrupt — fall back to an older generation (-ckpt-keep) or "
            "start the run fresh",
            kind="unreadable",
        ) from exc
    try:
        rule = LifeRule.from_rulestring(rulestring)
    except ValueError as exc:
        raise CheckpointError(
            f"{path} carries an unparseable rulestring "
            f"{rulestring!r}: {exc}", kind="truncated",
        ) from exc
    return board, turn, rule, stored, version


def load_verified_checkpoint(path) -> tuple[np.ndarray, int, LifeRule]:
    """``load_checkpoint`` with the integrity contract: the file must
    carry a digest and its contents must hash to it. Raises a typed,
    actionable :class:`CheckpointError` otherwise — a resume must never
    reattach state it cannot verify. Every attempt is counted
    (``gol_ckpt_verify_total{result}``)."""
    try:
        board, turn, rule, stored, version = _load_for_verification(path)
        if stored is None:
            raise CheckpointError(
                f"{path} carries no integrity digest (written by a "
                "pre-integrity version): -resume refuses unverified "
                "state; load it explicitly with load_checkpoint() if you "
                "accept the risk",
                kind="unverified",
            )
        if version > CKPT_FORMAT_VERSION:
            raise CheckpointError(
                f"{path} is a format-v{version} checkpoint but this "
                f"build verifies up to v{CKPT_FORMAT_VERSION}: load it "
                "with the version that wrote it",
                kind="format",
            )
        # verify against the version the FILE was written under — the
        # digest preimage is versioned with the file, not this build
        if checkpoint_digest(board, turn, rule.rulestring, version) != stored:
            raise CheckpointError(
                f"{path} failed digest verification: board/turn/rule do "
                "not hash to the embedded digest — the file is corrupt; "
                "fall back to an older generation (-ckpt-keep)",
                kind="digest",
            )
    except CheckpointError as exc:
        _ins.CKPT_VERIFY_TOTAL.labels("fail").inc()
        _journal.record(
            "ckpt.verify", "fail", path=str(path), kind=exc.kind
        )
        raise
    _ins.CKPT_VERIFY_TOTAL.labels("ok").inc()
    _journal.record("ckpt.verify", "ok", path=str(path), turn=turn)
    return board, turn, rule


def generation_path(path, gen: int) -> pathlib.Path:
    """Where generation ``gen`` of a rotated checkpoint lives: gen 0 is
    the configured path itself, gen N is ``<stem>.gN.npz`` beside it
    (newest-first numbering — g1 is the previous current)."""
    p = npz_path(path)
    return p if gen == 0 else p.with_name(f"{p.stem}.g{gen}.npz")


def rotate_generations(path, keep: int) -> None:
    """Shift the generation chain down one slot before a new current is
    written: current → .g1 → .g2 → …, keeping at most ``keep``
    generations total. Best-effort renames: a missing link just shortens
    the chain, it never blocks the new checkpoint."""
    if keep <= 1:
        return
    for gen in range(keep - 2, -1, -1):
        src = generation_path(path, gen)
        if src.exists():
            src.replace(generation_path(path, gen + 1))


def load_resume_checkpoint(path, keep: int = 1) -> tuple[np.ndarray, int, LifeRule, int]:
    """The ``-resume`` loader: newest VERIFIABLE generation of ``path``
    → ``(board, turn, rule, generation)``. Tries gen 0 … keep-1 in order
    and falls back past unverifiable files; raises a CheckpointError
    listing every attempt when none verifies — resuming from nothing must
    be an operator decision, never a silent from-zero run.

    When verified DELTA checkpoints newer than the chosen full generation
    exist beside it (``save_delta_checkpoint`` — the broker's
    auto-checkpoint writes them between full keyframes), the newest one
    that applies AND verifies advances the resume point; a corrupted or
    mismatched-base delta is skipped loudly (every delta is depth-1 from
    its full keyframe, so skipping one only costs its turns, never the
    chain)."""
    attempts = []
    for gen in range(max(1, keep)):
        p = generation_path(path, gen)
        if not p.exists():
            raw = pathlib.Path(path)
            if gen == 0 and raw.exists():
                p = raw  # an explicit non-.npz-suffixed path
            else:
                attempts.append(f"{p}: not found")
                continue
        try:
            board, turn, rule = load_verified_checkpoint(p)
        except CheckpointError as exc:
            attempts.append(f"{p}: [{exc.kind}] {exc}")
            continue
        for dturn, dpath in reversed(delta_checkpoint_paths(path)):
            if dturn <= turn:
                break
            try:
                board_d, turn_d = apply_delta_checkpoint(
                    dpath, board, turn, rule
                )
            except CheckpointError as exc:
                attempts.append(f"{dpath}: [{exc.kind}] {exc}")
                continue
            _journal.record(
                "ckpt.replay", "delta", turn=turn_d, gen=gen,
                base_turn=turn,
            )
            return board_d, turn_d, rule, gen
        _journal.record("ckpt.replay", "full", turn=turn, gen=gen)
        return board, turn, rule, gen
    raise CheckpointError(
        "no verifiable checkpoint generation to resume from:\n  "
        + "\n  ".join(attempts),
        kind="exhausted",
    )


# -- delta checkpoints (dirty-tile deltas between full generations) ----------
#
# The broker's auto-checkpoint accumulates a per-tile dirty bitmap from the
# resident workers' StripStep replies (ops/sparse.py wire tiles) and, between
# full keyframes, writes only the tiles that changed since the last FULL
# checkpoint — so every delta applies directly onto its keyframe (depth-1,
# never a delta-on-delta chain) and a <1%-active big board checkpoints in a
# fraction of the full write. Integrity mirrors the full format: the file
# embeds the digest of the base it applies to AND the digest of the board it
# must produce, so a wrong base, a flipped tile byte, or a truncated payload
# is a LOUD typed refusal at load time, never a silently-wrong resume.


def delta_checkpoint_path(path, turn: int) -> pathlib.Path:
    """Where the delta at ``turn`` lives: ``<stem>.d<turn>.npz`` beside
    the configured full checkpoint path."""
    p = npz_path(path)
    return p.with_name(f"{p.stem}.d{int(turn)}.npz")


def delta_checkpoint_paths(path) -> list[tuple[int, pathlib.Path]]:
    """Existing delta files for a checkpoint path, ``(turn, path)``
    sorted by turn ascending."""
    import re

    p = npz_path(path)
    pat = re.compile(re.escape(p.stem) + r"\.d(\d+)\.npz$")
    out = []
    for cand in p.parent.glob(f"{p.stem}.d*.npz"):
        m = pat.match(cand.name)
        if m:
            out.append((int(m.group(1)), cand))
    return sorted(out)


def clear_delta_checkpoints(path) -> None:
    """Drop every delta beside ``path`` — called when a new full keyframe
    lands (the deltas applied to the OLD base; their base digest would
    refuse anyway, this just keeps the directory honest). Best-effort."""
    for _turn, p in delta_checkpoint_paths(path):
        try:
            p.unlink()
        except OSError:
            pass  # a stale delta is refused by digest, never resumed


def save_delta_checkpoint(
    path,
    board,
    dirty: np.ndarray,
    turn: int,
    rule: LifeRule,
    base_turn: int,
    base_digest: str,
) -> pathlib.Path:
    """Write the dirty tiles of ``board`` as a delta against the full
    checkpoint whose board hashed to ``base_digest`` at ``base_turn``.
    Written tmp-then-rename like every checkpoint: a crash mid-write
    leaves no half-delta behind."""
    from ..ops.sparse import extract_dirty_tiles, wire_tile_grid

    board = np.asarray(board, np.uint8)
    dirty = np.asarray(dirty, bool)
    if dirty.shape != wire_tile_grid(board.shape):
        raise ValueError(
            f"dirty grid {dirty.shape} does not match board "
            f"{board.shape}'s wire-tile grid"
        )
    final = delta_checkpoint_path(path, turn)
    tmp = final.with_name(final.name + ".tmp")
    written = _save_npz(
        tmp,
        dirty=dirty,
        tiles=extract_dirty_tiles(board, dirty),
        height=np.int64(board.shape[0]),
        width=np.int64(board.shape[1]),
        turn=np.int64(turn),
        base_turn=np.int64(base_turn),
        base_digest=np.str_(base_digest),
        rulestring=np.str_(rule.rulestring),
        format_version=np.int64(CKPT_FORMAT_VERSION),
        digest=np.str_(checkpoint_digest(board, turn, rule.rulestring)),
    )
    written.replace(final)
    return final


def apply_delta_checkpoint(
    path, base_board: np.ndarray, base_turn: int, rule: LifeRule
) -> tuple[np.ndarray, int]:
    """Apply one delta file onto its verified base -> ``(board, turn)``.
    Refuses loudly (typed CheckpointError, counted on
    ``gol_ckpt_verify_total``) when the base is not the one the delta was
    cut against, when the payload is malformed, or when the applied
    result does not hash to the embedded digest — the corrupted-delta
    contract tests/test_sparse.py pins."""
    from ..ops.sparse import apply_dirty_tiles

    path = pathlib.Path(path)
    try:
        try:
            with np.load(path, allow_pickle=False) as data:
                missing = [
                    k
                    for k in (
                        "dirty", "tiles", "height", "width", "turn",
                        "base_turn", "base_digest", "rulestring", "digest",
                    )
                    if k not in data
                ]
                if missing:
                    raise CheckpointError(
                        f"{path} is missing delta field(s) "
                        f"{', '.join(missing)}: not a delta checkpoint, or "
                        "one cut short mid-write",
                        kind="truncated",
                    )
                dirty = np.asarray(data["dirty"], bool)
                tiles = np.asarray(data["tiles"], np.uint8)
                shape = (int(data["height"]), int(data["width"]))
                turn = int(data["turn"])
                d_base_turn = int(data["base_turn"])
                base_digest = str(data["base_digest"])
                rulestring = str(data["rulestring"])
                stored = str(data["digest"])
                version = (
                    int(data["format_version"])
                    if "format_version" in data else CKPT_FORMAT_VERSION
                )
        except CheckpointError:
            raise
        except Exception as exc:
            raise CheckpointError(
                f"{path} is not a readable delta checkpoint "
                f"({type(exc).__name__}: {exc})",
                kind="unreadable",
            ) from exc
        if rulestring != rule.rulestring:
            raise CheckpointError(
                f"{path} is a {rulestring} delta applied to a "
                f"{rule.rulestring} base", kind="format",
            )
        base_board = np.asarray(base_board, np.uint8)
        if shape != base_board.shape or d_base_turn != base_turn:
            raise CheckpointError(
                f"{path} was cut against a {shape} board at turn "
                f"{d_base_turn}, not this {base_board.shape} base at "
                f"turn {base_turn}", kind="delta-base",
            )
        if (
            checkpoint_digest(base_board, base_turn, rulestring, version)
            != base_digest
        ):
            raise CheckpointError(
                f"{path}: the base board does not hash to the delta's "
                "embedded base digest — it applies to a different full "
                "generation", kind="delta-base",
            )
        try:
            board = apply_dirty_tiles(base_board, dirty, tiles)
        except ValueError as exc:
            raise CheckpointError(
                f"{path}: malformed delta payload ({exc})", kind="truncated",
            ) from exc
        if checkpoint_digest(board, turn, rulestring, version) != stored:
            raise CheckpointError(
                f"{path} failed digest verification: the applied board "
                "does not hash to the embedded digest — the delta is "
                "corrupt; resume falls back to the full generation",
                kind="digest",
            )
    except CheckpointError as exc:
        _ins.CKPT_VERIFY_TOTAL.labels("fail").inc()
        _journal.record(
            "ckpt.verify", "fail", path=str(path), kind=exc.kind
        )
        raise
    _ins.CKPT_VERIFY_TOTAL.labels("ok").inc()
    _journal.record("ckpt.verify", "ok", path=str(path), turn=turn)
    return board, turn


def save_packed_checkpoint(
    path, packed, turn: int, rule: LifeRule = CONWAY, word_axis: int = 0
) -> pathlib.Path:
    """Checkpoint a bit-packed board WITHOUT decoding it: the int32 words
    cross the device boundary once and land compressed on disk (a 65536^2
    board is 512 MiB packed vs 4 GiB as bytes — and a sparse one
    compresses to almost nothing). The reference has no analogue; this is
    the big-board (bigboard.py / BASELINE config 5) snapshot path."""
    return _save_npz(
        path,
        packed=np.asarray(packed, np.int32),
        word_axis=np.int64(word_axis),
        turn=np.int64(turn),
        rulestring=np.str_(rule.rulestring),
    )


def checkpoint_shard_path(path, rank: int, num_processes: int) -> pathlib.Path:
    """Where rank ``rank``'s shard of a multi-host checkpoint lives:
    ``<stem>.rank<k>of<n>.npz`` next to the configured path. Works on a
    shared filesystem (all shards side by side) and on per-host disks
    (each rank only ever touches its own name)."""
    p = npz_path(path)
    return p.with_name(f"{p.stem}.rank{rank}of{num_processes}.npz")


def local_packed_rows(state) -> tuple[int, np.ndarray]:
    """This process's contiguous block of packed word rows, assembled from
    the global array's addressable shards -> (first_global_row, rows).

    Requires the process to own whole contiguous rows of the packed array
    (the canonical process-major ('rows', 'cols') placement —
    parallel/multihost.host_row_range makes the same demand of byte
    boards); raises if the addressable shards leave gaps."""
    shards = list(state.addressable_shards)
    if not shards:
        raise ValueError("state has no addressable shards on this process")
    n_rows, n_cols = state.shape
    row0 = min(s.index[0].start or 0 for s in shards)
    row1 = max(
        n_rows if s.index[0].stop is None else s.index[0].stop for s in shards
    )
    out = np.zeros((row1 - row0, n_cols), np.int32)
    filled = np.zeros((row1 - row0, n_cols), bool)
    for s in shards:
        r0 = s.index[0].start or 0
        c0 = s.index[1].start or 0
        data = np.asarray(s.data)
        out[r0 - row0 : r0 - row0 + data.shape[0], c0 : c0 + data.shape[1]] = data
        filled[r0 - row0 : r0 - row0 + data.shape[0], c0 : c0 + data.shape[1]] = True
    if not filled.all():
        raise ValueError(
            "this process's shards do not cover a contiguous whole-row "
            "block; use a process-major ('rows', 'cols') mesh placement"
        )
    return row0, out


def save_packed_checkpoint_sharded(
    path, state, turn: int, rule: LifeRule = CONWAY, word_axis: int = 0
) -> pathlib.Path:
    """One checkpoint shard per process for a multi-host packed board:
    each rank writes ONLY its own word rows (the 65536^2 board never
    materialises anywhere), to a temp name atomically renamed so a crash
    mid-write leaves the previous shard intact. Every shard stamps the
    turn / rule / global shape / process count, so the loader can refuse
    mismatched reassembly."""
    import jax

    rank, nprocs = jax.process_index(), jax.process_count()
    row0, rows = local_packed_rows(state)
    final = checkpoint_shard_path(path, rank, nprocs)
    tmp = final.with_name(final.name + ".tmp")
    written = _save_npz(
        tmp,
        packed=rows,
        row0=np.int64(row0),
        global_rows=np.int64(state.shape[0]),
        global_cols=np.int64(state.shape[1]),
        num_processes=np.int64(nprocs),
        process_index=np.int64(rank),
        word_axis=np.int64(word_axis),
        turn=np.int64(turn),
        rulestring=np.str_(rule.rulestring),
    )
    written.replace(final)
    return final


def load_packed_checkpoint_sharded(path, sharding):
    """Each rank loads ITS shard of a multi-host packed checkpoint and
    re-places it onto the mesh -> (global array, turn, rule, word_axis).

    ``sharding`` is the target NamedSharding (parallel/bit_halo
    ``packed_sharding(mesh)``). Validates that the shard was written by a
    job of the same process count, that this rank's stored row offset
    matches where the sharding will place its local block, and (via the
    global shape) that the board geometry is unchanged. COLLECTIVE in a
    multi-process job: ranks allgather their shard turns and refuse a
    mixed set — resuming ranks from different turns would desynchronise
    every later collective (a crash between two ranks' shard renames can
    leave exactly that on disk)."""
    import jax

    rank, nprocs = jax.process_index(), jax.process_count()
    p = checkpoint_shard_path(path, rank, nprocs)
    if nprocs == 1 and not p.exists() and npz_path(path).exists():
        # single-process runs write the plain packed format (the state is
        # fully addressable, engine/_write_checkpoint's other branch) —
        # accept it here so one-host and pod checkpoints interoperate
        packed, turn, rule, word_axis = load_packed_checkpoint(npz_path(path))
        arr = jax.make_array_from_process_local_data(
            sharding, packed, packed.shape
        )
        return arr, turn, rule, word_axis
    # Per-rank load + validation is caught, NOT raised: one rank raising
    # here while its peers proceed into the collective below strands them
    # in the allgather — a distributed hang instead of a clean error
    # (ADVICE r4). Every rank always reaches the agreement crossing with
    # an ok/turn word, mirroring the save path's protocol.
    err = None
    rows = turn = rule = word_axis = gshape = None
    try:
        with np.load(p, allow_pickle=False) as data:
            if "packed" not in data or "row0" not in data:
                raise ValueError(f"{p} is not a sharded packed checkpoint")
            if int(data["num_processes"]) != nprocs:
                raise ValueError(
                    f"{p} was written by {int(data['num_processes'])} "
                    f"processes; this job has {nprocs}"
                )
            rows = data["packed"].astype(np.int32)
            row0 = int(data["row0"])
            word_axis = int(data["word_axis"])
            turn = int(data["turn"])
            rule = LifeRule.from_rulestring(str(data["rulestring"]))
            gshape = (int(data["global_rows"]), int(data["global_cols"]))
        idx_map = sharding.addressable_devices_indices_map(gshape)
        want_row0 = min(idx[0].start or 0 for idx in idx_map.values())
        if row0 != want_row0:
            raise ValueError(
                f"shard {p} holds rows from {row0} but this rank's mesh "
                f"placement starts at {want_row0}: process/mesh order "
                "changed since the checkpoint was written"
            )
    except Exception as exc:
        err = exc
    if nprocs > 1:
        from jax.experimental import multihost_utils

        word = np.array(
            [0, -1] if err is not None else [1, turn], dtype=np.int64
        )
        agreed = multihost_utils.process_allgather(word)  # (nprocs, 2)
        if err is not None:
            raise err
        failed = int(nprocs - agreed[:, 0].sum())
        if failed:
            raise ValueError(
                f"checkpoint load: shard validation failed on {failed} "
                f"other rank(s); the job cannot resume from {path}"
            )
        turns = agreed[:, 1]
        if int(turns.min()) != int(turns.max()):
            raise ValueError(
                f"checkpoint shards disagree on the turn "
                f"({int(turns.min())}..{int(turns.max())}): a crash "
                "between per-rank writes left a mixed set; restore from "
                "an older consistent checkpoint"
            )
    elif err is not None:
        raise err
    arr = jax.make_array_from_process_local_data(sharding, rows, gshape)
    return arr, turn, rule, word_axis


def load_packed_checkpoint(path) -> tuple[np.ndarray, int, LifeRule, int]:
    """-> (packed int32 array, turn, rule, word_axis) — the byte loader's
    (board, turn, rule) shape with word_axis appended, so the two loaders
    never swap the bare-int positions of turn and word_axis."""
    with np.load(path, allow_pickle=False) as data:
        if "packed" not in data:
            raise ValueError(
                f"{path} is a byte-board checkpoint; use load_checkpoint"
            )
        packed = data["packed"].astype(np.int32)
        word_axis = int(data["word_axis"])
        turn = int(data["turn"])
        rule = LifeRule.from_rulestring(str(data["rulestring"]))
    return packed, turn, rule, word_axis
