"""Checkpoint / resume — a superset of the reference's snapshot mechanism.

The reference's only "checkpoint" is the PGM snapshot ('s' writes
out/<W>x<H>x<Turns>.pgm, gol/distributor.go:78-90); there is no resume —
input is always images/<W>x<H>.pgm and the turn counter starts at 0
(SURVEY.md §5). Here a checkpoint carries the board, the turn counter, and
the rule, so a run can continue exactly where it stopped: bit-identical to
an uninterrupted run (tests/test_checkpoint.py).

Resume ≡ uninterrupted run is proven bit-identical by
tests/test_aux.py::test_resume_equals_uninterrupted_run.

Format: a plain .npz — board (uint8 [H, W]), turn (int), rulestring (str).
"""

from __future__ import annotations

import pathlib

import numpy as np

from ..models import CONWAY, LifeRule


def save_checkpoint(path, world, turn: int, rule: LifeRule = CONWAY) -> pathlib.Path:
    """Returns the path actually written: ``np.savez_compressed`` appends
    ``.npz`` whenever the name doesn't already end with it (so e.g.
    ``ck.backup`` lands at ``ck.backup.npz``)."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    np.savez_compressed(
        path,
        board=np.asarray(world, np.uint8),
        turn=np.int64(turn),
        rulestring=np.str_(rule.rulestring),
    )
    return path if path.suffix == ".npz" else path.with_name(path.name + ".npz")


def load_checkpoint(path) -> tuple[np.ndarray, int, LifeRule]:
    with np.load(path, allow_pickle=False) as data:
        board = data["board"].astype(np.uint8)
        turn = int(data["turn"])
        rule = LifeRule.from_rulestring(str(data["rulestring"]))
    return board, turn, rule
