"""Checkpoint / resume — a superset of the reference's snapshot mechanism.

The reference's only "checkpoint" is the PGM snapshot ('s' writes
out/<W>x<H>x<Turns>.pgm, gol/distributor.go:78-90); there is no resume —
input is always images/<W>x<H>.pgm and the turn counter starts at 0
(SURVEY.md §5). Here a checkpoint carries the board, the turn counter, and
the rule, so a run can continue exactly where it stopped: bit-identical to
an uninterrupted run (tests/test_checkpoint.py).

Resume ≡ uninterrupted run is proven bit-identical by
tests/test_aux.py::test_resume_equals_uninterrupted_run.

Format: a plain .npz — board (uint8 [H, W]), turn (int), rulestring (str).
"""

from __future__ import annotations

import pathlib

import numpy as np

from ..models import CONWAY, LifeRule


def npz_path(path) -> pathlib.Path:
    """The path ``np.savez_compressed`` actually writes: ``.npz`` is
    appended whenever the name doesn't already end with it (so e.g.
    ``ck.backup`` lands at ``ck.backup.npz``)."""
    path = pathlib.Path(path)
    return path if path.suffix == ".npz" else path.with_name(path.name + ".npz")


def _save_npz(path, **arrays) -> pathlib.Path:
    """Write a compressed npz, returning the path actually written."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    np.savez_compressed(path, **arrays)
    return npz_path(path)


def save_checkpoint(path, world, turn: int, rule: LifeRule = CONWAY) -> pathlib.Path:
    return _save_npz(
        path,
        board=np.asarray(world, np.uint8),
        turn=np.int64(turn),
        rulestring=np.str_(rule.rulestring),
    )


def load_checkpoint(path) -> tuple[np.ndarray, int, LifeRule]:
    with np.load(path, allow_pickle=False) as data:
        if "packed" in data:
            raise ValueError(
                f"{path} is a packed-bitboard checkpoint; use "
                "load_packed_checkpoint (unpacking a config-5-scale board "
                "to bytes would materialise 32x the state on host)"
            )
        board = data["board"].astype(np.uint8)
        turn = int(data["turn"])
        rule = LifeRule.from_rulestring(str(data["rulestring"]))
    return board, turn, rule


def save_packed_checkpoint(
    path, packed, turn: int, rule: LifeRule = CONWAY, word_axis: int = 0
) -> pathlib.Path:
    """Checkpoint a bit-packed board WITHOUT decoding it: the int32 words
    cross the device boundary once and land compressed on disk (a 65536^2
    board is 512 MiB packed vs 4 GiB as bytes — and a sparse one
    compresses to almost nothing). The reference has no analogue; this is
    the big-board (bigboard.py / BASELINE config 5) snapshot path."""
    return _save_npz(
        path,
        packed=np.asarray(packed, np.int32),
        word_axis=np.int64(word_axis),
        turn=np.int64(turn),
        rulestring=np.str_(rule.rulestring),
    )


def load_packed_checkpoint(path) -> tuple[np.ndarray, int, LifeRule, int]:
    """-> (packed int32 array, turn, rule, word_axis) — the byte loader's
    (board, turn, rule) shape with word_axis appended, so the two loaders
    never swap the bare-int positions of turn and word_axis."""
    with np.load(path, allow_pickle=False) as data:
        if "packed" not in data:
            raise ValueError(
                f"{path} is a byte-board checkpoint; use load_checkpoint"
            )
        packed = data["packed"].astype(np.int32)
        word_axis = int(data["word_axis"])
        turn = int(data["turn"])
        rule = LifeRule.from_rulestring(str(data["rulestring"]))
    return packed, turn, rule, word_axis
