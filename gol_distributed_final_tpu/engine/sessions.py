"""Device-batched sessions — many concurrent Run universes in ONE batch.

The engine (engine/engine.py) serves one board per run; production
traffic is millions of small, INDEPENDENT universes. A ``SessionTable``
packs up to ``capacity`` concurrent sessions of one geometry/rule into a
single device-resident batch tensor (ops/batched.py planes) and advances
them together: one dispatch steps every universe, one batched reduction
yields every universe's alive count, and each session's events
(AliveCellsCount, TurnComplete, FinalTurnComplete) demux from that
reduction — the existing controller/event contract holds per universe.

Lifecycle:

* ``admit(board, turns)`` — admission control: a capacity bound, the
  batch's fixed geometry, and a positive turn budget; refusals raise
  ``SessionRejected`` with a machine-readable ``reason`` (the
  ``gol_sessions_rejected_total{reason}`` label). Admitted universes
  join the batch at the next ``advance`` boundary.
* ``advance()`` — one driver iteration, called from a single driver
  thread: join pending universes, ONE batched dispatch of k turns
  (k = the smallest remaining budget, capped by ``max_chunk`` — the
  whole k-turn evolution runs inside the kernel family's own
  ``lax.fori_loop``, so the host touches the batch only at these
  boundaries), demux counts, retire finished universes by SLOT
  COMPACTION (a device gather keeps the batch dense — a finishing
  universe frees its slot without stalling the others).
* ``snapshot(session)`` — a per-session Retrieve: (world?, turn, alive)
  consistent with the committed batch state.
* ``cancel(session)`` — mid-batch leave; the slot compacts away at the
  next boundary.

Every per-universe result is bit-identical to a sequential single-board
run of the same rule: batching amortises the per-launch dispatch latency
(BENCH_r04: 128^2 is latency-bound at ~0.10 us/turn — no unroll can fix a
per-turn launch floor, N universes per launch can), it never changes the
arithmetic.

Concurrency model: ``admit`` / ``snapshot`` / ``cancel`` may be called
from any thread (RPC handlers); ``advance`` must be called from ONE
driver thread (rpc/broker.SessionScheduler owns it). The batch state and
every session's committed (turns_done, alive_count) move together under
one lock, so a snapshot never pairs a new turn with a stale count.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

import numpy as np

from ..events import AliveCellsCount, FinalTurnComplete, TurnComplete
from ..models import CONWAY, LifeRule
from ..obs import accounting as _acct
from ..obs import instruments as _ins
from ..obs import journal as _journal
from ..obs import metrics as _metrics
from ..obs import perf as _perf
from ..utils import locksan as _locksan

#: admission-refusal reasons — the stable label set of
#: ``gol_sessions_rejected_total`` (README "Sessions" section)
REJECT_REASONS = ("capacity", "geometry", "rule", "turns", "tag")


class SessionRejected(RuntimeError):
    """Admission refusal. ``reason`` is machine-readable (REJECT_REASONS);
    the message is the operator-facing detail."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


def reject(reason: str, message: str, tenant: str = "-") -> "SessionRejected":
    """Count + build one admission refusal (the single place the
    rejection counter increments, so scheduler-level refusals — rule
    mismatch, tag collision — meter identically to table-level ones).
    ``tenant`` rides into the lifecycle journal when the caller knows
    the accounting identity (the scheduler does; table-level geometry/
    turns refusals pass the admit-time tenant)."""
    _ins.SESSIONS_REJECTED_TOTAL.labels(reason).inc()
    _journal.record(
        "session.reject", reason, tenant=tenant, message=message[:200]
    )
    return SessionRejected(reason, message)


class Session:
    """One universe in the batch: its budget, progress, the latest
    demuxed alive count, and the completion handshake."""

    __slots__ = (
        "sid", "turns", "turns_done", "alive_count", "done", "result",
        "cancelled", "error", "on_event", "tenant",
    )

    def __init__(
        self,
        sid: int,
        turns: int,
        initial_turn: int,
        alive_count: int,
        on_event: Optional[Callable] = None,
        tenant: str = "-",
    ):
        self.sid = sid
        self.turns = turns  # the budget: total turns this session runs to
        self.turns_done = initial_turn
        self.alive_count = alive_count
        self.done = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.cancelled = False
        self.error: Optional[Exception] = None
        self.on_event = on_event
        # the accounting identity (obs/accounting.tenant_of of the
        # client-chosen session tag): every chunk this session rides
        # attributes its share of the dispatch wall to this tenant
        self.tenant = tenant

    @property
    def remaining(self) -> int:
        return max(0, self.turns - self.turns_done)


class SessionTable:
    """Up to ``capacity`` concurrent universes of ONE geometry/rule in a
    device-resident batch tensor (see module docstring)."""

    # the batch tensor and the session lists move together under _lock:
    # a snapshot must never pair a new turn with a stale count, and a
    # session must be findable in exactly one list at any instant
    # (machine-enforced: analysis/locks.py)
    _GUARDED_BY = {
        "_state": "_lock",
        "_active": "_lock",
        "_pending": "_lock",
        "_next_sid": "_lock",
    }

    def __init__(
        self,
        rule: LifeRule = CONWAY,
        shape: tuple[int, int] = (0, 0),
        capacity: int = 256,
        *,
        plane=None,
        max_chunk: int = 4096,
        retire_dead: bool = True,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_chunk < 1:
            raise ValueError(f"max_chunk must be >= 1, got {max_chunk}")
        self.rule = rule
        self.shape = tuple(shape)
        self.capacity = capacity
        self.max_chunk = max_chunk
        # early-retire all-dead universes: under a non-B0 rule a universe
        # whose batched alive count hit 0 can never change again, so its
        # remaining budget is credited arithmetically at the next advance
        # boundary instead of burning batched dispatches to the end
        # (gol_early_exit_total{kind="dead"}); FinalTurnComplete carries
        # the full budget turn and the (empty) final board, exactly like
        # a computed drain. B0 rules disable it — a dead board births.
        self.retire_dead = retire_dead and not (rule.birth_mask & 1)
        if plane is None:
            from ..ops.auto import auto_batch_plane

            plane = auto_batch_plane(rule, self.shape)
        self._plane = plane
        self._lock = _locksan.lock("SessionTable._lock")
        self._state = None  # device batch [n, ...]; row i <-> _active[i]
        self._active: List[Session] = []
        self._pending: List[tuple[Session, np.ndarray]] = []
        self._next_sid = 1

    # -- admission control ------------------------------------------------

    def admit(
        self,
        board,
        turns: int,
        on_event: Optional[Callable] = None,
        tenant: str = "-",
    ) -> Session:
        """Admission-controlled join. The universe enters the device batch
        at the next ``advance`` boundary; until then snapshots serve its
        seed board."""
        board = np.asarray(board, np.uint8)
        if board.shape != self.shape:
            raise reject(
                "geometry",
                f"session board is {board.shape}, this batch serves "
                f"{self.shape} (one geometry per batch)",
                tenant=tenant,
            )
        if turns < 1:
            raise reject(
                "turns", f"turn budget must be >= 1, got {turns}",
                tenant=tenant,
            )
        with self._lock:
            if len(self._active) + len(self._pending) >= self.capacity:
                raise reject(
                    "capacity",
                    f"session table full ({self.capacity} universes)",
                    tenant=tenant,
                )
            sess = Session(
                self._next_sid, turns, 0, int(np.count_nonzero(board)),
                on_event, tenant,
            )
            self._next_sid += 1
            self._pending.append((sess, board.copy()))
            _ins.SESSIONS_ADMITTED_TOTAL.inc()
            _ins.SESSIONS_ACTIVE.set(len(self._active) + len(self._pending))
        # journal outside the table lock: record() takes its own lock and
        # must never extend this hot critical section
        _journal.record("session.admit", str(sess.sid), turns=turns, tenant=tenant)
        return sess

    def cancel(self, sess: Session) -> None:
        """Mid-batch leave: the session retires (result=None) and its slot
        compacts away at the next advance boundary."""
        with self._lock:
            sess.cancelled = True

    @property
    def occupancy(self) -> int:
        with self._lock:
            return len(self._active) + len(self._pending)

    # -- the driver -------------------------------------------------------

    def advance(self) -> int:
        """One driver iteration (single driver thread — see module
        docstring). Returns the number of sessions still in the table."""
        # join: encode pending universes in one batched pack and append.
        # The pending entries are removed only in the SAME critical
        # section that makes their sessions active: a concurrent snapshot
        # must always find a session in exactly one of the two lists,
        # never in the gap between them. (admit only appends and advance
        # is single-threaded, so the grabbed prefix is stable.)
        t_adv0 = time.monotonic()
        attribution = _metrics.enabled() and _perf.attribution_enabled()
        with self._lock:
            pending = list(self._pending)
        if pending:
            new = self._plane.encode(np.stack([b for _, b in pending]))
            with self._lock:
                self._state = self._plane.append(self._state, new)
                self._active.extend(s for s, _ in pending)
                # gol: allow(atomicity): the grabbed prefix is stable by
                # the concurrency contract — admit only APPENDS and
                # advance is the single driver thread, so entries
                # [0, len(pending)) are exactly the ones encoded above
                del self._pending[: len(pending)]
        with self._lock:
            active = list(self._active)
            state = self._state
        if not active:
            _ins.SESSIONS_ACTIVE.set(0)
            return 0

        # one batched dispatch: k turns for every universe (k bounded by
        # the smallest remaining budget so no session oversteps; a
        # cancelled session contributes nothing to k and retires below)
        remaining = [s.remaining for s in active if not s.cancelled]
        k = min(min(remaining), self.max_chunk) if remaining else 0
        if k > 2:
            # k feeds the kernels' STATIC turn count, so stepping by the
            # raw min-remaining would compile a fresh program per
            # distinct budget value — an unbounded jit cache in a
            # long-lived broker, and a driver-thread compile stall for
            # every in-flight universe each time. Quantize down to a
            # power of two: the key set is exactly {1, 2, 4, ...,
            # max_chunk} per batch shape, a budget-T session drains in
            # <= log2(T) + 2 dispatches, and sessions still land on
            # their budgets exactly.
            k = 1 << (k.bit_length() - 1)
        t_chunk = time.monotonic()
        if k > 0 and hasattr(self._plane, "step_n_counts"):
            # the fused-K x batched chunk program (ops/fused.py via
            # ops/batched.py): the chunk's turns AND the per-universe
            # alive reduction in ONE dispatch — the serving hot path
            # pays one launch chain per chunk instead of step + count
            state, counts = self._plane.step_n_counts(state, k)
        else:
            if k > 0:
                state = self._plane.step_n(state, k)
            # ONE batched reduction; every per-session count demuxes from it
            counts = self._plane.alive_counts(state)
        dt_chunk = time.monotonic() - t_chunk  # the count transfer forces
        # the dispatch, so this is real time, not enqueue time
        if attribution:
            # dispatch-wall decomposition (obs/perf.py): join/encode of
            # pending universes is host_prep, the forced batched dispatch
            # is device_compute; demux (count fan-out, retirement,
            # compaction, event delivery) closes at the bottom
            _ins.TURN_SEGMENT_SECONDS.labels(
                "sessions", "host_prep"
            ).observe(max(0.0, t_chunk - t_adv0))
            _ins.TURN_SEGMENT_SECONDS.labels(
                "sessions", "device_compute"
            ).observe(dt_chunk)
        t_demux0 = time.monotonic()

        events: List[tuple[Session, object]] = []
        finished: List[int] = []
        advanced: List[str] = []  # tenant per universe this chunk advanced
        died: List[int] = []  # sids early-retired all-dead this chunk
        with self._lock:
            self._state = state
            for i, s in enumerate(active):
                if k > 0 and not s.cancelled:
                    s.turns_done += k
                    s.alive_count = int(counts[i])
                    advanced.append(s.tenant)
                    if s.on_event is not None:
                        events.append(
                            (s, AliveCellsCount(s.turns_done, s.alive_count))
                        )
                        events.append((s, TurnComplete(s.turns_done)))
                    if (
                        self.retire_dead
                        and s.alive_count == 0
                        and s.remaining > 0
                    ):
                        # all-dead universe: it can never change again
                        # (non-B0 rule), so credit the remaining budget
                        # arithmetically — the per-chunk batched count
                        # already proved there is nothing left to compute
                        s.turns_done = s.turns
                        _ins.EARLY_EXIT_TOTAL.labels("dead").inc()
                        died.append(s.sid)
                if s.cancelled or s.remaining == 0:
                    finished.append(i)
            if advanced:
                _ins.SESSION_TURNS_TOTAL.inc(k * len(advanced))
        if advanced:
            # the serving-latency objective (obs/slo.py session-turn-
            # latency rule): the chunk wall normalized per universe-turn,
            # count == universe-turns — and the per-tenant attribution
            # (obs/accounting.py): the SAME wall, split evenly. All
            # three meters derive from the ONE `advanced` list the lock
            # committed, so ledger turns reconcile with
            # gol_session_turns_total EXACTLY even when a cancel() races
            # the chunk boundary.
            m = len(advanced)
            _ins.SESSION_TURN_SECONDS.observe_n(dt_chunk / (k * m), k * m)
            _acct.ledger().record_chunk(advanced, k, dt_chunk)
            # ONE journal record per chunk (not per universe): the commit
            # the whole batch just made, with the dispatch route taken
            _journal.record(
                "chunk.commit", "sessions", k=k, universes=m,
                dt_s=round(dt_chunk, 6),
                route="fused" if hasattr(self._plane, "step_n_counts")
                else "plain",
            )
        for sid in died:
            _journal.record("early.exit", "dead", sid=sid)

        # retire + compact: ONE gather + ONE decode for every finishing
        # universe (a burst of equal budgets retiring together must not
        # pay a per-universe dispatch at the boundary — the very latency
        # this batch exists to amortise), then one device gather keeps
        # the surviving batch dense. KNOWN LIMIT: compaction shrinks the
        # batch's leading dimension, and B is a trace-time shape — under
        # staggered budgets each distinct (B, k) pair compiles once
        # (bounded by capacity x log2(max_chunk), but each a driver-
        # thread stall). Padded capacity buckets with dead-row masking
        # are the fix and are queued on the ROADMAP follow-ons.
        if finished:
            fin = set(finished)
            live = [i for i in finished if not active[i].cancelled]
            if live:
                decoded = self._plane.decode(self._plane.take(state, live))
                for j, i in enumerate(live):
                    # copy: the session's result must not pin the whole
                    # decoded burst alive after its siblings are collected
                    active[i].result = decoded[j].copy()
            keep = [i for i in range(len(active)) if i not in fin]
            with self._lock:
                self._state = (
                    self._plane.take(state, keep) if keep else None
                )
                # gol: allow(atomicity): only this single driver thread
                # ever REPLACES _active; the earlier snapshot can only
                # trail it by appends-via-_pending, which stay pending
                # until the next advance — the compacted list is exact
                self._active = [active[i] for i in keep]
                left = len(self._active) + len(self._pending)
                _ins.SESSIONS_ACTIVE.set(left)
            for i in finished:
                s = active[i]
                if not s.cancelled:
                    _journal.record(
                        "session.final", str(s.sid), turn=s.turns_done,
                        tenant=s.tenant,
                    )
                if s.on_event is not None and not s.cancelled:
                    from ..ops import alive_cells

                    events.append(
                        (s, FinalTurnComplete(s.turns_done, alive_cells(s.result)))
                    )
        else:
            with self._lock:
                left = len(self._active) + len(self._pending)
                _ins.SESSIONS_ACTIVE.set(left)

        # callbacks outside the lock: user code must not hold the table
        for s, ev in events:
            try:
                s.on_event(ev)
            # gol: allow(hygiene): an observer callback must never stall
            # the batch, and this runs per event in the serving hot loop —
            # too hot for per-failure logging
            except Exception:
                pass
        # completion LAST: a waiter woken by done must find every event —
        # FinalTurnComplete included — already delivered
        if finished:
            for i in finished:
                active[i].done.set()
        if attribution and (advanced or finished):
            _ins.TURN_SEGMENT_SECONDS.labels("sessions", "demux").observe(
                time.monotonic() - t_demux0
            )
        return left

    def fail_all(self, exc: Exception) -> None:
        """Driver-crash path: every session in the table completes with
        ``error`` set (its waiter re-raises) instead of hanging forever."""
        with self._lock:
            sessions = [s for s in self._active]
            sessions += [s for s, _ in self._pending]
            self._active, self._pending, self._state = [], [], None
            _ins.SESSIONS_ACTIVE.set(0)
        _journal.record(
            "integrity.fail", "sessions.fail_all",
            error_kind=type(exc).__name__, sessions=len(sessions),
        )
        for s in sessions:
            s.error = exc
            s.done.set()

    # -- per-session retrieve ---------------------------------------------

    def snapshot(self, sess: Session, include_world: bool = False):
        """Per-session Retrieve: ``(world | None, turns_done, alive)`` at
        the committed batch state — the same consistency contract as the
        engine's retrieve (count and turn move together)."""
        with self._lock:
            if sess.done.is_set() or sess not in self._active:
                for p, board in self._pending:
                    if p is sess:
                        world = board.copy() if include_world else None
                        return world, sess.turns_done, sess.alive_count
                world = sess.result if include_world else None
                return world, sess.turns_done, sess.alive_count
            row = self._active.index(sess)
            state = self._state
            turn, alive = sess.turns_done, sess.alive_count
        world = self._plane.decode_one(state, row) if include_world else None
        return world, turn, alive
