"""The controller — the reference distributor + ticker, re-founded on queues.

Mirrors gol/distributor.go behavior exactly at the event level:

* load ``images/<W>x<H>.pgm``, make the blocking Run call, then emit
  ``FinalTurnComplete`` -> write ``out/<W>x<H>x<Turns>.pgm`` ->
  ``ImageOutputComplete`` -> ``StateChange{Quitting}`` -> close the stream
  (gol/distributor.go:131-185);
* a ticker thread that every 2 s retrieves a snapshot and emits
  ``AliveCellsCount`` (suppressed while paused) and that dispatches
  keypresses with the reference's exact semantics — including the
  ``TurnsCompleted - 1`` quirk on resume (gol/distributor.go:118)
  (gol/distributor.go:25-129).

The events channel is a ``queue.Queue``; stream end is signalled by the
``CLOSED`` sentinel (the Go ``close(events)`` equivalent). ``iter_events``
adapts a queue to a plain iterator for consumers and tests.
"""

from __future__ import annotations

import queue
import threading
import time

from ..events import (
    AliveCellsCount,
    FinalTurnComplete,
    ImageOutputComplete,
    Quitting,
    StateChange,
    State,
)
from ..io.pgm import read_board, write_board
from ..models import CONWAY
from ..obs import flight as _flight
from ..obs import instruments as _ins
from ..obs import metrics as _metrics
from ..obs import tracing as _tracing
from .engine import Engine, EngineConfig, RunResult

CLOSED = object()
"""Sentinel marking the end of an event stream (Go's close(events))."""


def _emit(events: "queue.Queue", ev) -> None:
    """events.put with per-event-type observability (obs/instruments.py):
    emit latency + a count by event class. A flag check when metrics are
    off — the CLOSED sentinel stays a raw put (it is stream plumbing,
    not an event)."""
    if not _metrics.enabled():
        events.put(ev)
        return
    t0 = time.monotonic()
    events.put(ev)
    _ins.CONTROLLER_EMIT_SECONDS.observe(time.monotonic() - t0)
    _ins.CONTROLLER_EVENTS_TOTAL.labels(type(ev).__name__).inc()


def iter_events(q: "queue.Queue", timeout: float | None = None):
    """Drain an event queue until the CLOSED sentinel.

    ``timeout`` bounds the wait for each *individual* event; if it expires,
    ``queue.Empty`` propagates (a stalled producer is a bug worth surfacing,
    not silently ending the stream). ``timeout=None`` blocks indefinitely.
    """
    while True:
        ev = q.get(timeout=timeout)  # timeout=None blocks, like Go's <-ch
        if ev is CLOSED:
            return
        yield ev


class InProcessBroker:
    """The broker surface (stubs/stubs.go verbs) served by a same-process
    Engine — the default backend when no remote server is given."""

    def __init__(self, engine: Engine | None = None):
        if engine is not None and not engine.config.final_world:
            # fail BEFORE a session runs for hours: this surface writes
            # the final PGM from the decoded world
            raise ValueError(
                "the session controller needs a world-shipping engine "
                "(final_world=True); final_world=False belongs to the "
                "bigboard surface"
            )
        self.engine = engine or Engine()

    def run(
        self,
        params,
        world,
        *,
        emit=None,
        emit_flips=False,
        initial_turn=0,
        rule=None,
        halo_depth=0,
    ) -> RunResult:
        if halo_depth:
            # accepted-and-rejected cleanly (like a mismatched rule), not
            # a TypeError mid-session: the knob belongs to mesh-backed
            # remote brokers, not the in-process engine
            raise ValueError(
                "halo_depth needs a mesh-backed broker (e.g. RemoteBroker "
                "to a tpu-backend server); the in-process engine has no "
                "mesh-plane knob"
            )
        if rule is not None and rule.rulestring != self.engine.config.rule.rulestring:
            # a resumed checkpoint's rule must match the engine it resumes
            # on — for the in-process path the session builds the engine
            # from the checkpoint, so a mismatch means a caller-supplied
            # engine configured differently
            raise ValueError(
                f"checkpoint rule {rule.rulestring} does not match the "
                f"engine's {self.engine.config.rule.rulestring}"
            )
        return self.engine.run(
            params,
            world,
            emit=emit,
            emit_flips=emit_flips,
            initial_turn=initial_turn,
        )

    def pause(self):
        return self.engine.pause()

    def quit(self):
        return self.engine.quit()

    def super_quit(self):
        return self.engine.super_quit()

    def retrieve(self, include_world: bool = True):
        return self.engine.retrieve(include_world=include_world)


class _Ticker:
    """The tickerFunc equivalent (gol/distributor.go:25-129): one thread
    multiplexing the 2 s tick, the keypress stream, and shutdown."""

    _POLL = 0.02

    def __init__(
        self, params, events, keypresses, broker, out_dir, tick_seconds,
        trace_parent=None,
    ):
        self.params = params
        self.events = events
        self.keypresses = keypresses
        self.broker = broker
        self.out_dir = out_dir
        self.tick_seconds = tick_seconds
        # the session span's context: tick/key spans run on THIS thread,
        # where the session's thread-local stack is invisible, so the
        # parent must be explicit for the whole session to be one trace
        self._trace_parent = trace_parent
        self.done = threading.Event()
        self.paused = False
        self._last_turn = 0  # last turn seen by any successful retrieve
        self._tick_failures = 0  # consecutive, for broker-outage log pacing
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def stop(self):
        self.done.set()
        self._thread.join()

    def _snapshot_to_pgm(self):
        snap = self.broker.retrieve()
        write_board(snap.world, self.params.output_filename, self.out_dir)
        return snap

    def _try_snapshot_turn(self) -> int:
        """Snapshot-to-PGM for the q/k paths, degrading to a count-only
        turn read, then to the last tick's turn: quitting must never be
        blocked by a broken snapshot OR a dead broker — if this raised,
        done.set()/quit() would be skipped and the session could never be
        quit from the keyboard."""
        try:
            turn = self._snapshot_to_pgm().turns_completed
            self._last_turn = turn
            return turn
        except Exception as exc:
            print(f"final snapshot failed: {exc}")
        try:
            turn = self.broker.retrieve(include_world=False).turns_completed
            self._last_turn = turn
            return turn
        except Exception as exc:
            print(f"turn read failed: {exc}")
            return self._last_turn

    def _loop(self):
        next_tick = time.monotonic() + self.tick_seconds
        while not self.done.is_set():
            key = None
            if self.keypresses is not None:
                try:
                    key = self.keypresses.get_nowait()
                except queue.Empty:
                    key = None
            if key is not None:
                # gated like every other site: metrics off = no clock
                # reads, no label-child allocation
                t_key = time.monotonic() if _metrics.enabled() else 0.0
                key_span = _tracing.start_span(
                    _tracing.SPAN_CONTROLLER_KEY,
                    parent_ctx=self._trace_parent,
                    key=key,
                )
                try:
                    self._handle_key(key)
                except Exception as exc:
                    # the control thread must survive a failed key action
                    # (e.g. a snapshot ValueError from an exotic broker):
                    # dying here silently kills the 2 s tick AND q/k/p
                    print(f"key '{key}' failed: {exc}")
                finally:
                    _tracing.end_span(key_span)
                    if t_key:
                        _ins.CONTROLLER_KEY_SECONDS.labels(key).observe(
                            time.monotonic() - t_key
                        )
                continue
            if time.monotonic() >= next_tick:
                # re-anchor rather than increment: after a long keypress
                # handler (PGM write, compile stall) we coalesce missed
                # ticks like Go's time.Ticker instead of bursting them
                next_tick = time.monotonic() + self.tick_seconds
                # count-only snapshot: a device-side reduction, no full-board
                # device->host copy on the tick path
                t_tick = time.monotonic() if _metrics.enabled() else 0.0
                tick_span = _tracing.start_span(
                    _tracing.SPAN_CONTROLLER_TICK,
                    parent_ctx=self._trace_parent,
                )
                try:
                    snap = self.broker.retrieve(include_world=False)
                except Exception as exc:
                    # a raising tick must not kill the control thread —
                    # keypresses (including 'q') still need servicing. A
                    # broker outage means one failure every tick: log the
                    # first and then every 10th (a reconnecting broker
                    # handle recovers on its own — see RpcClient), and
                    # leave each failure in the flight ring so the outage
                    # window is reconstructable post-mortem.
                    self._tick_failures += 1
                    _flight.record(
                        "controller.tick_error", type(exc).__name__,
                        consecutive=self._tick_failures, message=str(exc)[:200],
                    )
                    if self._tick_failures == 1 or self._tick_failures % 10 == 0:
                        print(
                            f"tick retrieve failed "
                            f"(x{self._tick_failures}): {exc}"
                        )
                    continue
                finally:
                    _tracing.end_span(tick_span)
                self._tick_failures = 0
                if t_tick:
                    _ins.CONTROLLER_TICK_SECONDS.observe(
                        time.monotonic() - t_tick
                    )
                self._last_turn = snap.turns_completed
                if not self.paused and not self.done.is_set():
                    _emit(
                        self.events,
                        AliveCellsCount(snap.turns_completed, snap.alive_count),
                    )
                continue
            time.sleep(self._POLL)

    def _handle_key(self, key):
        # gol/distributor.go:61-122
        if key == "q":
            turn = self._try_snapshot_turn()
            _emit(self.events, StateChange(turn, Quitting))
            self.done.set()
            self.broker.quit()
        elif key == "s":
            print(self.params.output_filename)
            self._snapshot_to_pgm()
        elif key == "k":
            turn = self._try_snapshot_turn()
            _emit(self.events, StateChange(turn, Quitting))
            self.done.set()
            self.broker.super_quit()
        elif key == "p":
            snap = self.broker.retrieve(include_world=False)
            self._last_turn = snap.turns_completed
            # pause() BEFORE the StateChange: if the broker call raises,
            # no Paused/Executing event has been emitted yet — otherwise
            # the printed state and the engine state silently disagree
            if not self.paused:
                self.broker.pause()
                _emit(self.events, StateChange(snap.turns_completed, State.PAUSED))
                self.paused = True
            else:
                self.broker.pause()
                # the reference reports one turn fewer on resume
                # (gol/distributor.go:118) — preserved for parity
                _emit(
                    self.events,
                    StateChange(snap.turns_completed - 1, State.EXECUTING),
                )
                self.paused = False


def run(
    params,
    events: "queue.Queue | None" = None,
    keypresses: "queue.Queue | None" = None,
    *,
    broker=None,
    rule=None,
    engine_config: EngineConfig | None = None,
    emit_flips: bool = False,
    images_dir="images",
    out_dir="out",
    tick_seconds: float = 2.0,
    resume_from=None,
    halo_depth: int = 0,
    report: bool = False,
) -> RunResult:
    """Run a full Game of Life session (gol.Run + distributor, gol/gol.go:12).

    Blocking; returns the engine's RunResult. Events are pushed to ``events``
    (created if None), ending with the CLOSED sentinel. ``keypresses`` is an
    optional queue of single-character commands ('s', 'q', 'k', 'p').

    ``broker`` selects the backend: None for an in-process engine, or any
    object with the stubs verb surface (e.g. rpc.client.RemoteBroker).

    ``resume_from`` continues from a checkpoint (engine/checkpoint.py)
    instead of loading images/<W>x<H>.pgm at turn 0 — the capability the
    reference lacks (SURVEY.md §5 checkpoint/resume). Either a path (the
    file is verified-or-refused here) or an already-verified
    ``(board, turn, rule)`` tuple as returned by
    ``load_verified_checkpoint`` — callers that verify early (the
    ``-resume`` CLI) pass the result through so the file is read and
    hashed once.

    ``halo_depth`` (0 = backend default) ships the wide-halo depth to a
    remote broker — the tpu backend's mesh planes, or the workers
    backend's resident batch depth K (``-wire resident``: K turns per
    StripStep round-trip) — the DCN lever on the session surface
    (VERDICT r4 item 5). Only meaningful with ``broker=``.

    Snapshot/pause semantics hold across every remote data plane: a
    resident-wire broker re-syncs its workers' strips before answering a
    full-world Retrieve (the 's' snapshot path) and before parking on
    Pause, so this controller needs no mode awareness — the ticker's
    count-only retrieve is served from the per-step alive counts the
    StripStep replies carry.

    ``report`` writes a RunReport (obs/report.py: the metrics registry +
    device inventory) to ``out_dir/report_<W>x<H>x<Turns>.json`` at
    ``FinalTurnComplete`` — the ``-report`` CLI flag. The registry must be
    enabled (``obs.metrics.enable()``; the flag does it) for the report to
    carry timings; a report failure is printed, never fatal.
    """
    initial_turn = 0
    ckpt_rule = None
    if resume_from is not None:
        if isinstance(resume_from, tuple):
            ckpt_world, initial_turn, ckpt_rule = resume_from
        else:
            # verified-or-refused (engine/checkpoint.py): a truncated,
            # corrupt, or digest-less file is a typed, actionable
            # CheckpointError here — never a raw zipfile/KeyError
            # traceback, and never a silently resumed wrong board
            from .checkpoint import load_verified_checkpoint

            ckpt_world, initial_turn, ckpt_rule = load_verified_checkpoint(
                resume_from
            )
        if ckpt_world.shape != (params.image_height, params.image_width):
            raise ValueError(
                f"checkpoint board is {ckpt_world.shape[1]}x"
                f"{ckpt_world.shape[0]} but params say "
                f"{params.image_width}x{params.image_height}: the output "
                "filename and visualiser window would mislabel the board"
            )
        if params.turns <= initial_turn:
            raise ValueError(
                f"turns={params.turns} is not beyond the checkpoint's "
                f"turn {initial_turn}: nothing would run, yet the output "
                f"would be named ...x{params.turns}.pgm"
            )

    if events is None:
        events = queue.Queue()
    if engine_config is None:
        engine_config = EngineConfig(
            rule=rule if rule is not None else (ckpt_rule or CONWAY)
        )
    elif rule is not None:
        raise ValueError(
            "pass the rule inside engine_config (EngineConfig(rule=...)); "
            "the separate rule= argument would be silently ignored"
        )
    if broker is None:
        broker = InProcessBroker(Engine(engine_config))

    ticker = None
    t_session = time.monotonic()
    # the session root span (obs/tracing.py, one flag check when -trace is
    # off): every tick, keypress, RPC, and remote engine chunk of this
    # session parents under it — one trace_id across all processes
    session_span = _tracing.start_span(
        _tracing.SPAN_CONTROLLER_SESSION,
        turns=params.turns,
        board=f"{params.image_width}x{params.image_height}",
    )
    try:
        world = ckpt_world if resume_from is not None else read_board(params, images_dir)
        ticker = _Ticker(
            params, events, keypresses, broker, out_dir, tick_seconds,
            trace_parent=session_span.ctx() if session_span else None,
        )
        ticker.start()
        # a non-default rule rides along to the broker — from a resumed
        # checkpoint or an explicit session rule — so a remote backend
        # cannot silently evolve the wrong family. Only passed when set:
        # brokers are duck-typed and plain-Conway fakes need not know the
        # kwarg
        if (
            rule is not None
            and ckpt_rule is not None
            and rule.rulestring != ckpt_rule.rulestring
        ):
            raise ValueError(
                f"rule={rule.rulestring} conflicts with the checkpoint's "
                f"{ckpt_rule.rulestring}: a resumed board must continue "
                "under the rule it was evolved with"
            )
        wire_rule = ckpt_rule if ckpt_rule is not None else rule
        if (
            wire_rule is None
            and engine_config.rule.rulestring != CONWAY.rulestring
        ):
            wire_rule = engine_config.rule
        extra = {} if wire_rule is None else {"rule": wire_rule}
        if halo_depth:
            # only when set, like rule: brokers are duck-typed and the
            # in-process engine has no mesh-plane knob to turn
            extra["halo_depth"] = halo_depth
        result = broker.run(
            params,
            world,
            emit=events.put if emit_flips else None,
            emit_flips=emit_flips,
            initial_turn=initial_turn,
            **extra,
        )
        # join the ticker BEFORE the closing sequence so no stray
        # AliveCellsCount can interleave after StateChange{Quitting}
        ticker.stop()
        if result.world is None:
            raise ValueError(
                "the session contract writes the final PGM from the world; "
                "a final_world=False engine belongs to the bigboard surface"
            )
        _emit(events, FinalTurnComplete(result.turns_completed, result.alive))
        if _tracing.enabled():
            # close the session span FIRST so it lands in the export, then
            # write the Chrome trace: local spans + whatever the broker's
            # Status verb ships back (its own spans, and — through a
            # workers-backend broker's aggregation — each worker's). One
            # file, one named track per process, Perfetto-loadable. A
            # failed export must not fail the session it describes.
            _tracing.end_span(session_span)
            session_span = None
            try:
                spans = _tracing.tracer().snapshot()
                status_fn = getattr(broker, "status", None)
                if callable(status_fn):
                    payload = status_fn()
                    spans.extend(payload.get("trace_spans") or [])
                # -timeline: counter tracks (throughput, HBM, queue
                # depth) land on the same Perfetto timeline as the spans
                from ..obs import timeline as _timeline

                sampler = _timeline.sampler()
                counters = (
                    sampler.chrome_counter_samples() if sampler else ()
                )
                path = _tracing.write_chrome_trace(
                    _tracing.trace_path(params, out_dir), spans, counters
                )
                print(f"chrome trace written to {path}")
            except Exception as exc:
                print(f"trace export failed: {exc}")
        if report:
            # the run's attribution artifact, dumped at FinalTurnComplete;
            # a failed dump must not fail the session it describes
            try:
                from ..obs.report import write_run_report

                path = write_run_report(
                    params,
                    out_dir,
                    wall_seconds=time.monotonic() - t_session,
                    extra={"turns_completed": result.turns_completed},
                )
                print(f"run report written to {path}")
            except Exception as exc:
                print(f"run report failed: {exc}")
        write_board(result.world, params.output_filename, out_dir)
        _emit(
            events,
            ImageOutputComplete(result.turns_completed, params.output_filename),
        )
        _emit(events, StateChange(result.turns_completed, Quitting))
        return result
    finally:
        # None when already closed for the export above; ends the error
        # paths' span so the thread-local stack cannot wedge across runs
        _tracing.end_span(session_span)
        if ticker is not None:
            ticker.done.set()
        # the stream must always terminate, even on error — a consumer
        # blocked on iter_events would otherwise hang forever
        events.put(CLOSED)
