"""The GoL engine — the broker's ``Operations`` service re-founded on a
device-resident board.

The reference broker runs a host-side per-turn loop that re-ships the full
board to every worker over TCP each turn and gathers strips back
(broker/broker.go:62-234). Here the board never leaves the device during
compute: the engine dispatches *chunks* of turns as single compiled
``lax.fori_loop`` programs (ops/stencil.step_n) and services control traffic
— pause / quit / snapshot, the semantics of broker/broker.go:236-277 —
between dispatches. Chunks grow by doubling (bounded compile count) and are
capped by a wall-clock target so the 2-second alive-count cadence and the
5-second first-report liveness bound (count_test.go:30-38) hold regardless
of board size.

Concurrency model: ``run`` executes on the caller's thread; ``pause`` /
``quit`` / ``super_quit`` / ``retrieve`` may be called from any other thread
(the controller's ticker, an RPC handler). The board snapshot is guarded by
a lock, like the broker's ``cWorld``/``cTurn`` under ``mt sync.Mutex``
(broker/broker.go:32-36).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, List, NamedTuple, Optional

import numpy as np

# max async chunk dispatches outstanding before the run loop blocks: deep
# enough that typical runs (and the bench's 2-chunk marginal) never pay a
# synchronous round-trip, shallow enough that at most 4 chunk outputs are
# ever live on device (4 x 512 MiB at the 65536^2 scale) and a retrieve
# observes a state at most ~depth dispatch-targets old
_PIPELINE_DEPTH = 3

from ..events import CellFlipped, TurnComplete
from ..models import CONWAY, LifeRule
from ..obs import device as _device
from ..obs import flight as _flight
from ..obs import instruments as _ins
from ..obs import journal as _journal
from ..obs import metrics as _metrics
from ..obs import perf as _perf
from ..obs import timeline as _timeline
from ..obs import tracing as _tracing
from ..ops import alive_cells
from ..utils import locksan as _locksan
from ..utils.cell import Cell


class Snapshot(NamedTuple):
    """What ``RetrieveCurrentData`` returns (broker/broker.go:256-277).
    ``world`` is None for count-only snapshots (retrieve(include_world=False))."""

    world: Optional[np.ndarray]
    turns_completed: int
    alive_count: int

    @property
    def alive(self) -> List[Cell]:
        return [] if self.world is None else alive_cells(self.world)


class RunResult:
    """What ``Operations.Run`` returns (broker/broker.go:228-230).

    ``alive`` is derived on first access, so paths that never read the
    cell list don't materialise O(alive) Python Cell objects — ~5M tuples
    for a dense 4096^2 board. The derivation source is ``world`` when the
    run decoded one, else the final plane state (a ``final_world=False``
    run, where the byte raster must never exist: cells come from the
    plane's sparse extraction)."""

    __slots__ = (
        "turns_completed",
        "world",
        "checkpoint_error",
        "_alive",
        "_state",
        "_plane",
    )

    def __init__(
        self,
        turns_completed: int,
        world: Optional[np.ndarray],
        alive: Optional[List[Cell]] = None,
        state=None,
        plane=None,
        checkpoint_error: Optional[Exception] = None,
    ):
        self.turns_completed = turns_completed
        self.world = world
        # non-fatal: the last periodic-checkpoint failure of ANY type, not
        # just OSError — the run itself completed (a disk-full must not
        # abort the multi-hour run checkpointing exists to protect,
        # ADVICE.md round 3; and a non-OSError shard-write failure must
        # take the same continue path on every rank, ADVICE r5)
        self.checkpoint_error = checkpoint_error
        self._alive = alive
        self._state = state
        self._plane = plane

    @property
    def alive(self) -> List[Cell]:
        if self._alive is None:
            if self.world is not None:
                self._alive = alive_cells(self.world)
            elif hasattr(self._plane, "alive_cells"):
                self._alive = self._plane.alive_cells(self._state)
            else:
                # planes only implementing the documented duck-typed core
                # (ops/plane.py:12-17) fall back through decode
                self._alive = alive_cells(self._plane.decode(self._state))
        return self._alive

    @property
    def alive_count(self) -> int:
        """The live-cell total WITHOUT materialising the O(alive) Cell
        list — a device-side popcount for plane-state results. What the
        big-board CLI prints (a dense 65536^2 board would otherwise build
        billions of Cell objects; ADVICE.md round 3)."""
        if self._alive is not None:
            return len(self._alive)
        if self.world is not None:
            return int(np.count_nonzero(self.world))
        if hasattr(self._plane, "alive_count"):
            return int(self._plane.alive_count(self._state))
        return len(self.alive)


@dataclasses.dataclass
class EngineConfig:
    rule: LifeRule = CONWAY
    # chunking: double from min_chunk up to max_chunk, but stop growing once
    # a dispatch exceeds target_dispatch_seconds (keeps control latency low);
    # long headless runs can raise min_chunk to skip the warm-up doublings
    min_chunk: int = 1
    max_chunk: int = 4096
    target_dispatch_seconds: float = 0.25
    # optional override: a board -> board step (e.g. a sharded halo step from
    # parallel/halo.py, or the pallas kernel); must preserve dtype/shape
    step_n_fn: Optional[Callable] = None  # (board, n) -> board
    # optional override: a full data plane (ops/plane.py interface) — e.g. a
    # mesh-sharded bitboard (parallel/bit_halo.ShardedBitPlane); the board
    # stays in the plane's representation across chunk dispatches
    plane: Optional[object] = None
    # pick the fastest correct data plane automatically (ops/auto.py):
    # the bitboard plane (pallas VMEM kernel under its VMEM gate) for
    # 32-divisible boards
    auto_fast: bool = True
    # False: RunResult ships world=None and derives `alive` through the
    # plane's sparse extraction instead of decoding the final board — the
    # config-5 setting, where decoding would materialise a 4 GiB raster
    final_world: bool = True
    # periodic crash-recovery checkpoints: every time the turn counter
    # crosses a multiple of checkpoint_every, the committed state is
    # written to checkpoint_path between chunk dispatches (packed .npz
    # for bitboard planes — no decode — else the byte format). The
    # reference has only the manual 's' snapshot (gol/distributor.go:78).
    checkpoint_every: int = 0  # 0: disabled
    checkpoint_path: Optional[str] = None
    # called between chunk dispatches as chunk_hook(engine, state, turn) —
    # the multi-host control plane's gate (parallel collectives, keypress
    # broadcast, coordinated pause; see pod.py). Every rank of an SPMD job
    # reaches the hook at the same (turn) sequence because multi-host
    # chunk growth is deterministic (see run()).
    chunk_hook: Optional[Callable] = None


class Engine:
    """Evolves one board; serves Run/Pause/Quit/SuperQuit/Retrieve."""

    def __init__(self, config: EngineConfig | None = None):
        self.config = config or EngineConfig()
        self._lock = _locksan.lock("Engine._lock")
        self._control = _locksan.condition("Engine._control", self._lock)
        # the device-resident board in its plane's representation (e.g. a
        # packed bitboard), owned by the run loop; kept after a run ends so
        # Retrieve keeps serving the final snapshot (the cWorld analogue)
        self._state = None
        self._plane = None
        # the committed state's on-device alive fold (ops/fused.py
        # step_n_counted protocol): a device vector whose int64 host sum
        # is the alive count — set by counted chunk commits, cleared by
        # anything else, so the count-only Retrieve ticker never pays a
        # reduction dispatch while a fused plane is driving
        self._state_counts = None
        self._world_host: np.ndarray | None = None  # last synced host copy
        self._host_dirty = False
        self._turn = 0
        self._paused = False
        self._parked = False  # run loop is actually waiting in the pause gate
        self._quit = False
        self._super_quit = False
        self._running = False

    # -- compute ----------------------------------------------------------

    def _choose_plane(self, world_shape, step_n_fn, plane, emit_flips):
        """Per-run plane selection: explicit plane > explicit step fn >
        config plane > config step fn > auto bitboard > byte stencil."""
        from ..ops.plane import BytePlane

        rule = self.config.rule
        if plane is not None:
            return plane
        if step_n_fn is not None:
            return BytePlane(rule, step_n_fn)
        if self.config.plane is not None:
            return self.config.plane
        if self.config.step_n_fn is not None:
            return BytePlane(rule, self.config.step_n_fn)
        if self.config.auto_fast and not emit_flips:
            from ..ops.auto import auto_plane

            fast = auto_plane(rule, world_shape)
            if fast is not None:
                return fast
        return BytePlane(rule)

    def _sync_host(self):
        """Refresh the host snapshot from the device state (under lock)."""
        if self._host_dirty and self._state is not None:
            self._world_host = self._plane.decode(self._state)
            self._host_dirty = False

    # -- Operations.Run (broker/broker.go:62-234) -------------------------

    def run(
        self,
        params,
        world: Optional[np.ndarray],
        *,
        emit: Optional[Callable] = None,
        emit_flips: bool = False,
        step_n_fn: Optional[Callable] = None,
        plane=None,
        initial_turn: int = 0,
        initial_state=None,
    ) -> RunResult:
        """Blocking: evolve ``world`` for ``params.turns`` turns (or until
        quit). Resets the turn counter — a reattaching controller starts a
        fresh run, the reference's detach/reattach semantics (README.md:187,
        broker/broker.go:64).

        With ``emit_flips`` (single-host visualiser mode) every turn emits
        ``CellFlipped`` for each changed cell before ``TurnComplete``
        (gol/event.go:50-60) — including the initial flips for cells alive
        in the loaded image.

        ``initial_state`` starts the run from a state already in
        ``plane``'s representation (``world`` must be None, ``plane``
        explicit): the board never exists as bytes on entry — the
        config-5 path, where the byte raster would be 4 GiB. Pair with
        ``EngineConfig.final_world=False`` so the exit side stays
        byte-free too.
        """
        if initial_state is not None:
            if world is not None or emit_flips:
                raise ValueError(
                    "initial_state replaces world (pass world=None) and "
                    "cannot emit per-cell flips"
                )
            if plane is None:
                raise ValueError(
                    "initial_state needs an explicit plane: the engine "
                    "cannot infer the representation from a byte board"
                )
            if self.config.final_world:
                raise ValueError(
                    "initial_state requires EngineConfig(final_world=False): "
                    "the default run exit would decode the full byte raster "
                    "the packed entry exists to avoid (decode explicitly "
                    "before run() if bytes are genuinely wanted)"
                )
        else:
            # defensive copy: the caller may reuse its buffer, and we hand
            # this array out via retrieve()/emit_flips diffs
            world = np.array(world, np.uint8, copy=True)
            world.flags.writeable = False
        with self._lock:
            if self._running:
                raise RuntimeError("engine is already running")
            self._running = True
            # per-run plane selection happens only after the already-running
            # check, so a rejected concurrent run can't clobber the active
            # run's representation
            self._state_counts = None  # a fresh run has no folded count yet
            if initial_state is not None:
                self._plane = plane
                self._state = initial_state
                self._world_host = None
                self._host_dirty = True  # decode on demand (Retrieve world)
            else:
                self._plane = self._choose_plane(
                    world.shape, step_n_fn, plane, emit_flips
                )
                self._state = self._plane.encode(world)
                self._world_host = world
                self._host_dirty = False
            # 0 for a fresh run (the reference's reset-on-Run semantics,
            # broker/broker.go:64); a checkpoint's turn for a resume
            self._turn = initial_turn
            # _quit/_paused are NOT reset here: a quit() or pause() issued
            # after the controller started its ticker but before the run
            # loop initialised must still take effect (they are consumed /
            # cleared when this run ends)

        # pre-run HBM baseline, ONCE PER RUN: even a run that dies in its
        # first chunk leaves the pre-run occupancy on the gauges, and the
        # first turn-chunk sample then shows the step's delta. This lives
        # HERE rather than in ops/auto.py because tier selection is now
        # cached per (rule, shape) — a repeat-geometry run would otherwise
        # inherit the previous run's end-state as its "baseline"
        _device.sample_hbm()

        # a multi-host (SPMD) run: every rank executes this same loop and
        # every jax collective must be issued in the same order on every
        # rank — so chunk growth must not depend on rank-local wall clocks
        multihost = not getattr(self._state, "is_fully_addressable", True)
        if multihost and self.config.checkpoint_every:
            # packed planes checkpoint per-rank shards; anything else has
            # no multi-host checkpoint format — fail at entry, not hours in
            if getattr(self._plane, "word_axis", None) is None:
                with self._lock:
                    self._running = False
                    self._control.notify_all()
                raise ValueError(
                    "checkpoint_every on a multi-host state needs a packed "
                    "bitboard plane (per-rank shard checkpoints); this "
                    "plane has no word_axis"
                )

        try:
            _journal.record(
                "run.start", "engine", turns=int(params.turns),
                initial_turn=initial_turn,
            )
            if emit_flips and emit is not None:
                for c in alive_cells(world):
                    emit(CellFlipped(0, c))
            chunk = max(1, min(self.config.min_chunk, self.config.max_chunk))
            # Pipelined dispatch: once the chunk size stops growing, the
            # loop queues chunks asynchronously and only blocks when more
            # than _PIPELINE_DEPTH results are outstanding. Each
            # block_until_ready costs a full dispatch round-trip (~0.1 s
            # under the remote tunnel — it measured ~50% of kernel time
            # per chunk when paid synchronously), so short runs pay NONE
            # and long runs pay one per chunk fully overlapped with queued
            # compute; the window bounds device-side buffer buildup and
            # keeps retrieve latency <= depth x target_dispatch_seconds.
            inflight: deque = deque()
            growth_done = False  # doubling ended (max_chunk OR slow dispatch)
            ckpt_error: Exception | None = None
            while True:
                t_iter0 = time.monotonic()
                park_dt = 0.0
                with self._lock:
                    if self._paused and not self._quit:
                        # the park gate, timed: how long control traffic
                        # held the data plane still (obs/instruments.py);
                        # the span makes the stall VISIBLE on the timeline
                        # (a wedged-looking run that is merely paused)
                        t_park = time.monotonic()
                        park_span = _tracing.start_span(
                            _tracing.SPAN_ENGINE_PARK, turn=self._turn
                        )
                        while self._paused and not self._quit:
                            self._parked = True
                            self._control.notify_all()
                            self._control.wait()
                        _tracing.end_span(park_span)
                        park_dt = time.monotonic() - t_park
                        _ins.ENGINE_PARK_SECONDS.observe(park_dt)
                    self._parked = False
                    if self._quit or self._turn >= params.turns:
                        break
                    n = min(chunk, params.turns - self._turn)
                    if emit_flips:
                        n = 1
                    state = self._state
                    active_plane = self._plane
                    # early exit (ops/plane.py protocol): a plane that
                    # marked its state steady — still life or period-2 —
                    # jumps ALL remaining turns arithmetically in this
                    # one "chunk"; the commit below then ends the run
                    # with the exact final board and turn count
                    early = None
                    if not emit_flips:
                        from ..ops.plane import plane_steady_kind

                        early = plane_steady_kind(active_plane, state)
                        if early:
                            n = params.turns - self._turn

                growing = not emit_flips and not growth_done
                t0 = time.monotonic()
                # per-chunk span (one flag check when -trace is off; the
                # ring bounds a million-turn run to the recent window).
                # The matching TraceAnnotation puts the same name on the
                # device timeline when -trace-device is active, so host
                # spans and profiler tracks line up.
                chunk_span = (
                    _tracing.start_span(_tracing.SPAN_ENGINE_CHUNK, turns=n)
                    if _tracing.enabled() else None
                )
                chunk_counts = None
                with _tracing.annotate("engine.chunk"):
                    if early:
                        # O(1): a still life is itself, a period-2 cycle
                        # lands on phase n % 2 — no dispatch at all
                        # (gol_early_exit_total was metered by the plane
                        # at DETECTION; this jump just cashes it in)
                        new_state = active_plane.fast_forward(state, n)
                    elif not emit_flips and hasattr(
                        active_plane, "step_n_counted"
                    ):
                        # the fused device-resident driver (ops/fused.py
                        # protocol, ops/plane.py): the chunk's turns AND
                        # its alive reduction in ONE dispatch — the host
                        # touches the board only at these boundaries,
                        # and the committed fold serves the count-only
                        # Retrieve ticker below with no dispatch.
                        # gol: allow(jit-cache): chunk doubles by powers
                        # of two; the min() only clips the FINAL
                        # remainder, so a run compiles at most
                        # log2(turns)+2 distinct n values
                        new_state, chunk_counts = active_plane.step_n_counted(
                            state, n
                        )
                    else:
                        # gol: allow(jit-cache): chunk doubles by powers
                        # of two; the min() only clips the FINAL
                        # remainder, so a run compiles at most
                        # log2(turns)+2 distinct n values
                        new_state = active_plane.step_n(state, n)
                if growing:
                    # accurate per-chunk timing drives the doubling below
                    new_state.block_until_ready()
                else:
                    inflight.append(new_state)
                    if len(inflight) > _PIPELINE_DEPTH:
                        inflight.popleft().block_until_ready()
                if chunk_span is not None:
                    _tracing.end_span(chunk_span, sync=growing)
                elapsed = time.monotonic() - t0
                attribution = _metrics.enabled() and _perf.attribution_enabled()
                if attribution:
                    # dispatch-wall decomposition (obs/perf.py): planning/
                    # lock time before the dispatch vs the dispatch itself
                    # (block_until_ready delta on growth chunks; enqueue
                    # wall once pipelined — the documented caveat). The
                    # demux segment closes after the commit below.
                    _ins.TURN_SEGMENT_SECONDS.labels(
                        "engine", "host_prep"
                    ).observe(max(0.0, t0 - t_iter0 - park_dt))
                    _ins.TURN_SEGMENT_SECONDS.labels(
                        "engine", "device_compute"
                    ).observe(elapsed)
                if _metrics.enabled() and not early:
                    # per-turn attribution (obs/): dispatch wall spread over
                    # the chunk's turns, so the step histogram's COUNT is
                    # the turn count (growth chunks are synchronous and
                    # accurate; pipelined chunks record enqueue time — the
                    # device-side truth lives in the jax.profiler trace).
                    # An early-exit jump is EXCLUDED: its millions of
                    # credited turns were never computed, and booking them
                    # as ~0-latency samples would crater the step p99 and
                    # fake the throughput panels (the sessions dead-retire
                    # posture: gol_early_exit_total is the meter for
                    # skipped turns, these meters count COMPUTED ones)
                    _ins.ENGINE_DISPATCH_SECONDS.observe(elapsed)
                    _ins.ENGINE_STEP_SECONDS.observe_n(elapsed / n, n)
                    _ins.ENGINE_TURNS_TOTAL.inc(n)
                    _ins.ENGINE_CHUNKS_TOTAL.inc()
                    _ins.ENGINE_CHUNK_SIZE.set(chunk)
                if _metrics.enabled():
                    # per-chunk HBM occupancy (obs/device.py): the gauges
                    # that bound a TPU run, live on the Status verb and
                    # the watch dashboard; one cached early-return on
                    # backends without memory stats (CPU)
                    _device.sample_hbm()
                # opportunistic timeline tick at the chunk boundary: a
                # dispatch loop that saturates the GIL must still sample
                # on cadence (one global load + branch while -timeline
                # is off)
                _timeline.maybe_sample()
                if growing:
                    if multihost:
                        # the wall-clock cap is rank-local: unagreed it
                        # could end growth at different sizes on different
                        # ranks, desynchronising the SPMD dispatch
                        # sequence. So the ranks AGREE on the slowest
                        # rank's elapsed — every rank then takes the same
                        # growth decision, and the dispatch-time target
                        # holds on a pod too (before this, multihost
                        # growth was pure doubling to max_chunk, whose
                        # 4096 default at 65536^2 meant minutes-long
                        # gates and a starved tick; VERDICT r4 item 6).
                        # Only growth chunks pay the allgather: <=
                        # log2(max_chunk) crossings per run, in identical
                        # program order (growth state is agreed by
                        # induction).
                        from jax.experimental import multihost_utils

                        elapsed = float(
                            multihost_utils.process_allgather(
                                np.float64(elapsed)
                            ).max()
                        )
                    if chunk >= self.config.max_chunk or (
                        elapsed >= self.config.target_dispatch_seconds
                    ):
                        # whichever way doubling ends — size cap or wall-
                        # clock cap — later chunks go async; the pipelined
                        # elapsed (~0, no sync) must never re-trigger
                        # doubling past the wall-clock cap
                        growth_done = True
                    else:
                        chunk = min(chunk * 2, self.config.max_chunk)

                t_commit0 = time.monotonic()
                with self._lock:
                    prev_host = self._world_host if emit_flips else None
                    self._state = new_state
                    # None unless this chunk was a fused counted dispatch
                    # — a stale fold must never outlive its state
                    self._state_counts = chunk_counts
                    self._host_dirty = True
                    self._turn += n
                    turn_now = self._turn
                    if emit_flips:
                        self._sync_host()
                        new_host = self._world_host

                # journal outside the lock: one record per chunk boundary
                # (the journal is opt-in; off, this is one global load)
                _journal.record(
                    "chunk.commit", "engine", k=n, turn=turn_now,
                    route="early" if early else (
                        "fused" if chunk_counts is not None else "plain"
                    ),
                )
                if early:
                    _journal.record("early.exit", early, turn=turn_now)
                if emit_flips and emit is not None:
                    changed = np.nonzero(prev_host != new_host)
                    for y, x in zip(*changed):
                        emit(CellFlipped(turn_now, Cell(int(x), int(y))))
                    emit(TurnComplete(turn_now))
                if attribution:
                    _ins.TURN_SEGMENT_SECONDS.labels(
                        "engine", "demux"
                    ).observe(time.monotonic() - t_commit0)

                if self.config.chunk_hook is not None:
                    # the multi-host control gate: collectives + rank-0
                    # keypress fan-out happen here, at the same (turn)
                    # point on every rank (pod.py). A hook that blocks IS
                    # a pause: the dispatch loop cannot advance past it.
                    self.config.chunk_hook(self, new_state, turn_now)

                every = self.config.checkpoint_every
                if every and turn_now // every > (turn_now - n) // every:
                    # HBM sample at EVERY checkpoint, metrics on or off:
                    # advances the peak-observed high-water mark the
                    # RunReport publishes, so a mid-run spike is visible
                    # in the final artifact (obs/report.device_inventory)
                    _device.sample_hbm()
                    t_ckpt = time.monotonic()
                    ckpt_span = _tracing.start_span(
                        _tracing.SPAN_ENGINE_CHECKPOINT, turn=turn_now
                    )
                    attempt_ok = True
                    try:
                        self._write_checkpoint(new_state, turn_now)
                        _journal.record("ckpt.write", "engine", turn=turn_now)
                    except Exception as exc:
                        # catch EVERYTHING, not just OSError: a full disk
                        # must not abort the multi-hour run this checkpoint
                        # exists to protect (ADVICE.md round 3) — and in an
                        # SPMD job the write can fail with ANY exception
                        # type (a pickling error, a shard-shape bug). Were
                        # only OSError caught, the raising rank would abort
                        # while its peers continue and hang at the next
                        # collective; _write_checkpoint's multihost path
                        # agrees the failure via allgather, so this broad
                        # catch makes every rank take the SAME continue
                        # decision (ADVICE r5). Surfaced on the RunResult.
                        ckpt_error = exc
                        attempt_ok = False
                        _ins.ENGINE_CHECKPOINT_ERRORS_TOTAL.inc()
                        print(
                            f"checkpoint at turn {turn_now} failed: {exc}"
                        )
                    _tracing.end_span(ckpt_span, ok=attempt_ok)
                    _ins.ENGINE_CHECKPOINT_SECONDS.observe(
                        time.monotonic() - t_ckpt
                    )

            with self._lock:
                turns_done = self._turn
                if self.config.final_world:
                    self._sync_host()
                    return RunResult(
                        turns_done,
                        self._world_host,
                        checkpoint_error=ckpt_error,
                    )
                state_f, plane_f = self._state, self._plane
            # lazy: .alive extracts from the plane state only if read
            return RunResult(
                turns_done,
                None,
                state=state_f,
                plane=plane_f,
                checkpoint_error=ckpt_error,
            )
        except BaseException as exc:
            # an UNHANDLED engine exception is exactly the moment the
            # flight recorder exists for: dump the last-events ring to
            # out/flight_<host>.jsonl (obs/flight.py — no-op unless -trace
            # opted in, never raises) before propagating, so a crashed or
            # desynced rank leaves its post-mortem on disk
            _flight.dump_on_crash(exc)
            # same posture for the journal: flush the buffered writer and
            # record the crash event before propagating (never raises)
            _journal.flush_on_crash(exc)
            raise
        finally:
            _journal.record("run.end", "engine", turn=self._turn)
            with self._lock:
                self._running = False
                self._paused = False
                self._quit = False  # consumed; a reattached run starts fresh
                # _plane/_state stay: Retrieve keeps serving the final board
                self._control.notify_all()

    def _write_checkpoint(self, state, turn: int) -> None:
        """Periodic crash-recovery checkpoint, between chunk dispatches.

        Bitboard-plane states go down packed — no decode, the config-5
        requirement — anything else through the byte format. Written to a
        temp name then atomically renamed, so a crash mid-write leaves
        the previous checkpoint intact."""
        import pathlib

        from .checkpoint import (
            npz_path,
            save_checkpoint,
            save_packed_checkpoint,
            save_packed_checkpoint_sharded,
        )

        # the ACTIVE plane's rule, not the config's: an explicit
        # plane=BitPlane(HIGHLIFE) run must not stamp a Conway checkpoint
        rule = getattr(self._plane, "rule", self.config.rule)
        path = pathlib.Path(self.config.checkpoint_path or "out/engine_ck.npz")
        word_axis = getattr(self._plane, "word_axis", None)
        if not getattr(state, "is_fully_addressable", True):
            # multi-host: each rank writes only its own word rows, to a
            # rank-suffixed shard (atomic rename inside) — run() entry
            # already guaranteed the plane is packed (word_axis set).
            # Success is agreed COLLECTIVELY: a rank-local failure must
            # surface on every rank (the operator watches rank 0), and
            # the resulting mixed-turn shard set must not look like a
            # success anywhere. Every rank reaches this crossing at the
            # same turn (deterministic multi-host chunking), so the
            # allgather is in identical program order.
            # catch EVERYTHING, not just OSError: a rank that propagates
            # before its allgather strands every peer inside the
            # collective — a distributed hang instead of a clean error
            # (ADVICE r4)
            ok, err = 1, None
            try:
                save_packed_checkpoint_sharded(path, state, turn, rule, word_axis)
            except Exception as exc:
                ok, err = 0, exc
            from jax.experimental import multihost_utils

            # the vote this rank is about to cast, recorded BEFORE the
            # collective: if a peer never shows up and the allgather
            # wedges, every surviving rank's flight ring names this exact
            # crossing as its last act (the rank-desync post-mortem)
            _flight.record(
                "ckpt.vote", "checkpoint_agreement", turn=turn, ok=bool(ok)
            )
            oks = multihost_utils.process_allgather(np.int64(ok))
            failed = int(len(oks) - oks.sum())
            _flight.record(
                "ckpt.agree", "checkpoint_agreement", turn=turn,
                failed_ranks=failed,
            )
            if failed:
                raise err if err is not None else OSError(
                    f"checkpoint at turn {turn}: shard write failed on "
                    f"{failed} other rank(s); the on-disk set is mixed "
                    "until the next successful crossing"
                )
            return
        tmp = path.with_name(path.name + ".tmp")
        if word_axis is not None and hasattr(state, "dtype") and state.dtype == np.int32:
            written = save_packed_checkpoint(tmp, state, turn, rule, word_axis)
        else:
            written = save_checkpoint(tmp, self._plane.decode(state), turn, rule)
        written.replace(npz_path(path))

    # -- control plane (broker/broker.go:236-277) -------------------------

    def pause(self) -> bool:
        """Toggle pause; same RPC both pauses and resumes
        (broker/broker.go:251-254, 83-86, 126-129). Returns new paused state.

        On pause, blocks until the run loop has actually parked (any
        in-flight chunk has committed), so after pause() returns the board
        is guaranteed not to advance until resume."""
        with self._lock:
            self._paused = not self._paused
            state = self._paused
            self._control.notify_all()
            print("State paused" if state else "State unpaused")
            if state:
                # re-check _paused each wake: a concurrent unpause (another
                # controller toggling) means the loop will never park — the
                # wait must end with the toggle, not strand until run-end
                while (
                    self._paused
                    and self._running
                    and not self._parked
                    and not self._quit
                ):
                    self._control.wait(timeout=0.1)
            return state

    def quit(self):
        """Break the run loop; the engine object survives and accepts a new
        ``run`` (broker/broker.go:236-239 + README.md:187)."""
        with self._lock:
            self._quit = True
            self._control.notify_all()

    def super_quit(self):
        """Coordinated full shutdown (broker/broker.go:241-249). At engine
        level this is quit + a flag the owning server uses to stop serving."""
        with self._lock:
            self._super_quit = True
            self._quit = True
            self._control.notify_all()

    @property
    def super_quit_requested(self) -> bool:
        with self._lock:
            return self._super_quit

    def final_state(self):
        """The current/last state in its plane's representation (the
        ``cWorld`` analogue without the decode): the latest committed
        chunk mid-run, the final board after. What a config-5 caller
        streams to PGM (bigboard.stream_packed_to_pgm)."""
        with self._lock:
            return self._state

    def state_snapshot(self):
        """``(state, turns_completed)`` under ONE lock acquisition: a
        consistent pair for packed snapshots (two separate reads could
        straddle a chunk commit and disagree by up to max_chunk turns)."""
        with self._lock:
            return self._state, self._turn

    def retrieve(self, include_world: bool = True) -> Snapshot:
        """Mutex-guarded snapshot {World, TurnsCompleted, AliveCount}
        (broker/broker.go:256-277).

        With ``include_world=False`` (the 2-second ticker's path) the count
        is a device-side reduction in the plane's own representation (a
        popcount for the bitboard) — a few bytes cross the device boundary
        instead of the whole board. The reference re-ships the full world on
        every Retrieve (broker/broker.go:262-270); the TPU-first control
        plane does not."""
        if include_world and not self.config.final_world:
            # mirror of bigboard._check_byte_free_engine, enforced at the
            # Engine surface itself: a final_world=False run promises the
            # byte raster never exists, and decoding it here would
            # materialise 4 GiB at 65536^2 (ADVICE.md round 3)
            raise ValueError(
                "retrieve(include_world=True) on a final_world=False "
                "engine would decode the full byte raster this "
                "configuration promises never exists; use "
                "include_world=False (count-only) or state_snapshot()"
            )
        with self._lock:
            turn = self._turn
            if include_world:
                self._sync_host()
                world = self._world_host
            else:
                state, active_plane = self._state, self._plane
                counts = self._state_counts
                world = None
        if not include_world:
            if state is None:
                count = 0
            elif counts is not None:
                # the fused driver already folded this state's count on
                # device inside the chunk dispatch (step_n_counted) —
                # the 2-second ticker costs a host sum, not a reduction
                # dispatch
                from ..ops.fused import fold_counts

                count = fold_counts(counts)
            else:
                count = active_plane.alive_count(state)
            return Snapshot(world, turn, count)
        if world is None:
            world = np.zeros((0, 0), np.uint8)
        return Snapshot(world, turn, int(np.count_nonzero(world)))
