"""gol_distributed_final_tpu — a TPU-native distributed Game of Life framework.

A ground-up JAX/XLA re-founding of the capabilities of the reference system
(ao22174/Gol-distributed-final: a Go controller/broker/worker cluster over
net/rpc). The compute plane is a single device-resident ``jnp.uint8[H, W]``
board evolved by a fused, jitted 3x3 toroidal stencil — sharded over a device
mesh with ``shard_map`` + ``lax.ppermute`` halo exchange where the reference
fanned full-board copies to Go workers (reference: broker/broker.go:135-224).
The control plane (run / pause / quit / snapshot, the 2-second alive-count
ticker, PGM image IO, and the typed event stream) preserves the reference's
observable contract (reference: stubs/stubs.go, gol/event.go, gol/io.go).

Package layout:
    ops/       jitted stencil kernels (roll-based, pallas), reductions
    models/    life-like automaton rule family (B/S rulestrings); Conway flagship
    parallel/  device meshes, shard_map halo-exchange steps, multi-host helpers
    engine/    the GoL engine (broker equivalent) + controller (distributor)
    io/        PGM P5 codec, images/ -> out/ conventions, streamed shard IO
    events/    the 6-event observability stream
    rpc/       TCP control plane preserving the stubs/ method vocabulary
    viz/       visualiser (SDL-equivalent) with headless fallback + BigView
    utils/     Cell, board visualisation for test failures
    bigboard   BASELINE config 5: packed-only boards up to 65536^2 —
               sparse seeding, streamed PGM, decode_window, big_session
"""

from .params import Params
from .events import (
    AliveCellsCount,
    CellFlipped,
    Event,
    FinalTurnComplete,
    ImageOutputComplete,
    State,
    StateChange,
    TurnComplete,
)
from .utils.cell import Cell

__version__ = "0.1.0"

__all__ = [
    "Params",
    "Cell",
    "Event",
    "AliveCellsCount",
    "ImageOutputComplete",
    "StateChange",
    "CellFlipped",
    "TurnComplete",
    "FinalTurnComplete",
    "State",
    "run",
    "__version__",
]


def run(params, events=None, keypresses=None, **kwargs):
    """Run a full Game of Life session (the ``gol.Run`` equivalent).

    Lazy import so that ``import gol_distributed_final_tpu`` stays cheap and
    does not pull in JAX until compute is actually requested.

    Reference: gol/gol.go:12-41.
    """
    from .engine.controller import run as _run

    return _run(params, events=events, keypresses=keypresses, **kwargs)
