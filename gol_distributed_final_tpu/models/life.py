"""The life-like automaton model family.

The reference hard-codes Conway's rule inside its worker kernel
(worker/worker.go:41-46). Here the rule is a first-class model: any
totalistic life-like automaton expressed as a B.../S... rulestring, compiled
to static 9-bit masks that the jitted stencil consumes (ops/stencil.py).
``CONWAY`` is the flagship model — the one the goldens, the benchmark, and
``__graft_entry__`` exercise.
"""

from __future__ import annotations

import dataclasses
import functools
import re

import jax

from ..ops import stencil


def _mask(counts) -> int:
    m = 0
    for c in counts:
        if not 0 <= c <= 8:
            raise ValueError(f"neighbour count out of range: {c}")
        m |= 1 << c
    return m


@dataclasses.dataclass(frozen=True)
class LifeRule:
    """A totalistic life-like rule, e.g. Conway = B3/S23."""

    name: str
    birth_mask: int
    survive_mask: int

    @classmethod
    def from_rulestring(cls, rulestring: str, name: str | None = None) -> "LifeRule":
        m = re.fullmatch(r"B(\d*)/S(\d*)", rulestring.strip(), re.IGNORECASE)
        if m is None:
            raise ValueError(f"not a B/S rulestring: {rulestring!r}")
        birth = [int(ch) for ch in m.group(1)]
        survive = [int(ch) for ch in m.group(2)]
        return cls(
            name=name or rulestring.upper(),
            birth_mask=_mask(birth),
            survive_mask=_mask(survive),
        )

    @property
    def rulestring(self) -> str:
        birth = "".join(str(i) for i in range(9) if self.birth_mask >> i & 1)
        survive = "".join(str(i) for i in range(9) if self.survive_mask >> i & 1)
        return f"B{birth}/S{survive}"

    def step(self, board: jax.Array) -> jax.Array:
        """One jitted turn under this rule."""
        return stencil.step(
            board, birth_mask=self.birth_mask, survive_mask=self.survive_mask
        )

    def step_n(self, board: jax.Array, n: int) -> jax.Array:
        """``n`` turns in one device dispatch."""
        return stencil.step_n(
            board, n, birth_mask=self.birth_mask, survive_mask=self.survive_mask
        )

    def step_fn(self):
        """A plain ``board -> board`` closure with the masks baked in, for
        wrapping in jit/shard_map by callers (parallel/halo.py, bench)."""
        return functools.partial(
            stencil.step,
            birth_mask=self.birth_mask,
            survive_mask=self.survive_mask,
        )


CONWAY = LifeRule.from_rulestring("B3/S23", name="conway")
HIGHLIFE = LifeRule.from_rulestring("B36/S23", name="highlife")
SEEDS = LifeRule.from_rulestring("B2/S", name="seeds")
DAY_AND_NIGHT = LifeRule.from_rulestring("B3678/S34678", name="day-and-night")
