from .life import CONWAY, DAY_AND_NIGHT, HIGHLIFE, SEEDS, LifeRule

__all__ = ["LifeRule", "CONWAY", "HIGHLIFE", "SEEDS", "DAY_AND_NIGHT"]
