"""Alive-cell reductions (reference: broker/broker.go:47-58, ``calculateAliveCells``).

Two consumers with different shapes:
  * ``alive_count`` — the scalar behind the 2-second ``AliveCellsCount`` event;
    a device-side reduction so the ticker never copies the board to host.
  * ``alive_cells`` — the ``[]util.Cell`` payload of ``FinalTurnComplete``;
    inherently host-side (variable length), produced row-major like the
    reference's nested loop so orderings agree byte-for-byte.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.cell import Cell


@jax.jit
def alive_count(board: jax.Array) -> jax.Array:
    """Number of alive cells as a device scalar (int32)."""
    return jnp.sum(board != 0, dtype=jnp.int32)


@jax.jit
def alive_count_batch(boards: jax.Array) -> jax.Array:
    """Per-universe alive counts of a batched byte board ``[B, H, W]`` as
    a device ``int32[B]`` — ONE batched reduction for the whole session
    batch, from which every per-session AliveCellsCount ticker demuxes
    (B scalars cross the device boundary, never B boards)."""
    return jnp.sum(boards != 0, axis=(1, 2), dtype=jnp.int32)


def alive_cells(board) -> list[Cell]:
    """Coordinates of alive cells as ``Cell(x, y)``, row-major."""
    arr = np.asarray(board)
    ys, xs = np.nonzero(arr)
    return [Cell(int(x), int(y)) for x, y in zip(xs, ys)]
