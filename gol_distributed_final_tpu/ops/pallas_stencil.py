"""Pallas TPU kernel for the Game of Life stencil — the hot-op fast path.

The roll-based XLA stencil (ops/stencil.py) re-reads the board from HBM
every turn: ~2 x H x W bytes of HBM traffic per turn plus intermediate
materialisation. This kernel instead keeps the ENTIRE board resident in
VMEM (a 512x512 uint8 board is 256 KiB against ~16 MiB of VMEM) and runs
all ``n`` turns inside one kernel launch: HBM is touched exactly twice —
one load at entry, one store at exit — regardless of ``n``. The per-turn
work is pure VPU: 8 shifted adds on (8, 128)-lane uint8 vregs and a
branch-free rule select.

Boards larger than the VMEM budget fall back to the XLA stencil
(``fits_vmem`` gate); the sharded mesh path gives each device a
VMEM-sized block long before single-board VMEM becomes the limit.

Reference equivalence: this computes exactly worker/worker.go:15-70's
``calculateNextState`` over the full board, values in {0, 255}.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..obs import device as _device

# Physical VMEM is ~16 MiB/core (v4/v5e). The gates below are BYTE budgets
# on the kernel's int32 WORKING SET, not element counts (the round-1 gate
# compared elements against bytes and over-admitted 4x-16x — VERDICT.md).
VMEM_BYTES = 16 * 1024 * 1024

# The n-turn fori_loop keeps ~2 int32 boards live plus Mosaic temporaries
# for the fused shift/add chain. Measured on a real v5e chip (2026-07):
# the bitboard kernel compiles at packed <= 1.5 MiB and fails at 2 MiB,
# i.e. the compiler's working set is ~10x the packed array. The byte
# kernel upcasts the uint8 board to int32, so its working set is ~10x
# of 4*H*W.
_WORKING_SET_FACTOR = 10


def fits_vmem(shape: tuple[int, int], itemsize: int) -> bool:
    """True if an n-turn VMEM-resident kernel over an array of ``shape`` x
    ``itemsize`` bytes fits the measured working-set budget.

    For the byte kernel pass itemsize=4 (the board is carried as int32
    inside the loop); for the bitboard kernel pass the packed dtype's
    itemsize (4)."""
    return shape[0] * shape[1] * itemsize * _WORKING_SET_FACTOR <= VMEM_BYTES


def default_interpret() -> bool:
    """Interpret-mode default for every pallas path: real Mosaic kernels
    on TPU, the pallas interpreter elsewhere (the CPU test mesh). ONE
    probe shared by all call sites so a future change (per-device
    platforms, env overrides) lands everywhere at once."""
    import jax

    return jax.devices()[0].platform != "tpu"


def _rot1(a, shift: int, axis: int, *, interpret: bool = False):
    """Toroidal rotate by +/-1 along an axis, Mosaic-safe.

    On TPU this is ``pltpu.roll`` — a native lane/sublane rotate, far
    cheaper than the concat-of-slices ``jnp.roll`` lowers to (and
    ``jnp.roll``'s zero-length slice for a 0 shift doesn't lower at all).
    The interpreter path composes explicit nonempty slices instead."""
    if shift == 0:
        return a
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu

        # pltpu.roll requires a non-negative shift: -1 == size-1
        return pltpu.roll(a, shift % a.shape[axis], axis)
    if axis == 0:
        return (
            jnp.concatenate([a[-1:], a[:-1]], axis=0)
            if shift > 0
            else jnp.concatenate([a[1:], a[:1]], axis=0)
        )
    return (
        jnp.concatenate([a[:, -1:], a[:, :-1]], axis=1)
        if shift > 0
        else jnp.concatenate([a[:, 1:], a[:, :1]], axis=1)
    )


def byte_turn_fn(birth_mask: int, survive_mask: int, interpret: bool):
    """One byte-stencil turn on an int32 {0, 255} board, torus-wrapping
    through the rotate primitive — the shared body of the whole-board
    byte kernel and the fused byte tiles (ops/fused.py, where the cyclic
    rotate only contaminates the halo ring the interior slice discards)."""

    def rot(a, shift, axis):
        return _rot1(a, shift, axis, interpret=interpret)

    def one_turn(b):
        alive = b != 0
        ones = alive.astype(jnp.int32)
        # separable 3x3 sum: vertical (cheap sublane shifts) then horizontal
        # (lane shifts) — 4 rotates instead of 8, self subtracted at the end
        vert = ones + rot(ones, 1, 0) + rot(ones, -1, 0)
        counts = vert + rot(vert, 1, 1) + rot(vert, -1, 1) - ones
        born = (jnp.int32(birth_mask) >> counts) & 1
        survives = (jnp.int32(survive_mask) >> counts) & 1
        next_alive = jnp.where(alive, survives, born) != 0
        return jnp.where(next_alive, jnp.int32(255), jnp.int32(0))

    return one_turn


def _kernel(board_ref, out_ref, *, n, birth_mask, survive_mask, interpret):
    # Mosaic (v5e) vectors support only i16/i32 arithmetic — carry the board
    # as int32 {0, 255} across turns, touch uint8 only at the HBM boundary
    one_turn = byte_turn_fn(birth_mask, survive_mask, interpret)
    final = lax.fori_loop(
        0, n, lambda _, b: one_turn(b), board_ref[:].astype(jnp.int32)
    )
    out_ref[:] = final.astype(jnp.uint8)


def byte_pallas_call(n: int, birth_mask: int, survive_mask: int, interpret: bool):
    """The RAW n-turn whole-board byte launch: a traceable callable
    ``uint8[H, W] -> uint8[H, W]`` (one ``pl.pallas_call``), shared by the
    jitted single-launch path below and the fused K-turn ladder
    (ops/fused.py), which composes several of these inside ONE jitted
    program. Deliberately uninstrumented — callers wrap the COMPOSED
    program in ``_device.instrument_jit`` so the dispatch wall lands on
    the right site."""
    from jax.experimental import pallas as pl

    kernel = functools.partial(
        _kernel,
        n=n,
        birth_mask=birth_mask,
        survive_mask=survive_mask,
        interpret=interpret,
    )

    def launch(board):
        if interpret:
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(board.shape, board.dtype),
                interpret=True,
            )(board)
        from jax.experimental.pallas import tpu as pltpu

        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(board.shape, board.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )(board)

    return launch


@functools.lru_cache(maxsize=None)
def _compiled(n: int, birth_mask: int, survive_mask: int, interpret: bool):
    run = jax.jit(byte_pallas_call(n, birth_mask, survive_mask, interpret))
    # compile wall + cost analysis attributed to this kernel site (obs/)
    return _device.instrument_jit("pallas.vmem_byte", run)


def pick_rot1(interpret: bool):
    """The rotate primitive for bitboard kernels: jnp.roll under the
    interpreter (bit_step never rotates by 0), the Mosaic-safe pltpu.roll
    wrapper on real TPU. Shared by the whole-board and tiled kernels."""
    if interpret:
        return None
    return functools.partial(_rot1, interpret=False)


def _bit_kernel(
    packed_ref, out_ref, *, n, word_axis, interpret, birth_mask, survive_mask
):
    from .bitpack import bit_step

    rot1 = pick_rot1(interpret)

    def step(b):
        return bit_step(
            b, word_axis, rot1, birth_mask=birth_mask, survive_mask=survive_mask
        )

    # Two turns per loop iteration: the fori_loop's per-iteration
    # bookkeeping costs ~one turn-fraction (u=1 -> u=2 measured
    # 123 -> ~100 ns/turn at 128^2, 169 -> ~154 at 512^2 on v5e), and
    # Mosaic's fori_loop rejects partial `unroll`, so unroll by hand.
    #
    # Why not deeper, and why the SMALL-board floor is what it is
    # (BENCH c2, 128^2 ~0.10 us/turn vs 512^2 ~0.15 for 16x the cells):
    # a full unroll sweep u in {1,2,4,8,16,32} at 128^2 and u up to 64 at
    # 512^2 (r4 session, marginal fits over 2M turns, every point
    # parity-checked) measured u>=2 indistinguishable at both sizes
    # (128^2: ~100 +-5 ns across u=2..32; 512^2: 150-154 ns across
    # u=2..64). So past u=2 loop overhead is invisible, and the ~100 ns
    # floor at 128^2 is the SERIAL LATENCY of one turn's ~39-operation
    # bit-plane dependency chain: turns are sequentially dependent, so no
    # unroll can overlap them, and a 128^2 packed board is 512 int32
    # words — HALF one (8,128) int32 vreg — so the VPU finishes each
    # op's data in a single issue, making the chain's issue latency, not
    # throughput, the bound. 512^2 (8 vregs, 16x the work) costing only
    # ~1.5x per turn confirms throughput is nearly free at these sizes.
    # Shrinking the chain itself is the only lever left, and bit_step is
    # already pruned to the CSA minimum (ops/bitpack.py).
    out = lax.fori_loop(0, n // 2, lambda _, b: step(step(b)), packed_ref[:])
    if n % 2:
        out = step(out)
    out_ref[:] = out


def bit_pallas_call(
    n: int,
    word_axis: int,
    interpret: bool,
    birth_mask: int | None = None,
    survive_mask: int | None = None,
):
    """The RAW n-turn whole-board bitboard launch: a traceable callable
    ``int32[Hw, W] -> int32[Hw, W]`` (one ``pl.pallas_call``), shared by
    ``_bit_compiled`` and the fused K-turn ladder (ops/fused.py), which
    strings several launches inside ONE jitted program. Uninstrumented on
    purpose — the composed program owns the site attribution."""
    from jax.experimental import pallas as pl

    from .stencil import CONWAY_BIRTH_MASK, CONWAY_SURVIVE_MASK

    kernel = functools.partial(
        _bit_kernel,
        n=n,
        word_axis=word_axis,
        interpret=interpret,
        birth_mask=CONWAY_BIRTH_MASK if birth_mask is None else birth_mask,
        survive_mask=CONWAY_SURVIVE_MASK if survive_mask is None else survive_mask,
    )

    def launch(packed):
        kwargs = {}
        if interpret:
            kwargs["interpret"] = True
        else:
            from jax.experimental.pallas import tpu as pltpu

            kwargs["in_specs"] = [pl.BlockSpec(memory_space=pltpu.VMEM)]
            kwargs["out_specs"] = pl.BlockSpec(memory_space=pltpu.VMEM)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(packed.shape, packed.dtype),
            **kwargs,
        )(packed)

    return launch


@functools.lru_cache(maxsize=None)
def _bit_compiled(
    n: int,
    word_axis: int,
    interpret: bool,
    birth_mask: int | None = None,
    survive_mask: int | None = None,
):
    run = jax.jit(
        bit_pallas_call(n, word_axis, interpret, birth_mask, survive_mask)
    )
    # compile wall + cost analysis attributed to this kernel site (obs/)
    return _device.instrument_jit("pallas.vmem_bit", run)


def _bit_kernel_batch(
    packed_ref, out_ref, *, n, word_axis, interpret, birth_mask, survive_mask
):
    # one grid program per universe: the (1, Hw, W) block squeezes to the
    # single-board shape (a layout no-op), runs the SAME n-turn bit_step
    # loop as _bit_kernel entirely in VMEM, and writes its board back —
    # HBM touched twice per universe per launch, for the whole batch
    from .bitpack import bit_step

    rot1 = pick_rot1(interpret)

    def step(b):
        return bit_step(
            b, word_axis, rot1, birth_mask=birth_mask, survive_mask=survive_mask
        )

    board = packed_ref[:].reshape(packed_ref.shape[1:])
    out = lax.fori_loop(0, n // 2, lambda _, b: step(step(b)), board)
    if n % 2:
        out = step(out)
    out_ref[:] = out.reshape(out_ref.shape)


def bit_batch_pallas_call(
    n: int,
    word_axis: int,
    interpret: bool,
    birth_mask: int | None = None,
    survive_mask: int | None = None,
):
    """The RAW n-turn batched bitboard launch (one grid program per
    universe): a traceable callable ``int32[B, Hw, W] -> [B, Hw, W]``,
    shared by ``_bit_compiled_batch`` and the fused batched ladder /
    fused step+count programs (ops/fused.py). Uninstrumented on purpose
    (the composed program owns the site attribution)."""
    from jax.experimental import pallas as pl

    from .stencil import CONWAY_BIRTH_MASK, CONWAY_SURVIVE_MASK

    kernel = functools.partial(
        _bit_kernel_batch,
        n=n,
        word_axis=word_axis,
        interpret=interpret,
        birth_mask=CONWAY_BIRTH_MASK if birth_mask is None else birth_mask,
        survive_mask=CONWAY_SURVIVE_MASK if survive_mask is None else survive_mask,
    )

    def launch(packed):
        b, rows, width = packed.shape
        return pl.pallas_call(
            kernel,
            grid=(b,),
            in_specs=[pl.BlockSpec((1, rows, width), lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec((1, rows, width), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct(packed.shape, packed.dtype),
            interpret=interpret,
        )(packed)

    return launch


@functools.lru_cache(maxsize=None)
def _bit_compiled_batch(
    n: int,
    word_axis: int,
    interpret: bool,
    birth_mask: int | None = None,
    survive_mask: int | None = None,
):
    """The batched VMEM bitboard kernel: ``int32[B, Hw, W] -> [B, Hw, W]``,
    n turns for B independent universes in ONE launch. Where ``vmap``
    would hand XLA a batched op graph (bit-plane temporaries spilling to
    HBM once the batch outgrows on-chip memory), an EXPLICIT batch grid
    dimension keeps the per-program working set at one universe — the
    single-board VMEM gate applies per universe, not per batch, so a
    thousand 128^2 boards batch into one launch that amortises the
    dispatch-latency floor (BENCH_r04) N ways."""
    run = jax.jit(
        bit_batch_pallas_call(n, word_axis, interpret, birth_mask, survive_mask)
    )
    # compile wall + cost analysis attributed to this kernel site (obs/)
    return _device.instrument_jit("pallas.vmem_bit_batch", run)


def pallas_bit_step_n_fn(
    *, word_axis: int = 0, interpret: bool | None = None, rule=None
):
    """Conway on the VMEM-resident int32 bitboard: 32 cells/word, the whole
    n-turn evolution in ONE kernel launch — bitwise adder trees on (8,128)
    int32 vregs, HBM touched twice total. The fastest single-device path:
    ~0.17 us/turn on a 512x512 board on v5e (~1.6e12 cell-updates/s), ~40x
    the roll-based XLA stencil.

    ``word_axis=0`` (rows packed, array [H/32, W]) keeps the lane dimension
    W wide — ~6x faster on TPU than word_axis=1's [H, W/32].

    Engine-compatible ``(board_uint8, n) -> board_uint8``.
    """
    from .bitpack import bit_step_n, pack_device, unpack_device
    from .stencil import CONWAY_BIRTH_MASK, CONWAY_SURVIVE_MASK

    birth = rule.birth_mask if rule else CONWAY_BIRTH_MASK
    survive = rule.survive_mask if rule else CONWAY_SURVIVE_MASK
    if interpret is None:
        interpret = default_interpret()

    def step_n(board, n):
        n = int(n)
        packed = pack_device(jnp.asarray(board), word_axis)
        if not fits_vmem(packed.shape, itemsize=4):
            out = _device.compile_and_call(
                "bitpack.xla_step", bit_step_n,
                packed, n, word_axis, birth, survive,
                static_argnums=(1, 2, 3, 4),
            )
        else:
            out = _bit_compiled(n, word_axis, interpret, birth, survive)(packed)
        return unpack_device(out, word_axis)

    return step_n


def pallas_step_n_fn(
    rule=None,
    *,
    interpret: bool | None = None,
    fallback=None,
):
    """Build an ``(board, n) -> board`` running n turns in one VMEM-resident
    kernel launch. Engine-compatible (``EngineConfig.step_n_fn``).

    ``interpret`` defaults to True off-TPU (tests on the virtual CPU mesh)
    and False on TPU. Boards too large for VMEM go to ``fallback``
    (default: the XLA roll stencil).
    """
    from ..models import CONWAY

    rule = rule or CONWAY
    if interpret is None:
        interpret = default_interpret()
    if fallback is None:
        fallback = rule.step_n

    def step_n(board, n):
        n = int(n)
        if not fits_vmem(board.shape, itemsize=4):  # carried as int32 in-loop
            return fallback(board, n)
        fn = _compiled(n, rule.birth_mask, rule.survive_mask, interpret)
        return fn(board)

    return step_n
