"""Activity-sparse stepping — compute only where the board is alive.

Every dense tier pays O(board) per turn regardless of content: BENCH_r05
times the 16384^2 and 65536^2 R-pentomino cases at full dense cost
(~131 us and ~3.6 ms/turn) even though <1% of the board is ever active.
This module turns per-turn cost into O(active frontier) — the classic
Life optimisation recast in the convolution+rule shape the existing
bitboard kernel already has (CAX, arxiv 2410.02651), with the tile-block
decomposition following the TPU playbook of arxiv 2112.09017.

The invariant (exact for any life-like rule WITHOUT birth-on-0, i.e.
``not rule.birth_mask & 1``): a cell can change at turn t -> t+1 only if
some cell in its 3x3 neighbourhood changed at t-1 -> t. Lifted to tiles
(>= 1 cell on every side), a tile can change next turn only if it or one
of its 8 neighbours changed this turn — so the active set evolves as
``active(t+1) = dilate3x3(changed(t))`` and everything outside it is
skipped without ever being read.

``SparseBitPlane`` is a drop-in data plane (ops/plane.py interface) over
the int32 bitboard: the board and the [GR, GC] activity bitmap both live
on device, and ``step_n`` runs the whole turn loop in ONE dispatch — a
``lax.while_loop`` whose body gathers the active tiles (indices from
``jnp.nonzero(..., size=capacity)``) into a compact halo-extended batch,
advances it ``SPARSE_TURNS_PER_GATHER`` turns with the existing
``bit_step`` bit-plane kernel (the window's margins cover the whole
block's dependency cone, and per-turn change accumulation keeps
oscillators whose period divides the block depth active), scatters the
interiors back, and recomputes the activity bitmap from the per-tile
change flags. The gather capacity is padded to power-of-two buckets (the
engine/sessions.py chunk-quantisation trick) so frontier churn keys at
most log2(tiles) compiled programs, never one per frontier size; an
in-flight overflow commits the turns already done and re-dispatches at
the next bucket. Above the measured density crossover the plane routes
the whole remaining chunk through the dense ``BitPlane`` path (the
crossover point is where gather/scatter overhead exceeds the dense
kernel's content-independent cost) and rebuilds the bitmap from tile
occupancy afterwards.

Steady states short-circuit arithmetically: an empty activity bitmap is
a still life (the remaining turns of the call are no-ops, reported
done), and a small surviving frontier is probed for period-2 cycles
(board(t+2) == board(t)) — both mark the state ``steady`` so the engine
can jump the rest of the run in O(1) (``gol_early_exit_total{kind}``).

The bottom of the file is the WIRE-TILE toolkit: pure-numpy helpers the
resident-strip workers and the delta-checkpoint layer share to turn a
(before, after) board pair into a per-tile dirty bitmap, extract the
dirty tiles into one flat sidecar buffer, and re-apply them onto a base
board (rpc/worker.py StripStep/StripFetch deltas, engine/checkpoint.py
delta checkpoints).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np

from ..models import CONWAY, LifeRule
from ..obs import instruments as _ins
from ..obs import metrics as _metrics

# -- knobs (README "Sparse stepping") ----------------------------------------

#: auto_plane routes boards at least this big through SparseBitPlane.
#: Below it the tile grid is too coarse for the bitmap to pay for its
#: upkeep: measured on CPU, a sparse R-pentomino at 1024² is a wash-to-
#: loss (64 coarse tiles, half active), while 4096²+ wins whenever the
#: board is actually sparse (the dense kernel's content-independent cost
#: grows with area; the sparse loop's does not)
SPARSE_MIN_CELLS = 4096 * 4096
#: active-tile fraction above which step_n routes the chunk through the
#: dense path: measured on CPU (and conservatively on v5e numbers), the
#: per-tile gather/scatter + bitmap upkeep costs ~2-4x a dense tile's
#: in-place step, so sparse stops winning near ~1/3 active
SPARSE_DENSITY_CROSSOVER = 0.25
#: probe the frontier for a period-2 cycle only while it is this small —
#: the probe costs two single-turn dispatches plus one whole-board
#: equality reduce per step_n call, which must stay negligible
P2_PROBE_MAX_TILES = 64
#: hard byte ceiling for one gather batch's halo-extended windows: past
#: it the dense path is taken even below the density crossover (the
#: bit-plane temporaries multiply the ext working set ~10x)
_SPARSE_EXT_BUDGET = 256 << 20
#: cap on while-loop blocks per dispatch: keeps the int32 active-tile
#: accumulator exact (blocks x capacity < 2^31 at every supported board
#: size) and bounds a single dispatch's wall; the host loop re-dispatches
#: the remainder seamlessly
_MAX_BLOCKS_PER_DISPATCH = 8192
#: turns advanced per gather/scatter round: the ext window carries an
#: H-cell column margin (the word-row margin is 32 cells already), so H
#: turns evolve inside one gathered batch before anything is scattered
#: back — amortising the per-turn launch overhead of the loop body H-fold
#: (the resident wire's K-batching argument, applied inside the chip).
#: Clamped per tile geometry: influence must stay within one tile ring.
SPARSE_TURNS_PER_GATHER = 8

#: wire/checkpoint delta tile geometry (cells) — the dirty-bitmap unit
#: the resident workers report and the delta codecs ship
WIRE_TILE_ROWS = 64
WIRE_TILE_COLS = 256


def _pow2_ceil(n: int) -> int:
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


def sparse_capable(rule: LifeRule, shape: tuple[int, int]) -> bool:
    """Whether the sparse plane may serve this (rule, geometry): rows
    packable (H % 32), no birth-on-0 (a B0 rule births cells in fully
    dead regions — the activity invariant does not hold), and the board
    big enough to pay for bitmap upkeep. ``GOL_SPARSE=on`` drops the
    size floor, ``GOL_SPARSE=off`` disables routing entirely (the knob
    row in README "Sparse stepping")."""
    mode = os.environ.get("GOL_SPARSE", "auto").lower()
    if mode == "off":
        return False
    h, w = shape
    if h % 32 != 0 or rule.birth_mask & 1:
        return False
    if mode == "on":
        return True
    return h * w >= SPARSE_MIN_CELLS


class SparseState:
    """The sparse plane's device state: the packed bitboard, the [GR, GC]
    activity bitmap (device bool), the host-cached active-tile count, and
    the steady-state verdict (``None`` / ``"still"`` / ``"period2"``,
    with ``alt`` holding the opposite phase of a period-2 cycle)."""

    __slots__ = ("packed", "grid", "count", "steady", "alt")

    def __init__(self, packed, grid, count: int, steady: Optional[str] = None,
                 alt=None):
        self.packed = packed
        self.grid = grid
        self.count = int(count)
        self.steady = steady
        self.alt = alt

    def block_until_ready(self):
        # the engine's growth-chunk sync + pipeline drain call this on
        # whatever the plane returned (engine/engine.py)
        self.packed.block_until_ready()
        return self


@functools.lru_cache(maxsize=None)
def _occupancy_program(shape: tuple[int, int], tr: int, tc: int):
    """(packed) -> dilated per-tile occupancy bitmap: the conservative
    initial active set (a tile with no live cell in itself or any
    neighbour cannot change under a non-B0 rule), also the rebuild after
    a dense chunk."""
    import jax
    import jax.numpy as jnp

    rows, width = shape
    gr, gc = rows // tr, width // tc

    @jax.jit
    def occupancy(packed):
        occ = jnp.any(
            packed.reshape(gr, tr, gc, tc) != 0, axis=(1, 3)
        )
        out = occ
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                if (dy, dx) != (0, 0):
                    out = out | jnp.roll(occ, (dy, dx), axis=(0, 1))
        return out

    return occupancy


def sparse_block_turns(tr: int, tc: int) -> int:
    """Turns one gathered block may advance for a (word rows, cols) tile:
    ``SPARSE_TURNS_PER_GATHER`` clamped so H turns of influence (H cells)
    stay within one tile ring — the dilate-by-one active-set update is
    exact only while H <= min(tile cell rows, tile cols, 32); the 32 is
    the word-row halo margin."""
    return max(1, min(SPARSE_TURNS_PER_GATHER, tr * 32, tc, 32))


@functools.lru_cache(maxsize=None)
def _sparse_program(
    shape: tuple[int, int],
    tr: int,
    tc: int,
    birth_mask: int,
    survive_mask: int,
    capacity: int,
    h: int,
):
    """The one-dispatch sparse stepping loop for a packed shape, a
    power-of-two gather capacity, and a block depth ``h`` (turns per
    gather). ``(packed, grid, n_blocks) -> (packed, grid, blocks_done,
    overflow, active_block_sum)``; ``n_blocks`` is a TRACED bound (the
    loop lowers to while_loop), so only the (capacity bucket, h) pair
    keys a compile — the jit-cache boundedness contract the
    frontier-churn test pins."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .bitpack import bit_step

    rows, width = shape
    gr, gc = rows // tr, width // tc

    def one_block(packed, grid):
        cnt = jnp.sum(grid, dtype=jnp.int32)
        trow, tcol = jnp.nonzero(grid, size=capacity, fill_value=0)
        # halo-extended windows, gathered ONCE per h-turn block: one word
        # row (32 cells) of margin above/below and h cell columns each
        # side — the dependency cone of h turns stays inside, so every
        # intermediate interior is exact (the ops/pallas_tiled.py
        # argument: the window's own cyclic rotate only contaminates a
        # creeping border ring, which the interior slice never reaches
        # while the step index <= margin). Torus wrap falls out of the
        # modular window indexing. Padding entries (nonzero's fill)
        # recompute tile 0 redundantly — always correct.
        wr = (trow[:, None] * tr - 1 + jnp.arange(tr + 2)[None, :]) % rows
        wc = (tcol[:, None] * tc - h + jnp.arange(tc + 2 * h)[None, :]) % width
        ext = packed[wr[:, :, None], wc[:, None, :]]
        inner = (slice(None), slice(1, -1), slice(h, -h))
        old = ext[inner]
        cur = ext
        # h turns inside the gathered batch; `changed` accumulates PER
        # TURN — an oscillator whose period divides h returns to its
        # start state by block end, and a start-vs-end diff would wrongly
        # freeze it
        changed = jnp.zeros((capacity,), bool)
        for _ in range(h):
            nxt = jax.vmap(
                lambda e: bit_step(
                    e, 0, birth_mask=birth_mask, survive_mask=survive_mask
                )
            )(cur)
            changed = changed | jnp.any(
                nxt[inner] != cur[inner], axis=(1, 2)
            )
            cur = nxt
        ok = cnt <= capacity
        # an overflowing block must commit NOTHING: writing the old
        # values back makes the scatter a no-op without an O(board)
        # select
        new = jnp.where(ok, cur[inner], old)
        packed = packed.at[
            wr[:, 1:-1, None], wc[:, None, h:-h]
        ].set(new)
        cgrid = jnp.zeros((gr, gc), bool).at[trow, tcol].max(changed)
        dil = cgrid
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                if (dy, dx) != (0, 0):
                    dil = dil | jnp.roll(cgrid, (dy, dx), axis=(0, 1))
        grid = jnp.where(ok, dil, grid)
        return packed, grid, cnt, ok

    @jax.jit
    def run(packed, grid, n_blocks):
        def cond(carry):
            _packed, grid, t, over, _act = carry
            # stop early on overflow (host re-buckets) and on an EMPTY
            # bitmap (still life: the remaining turns are no-ops the
            # host counts as done)
            return (t < n_blocks) & jnp.logical_not(over) & jnp.any(grid)

        def body(carry):
            packed, grid, t, over, act = carry
            packed, grid, cnt, ok = one_block(packed, grid)
            t = jnp.where(ok, t + 1, t)
            # int32 accumulation is exact (float32 rounds past 2^24 and
            # would silently skew the skip accounting on big boards);
            # the host caps blocks-per-dispatch so blocks x capacity
            # stays under 2^31
            act = act + jnp.where(ok, cnt, 0)
            return packed, grid, t, over | jnp.logical_not(ok), act

        packed, grid, t, over, act = lax.while_loop(
            cond,
            body,
            (packed, grid, jnp.int32(0), jnp.bool_(False), jnp.int32(0)),
        )
        return packed, grid, t, over, act

    return run


def compiled_program_count() -> int:
    """How many sparse turn-loop programs have been compiled (one per
    (shape, tile, rule, capacity-bucket)) — the frontier-churn jit-cache
    boundedness gate reads this."""
    return _sparse_program.cache_info().currsize


class SparseBitPlane:
    """Activity-sparse bitboard data plane (ops/plane.py interface plus
    the early-exit protocol: ``steady_kind``/``fast_forward``). Dense
    bit-exactness is the contract — the sparse path, the dense-crossover
    path, and the steady-state jumps all land on the same bits as
    ``BitPlane.step_n`` (tests/test_sparse.py pins it against the numpy
    oracle across tile boundaries)."""

    def __init__(
        self,
        rule: LifeRule = CONWAY,
        tile: Optional[tuple[int, int]] = None,
    ):
        if rule.birth_mask & 1:
            raise ValueError(
                f"rule {rule.rulestring} births on 0 neighbours: the "
                "activity invariant does not hold; use the dense plane"
            )
        from .plane import BitPlane

        self.rule = rule
        self.word_axis = 0  # rows packed: the activity tiles span word rows
        self._tile = tile  # explicit (word_rows, cols) override, else picked
        self._dense = BitPlane(rule, 0)

    # -- geometry ---------------------------------------------------------

    def _tiles_for(self, packed_shape: tuple[int, int]) -> tuple[int, int]:
        if self._tile is not None:
            tr, tc = self._tile
            if packed_shape[0] % tr or packed_shape[1] % tc:
                raise ValueError(
                    f"tile {self._tile} does not divide packed shape "
                    f"{packed_shape}"
                )
            return tr, tc
        from .pallas_tiled import sparse_tile_shape

        return sparse_tile_shape(packed_shape)

    def _grid_state(self, packed) -> SparseState:
        """Rebuild the activity bitmap from tile occupancy (encode, and
        after any dense chunk)."""
        import jax.numpy as jnp

        tr, tc = self._tiles_for(tuple(packed.shape))
        grid = _occupancy_program(tuple(packed.shape), tr, tc)(packed)
        return SparseState(packed, grid, int(jnp.sum(grid)))

    # -- plane interface --------------------------------------------------

    def encode(self, board):
        return self._grid_state(self._dense.encode(board))

    def decode(self, state) -> np.ndarray:
        return self._dense.decode(state.packed)

    def alive_count(self, state) -> int:
        return self._dense.alive_count(state.packed)

    def alive_cells(self, state):
        return self._dense.alive_cells(state.packed)

    def step_n(self, state, n: int):
        import jax.numpy as jnp

        n = int(n)
        if n <= 0:
            return state
        st = state
        shape = tuple(st.packed.shape)
        tr, tc = self._tiles_for(shape)
        total = (shape[0] // tr) * (shape[1] // tc)
        h_full = sparse_block_turns(tr, tc)
        birth, survive = self.rule.birth_mask, self.rule.survive_mask
        remaining = n
        while remaining > 0:
            if st.steady is not None:
                return self.fast_forward(st, remaining)
            capacity = min(_pow2_ceil(max(st.count, 8) * 2), _pow2_ceil(total))
            ext_bytes = capacity * (tr + 2) * (tc + 2 * h_full) * 4
            if (
                st.count > SPARSE_DENSITY_CROSSOVER * total
                or ext_bytes > _SPARSE_EXT_BUDGET
            ):
                # dense crossover: the whole remaining chunk through the
                # dense kernel routing (pow2 pieces keep its static turn
                # count quantised), then rebuild the bitmap from occupancy
                packed = st.packed
                left = remaining
                while left > 0:
                    piece = 1 << (left.bit_length() - 1)
                    packed = self._dense.step_n(packed, piece)
                    left -= piece
                st = self._grid_state(packed)
                remaining = 0
                break
            # h turns per gathered block; the tail under one full block
            # runs at h=1 — (capacity, h) pairs bound the compile count
            h_eff = h_full if remaining >= h_full else 1
            n_units = min(remaining // h_eff, _MAX_BLOCKS_PER_DISPATCH)
            program = _sparse_program(
                shape, tr, tc, birth, survive, capacity, h_eff
            )
            packed, grid, t, over, act = program(
                st.packed, st.grid, jnp.int32(n_units)
            )
            t = int(t)
            turns_done = t * h_eff
            count = int(jnp.sum(grid))
            if _metrics.enabled():
                _ins.ACTIVE_TILES.set(count)
                if turns_done:
                    _ins.TILE_SKIPS_TOTAL.inc(max(
                        0, turns_done * total - int(act) * h_eff
                    ))
            remaining -= turns_done
            if bool(over):
                # frontier outgrew the bucket mid-loop: the blocks already
                # done are committed; re-dispatch the rest one bucket up
                st = SparseState(packed, grid, count)
                continue
            if t < n_units:
                # the bitmap drained before the budget: still life — the
                # remaining turns are no-ops, done by definition
                st = SparseState(packed, grid, count, steady="still")
                if _metrics.enabled():
                    _ins.EARLY_EXIT_TOTAL.labels("still").inc()
                return st
            st = SparseState(packed, grid, count)
        if st.steady is None and 0 < st.count <= P2_PROBE_MAX_TILES:
            st = self._probe_period2(st, shape, tr, tc, birth, survive)
        return st

    def _probe_period2(self, st, shape, tr, tc, birth, survive):
        """Two probe turns on a small frontier: if board(t+2) == board(t)
        the run is blinker-stable and every later chunk is arithmetic.
        The probe mutates nothing — a failed probe discards its states."""
        import jax.numpy as jnp

        capacity = _pow2_ceil(max(st.count, 8) * 2)
        program = _sparse_program(shape, tr, tc, birth, survive, capacity, 1)
        p1, g1, t1, o1, _ = program(st.packed, st.grid, jnp.int32(1))
        if int(t1) != 1 or bool(o1):
            return st
        p2, _g2, t2, o2, _ = program(p1, g1, jnp.int32(1))
        if int(t2) != 1 or bool(o2):
            return st
        if bool(jnp.all(p2 == st.packed)) and not bool(
            jnp.all(p1 == st.packed)
        ):
            if _metrics.enabled():
                _ins.EARLY_EXIT_TOTAL.labels("period2").inc()
            return SparseState(
                st.packed, st.grid, st.count, steady="period2", alt=p1
            )
        return st

    # -- the early-exit protocol (ops/plane.py docstring) -----------------

    def steady_kind(self, state) -> Optional[str]:
        return state.steady

    def fast_forward(self, state, k: int):
        """``k`` turns of a steady state in O(1): a still life is itself,
        a period-2 cycle lands on phase ``k % 2``."""
        if state.steady == "period2" and int(k) % 2 == 1:
            return SparseState(
                state.alt, state.grid, state.count,
                steady="period2", alt=state.packed,
            )
        return state

    def from_packed(self, packed) -> SparseState:
        """Adopt an existing packed bitboard (e.g. ``bigboard.seed_packed``
        output) as a sparse state — the activity bitmap rebuilds from
        tile occupancy, exactly like ``encode``'s."""
        return self._grid_state(packed)

    def active_fraction(self, state) -> float:
        """Active tiles over total tiles — the sparsity figure the bench
        embeds (``active_fraction`` on the sparse-board cases)."""
        shape = tuple(state.packed.shape)
        tr, tc = self._tiles_for(shape)
        total = (shape[0] // tr) * (shape[1] // tc)
        return state.count / total if total else 0.0


def active_fraction_of(packed) -> float:
    """Active-tile fraction of a bare packed bitboard under the default
    tile geometry — what the bench stamps on the dense sparse-board
    cases (``active_fraction``) without constructing a plane."""
    plane = SparseBitPlane(CONWAY)
    return plane.active_fraction(plane.from_packed(packed))


# -- wire/checkpoint tile deltas (pure numpy) --------------------------------
#
# The dirty-tile unit the resident-strip workers report per StripStep, the
# broker ships per delta sync (protocol-5 sidecar: one flat uint8 buffer +
# the bool bitmap), and the delta checkpoints store. Tiles are a fixed
# (WIRE_TILE_ROWS x WIRE_TILE_COLS) grid with ragged right/bottom edges, so
# geometry is a pure function of the board shape — both ends derive it
# independently and the flat buffer's layout is deterministic.


def wire_tile_grid(
    shape: tuple[int, int],
    tile_rows: int = WIRE_TILE_ROWS,
    tile_cols: int = WIRE_TILE_COLS,
) -> tuple[int, int]:
    """(tile grid rows, cols) for a board/strip shape — ceil division,
    ragged edge tiles included."""
    h, w = shape
    return -(-h // tile_rows), -(-w // tile_cols)


def dirty_tile_grid(
    before: np.ndarray,
    after: np.ndarray,
    tile_rows: int = WIRE_TILE_ROWS,
    tile_cols: int = WIRE_TILE_COLS,
) -> np.ndarray:
    """Per-tile change bitmap between two same-shape boards: bool
    [grid_rows, grid_cols], True where any cell in the tile differs."""
    if before.shape != after.shape:
        raise ValueError(
            f"dirty grid needs same shapes, got {before.shape} vs "
            f"{after.shape}"
        )
    diff = before != after
    h, w = diff.shape
    rows = np.arange(0, h, tile_rows)
    cols = np.arange(0, w, tile_cols)
    return (
        np.add.reduceat(
            np.add.reduceat(diff.astype(np.int32), rows, axis=0),
            cols,
            axis=1,
        )
        > 0
    )


def _tile_bounds(shape, idx_r, idx_c, tile_rows, tile_cols):
    h, w = shape
    r0, c0 = idx_r * tile_rows, idx_c * tile_cols
    return r0, min(r0 + tile_rows, h), c0, min(c0 + tile_cols, w)


def extract_dirty_tiles(
    board: np.ndarray,
    dirty: np.ndarray,
    tile_rows: int = WIRE_TILE_ROWS,
    tile_cols: int = WIRE_TILE_COLS,
) -> np.ndarray:
    """The dirty tiles' bytes as ONE flat contiguous uint8 buffer, in
    row-major dirty-bitmap order — the protocol-5 sidecar payload of a
    delta frame. Deterministic layout: both ends derive tile bounds from
    (shape, bitmap) alone."""
    if dirty.shape != wire_tile_grid(board.shape, tile_rows, tile_cols):
        raise ValueError(
            f"dirty grid {dirty.shape} does not match board "
            f"{board.shape} at tile ({tile_rows}, {tile_cols})"
        )
    parts = []
    for idx_r, idx_c in zip(*np.nonzero(dirty)):
        r0, r1, c0, c1 = _tile_bounds(
            board.shape, idx_r, idx_c, tile_rows, tile_cols
        )
        parts.append(np.ascontiguousarray(board[r0:r1, c0:c1]).ravel())
    if not parts:
        return np.zeros(0, np.uint8)
    return np.concatenate(parts).astype(np.uint8, copy=False)


def apply_dirty_tiles(
    base: np.ndarray,
    dirty: np.ndarray,
    flat: np.ndarray,
    tile_rows: int = WIRE_TILE_ROWS,
    tile_cols: int = WIRE_TILE_COLS,
) -> np.ndarray:
    """Reconstruct a board from ``base`` plus a dirty-tile delta (a COPY;
    the base is never mutated). Raises ``ValueError`` on any geometry or
    length mismatch — a malformed delta must never half-apply. Callers
    that hold a digest of the intended result (the broker's committed
    strip chain, a delta checkpoint's embedded digest) verify it AFTER
    this, making delta application end-to-end safe."""
    if dirty.shape != wire_tile_grid(base.shape, tile_rows, tile_cols):
        raise ValueError(
            f"dirty grid {dirty.shape} does not match base {base.shape} "
            f"at tile ({tile_rows}, {tile_cols})"
        )
    flat = np.asarray(flat, np.uint8).ravel()
    out = np.array(base, np.uint8, copy=True)
    cursor = 0
    for idx_r, idx_c in zip(*np.nonzero(dirty)):
        r0, r1, c0, c1 = _tile_bounds(
            base.shape, idx_r, idx_c, tile_rows, tile_cols
        )
        size = (r1 - r0) * (c1 - c0)
        if cursor + size > flat.size:
            raise ValueError(
                f"delta payload truncated: needs >= {cursor + size} "
                f"bytes, got {flat.size}"
            )
        out[r0:r1, c0:c1] = flat[cursor:cursor + size].reshape(
            r1 - r0, c1 - c0
        )
        cursor += size
    if cursor != flat.size:
        raise ValueError(
            f"delta payload has {flat.size - cursor} trailing bytes "
            "beyond its dirty bitmap"
        )
    return out


def _selfcheck() -> int:
    """Oracle parity + early-exit smoke (scripts/check default path):
    an R-pentomino crossing tile boundaries, a still life draining the
    bitmap, an all-dead board, and a delta round-trip."""
    h = w = 128
    board = np.zeros((h, w), np.uint8)
    for dx, dy in ((1, 0), (2, 0), (0, 1), (1, 1), (1, 2)):
        board[h // 2 + dy, w // 2 + dx] = 255

    def oracle(b, n):
        ones = (b != 0).astype(np.int32)
        for _ in range(n):
            c = sum(
                np.roll(np.roll(ones, dy, 0), dx, 1)
                for dy in (-1, 0, 1)
                for dx in (-1, 0, 1)
                if (dy, dx) != (0, 0)
            )
            ones = ((c == 3) | ((ones == 1) & (c == 2))).astype(np.int32)
        return (ones * 255).astype(np.uint8)

    plane = SparseBitPlane(CONWAY, tile=(1, 16))
    state = plane.step_n(plane.encode(board), 150)
    if not np.array_equal(plane.decode(state), oracle(board, 150)):
        print("sparse selfcheck: R-pentomino parity FAILED")
        return 1
    block = np.zeros((64, 64), np.uint8)
    block[10:12, 10:12] = 255
    still_plane = SparseBitPlane(CONWAY, tile=(1, 2))
    st = still_plane.step_n(still_plane.encode(block), 50)
    if st.steady != "still":
        print("sparse selfcheck: still-life early exit FAILED")
        return 1
    dead = SparseBitPlane(CONWAY, tile=(1, 8))
    std = dead.step_n(dead.encode(np.zeros((64, 64), np.uint8)), 10)
    if dead.alive_count(std) != 0 or std.count != 0:
        print("sparse selfcheck: all-dead FAILED")
        return 1
    after = oracle(board, 3)
    dirty = dirty_tile_grid(board, after, 16, 16)
    flat = extract_dirty_tiles(after, dirty, 16, 16)
    if not np.array_equal(
        apply_dirty_tiles(board, dirty, flat, 16, 16), after
    ):
        print("sparse selfcheck: delta round-trip FAILED")
        return 1
    print(
        "sparse selfcheck ok: oracle parity (150 turns), still-life "
        "early exit, all-dead, delta round-trip"
    )
    return 0


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="activity-sparse stepping utilities"
    )
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="oracle parity + early-exit + delta round-trip smoke",
    )
    args = parser.parse_args(argv)
    if args.selfcheck:
        return _selfcheck()
    parser.error("nothing to do (want --selfcheck)")
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
