"""Automatic data-plane selection for the engine.

Given a rule and board geometry, pick the fastest correct single-device
step implementation available:

* Conway + a 32-divisible axis + TPU  -> the pallas VMEM bitboard kernel
  (~40x the roll stencil on v5e);
* Conway + a 32-divisible axis       -> the XLA bitboard step;
* anything else                       -> None (caller falls back to the
  roll-based stencil, which handles every rule and geometry).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from .stencil import CONWAY_BIRTH_MASK, CONWAY_SURVIVE_MASK


def auto_step_n_fn(rule, shape: tuple[int, int]) -> Optional[Callable]:
    """An engine-compatible ``(board_uint8, n) -> board_uint8`` or None."""
    if (rule.birth_mask, rule.survive_mask) != (
        CONWAY_BIRTH_MASK,
        CONWAY_SURVIVE_MASK,
    ):
        return None  # bit kernels encode Conway's T==3/T==4 rule only
    h, w = shape
    if h % 32 == 0:
        word_axis = 0  # rows packed: [H/32, W] keeps lanes wide on TPU
    elif w % 32 == 0:
        word_axis = 1
    else:
        return None

    if jax.devices()[0].platform == "tpu":
        from .pallas_stencil import pallas_bit_step_n_fn

        return pallas_bit_step_n_fn(word_axis=word_axis, interpret=False)

    from .bitpack import packed_step_n_fn

    return packed_step_n_fn(word_axis)
