"""Automatic data-plane selection for the engine.

Given a rule and board geometry, pick the fastest correct single-device
step implementation available:

* any life-like rule + a 32-divisible axis + TPU -> the pallas VMEM
  bitboard kernel (~40x the roll stencil on v5e);
* any life-like rule + a 32-divisible axis       -> the XLA bitboard step;
* indivisible geometry                            -> None (caller falls
  back to the roll-based stencil, which handles every geometry).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax


def choose_word_axis(shape: tuple[int, int]) -> Optional[int]:
    """The single-device packed-layout policy: pack rows when H divides by
    32 ([H/32, W] keeps the lane dimension W wide — fastest on TPU), else
    columns, else None (only the roll stencil applies)."""
    h, w = shape
    if h % 32 == 0:
        return 0
    if w % 32 == 0:
        return 1
    return None


def auto_plane(rule, shape: tuple[int, int]):
    """The fastest correct single-device data plane (ops/plane.py interface)
    for this rule/geometry, or None if only the roll stencil applies.

    Unlike the legacy ``auto_step_n_fn`` (which pack/unpacks per call), a
    plane keeps the board bit-packed across chunk dispatches — the engine's
    hot loop does no representation changes at all."""
    word_axis = choose_word_axis(shape)
    if word_axis is None:
        return None

    from .plane import BitPlane

    return BitPlane(rule, word_axis)


def auto_step_n_fn(rule, shape: tuple[int, int]) -> Optional[Callable]:
    """An engine-compatible ``(board_uint8, n) -> board_uint8`` or None.

    Legacy per-call pack/evolve/unpack form of ``auto_plane`` — same layout
    policy, kept for callers that want a plain step function."""
    word_axis = choose_word_axis(shape)
    if word_axis is None:
        return None

    if jax.devices()[0].platform == "tpu":
        from .pallas_stencil import pallas_bit_step_n_fn

        return pallas_bit_step_n_fn(word_axis=word_axis, interpret=False, rule=rule)

    from .bitpack import packed_step_n_fn

    return packed_step_n_fn(word_axis, rule=rule)
