"""Automatic data-plane selection for the engine.

Given a rule and board geometry, pick the fastest correct single-device
step implementation available:

* any life-like rule + a 32-divisible axis + TPU -> the pallas VMEM
  bitboard kernel (~40x the roll stencil on v5e);
* any life-like rule + a 32-divisible axis       -> the XLA bitboard step;
* indivisible geometry                            -> None (caller falls
  back to the roll-based stencil, which handles every geometry).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from ..obs import device as _device
from ..obs import instruments as _ins


def choose_word_axis(shape: tuple[int, int]) -> Optional[int]:
    """The single-device packed-layout policy: pack rows when H divides by
    32 ([H/32, W] keeps the lane dimension W wide — fastest on TPU), else
    columns, else None (only the roll stencil applies)."""
    h, w = shape
    if h % 32 == 0:
        return 0
    if w % 32 == 0:
        return 1
    return None


# (rulestring, shape) -> the selected plane (or None). Selection is pure
# in its inputs, so the FIRST call per key does the work — the HBM
# baseline sample and the tier-selection counter bump — and every later
# call is a dict hit. Before this cache, auto_plane sampled HBM and
# bumped the gauge on EVERY call: a hot serving loop admitting thousands
# of sessions per second paid a device memory_stats round-trip per
# universe and skewed the tier counter from "routing decisions" into
# "admissions" (ISSUE 7 satellite).
_PLANE_CACHE: dict = {}
_BATCH_PLANE_CACHE: dict = {}


def _note_selection(tier: str) -> None:
    """One selection event: an HBM sample at decision time plus the tier
    counter a Status snapshot shows routing decisions on. The PER-RUN
    baseline guarantee lives in Engine.run (which samples at every run
    start regardless of this cache); this sample only adds the
    first-decision-per-geometry data point."""
    _device.sample_hbm()
    _ins.OPS_PLANE_SELECTED_TOTAL.labels(tier).inc()


def auto_plane(rule, shape: tuple[int, int]):
    """The fastest correct single-device data plane (ops/plane.py interface)
    for this rule/geometry, or None if only the roll stencil applies.

    Unlike the legacy ``auto_step_n_fn`` (which pack/unpacks per call), a
    plane keeps the board bit-packed across chunk dispatches — the engine's
    hot loop does no representation changes at all. Decisions are cached
    per (rule, shape): repeated admissions of the same geometry cost a
    dict hit, not an HBM sample + counter bump per universe."""
    key = (rule.rulestring, shape)
    if key in _PLANE_CACHE:
        return _PLANE_CACHE[key]
    word_axis = choose_word_axis(shape)
    if word_axis is None:
        _note_selection("roll_stencil")
        plane = None
    else:
        from .sparse import SparseBitPlane, sparse_capable

        if word_axis == 0 and sparse_capable(rule, shape):
            # big boards go quiescent almost everywhere: the activity-
            # sparse plane steps only the live frontier and falls back
            # to the dense bitboard path by itself above the density
            # crossover (ops/sparse.py — the GOL_SPARSE knob and the
            # SPARSE_MIN_CELLS floor live there)
            _note_selection("sparse_bitplane")
            plane = SparseBitPlane(rule)
        else:
            from .bitpack import packed_shape
            from .fused import FusedBitPlane, fused_enabled
            from .pallas_stencil import fits_vmem

            if fused_enabled() and fits_vmem(
                packed_shape(*shape, word_axis), itemsize=4
            ):
                # the fused K-turns-per-launch tier (ops/fused.py) for
                # VMEM-FIT bitboards — the launch-bound class: the same
                # BitPlane step routing plus the fused step+count
                # protocol the engine's chunk driver consumes — its own
                # selection label AND its own kernel sites
                # (pallas.fused_*) so the roofline table attributes
                # fused dispatches separately from pallas.vmem_bit
                # (GOL_FUSED=off restores the classic tier). Boards past
                # the gate keep the classic tier: their chunk walls are
                # compute/memory-bound, and the counted driver's
                # per-chunk fold would be a full-board popcount inserted
                # into the pipelined dispatch chain for nothing.
                _note_selection("fused_bitplane")
                plane = FusedBitPlane(rule, word_axis)
            else:
                from .plane import BitPlane

                _note_selection("bitplane")
                plane = BitPlane(rule, word_axis)
    _PLANE_CACHE[key] = plane
    return plane


def auto_batch_plane(rule, shape: tuple[int, int]):
    """The fastest correct BATCHED data plane (ops/batched.py interface)
    for this per-universe rule/geometry: the batched bitboard family for
    32-divisible boards (pallas batch-grid kernel on TPU under the
    per-universe VMEM gate, vmapped XLA bitboard otherwise), the vmapped
    roll stencil for every other geometry. Always returns a plane —
    the byte tier handles everything. Same once-per-decision caching as
    ``auto_plane``: a session table admitting per universe never pays
    per-call telemetry."""
    key = (rule.rulestring, shape)
    if key in _BATCH_PLANE_CACHE:
        return _BATCH_PLANE_CACHE[key]
    from .batched import BatchBitPlane, BatchBytePlane

    word_axis = choose_word_axis(shape)
    if word_axis is None:
        _note_selection("batch_roll_stencil")
        plane = BatchBytePlane(rule)
    else:
        _note_selection("batch_bitplane")
        plane = BatchBitPlane(rule, word_axis)
    _BATCH_PLANE_CACHE[key] = plane
    return plane


def auto_step_n_fn(rule, shape: tuple[int, int]) -> Optional[Callable]:
    """An engine-compatible ``(board_uint8, n) -> board_uint8`` or None.

    Legacy per-call pack/evolve/unpack form of ``auto_plane`` — same layout
    policy, kept for callers that want a plain step function."""
    word_axis = choose_word_axis(shape)
    if word_axis is None:
        _note_selection("roll_stencil")
        return None

    if jax.devices()[0].platform == "tpu":
        from .pallas_stencil import pallas_bit_step_n_fn

        _note_selection("pallas_bit_step")
        return pallas_bit_step_n_fn(word_axis=word_axis, interpret=False, rule=rule)

    from .bitpack import packed_step_n_fn

    _note_selection("packed_xla_step")
    return packed_step_n_fn(word_axis, rule=rule)
