"""Automatic data-plane selection for the engine.

Given a rule and board geometry, pick the fastest correct single-device
step implementation available:

* any life-like rule + a 32-divisible axis + TPU -> the pallas VMEM
  bitboard kernel (~40x the roll stencil on v5e);
* any life-like rule + a 32-divisible axis       -> the XLA bitboard step;
* indivisible geometry                            -> None (caller falls
  back to the roll-based stencil, which handles every geometry).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from ..obs import device as _device
from ..obs import instruments as _ins


def choose_word_axis(shape: tuple[int, int]) -> Optional[int]:
    """The single-device packed-layout policy: pack rows when H divides by
    32 ([H/32, W] keeps the lane dimension W wide — fastest on TPU), else
    columns, else None (only the roll stencil applies)."""
    h, w = shape
    if h % 32 == 0:
        return 0
    if w % 32 == 0:
        return 1
    return None


def auto_plane(rule, shape: tuple[int, int]):
    """The fastest correct single-device data plane (ops/plane.py interface)
    for this rule/geometry, or None if only the roll stencil applies.

    Unlike the legacy ``auto_step_n_fn`` (which pack/unpacks per call), a
    plane keeps the board bit-packed across chunk dispatches — the engine's
    hot loop does no representation changes at all."""
    # baseline HBM reading at tier-selection time (run start): even a run
    # that dies in its first chunk leaves the pre-run occupancy on the
    # gauges, and the first turn-chunk sample then shows the step's delta
    _device.sample_hbm()
    word_axis = choose_word_axis(shape)
    if word_axis is None:
        # the caller falls back to the roll stencil; counted so a Status
        # snapshot shows WHICH tier runs are landing on (obs/)
        _ins.OPS_PLANE_SELECTED_TOTAL.labels("roll_stencil").inc()
        return None

    from .plane import BitPlane

    _ins.OPS_PLANE_SELECTED_TOTAL.labels("bitplane").inc()
    return BitPlane(rule, word_axis)


def auto_step_n_fn(rule, shape: tuple[int, int]) -> Optional[Callable]:
    """An engine-compatible ``(board_uint8, n) -> board_uint8`` or None.

    Legacy per-call pack/evolve/unpack form of ``auto_plane`` — same layout
    policy, kept for callers that want a plain step function."""
    _device.sample_hbm()  # pre-run HBM baseline, as in auto_plane
    word_axis = choose_word_axis(shape)
    if word_axis is None:
        _ins.OPS_PLANE_SELECTED_TOTAL.labels("roll_stencil").inc()
        return None

    if jax.devices()[0].platform == "tpu":
        from .pallas_stencil import pallas_bit_step_n_fn

        _ins.OPS_PLANE_SELECTED_TOTAL.labels("pallas_bit_step").inc()
        return pallas_bit_step_n_fn(word_axis=word_axis, interpret=False, rule=rule)

    from .bitpack import packed_step_n_fn

    _ins.OPS_PLANE_SELECTED_TOTAL.labels("packed_xla_step").inc()
    return packed_step_n_fn(word_axis, rule=rule)
