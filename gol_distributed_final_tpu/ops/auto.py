"""Automatic data-plane selection for the engine.

Given a rule and board geometry, pick the fastest correct single-device
step implementation available:

* any life-like rule + a 32-divisible axis + TPU -> the pallas VMEM
  bitboard kernel (~40x the roll stencil on v5e);
* any life-like rule + a 32-divisible axis       -> the XLA bitboard step;
* indivisible geometry                            -> None (caller falls
  back to the roll-based stencil, which handles every geometry).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax


def auto_step_n_fn(rule, shape: tuple[int, int]) -> Optional[Callable]:
    """An engine-compatible ``(board_uint8, n) -> board_uint8`` or None."""
    h, w = shape
    if h % 32 == 0:
        word_axis = 0  # rows packed: [H/32, W] keeps lanes wide on TPU
    elif w % 32 == 0:
        word_axis = 1
    else:
        return None

    if jax.devices()[0].platform == "tpu":
        from .pallas_stencil import pallas_bit_step_n_fn

        return pallas_bit_step_n_fn(word_axis=word_axis, interpret=False, rule=rule)

    from .bitpack import packed_step_n_fn

    return packed_step_n_fn(word_axis, rule=rule)
