"""Batched data planes — many independent universes in one device tensor.

Production traffic is millions of SMALL boards, not one huge one: the
serving unit is a session (one universe, one turn budget), and the device
unit is a batch tensor with a leading universe axis. These planes are the
batch-shaped mirror of ops/plane.py — same duck-typed surface, plus the
per-universe operations a session table needs (slot compaction, single-
universe decode, one batched alive reduction):

    encode(boards_uint8[B, H, W]) -> state      device batch state
    step_n(state, n) -> state                   n turns for ALL universes,
                                                one (or few) dispatches
    decode(state) -> np.uint8 [B, H, W]         full host batch
    decode_one(state, i) -> np.uint8 [H, W]     one universe (session exit)
    alive_counts(state) -> np.int64 [B]         ONE batched reduction
    take(state, rows) -> state                  slot compaction: keep rows,
                                                in order (a device gather)

Kernel family (ops/auto.auto_batch_plane picks the tier):

* ``BatchBitPlane`` — int32 bitboards [B, H/32, W]: the batched pallas
  VMEM kernel (explicit batch GRID dimension — the per-program working
  set stays one universe, so the single-board VMEM gate applies per
  universe) on real TPU, the vmapped XLA bitboard step elsewhere or past
  the gate.
* ``BatchBytePlane`` — uint8 [B, H, W] via the vmapped roll stencil:
  every geometry, any life-like rule.

Every tier is bit-identical per universe to its sequential single-board
counterpart: the batch axis only amortises the per-launch dispatch
latency that floors small boards (BENCH_r04: 128^2 latency-bound at
~0.10 us/turn), it never changes the arithmetic.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..models import CONWAY, LifeRule
from ..obs import device as _device

# batch shape -> whether the batched VMEM kernel actually compiled+ran
# (the ops/plane.py _VMEM_KERNEL_OK posture: first failure for a shape
# routes it to the XLA batch path, cached so the compile never re-runs)
_BATCH_VMEM_OK: dict = {}


def _require_batch(boards) -> np.ndarray:
    boards = np.asarray(boards, np.uint8)
    if boards.ndim != 3:
        raise ValueError(f"batch boards must be [B, H, W], got {boards.shape}")
    return boards


class _BatchPlane:
    """The representation-agnostic batch operations: slot compaction and
    join are pure leading-axis gathers/concats, identical for every
    tier — one definition so a compaction-semantics fix cannot make the
    tiers diverge."""

    def take(self, state, rows: Sequence[int]):
        import jax.numpy as jnp

        return jnp.take(state, jnp.asarray(list(rows), jnp.int32), axis=0)

    def append(self, state, other):
        import jax.numpy as jnp

        if state is None:
            return other
        return jnp.concatenate([state, other], axis=0)


class BatchBytePlane(_BatchPlane):
    """Batched identity representation: a device uint8 {0,255} [B, H, W]
    tensor stepped by the vmapped roll stencil — handles every geometry
    and rule (the roll-stencil tier of the batched family)."""

    def __init__(self, rule: LifeRule = CONWAY):
        self.rule = rule

    def encode(self, boards):
        import jax.numpy as jnp

        return jnp.asarray(_require_batch(boards))

    def step_n(self, state, n: int):
        from .stencil import step_n_batch

        return step_n_batch(
            state,
            int(n),
            birth_mask=self.rule.birth_mask,
            survive_mask=self.rule.survive_mask,
        )

    def step_n_counts(self, state, n: int):
        """The fused chunk program (ops/fused.py): n turns for every
        universe AND the per-universe alive reduction in ONE dispatch —
        the session table's demux count stops paying its own launch.
        Returns ``(state, np.int64[B])``; the host transfer forces the
        dispatch (the advance loop's timing contract)."""
        from .fused import _fused_byte_batch_counted_compiled, _meter_single

        n = int(n)
        if n <= 0:
            return state, self.alive_counts(state)
        fn = _fused_byte_batch_counted_compiled(
            n, self.rule.birth_mask, self.rule.survive_mask
        )
        out, counts = fn(state)
        _meter_single(n)
        return out, np.asarray(counts).astype(np.int64)

    def decode(self, state) -> np.ndarray:
        return np.asarray(state)

    def decode_one(self, state, i: int) -> np.ndarray:
        return np.asarray(state[i])

    def alive_counts(self, state) -> np.ndarray:
        from .reduce import alive_count_batch

        return np.asarray(alive_count_batch(state)).astype(np.int64)


class BatchBitPlane(_BatchPlane):
    """Batched int32 bitboard representation: [B, H/32, W] (word_axis=0)
    or [B, H, W/32]. ``step_n`` routes by tier: the batched pallas VMEM
    kernel (one grid program per universe) on real TPU while a SINGLE
    universe fits the VMEM working-set gate, else the vmapped XLA
    bitboard step; ``alive_counts`` is one batched popcount reduction."""

    def __init__(
        self,
        rule: LifeRule = CONWAY,
        word_axis: int = 0,
        interpret: Optional[bool] = None,
    ):
        from .pallas_stencil import default_interpret

        self.rule = rule
        self.word_axis = word_axis
        self.interpret = default_interpret() if interpret is None else interpret

    def encode(self, boards):
        import jax.numpy as jnp

        from .bitpack import pack_device_batch

        return pack_device_batch(
            jnp.asarray(_require_batch(boards)), self.word_axis
        )

    def step_n(self, state, n: int):
        from . import pallas_stencil
        from .bitpack import bit_step_n_batch
        from .plane import run_vmem_gated

        n = int(n)
        birth, survive = self.rule.birth_mask, self.rule.survive_mask
        shape = tuple(state.shape)

        def fallback():
            return _device.compile_and_call(
                "bitpack.xla_step_batch", bit_step_n_batch,
                state, n, self.word_axis, birth, survive,
                static_argnums=(1, 2, 3, 4),
            )

        # the VMEM gate is PER UNIVERSE (the batch grid gives each program
        # one board's working set); interpret-mode pallas would trace the
        # grid serially — B copies of the loop — so off-TPU the vmapped
        # XLA step is both the fast and the compile-sane tier
        if not self.interpret and pallas_stencil.fits_vmem(
            shape[1:], itemsize=4
        ):
            return run_vmem_gated(
                _BATCH_VMEM_OK,
                shape,
                lambda: pallas_stencil._bit_compiled_batch(
                    n, self.word_axis, self.interpret, birth, survive
                )(state),
                fallback,
            )
        return fallback()

    def step_n_counts(self, state, n: int):
        """The fused-K × batched chunk program (ops/fused.py): n turns
        for every universe (the batch-grid pallas kernel under the
        per-universe VMEM gate, vmapped XLA elsewhere) AND the batched
        popcount reduction in ONE dispatch — the sessions serving hot
        path pays one launch chain per chunk instead of step + count.
        Returns ``(state, np.int64[B])``; the host fold forces the
        dispatch (the advance loop's timing contract)."""
        from . import fused as _fused
        from . import pallas_stencil
        from .plane import run_vmem_gated

        n = int(n)
        if n <= 0:
            return state, self.alive_counts(state)
        birth, survive = self.rule.birth_mask, self.rule.survive_mask
        shape = tuple(state.shape)

        def fold(out_pc):
            out, pc = out_pc
            pc = np.asarray(pc)
            return out, np.sum(
                pc.reshape(pc.shape[0], -1), axis=1, dtype=np.int64
            )

        def xla_call():
            return _fused._fused_batch_counted_compiled(
                n, self.word_axis, self.interpret, birth, survive, False
            )(state)

        _fused._meter_single(n)
        if not self.interpret and pallas_stencil.fits_vmem(
            shape[1:], itemsize=4
        ):
            return fold(run_vmem_gated(
                _BATCH_VMEM_OK,
                shape,
                lambda: _fused._fused_batch_counted_compiled(
                    n, self.word_axis, self.interpret, birth, survive, True
                )(state),
                xla_call,
            ))
        return fold(xla_call())

    def decode(self, state) -> np.ndarray:
        from .bitpack import unpack_device_batch

        return np.asarray(unpack_device_batch(state, self.word_axis))

    def decode_one(self, state, i: int) -> np.ndarray:
        from .bitpack import unpack_device

        return np.asarray(unpack_device(state[i], self.word_axis))

    def alive_counts(self, state) -> np.ndarray:
        from .bitpack import alive_count_packed_batch

        return alive_count_packed_batch(state)
