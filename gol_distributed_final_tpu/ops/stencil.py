"""The core Game of Life stencil, TPU-first.

Where the reference evolves the board with a per-cell Go loop that re-counts
the 8 toroidal neighbours up to 4x per cell (reference: worker/worker.go:15-70,
``calculateNextState`` / ``calculateSurroundings``), this module expresses one
turn as a fused, branch-free XLA computation over the whole ``uint8[H, W]``
board: 8 ``jnp.roll`` shifts summed into a neighbour-count plane, then a
vectorised rule lookup. XLA fuses the shifts + sum + select into a single
pass over HBM; there is no per-cell control flow, so the VPU processes whole
(8, 128) vregs per tick.

Cell encoding matches the reference wire format: alive = 255, dead = 0
(reference: README.md:27, worker/worker.go:44).

Rules are expressed as 9-bit masks over the neighbour count (bit ``n`` set =>
the transition applies at count ``n``), which generalises Conway B3/S23 to the
whole life-like family while keeping the masks static under ``jit`` — the
lookup compiles to a shift+and, not a gather.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

ALIVE = 255
DEAD = 0

# Conway's Life: B3/S23.
CONWAY_BIRTH_MASK = 1 << 3
CONWAY_SURVIVE_MASK = (1 << 2) | (1 << 3)

_NEIGHBOUR_OFFSETS = tuple(
    (dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1) if (dy, dx) != (0, 0)
)


def neighbour_counts(board: jax.Array) -> jax.Array:
    """Toroidal 8-neighbour count, ``uint8[H, W]`` with values in 0..8.

    ``jnp.roll`` gives the wrap-around semantics the reference implements with
    explicit edge branches (worker/worker.go:48-63).
    """
    ones = (board != 0).astype(jnp.uint8)
    total = jnp.zeros_like(ones)
    for dy, dx in _NEIGHBOUR_OFFSETS:
        total = total + jnp.roll(ones, (dy, dx), axis=(0, 1))
    return total


def counts_from_extended(ext: jax.Array, h: int, w: int) -> jax.Array:
    """8-neighbour counts for the (h, w) centre of an extended array that
    already carries a 1-cell border (halo rows/cols or local wrap).

    Shared by every data plane that materialises halos explicitly: the
    shard_map mesh step (parallel/halo.py) and the worker strip kernel
    (rpc/worker.py) — one definition, so rule/encoding changes can't make
    the planes diverge.
    """
    ones = (ext != 0).astype(jnp.uint8)
    counts = jnp.zeros((h, w), jnp.uint8)
    for dy in (0, 1, 2):
        for dx in (0, 1, 2):
            if (dy, dx) == (1, 1):
                continue
            counts = counts + ones[dy : dy + h, dx : dx + w]
    return counts


def apply_rule(
    board: jax.Array,
    counts: jax.Array,
    *,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
) -> jax.Array:
    """Life-like transition: next state from current state + neighbour count.

    With Conway masks this is exactly the reference's rule (worker/worker.go:
    41-46): dead cell with 3 neighbours is born; live cell survives on 2-3.
    """
    alive = board != 0
    shifted_b = jnp.right_shift(jnp.uint16(birth_mask), counts.astype(jnp.uint16))
    shifted_s = jnp.right_shift(jnp.uint16(survive_mask), counts.astype(jnp.uint16))
    born = (shifted_b & 1) != 0
    survives = (shifted_s & 1) != 0
    next_alive = jnp.where(alive, survives, born)
    return jnp.where(next_alive, jnp.uint8(ALIVE), jnp.uint8(DEAD))


@functools.partial(jax.jit, static_argnames=("birth_mask", "survive_mask"))
def step(
    board: jax.Array,
    *,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
) -> jax.Array:
    """One turn on a single device. The ``calculateNextState`` equivalent
    for the full board (reference: worker/worker.go:15-70)."""
    return apply_rule(
        board,
        neighbour_counts(board),
        birth_mask=birth_mask,
        survive_mask=survive_mask,
    )


@functools.partial(jax.jit, static_argnames=("n", "birth_mask", "survive_mask"))
def step_n(
    board: jax.Array,
    n: int,
    *,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
) -> jax.Array:
    """``n`` turns in one device dispatch via ``lax.fori_loop``.

    This is the engine's hot path: the per-turn host round-trip of the
    reference broker (one RPC fan-out per turn, broker/broker.go:135-224)
    becomes a single compiled loop that never leaves the device. ``n`` is
    static; the engine amortises compilation by chunking with a doubling
    schedule (engine/engine.py).
    """
    body = functools.partial(
        apply_rule, birth_mask=birth_mask, survive_mask=survive_mask
    )
    return lax.fori_loop(0, n, lambda _, b: body(b, neighbour_counts(b)), board)


@functools.partial(jax.jit, static_argnames=("n", "birth_mask", "survive_mask"))
def step_n_batch(
    boards: jax.Array,
    n: int,
    *,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
) -> jax.Array:
    """``n`` turns over a BATCH of independent universes ``uint8[B, H, W]``
    in one device dispatch — the multi-universe serving shape (millions of
    small boards, not one huge one). ``vmap`` maps the same per-board
    ``apply_rule``/``neighbour_counts`` over the leading axis, so each
    universe's evolution is bit-identical to a sequential ``step_n`` run,
    and the per-turn dispatch latency that floors small boards (BENCH_r04:
    128^2 latency-bound at ~0.10 us/turn) is amortised over all B."""
    body = functools.partial(
        apply_rule, birth_mask=birth_mask, survive_mask=survive_mask
    )
    one = jax.vmap(lambda b: body(b, neighbour_counts(b)))
    return lax.fori_loop(0, n, lambda _, bs: one(bs), boards)


@functools.partial(jax.jit, static_argnames=("n", "birth_mask", "survive_mask"))
def alive_history(
    board: jax.Array,
    n: int,
    *,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
) -> jax.Array:
    """Per-turn alive counts for turns 1..n in ONE dispatch, on the BYTE
    stencil — the sibling of ``bitpack.alive_history`` for boards whose
    packed axis does not divide by 32 (the reference's 16x16 fixture
    family, count_test.go:45-51 + check/alive/16x16.csv; VERDICT r4
    item 3). Padding the torus out to a packable size is NOT an option:
    zero rows between the wrap seam would change the evolution."""

    def body(state, _):
        nxt = step(state, birth_mask=birth_mask, survive_mask=survive_mask)
        return nxt, jnp.sum(nxt != 0, dtype=jnp.int32)

    _, counts = lax.scan(body, board, None, length=n)
    return counts
