"""Fused K-turns-per-launch kernels — killing the per-turn dispatch floor.

BENCH_r04 pins the small/hot board case (128²) as LAUNCH-BOUND (~0.10
µs/turn whole-board, but ~5-30 µs/turn the moment a caller issues one
kernel launch per turn), and obs/perf.py now proves it per site: for
every board that fits on-chip, dispatch — not FLOPs — is the ceiling.
This module advances **K turns inside one launch** so the launch floor is
paid once per K turns instead of once per turn, for every tier:

* **whole-board VMEM** (byte AND packed-bitboard): the K-turn kernel body
  runs K steps in-register (torus wrap is the in-kernel rotate — no halo
  needed), and ``n`` turns decompose into a ``lax.fori_loop`` of full-K
  launches plus a power-of-two remainder ladder, ALL inside one jitted
  program — the host dispatches once per ``step_n`` call, the device
  launches once per K turns.
* **grid-tiled bitboard** (boards past the whole-board VMEM gate): each
  grid program loads its tile plus the SAME 8-word-row halo strips the
  single-turn kernel reads (ops/pallas_tiled.py), then steps K times
  in-register; every step contaminates one more halo row inward — the
  shrinking dependency cone ``_recompute_rows`` uses on the broker — so
  up to ``_SUBLANE`` = 8 turns run per launch on one halo read before the
  garbage reaches the interior the write keeps. K-deep halos cost ZERO
  extra VMEM here: the 8-row strips Mosaic alignment already forces ARE
  the K ≤ 8 cone budget.
* **grid-tiled byte**: same shape with 32-row strips (the uint8 sublane
  tile), cone budget K ≤ 32 (clamped to the same pow2 ladder).
* **batched grid** (the sessions serving hot path): one grid program per
  universe × K turns per launch — fused-K × batched, so PR 7's batch
  amortisation and this PR's launch fusion compound.
* **fused step+count programs**: a chunk's evolution AND its alive
  reduction in ONE dispatch (``*_counted`` / ``*_counts``) — the
  engine's chunk driver and the session table's demux reduction stop
  paying a second dispatch per chunk, and the 2-second ticker serves the
  folded count with no dispatch at all.
* **``fused_strip_steps``**: the resident worker's StripStep batch as one
  jitted shrinking-form program (rpc/worker.py routes big strips here) —
  PR 5's K-turn wire batching and the fused kernel compound: one RPC, one
  dispatch, K turns.

K is ALWAYS quantised to a power of two (``quantise_k``) before it
reaches a compile cache, mirroring the session batcher: chunk churn in a
long-lived broker lands on the bounded key set {1, 2, 4, 8}, never on a
fresh Mosaic compile per distinct chunk size.

Metering: ``gol_fused_launches_total`` counts device launches issued by
this tier and ``gol_fused_turns_per_launch`` their K distribution — the
pair the README "Fused stepping" section documents and obs/lint.py
enforces. Kernel sites (``pallas.fused_bit`` / ``pallas.fused_byte`` /
``pallas.fused_tiled`` / ``pallas.fused_bit_batch`` / ``fused.*``) are
attributed separately from the classic tiers so the PR 12 roofline table
shows the fused sites' bound-class flip on their own rows.

Every path is bit-identical to the serial per-turn computation — fusing
changes WHEN launches happen, never the arithmetic (tests/test_fused.py
pins parity across K, odd remainders, geometries, rules, and the batch).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..obs import device as _device
from ..obs import instruments as _ins
from . import pallas_stencil
from .bitpack import bit_step_n, bit_step_n_batch
from .pallas_tiled import _EXT_BYTES_TARGET, _SUBLANE, can_tile, tiled_pallas_call
from .plane import BitPlane, run_vmem_gated
from .stencil import CONWAY_BIRTH_MASK, CONWAY_SURVIVE_MASK

#: the fused-K ceiling: the tiled kernels' 8-word-row halo strips are the
#: dependency-cone budget (one row consumed per fused step), and the
#: whole-board ladder keeps the same bound so ONE quantiser serves every
#: tier. Power of two by construction.
FUSED_MAX_K = _SUBLANE  # 8
FUSED_K_DEFAULT = 8

#: byte-tiled geometry: uint8 Mosaic tiles are (32, 128), so halo strips
#: are 32 cell rows deep and blocks align to 32 rows / full width
_BYTE_SUBLANE = 32
_BYTE_LANE = 128

#: shape -> whether the fused whole-board VMEM kernels actually
#: compiled+ran (the ops/plane.py _VMEM_KERNEL_OK posture: fits_vmem is
#: an estimate, so the FIRST Mosaic failure for a shape routes it to the
#: tiled/XLA fallback and is cached, never re-attempted) — one cache per
#: kernel family
_FUSED_VMEM_OK: dict = {}
_FUSED_BYTE_VMEM_OK: dict = {}
_FUSED_BATCH_VMEM_OK: dict = {}


def fused_enabled() -> bool:
    """The ``GOL_FUSED`` routing knob (ops/auto.py): ``on``/``auto``
    (default) route VMEM-fit bitboards to the fused plane, ``off``
    keeps the classic tiers."""
    return os.environ.get("GOL_FUSED", "auto").lower() != "off"


def quantise_k(k: int) -> int:
    """The fused-K quantiser: the largest power of two <= min(k,
    FUSED_MAX_K), >= 1 — the SAME pow2 posture as the session batcher's
    chunk quantisation, so chunk churn never compiles a fresh kernel
    (compile keys land on {1, 2, 4, 8})."""
    k = max(1, min(int(k), FUSED_MAX_K))
    return 1 << (k.bit_length() - 1)


def _ladder(n: int, k: int) -> tuple[int, tuple[int, ...]]:
    """``n`` turns as ``full`` launches of K plus a pow2 remainder ladder
    (one launch per set bit of ``n % k``) — launch sizes drawn from the
    bounded set {k, k/2, ..., 1}, so a long-lived process compiles at
    most log2(k)+1 kernel bodies per tier."""
    full, rem = divmod(n, k)
    rem_ks = tuple(1 << b for b in range(k.bit_length()) if rem >> b & 1)
    return full, rem_ks


def _meter_ladder(n: int, k: int) -> None:
    full, rem_ks = _ladder(n, k)
    _ins.FUSED_LAUNCHES_TOTAL.inc(full + len(rem_ks))
    if full:
        _ins.FUSED_TURNS_PER_LAUNCH.observe_n(float(k), full)
    for r in rem_ks:
        _ins.FUSED_TURNS_PER_LAUNCH.observe(float(r))


def _meter_single(n: int) -> None:
    """One launch covering all ``n`` turns (the fused step+count programs
    and the XLA fallbacks — still one fused dispatch, K == n)."""
    _ins.FUSED_LAUNCHES_TOTAL.inc()
    _ins.FUSED_TURNS_PER_LAUNCH.observe(float(n))


def _resolve(rule, birth_mask, survive_mask) -> tuple[int, int]:
    if rule is not None:
        return rule.birth_mask, rule.survive_mask
    return (
        CONWAY_BIRTH_MASK if birth_mask is None else birth_mask,
        CONWAY_SURVIVE_MASK if survive_mask is None else survive_mask,
    )


def _jit_ladder(launch_k, rem_launches, full: int):
    """ONE jitted program: ``full`` K-turn launches under a device-side
    ``lax.fori_loop`` + the remainder launches — the host dispatches
    once regardless of n."""

    @jax.jit
    def run(state):
        out = state
        if full:
            out = lax.fori_loop(0, full, lambda _, s: launch_k(s), out)
        for launch in rem_launches:
            out = launch(out)
        return out

    return run


# -- packed-bitboard tier -----------------------------------------------------


@functools.lru_cache(maxsize=None)
def _fused_vmem_bit_compiled(
    n: int, k: int, word_axis: int, interpret: bool,
    birth_mask: int, survive_mask: int,
):
    # the ladder's stage body: the K-turn VMEM pallas launch on real
    # TPU; under the interpreter the SAME K-turn evolution as a plain
    # bit_step chain — the BatchBitPlane posture (interpret-mode pallas
    # pays per-launch emulation overhead that would bury the very floor
    # this tier removes; off-TPU there is no launch floor, only the
    # ladder structure matters and parity is bit-exact either way)
    def stage(turns: int):
        if not interpret:
            return pallas_stencil.bit_pallas_call(
                turns, word_axis, interpret, birth_mask, survive_mask
            )
        # positional statics: jit(static_argnums=...) rejects keywords
        return lambda p, t=turns: bit_step_n(
            p, t, word_axis, birth_mask, survive_mask
        )

    full, rem_ks = _ladder(n, k)
    return _device.instrument_jit(
        "pallas.fused_bit",
        _jit_ladder(stage(k), [stage(r) for r in rem_ks], full),
    )


@functools.lru_cache(maxsize=None)
def _fused_tiled_compiled(
    n: int, k: int, shape: tuple[int, int], interpret: bool,
    birth_mask: int, survive_mask: int, word_axis: int = 0,
    block_rows: int | None = None, block_cols: int | None = None,
):
    full, rem_ks = _ladder(n, k)
    launch_k = tiled_pallas_call(
        k, shape, interpret, birth_mask, survive_mask,
        block_rows, block_cols, word_axis,
    )
    rems = [
        tiled_pallas_call(
            r, shape, interpret, birth_mask, survive_mask,
            block_rows, block_cols, word_axis,
        )
        for r in rem_ks
    ]
    return _device.instrument_jit(
        "pallas.fused_tiled", _jit_ladder(launch_k, rems, full)
    )


def fused_bit_step_n(
    packed,
    n: int,
    *,
    k: Optional[int] = None,
    word_axis: int = 0,
    rule=None,
    birth_mask: Optional[int] = None,
    survive_mask: Optional[int] = None,
    interpret: Optional[bool] = None,
    block_rows: int | None = None,
    block_cols: int | None = None,
):
    """``n`` turns on an int32 bitboard, K turns per device launch, one
    host dispatch. Routes by the per-tile VMEM gate: the whole board as
    the tile when it fits (halo = the in-kernel torus rotate), the
    grid-tiled fused kernel (8-row halo strips, shrinking cone) when the
    packed shape tiles, else the XLA bitboard step (no launch floor to
    fuse — one dispatch either way). Bit-identical to ``bit_step_n``."""
    n = int(n)
    if n <= 0:
        return packed
    birth, survive = _resolve(rule, birth_mask, survive_mask)
    if interpret is None:
        interpret = pallas_stencil.default_interpret()
    kq = quantise_k(FUSED_K_DEFAULT if k is None else k)
    shape = tuple(packed.shape)

    def tiled_or_xla():
        if can_tile(shape):
            fn = _fused_tiled_compiled(
                n, kq, shape, interpret, birth, survive, word_axis,
                block_rows, block_cols,
            )
            _meter_ladder(n, kq)
            return fn(packed)
        _meter_single(n)
        return bit_step_n(packed, n, word_axis, birth, survive)

    if pallas_stencil.fits_vmem(shape, itemsize=4) and block_rows is None \
            and block_cols is None:
        def kernel_call():
            out = _fused_vmem_bit_compiled(
                n, kq, word_axis, interpret, birth, survive
            )(packed)
            _meter_ladder(n, kq)
            return out

        return run_vmem_gated(_FUSED_VMEM_OK, shape, kernel_call, tiled_or_xla)
    return tiled_or_xla()


# -- byte-stencil tier --------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _fused_vmem_byte_compiled(
    n: int, k: int, birth_mask: int, survive_mask: int, interpret: bool
):
    from .stencil import step_n

    # interpret-mode stages route through the XLA roll stencil (the same
    # posture as the bitboard ladder above): bit-identical, and the
    # emulated per-launch overhead never lands in the ladder
    def stage(turns: int):
        if not interpret:
            return pallas_stencil.byte_pallas_call(
                turns, birth_mask, survive_mask, interpret
            )
        return lambda b, t=turns: step_n(
            b, t, birth_mask=birth_mask, survive_mask=survive_mask
        )

    full, rem_ks = _ladder(n, k)
    return _device.instrument_jit(
        "pallas.fused_byte",
        _jit_ladder(stage(k), [stage(r) for r in rem_ks], full),
    )


def can_tile_byte(shape: tuple[int, int]) -> bool:
    """Byte boards the fused byte-tile kernel serves: 32-row-aligned
    blocks (the uint8 Mosaic sublane tile) with more than one block,
    128-lane-aligned full width, and a (32+64)-row ext within the VMEM
    working-set budget (carried int32 in-kernel)."""
    h, w = shape
    return (
        h % _BYTE_SUBLANE == 0
        and h // _BYTE_SUBLANE >= 2
        and w % _BYTE_LANE == 0
        and (_BYTE_SUBLANE + 2 * _BYTE_SUBLANE) * w * 4 <= _EXT_BYTES_TARGET
    )


def _byte_tiled_plan(h: int, w: int) -> int:
    """Block rows for the fused byte-tile kernel: the largest 32-aligned
    divisor of h whose int32 ext fits the VMEM ext budget."""
    best = _BYTE_SUBLANE
    for pb in range(_BYTE_SUBLANE, h + 1, _BYTE_SUBLANE):
        if h % pb == 0 and (pb + 2 * _BYTE_SUBLANE) * w * 4 <= _EXT_BYTES_TARGET:
            best = pb
    return best


def _fused_byte_tiled_kernel(
    top_ref, body_ref, bot_ref, out_ref, *, turns, birth_mask, survive_mask,
    interpret,
):
    # the byte mirror of _tiled_kernel_rows: 32-row halo strips (uint8
    # tile alignment), full-width blocks (column torus = the lane
    # rotate), K steps on the int32 ext — one CELL row of contamination
    # per step from each edge, discarded by the interior write
    ext = jnp.concatenate(
        [top_ref[:], body_ref[:], bot_ref[:]], axis=0
    ).astype(jnp.int32)
    one_turn = pallas_stencil.byte_turn_fn(birth_mask, survive_mask, interpret)
    for _ in range(turns):
        ext = one_turn(ext)
    out_ref[:] = ext[_BYTE_SUBLANE:-_BYTE_SUBLANE, :].astype(jnp.uint8)


def byte_tiled_pallas_call(
    turns: int, shape: tuple[int, int], birth_mask: int, survive_mask: int,
    interpret: bool,
):
    """The RAW fused byte-tile launch: ``turns`` turns per grid program
    over (pb, W) uint8 blocks with 32-row halo strips."""
    from jax.experimental import pallas as pl

    if not 1 <= turns <= _BYTE_SUBLANE:
        raise ValueError(
            f"byte tiles support 1..{_BYTE_SUBLANE} fused turns, got {turns}"
        )
    h, w = shape
    pb = _byte_tiled_plan(h, w)
    gr = h // pb
    rsub = pb // _BYTE_SUBLANE  # 32-row tiles per block

    def up(i):
        return ((i - 1) % gr) * rsub + rsub - 1

    def down(i):
        return ((i + 1) % gr) * rsub

    kernel = functools.partial(
        _fused_byte_tiled_kernel,
        turns=turns,
        birth_mask=birth_mask,
        survive_mask=survive_mask,
        interpret=interpret,
    )
    one = pl.pallas_call(
        kernel,
        grid=(gr,),
        in_specs=[
            pl.BlockSpec((_BYTE_SUBLANE, w), lambda i: (up(i), 0)),
            pl.BlockSpec((pb, w), lambda i: (i, 0)),
            pl.BlockSpec((_BYTE_SUBLANE, w), lambda i: (down(i), 0)),
        ],
        out_specs=pl.BlockSpec((pb, w), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.uint8),
        interpret=interpret,
    )
    return lambda board: one(board, board, board)


@functools.lru_cache(maxsize=None)
def _fused_byte_tiled_compiled(
    n: int, k: int, shape: tuple[int, int], birth_mask: int,
    survive_mask: int, interpret: bool,
):
    full, rem_ks = _ladder(n, k)
    launch_k = byte_tiled_pallas_call(k, shape, birth_mask, survive_mask, interpret)
    rems = [
        byte_tiled_pallas_call(r, shape, birth_mask, survive_mask, interpret)
        for r in rem_ks
    ]
    return _device.instrument_jit(
        "pallas.fused_byte", _jit_ladder(launch_k, rems, full)
    )


def fused_step_n(
    board,
    n: int,
    *,
    k: Optional[int] = None,
    rule=None,
    birth_mask: Optional[int] = None,
    survive_mask: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """The byte-stencil tier's fused form: ``n`` turns on a uint8 {0,255}
    board, K turns per launch — the whole board as the VMEM tile when it
    fits, 32-row-strip byte tiles when the geometry aligns, else the
    roll stencil (already one dispatch for all n). Engine-compatible
    ``(board, n) -> board``; bit-identical to the serial stencil."""
    n = int(n)
    board = jnp.asarray(board)
    if n <= 0:
        return board
    birth, survive = _resolve(rule, birth_mask, survive_mask)
    if interpret is None:
        interpret = pallas_stencil.default_interpret()
    kq = quantise_k(FUSED_K_DEFAULT if k is None else k)
    shape = tuple(board.shape)

    def tiled_or_roll():
        if can_tile_byte(shape):
            fn = _fused_byte_tiled_compiled(
                n, kq, shape, birth, survive, interpret
            )
            _meter_ladder(n, kq)
            return fn(board)
        from .stencil import step_n

        _meter_single(n)
        return step_n(board, n, birth_mask=birth, survive_mask=survive)

    if pallas_stencil.fits_vmem(shape, itemsize=4):
        def kernel_call():
            out = _fused_vmem_byte_compiled(n, kq, birth, survive, interpret)(
                board
            )
            _meter_ladder(n, kq)
            return out

        return run_vmem_gated(
            _FUSED_BYTE_VMEM_OK, shape, kernel_call, tiled_or_roll
        )
    return tiled_or_roll()


# -- batched grid variant (fused-K x batched: the serving hot path) -----------


@functools.lru_cache(maxsize=None)
def _fused_batch_compiled(
    n: int, k: int, word_axis: int, interpret: bool,
    birth_mask: int, survive_mask: int,
):
    full, rem_ks = _ladder(n, k)
    launch_k = pallas_stencil.bit_batch_pallas_call(
        k, word_axis, interpret, birth_mask, survive_mask
    )
    rems = [
        pallas_stencil.bit_batch_pallas_call(
            r, word_axis, interpret, birth_mask, survive_mask
        )
        for r in rem_ks
    ]
    return _device.instrument_jit(
        "pallas.fused_bit_batch", _jit_ladder(launch_k, rems, full)
    )


def fused_bit_step_n_batch(
    packed,
    n: int,
    *,
    k: Optional[int] = None,
    word_axis: int = 0,
    rule=None,
    birth_mask: Optional[int] = None,
    survive_mask: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """The batched grid variant: ``int32[B, Hw, W]`` — one grid program
    per universe × K turns per launch, so the launch floor is amortised
    B×K ways (fused-K × PR 7's batch axis). Per-universe VMEM gate; the
    vmapped XLA step serves interpret mode (a serially-traced B-grid
    would compile B copies) and gate-exceeding universes."""
    n = int(n)
    if n <= 0:
        return packed
    birth, survive = _resolve(rule, birth_mask, survive_mask)
    if interpret is None:
        interpret = pallas_stencil.default_interpret()
    kq = quantise_k(FUSED_K_DEFAULT if k is None else k)
    shape = tuple(packed.shape)

    def xla_batch():
        _meter_single(n)
        return bit_step_n_batch(packed, n, word_axis, birth, survive)

    if not interpret and pallas_stencil.fits_vmem(shape[1:], itemsize=4):
        def kernel_call():
            out = _fused_batch_compiled(
                n, kq, word_axis, interpret, birth, survive
            )(packed)
            _meter_ladder(n, kq)
            return out

        return run_vmem_gated(
            _FUSED_BATCH_VMEM_OK, shape, kernel_call, xla_batch
        )
    return xla_batch()


# -- fused step+count programs ------------------------------------------------


@functools.lru_cache(maxsize=None)
def _fused_bit_counted_compiled(
    n: int, word_axis: int, interpret: bool, birth_mask: int, survive_mask: int
):
    """n turns + the row popcounts of the result in ONE dispatch: the
    engine chunk driver's program — the alive count folds on device into
    the same launch chain, so the ticker's count-only Retrieve costs no
    extra dispatch (engine/engine.py caches the folded counts)."""
    launch = pallas_stencil.bit_pallas_call(
        n, word_axis, interpret, birth_mask, survive_mask
    )

    @jax.jit
    def run(packed):
        out = launch(packed)
        return out, jnp.sum(lax.population_count(out), axis=1)

    return _device.instrument_jit("pallas.fused_bit", run)


@functools.lru_cache(maxsize=None)
def _fused_batch_counted_compiled(
    n: int, word_axis: int, interpret: bool, birth_mask: int,
    survive_mask: int, use_pallas: bool,
):
    """The sessions chunk program: n turns for every universe AND the
    per-universe popcount reduction in ONE dispatch — the demux count no
    longer pays its own launch (engine/sessions.py's step_n_counts
    path)."""
    if use_pallas:
        step = pallas_stencil.bit_batch_pallas_call(
            n, word_axis, interpret, birth_mask, survive_mask
        )
    else:
        def step(packed):
            return bit_step_n_batch(
                packed, n, word_axis, birth_mask, survive_mask
            )

    @jax.jit
    def run(packed):
        out = step(packed)
        return out, jnp.sum(lax.population_count(out), axis=-1)

    return _device.instrument_jit(
        "pallas.fused_bit_batch" if use_pallas else "fused.xla_bit_batch", run
    )


@functools.lru_cache(maxsize=None)
def _fused_byte_batch_counted_compiled(n: int, birth_mask: int, survive_mask: int):
    from .stencil import step_n_batch

    @jax.jit
    def run(boards):
        out = step_n_batch(
            boards, n, birth_mask=birth_mask, survive_mask=survive_mask
        )
        return out, jnp.sum(out != 0, axis=(1, 2), dtype=jnp.int32)

    return _device.instrument_jit("fused.xla_byte_batch", run)


def fold_counts(counts) -> int:
    """Host int64 fold of a fused count vector (the alive_count_packed
    overflow posture: per-row int32 partials, int64 total)."""
    return int(np.sum(np.asarray(counts), dtype=np.int64))


class FusedBitPlane(BitPlane):
    """The fused-tier data plane ops/auto.py routes VMEM-fit bitboards
    to: a ``BitPlane`` (identical ``step_n`` — the whole-n single launch
    is already optimal for a plain step) plus the fused step+count
    protocol the engine's device-resident chunk driver consumes:

        step_n_counted(state, n) -> (state, counts)

    ``counts`` is a device vector whose int64 host sum (``fold_counts``)
    is the alive count of the returned state — folded ON DEVICE in the
    SAME dispatch as the chunk's turns, so the host touches the board
    only at chunk boundaries and the count-only Retrieve ticker is
    served from the cache with no dispatch at all."""

    def step_n_counted(self, state, n: int):
        n = int(n)
        shape = tuple(state.shape)
        birth, survive = self.rule.birth_mask, self.rule.survive_mask
        if n > 0 and pallas_stencil.fits_vmem(shape, itemsize=4):
            def kernel_call():
                fn = _fused_bit_counted_compiled(
                    n, self.word_axis, self.interpret, birth, survive
                )
                out = fn(state)
                _meter_single(n)
                return out

            return run_vmem_gated(
                _FUSED_VMEM_OK, shape, kernel_call,
                lambda: self._counted_fallback(state, n),
            )
        return self._counted_fallback(state, n)

    def _counted_fallback(self, state, n: int):
        # past the VMEM gate (or a gate-failed shape): the classic step
        # routing plus a separate popcount — same result, two dispatches
        from .bitpack import _row_popcounts

        out = self.step_n(state, n) if n > 0 else state
        return out, _row_popcounts(out)


# -- the resident worker's fused strip batch (rpc/worker.py) ------------------


def _jax_strip_turn(x):
    """One shrinking-form strip turn, the exact jnp mirror of
    rpc/worker._strip_step: columns wrap locally, rows shrink by one per
    side (the halo rows are consumed), values stay uint8 {0, 255} —
    bit-identical to the numpy kernel (Conway, like the reference)."""
    ext = jnp.concatenate([x[:, -1:], x, x[:, :1]], axis=1)
    b = (ext != 0).astype(jnp.int32)
    counts = (
        b[:-2, :-2] + b[:-2, 1:-1] + b[:-2, 2:]
        + b[1:-1, :-2] + b[1:-1, 2:]
        + b[2:, :-2] + b[2:, 1:-1] + b[2:, 2:]
    )
    alive = b[1:-1, 1:-1] == 1
    nxt = jnp.where(alive, (counts == 2) | (counts == 3), counts == 3)
    return jnp.where(nxt, jnp.uint8(255), jnp.uint8(0))


@functools.lru_cache(maxsize=None)
def _strip_steps_compiled(shape: tuple[int, int], k: int, h: int, attest: bool):
    @jax.jit
    def run(padded):
        cur = padded
        counts = []
        bands = []
        for i in range(k):
            cur = _jax_strip_turn(cur)
            off = k - (i + 1)
            counts.append(jnp.sum(cur[off : off + h] != 0, dtype=jnp.int32))
            if attest:
                band = 2 * off
                bands.append((cur[:band], cur[cur.shape[0] - band :]))
        return cur, jnp.stack(counts), bands

    return _device.instrument_jit("fused.strip", run)


def fused_strip_steps(padded, k: int, strip_rows: int, *, attest: bool = False):
    """K turns of a resident strip from its depth-K halo block in ONE
    dispatch — the fused kernel running under the resident workers'
    StripStep (rpc/worker.py routes big strips here), so PR 5's K-turn
    wire batching compounds with launch fusion: one RPC, one dispatch,
    K turns.

    ``padded`` is the (strip_rows + 2K, w) uint8 block ([top K; strip;
    bottom K]); returns ``(strip, counts, bands)`` where ``strip`` is the
    K-turns-later strip, ``counts[i]`` the strip's alive count after step
    i+1 (the AliveCellsCount feed), and ``bands`` — when ``attest`` — the
    per-step shrinking attestation band pairs, materialised so the
    caller's digest fold is byte-identical to the numpy path's
    (rpc/integrity.py cross-attestation survives the routing)."""
    k = int(k)
    fn = _strip_steps_compiled(
        tuple(padded.shape), k, int(strip_rows), bool(attest)
    )
    strip, counts, bands = fn(jnp.asarray(padded))
    _meter_single(k)
    return (
        np.asarray(strip),
        [int(c) for c in np.asarray(counts)],
        [(np.asarray(t), np.asarray(b)) for t, b in bands],
    )
