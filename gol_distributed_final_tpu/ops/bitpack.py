"""Bit-packed Game of Life: 32 cells per int32 lane element.

The byte-per-cell stencil moves 8 bits of state per cell per turn and
spends a full VPU lane-element per cell. Packing 32 cells into each int32
word gives

* 32x smaller state (a 512x512 board becomes 16x512 words = 32 KiB),
* ~1 op/cell/turn via bit-sliced carry-save adders instead of ~12.

Layout is chosen by ``word_axis`` — which SPATIAL axis is packed into
bits. ``word_axis=0`` (default) packs rows: array shape [H/32, W], so the
lane dimension stays W wide (VPU-friendly: 512 lanes busy, and the
per-turn bit twiddling runs on (8,128) int32 vregs). ``word_axis=1``
packs columns: [H, W/32].

Per turn, for each word: the three neighbours along the packed axis
collapse into a 2-bit sum (full adder over bit-shifted words, with carry
bits crossing word boundaries via the adjacent element — torus wrap falls
out of the rotate being cyclic). Then the triple of those 2-bit sums
along the other axis is added with a 4-bit adder tree, giving the total T
of the 3x3 neighbourhood INCLUDING the cell. Conway in terms of T:
``next = (T == 3) | (alive & (T == 4))`` — no self-subtraction needed.

Everything is plain jnp bitwise ops on int32, so the SAME step runs under
jit on any backend, inside shard_map, and inside a pallas kernel (Mosaic
supports i32 vectors natively; pass ``rot1=pltpu.roll``-backed rotates).

Reference equivalence: bit-exact with worker/worker.go:15-70 (verified
against the NumPy oracle and golden CSVs in tests/test_bitpack.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

WORD = 32


def packed_shape(height: int, width: int, word_axis: int = 0) -> tuple[int, int]:
    """The packed-array shape of a ``height x width`` board: the chosen
    spatial axis collapses 32 cells into each int32 word. The ONE place
    this arithmetic lives — seeding, streamed loading, and pod placement
    all derive their global shapes from it."""
    if word_axis == 0:
        return height // WORD, width
    return height, width // WORD


def pack(board: np.ndarray | jax.Array, word_axis: int = 0) -> jax.Array:
    """uint8 {0,255} board -> int32 bitboard. The packed spatial axis must
    be divisible by 32. Bit j of word w along that axis = cell 32*w + j."""
    bits = (np.asarray(board) != 0).astype(np.uint32)
    if word_axis == 1:
        h, w = bits.shape
        if w % WORD:
            raise ValueError(f"width {w} not divisible by {WORD}")
        words = bits.reshape(h, w // WORD, WORD)
        axis = 2
    else:
        h, w = bits.shape
        if h % WORD:
            raise ValueError(f"height {h} not divisible by {WORD}")
        words = bits.reshape(h // WORD, WORD, w)
        axis = 1
    weights_shape = [1, 1, 1]
    weights_shape[axis] = WORD
    weights = (1 << np.arange(WORD, dtype=np.uint64)).reshape(weights_shape)
    packed = (words.astype(np.uint64) * weights).sum(axis=axis).astype(np.uint32)
    return jnp.asarray(packed.view(np.int32))


def unpack(packed: np.ndarray | jax.Array, word_axis: int = 0) -> np.ndarray:
    """int32 bitboard -> uint8 {0,255} board."""
    words = np.asarray(packed).view(np.uint32)
    shifts = np.arange(WORD, dtype=np.uint32)
    if word_axis == 1:
        bits = (words[:, :, None] >> shifts) & 1
        board = bits.reshape(words.shape[0], -1)
    else:
        bits = (words[:, None, :] >> shifts[:, None]) & 1
        board = bits.reshape(-1, words.shape[1])
    return (board * 255).astype(np.uint8)


@functools.partial(jax.jit, static_argnums=(1,))
def pack_device(board, word_axis: int = 0):
    """On-device jnp ``pack``: uint8 {0,255} [H, W] -> int32 bitboard.

    Runs under jit (and inside pjit with a sharded board), so the engine's
    hot path never round-trips through host numpy (the round-1 pack/unpack
    were numpy-only, costing a D2H+H2D per chunk dispatch)."""
    bits = (board != 0).astype(jnp.uint32)
    h, w = board.shape
    if word_axis == 1:
        if w % WORD:
            raise ValueError(f"width {w} not divisible by {WORD}")
        words = bits.reshape(h, w // WORD, WORD)
        axis = 2
        shifts = jnp.arange(WORD, dtype=jnp.uint32)
    else:
        if h % WORD:
            raise ValueError(f"height {h} not divisible by {WORD}")
        words = bits.reshape(h // WORD, WORD, w)
        axis = 1
        shifts = jnp.arange(WORD, dtype=jnp.uint32)[:, None]
    packed = jnp.sum(words << shifts, axis=axis, dtype=jnp.uint32)
    return lax.bitcast_convert_type(packed, jnp.int32)


@functools.partial(jax.jit, static_argnums=(1,))
def unpack_device(packed, word_axis: int = 0):
    """On-device jnp ``unpack``: int32 bitboard -> uint8 {0,255} [H, W]."""
    words = lax.bitcast_convert_type(packed, jnp.uint32)
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    if word_axis == 1:
        bits = (words[:, :, None] >> shifts) & 1
        board = bits.reshape(words.shape[0], -1)
    else:
        bits = (words[:, None, :] >> shifts[:, None]) & 1
        board = bits.reshape(-1, words.shape[1])
    return (board * 255).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=(1,))
def pack_device_batch(boards, word_axis: int = 0):
    """On-device batched ``pack``: uint8 {0,255} [B, H, W] -> int32
    bitboards with a leading batch axis ([B, H/32, W] for word_axis=0).
    One dispatch packs every universe of a session batch."""
    return jax.vmap(lambda b: pack_device(b, word_axis))(boards)


@functools.partial(jax.jit, static_argnums=(1,))
def unpack_device_batch(packed, word_axis: int = 0):
    """On-device batched ``unpack``: int32 [B, ...] -> uint8 [B, H, W]."""
    return jax.vmap(lambda p: unpack_device(p, word_axis))(packed)


@jax.jit
def _row_popcounts(packed):
    # int32 row sums are safe (a row covers <= 32 * W cells); the final
    # accumulation happens on host in int64 so boards >= 2^31 cells can't
    # overflow the count
    return jnp.sum(lax.population_count(packed), axis=1)


def alive_count_packed(packed) -> int:
    """Alive cells of a bitboard: a device-side popcount reduction — no
    unpack, ~4*H bytes cross the device boundary instead of H*W.

    Multihost-safe: on a global array with non-addressable shards (a
    ``jax.distributed`` job where each process owns a row range) the row
    popcounts are all-gathered across processes, so every rank returns the
    GLOBAL count — ``np.asarray`` on such an array would raise."""
    pc = _row_popcounts(packed)
    if getattr(pc, "is_fully_addressable", True):
        return int(np.sum(np.asarray(pc), dtype=np.int64))
    from jax.experimental import multihost_utils

    # tiled=True: assemble the GLOBAL row vector (required for global
    # non-fully-addressable inputs) rather than stacking per-process copies
    gathered = multihost_utils.process_allgather(pc, tiled=True)
    return int(np.sum(gathered, dtype=np.int64))


def alive_cells_packed(packed, word_axis: int = 0):
    """``FinalTurnComplete``-shaped ``Cell(x, y)`` list straight from a
    bitboard, row-major like the reference's nested loop
    (broker/broker.go:47-58) — but O(populated rows), not O(cells): a
    device-side popcount finds the nonzero packed rows, only THOSE rows
    cross the device boundary, and only they unpack. A stabilised
    65536^2 R-pentomino costs a few row transfers instead of a 4 GiB
    raster. Dense boards degrade gracefully to a full unpack.

    Single-host states only (the cell list is inherently host-side)."""
    from ..utils.cell import Cell

    pc = np.asarray(_row_popcounts(packed))
    nz = np.nonzero(pc)[0]
    if nz.size == 0:
        return []
    sub = np.asarray(jnp.take(packed, jnp.asarray(nz), axis=0))
    board = unpack(sub, word_axis)
    ys, xs = np.nonzero(board)
    if word_axis == 0:
        ys = nz[ys // WORD] * WORD + ys % WORD
    else:
        ys = nz[ys]
    return [Cell(int(x), int(y)) for x, y in zip(xs, ys)]


def _default_rot1(a, shift: int, axis: int):
    return jnp.roll(a, shift, axis=axis)


def _full_adder3(a, b, c):
    """Bitplane sum of three 1-bit values: (parity, carry)."""
    axb = a ^ b
    return axb ^ c, (a & b) | (c & axb)


from .stencil import CONWAY_BIRTH_MASK, CONWAY_SURVIVE_MASK


def _rule_planes(birth_mask: int, survive_mask: int):
    """T-value sets for a B/S rule, where T = 3x3 sum INCLUDING the cell.

    A dead cell has T = neighbours, a live cell T = neighbours + 1, so:
    dead next-alive iff T in birth; live next-alive iff (T-1) in survive.
    """
    dead_ts = [t for t in range(9) if birth_mask >> t & 1]
    live_ts = [t + 1 for t in range(9) if survive_mask >> t & 1]
    return dead_ts, live_ts


def bit_step(
    packed,
    word_axis: int = 0,
    rot1=None,
    *,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
):
    """One life-like turn on an int32 bitboard.

    ``rot1(a, shift, axis)`` overrides the cyclic rotate primitive
    (e.g. a pltpu.roll wrapper inside pallas kernels). The rule is encoded
    as equality tests on the 4 bitplanes of the inclusive 3x3 sum T —
    Conway's B3/S23 needs exactly two (T==3, alive&T==4); other rules cost
    ~4 ops per additional member of the birth/survive sets.
    """
    rot = rot1 or _default_rot1
    elem_axis = 1 - word_axis

    # neighbours along the PACKED axis: bit shifts, carries crossing word
    # boundaries through the adjacent word element (cyclic => torus wrap)
    def packed_minus(x):  # cell at packed-coordinate - 1
        carry = lax.shift_right_logical(rot(x, 1, word_axis), WORD - 1)
        return lax.shift_left(x, 1) | carry

    def packed_plus(x):  # cell at packed-coordinate + 1
        carry = lax.shift_left(rot(x, -1, word_axis), WORD - 1)
        return lax.shift_right_logical(x, 1) | carry

    mid = packed
    # 2-bit sums v = prev + self + next along the packed axis
    v0, v1 = _full_adder3(packed_minus(mid), mid, packed_plus(mid))

    # triple sum along the other axis: T = v(-1) + v + v(+1), 4 bitplanes
    l0, r0 = rot(v0, 1, elem_axis), rot(v0, -1, elem_axis)
    l1, r1 = rot(v1, 1, elem_axis), rot(v1, -1, elem_axis)

    a_s, a_c = _full_adder3(l0, v0, r0)  # weight 1 plane + weight-2 carry
    b_s, b_c = _full_adder3(l1, v1, r1)  # weight 2 plane + weight-4 carry
    c_s = a_c ^ b_s  # weight-2 plane of T
    c_c = a_c & b_s  # weight-4 carry
    t2 = b_c ^ c_c  # weight-4 plane
    t3 = b_c & c_c  # weight-8 plane
    planes = (a_s, c_s, t2, t3)  # T = p0 + 2*p1 + 4*p2 + 8*p3, T in 0..9

    def eq(value: int):
        acc = None
        for bit, plane in enumerate(planes):
            # the weight-8 plane only separates T in {8, 9} from {0, 1}:
            # for a target in 2..7 the aliasing value (target + 8 > 9) is
            # unreachable, so the ~p3 term is dead weight on the hot path
            if bit == 3 and 2 <= value <= 7:
                continue
            term = plane if value >> bit & 1 else ~plane
            acc = term if acc is None else acc & term
        return acc

    def any_eq(values):
        acc = None
        for v in values:
            acc = eq(v) if acc is None else acc | eq(v)
        return acc

    dead_ts, live_ts = _rule_planes(birth_mask, survive_mask)
    # Hoist the shared T-values out of the mid-select: with D = dead-only,
    # L = live-only, C = common, the select (~m & (C|D)) | (m & (C|L))
    # simplifies to C | (~m & D) | (m & L) — for Conway (C={3}, D={},
    # L={4}) that is eq(3) | (mid & eq(4)), the minimal form.
    common = sorted(set(dead_ts) & set(live_ts))
    dead_only = [t for t in dead_ts if t not in common]
    live_only = [t for t in live_ts if t not in common]
    terms = []
    if common:
        terms.append(any_eq(common))
    if dead_only:
        terms.append(~mid & any_eq(dead_only))
    if live_only:
        terms.append(mid & any_eq(live_only))
    if not terms:
        return packed ^ packed  # a zero of the right dtype/shape
    out = terms[0]
    for t in terms[1:]:
        out = out | t
    return out


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def bit_step_n(
    packed,
    n: int,
    word_axis: int = 0,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
):
    """n turns on the bitboard in one dispatch."""
    return lax.fori_loop(
        0,
        n,
        lambda _, b: bit_step(
            b, word_axis, birth_mask=birth_mask, survive_mask=survive_mask
        ),
        packed,
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def bit_step_n_batch(
    packed,
    n: int,
    word_axis: int = 0,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
):
    """n turns over a batch of independent bitboards ``int32[B, ...]`` in
    one dispatch: ``vmap`` of ``bit_step`` over the leading axis inside a
    single ``lax.fori_loop``. The XLA tier of the batched kernel family —
    every geometry the single-board bitboard step handles, amortising the
    per-launch dispatch latency over all B universes."""
    one = jax.vmap(
        lambda b: bit_step(
            b, word_axis, birth_mask=birth_mask, survive_mask=survive_mask
        )
    )
    return lax.fori_loop(0, n, lambda _, bs: one(bs), packed)


@jax.jit
def _batch_word_popcounts(packed):
    # per-universe popcounts reduced over the trailing (word) axes on
    # device, int32-safe per partial row; the final per-universe total is
    # accumulated on host in int64 (the alive_count_packed posture)
    return jnp.sum(lax.population_count(packed), axis=-1)


def alive_count_packed_batch(packed) -> np.ndarray:
    """Per-universe alive counts of a batched bitboard ``int32[B, ...]``
    as ``np.int64[B]`` — ONE batched device-side popcount reduction, a
    [B, rows]-int32 transfer, and a host int64 fold. The demux source for
    every per-session AliveCellsCount ticker in a session batch."""
    pc = np.asarray(_batch_word_popcounts(packed))
    return np.sum(pc.reshape(pc.shape[0], -1), axis=1, dtype=np.int64)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def alive_history(
    packed,
    n: int,
    word_axis: int = 0,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
):
    """Per-turn alive counts for turns 1..n in ONE dispatch.

    ``lax.scan`` steps the bitboard and popcounts every state on device, so
    validating the reference's strictest fixture — every line of the 10k-turn
    ``check/alive/*.csv`` goldens (count_test.go:45-51) — costs one dispatch
    and an [n]-int32 transfer instead of n round-trips."""
    def body(state, _):
        nxt = bit_step(
            state, word_axis, birth_mask=birth_mask, survive_mask=survive_mask
        )
        return nxt, jnp.sum(lax.population_count(nxt))

    _, counts = lax.scan(body, packed, None, length=n)
    return counts


def packed_step_n_fn(word_axis: int = 0, rule=None):
    """Engine-compatible ``(board_uint8, n) -> board_uint8``: pack, evolve
    on the bitboard, unpack — all on-device, no host round-trips."""
    birth = rule.birth_mask if rule else CONWAY_BIRTH_MASK
    survive = rule.survive_mask if rule else CONWAY_SURVIVE_MASK

    def step_n(board, n):
        from ..obs import device as _device

        packed = pack_device(jnp.asarray(board), word_axis)
        # timed lower/compile + cost analysis on first call per shape
        # (obs/device.py) — the legacy engine path's compile telemetry
        out = _device.compile_and_call(
            "bitpack.xla_step", bit_step_n,
            packed, int(n), word_axis, birth, survive,
            static_argnums=(1, 2, 3, 4),
        )
        return unpack_device(out, word_axis)

    return step_n
