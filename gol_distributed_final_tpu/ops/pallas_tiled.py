"""Grid-tiled pallas bitboard kernel — the fast path for boards whose
packed form exceeds the whole-board VMEM gate (ops/pallas_stencil.py,
packed <= ~1.5 MiB). Before round 2 the fallback was the XLA bitboard
step, which at 16384^2 runs ~5x slower: XLA materialises the ~10
bit-plane intermediates of ``bit_step`` in HBM once the working set stops
fitting on-chip.

The kernel processes the packed array block by block; each grid step
extends its block with halo data from the neighbouring blocks (wrapping
modulo the grid, so torus wrap falls out of the index arithmetic), steps
the extended window with ``bit_step`` — whose bit-plane temporaries stay
in VMEM — and writes back the interior. All ``n`` turns run in ONE jitted
dispatch (lax.fori_loop around the pallas_call), one launch per turn.

Two regimes, chosen by ``_plan`` from the ext byte budget (all measured
on a real v5e, 2026-07; a third "resident" regime — round 2's full-block
halos on the theory that small boards stay VMEM-resident between calls —
measured strictly slower than ``rows`` at every size and was removed):

* ``rows`` — boards of moderate width: full-width blocks, 8-row
  edge-strip halos above/below (Mosaic block shapes must be sublane(8)-
  aligned, so strips cannot be single word-rows). Reads are contiguous
  HBM row ranges, (1 + 16/pb)x read + 1x write per turn, ext (pb+16, W).
  4096^2: 6.8 us/turn vs 7.5-10 for the round-2 full-block scheme
  re-measured today (its committed 2.95 did not reproduce).
* ``grid2d`` — boards too wide for a full-width ext to fit VMEM (packed
  width >= ~8192, e.g. 65536^2 whose packed rows are 256 KiB — the shape
  that overflowed the round-2 full-width-only scheme): blocks split BOTH
  axes; each grid step reads its body plus the eight neighbours' edge
  tiles (8-row/128-lane strips and corners) into a fully tile-aligned
  (pb+16, wb+256) ext. Keeping the ext tile-aligned matters: a minimal
  (pb+2, wb+2) ext measured ~2.5x slower from Mosaic's unaligned-lane
  handling. Column-halo reads are strided, which is why full-width
  regimes are preferred whenever they fit.

Cyclic rotates inside ``bit_step`` only contaminate the ext's outer
ring, which the interior slice discards; where the ext spans the full
width, the lane rotate IS the column torus wrap.

Measured at 16384^2 (grid2d (128, 2048)): 128-130 us/turn (round 2's
full-block scheme: 138). The limit is NOT HBM traffic (~75 us at these
blocks) but the VPU compute roofline: ~39 bitwise ops/word x 1.27
halo-overhead x 8.4M words at ~4e12 int32 ops/s is ~115 us — the kernel
runs within ~10% of that. Multi-turn-per-launch variants (amortising
halo DMA over up to 127 turns of in-VMEM evolution — the halo tiles are
256 cell-rows / 128 cell-columns deep) measured SLOWER (~165 us/turn):
the in-kernel fori_loop defeats Mosaic's pipelining, so the single-turn
form stands.

At 65536^2 effective bandwidth is ~350 GB/s against a 995 GB/s XLA
streaming ceiling — and a TRIVIAL pallas copy kernel (out = in + 1)
over the same grid/blocks measures the same ~315 GB/s: the life kernel
sits AT the pallas pipeline's own HBM-DMA ceiling on this
chip/toolchain, so the gap is Mosaic's grid pipeline, not this kernel.
Also ruled out empirically: strided body reads (word_axis=1's narrow
[H, W/32] layout makes every read contiguous yet measured only ~5%
faster — 3.41 vs 3.58 ms/turn) and block-shape choice (a sweep moved
<7%). Net: the kernel is compute-roofline-bound at <= 16384^2 and
pallas-pipeline-DMA-bound above. Both packings are supported
(``word_axis=``); the halo geometry is packing-agnostic because output
word (i, j) reads words (i+-1, j+-1) either way (ops/bitpack.py).

Reference equivalence: each turn computes exactly worker/worker.go:15-70's
``calculateNextState`` over the full board (via ops/bitpack.bit_step —
bit-exact against the numpy oracle and the ``check/`` goldens at every
size the suite and bench cover, up to 65536^2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..obs import device as _device
from .bitpack import bit_step
from .stencil import CONWAY_BIRTH_MASK, CONWAY_SURVIVE_MASK

_SUBLANE = 8  # int32 sublane tile: min rows of any block
_LANE = 128  # lane tile: min cols of any block

# bit_step keeps ~10 bit-plane temporaries live over the ext; with the
# double-buffered in/out pipeline the per-step working set is ~12x ext
# bytes. Measured on v5e: 1.27 MiB exts compile and run, ~2.5 MiB fail
# Mosaic allocation. Larger blocks shrink the halo-overhead fraction, so
# target the largest ext that fits.
_EXT_BYTES_TARGET = 1_340_000


def can_tile(shape: tuple[int, int]) -> bool:
    """Mosaic block shapes must be sublane(8)/lane(128)-aligned: the packed
    row count must factor into 8-row blocks with more than one block, and
    the width into 128-lane blocks."""
    return (
        shape[0] % _SUBLANE == 0
        and shape[0] // _SUBLANE >= 2
        and shape[1] % _LANE == 0
    )


def _aligned_divisors(n: int, align: int):
    return [d for d in range(align, n + 1, align) if n % d == 0]


def sparse_tile_shape(packed_shape: tuple[int, int]) -> tuple[int, int]:
    """Default activity-tile geometry for the sparse layer (ops/sparse.
    SparseBitPlane): (word rows, cols) per tile, aligned with this
    kernel's Mosaic tiling (8-sublane x 128-lane) when the packed shape
    allows — so a sparse frontier's gather windows coincide with the
    tiles the dense kernel would process — and falling back to smaller
    exact divisors so small boards still get a multi-tile grid (at
    least ~8 tiles per axis when any divisor allows it)."""

    def pick(n: int, cap: int, min_grid: int = 8) -> int:
        divisors = [d for d in range(1, cap + 1) if n % d == 0]
        fine = [d for d in divisors if n // d >= min_grid]
        return max(fine) if fine else max(divisors)

    rows, width = packed_shape
    return pick(rows, _SUBLANE), pick(width, _LANE)


def _validate_block(name: str, val: int, total: int, align: int) -> None:
    if val % align or total % val:
        raise ValueError(
            f"{name}={val} must be a multiple of {align} dividing {total}"
        )


def _ext_shape(pb: int, wb: int, width: int) -> tuple[int, int]:
    """The extended-window shape a (pb, wb) block is computed over: +16
    halo rows always; +256 halo cols only when the lane axis is split
    (full-width blocks wrap columns with the cyclic lane rotate instead)."""
    return pb + 2 * _SUBLANE, wb + (2 * _LANE if wb < width else 0)


def _pick_blocks(rows: int, width: int) -> tuple[int, int]:
    """The (block_rows, block_cols) ``_plan`` would run ``rows``/``grid2d``
    with: minimise the ext/body compute ratio subject to the ext byte
    budget, preferring full width. An (8, 128) block always qualifies
    (ext 96 KiB), so any ``can_tile`` shape gets a valid choice."""
    best = None
    for pb in _aligned_divisors(rows, _SUBLANE):
        for wb in _aligned_divisors(width, _LANE):
            er, ec = _ext_shape(pb, wb, width)
            if er * ec * 4 > _EXT_BYTES_TARGET:
                continue
            full_width = wb == width
            ratio = (er * ec) / (pb * wb)
            key = (not full_width, ratio, -pb * wb)
            if best is None or key < best[0]:
                best = (key, (pb, wb))
    assert best is not None, (rows, width)
    return best[1]


def _plan(
    rows: int,
    width: int,
    block_rows: int | None = None,
    block_cols: int | None = None,
) -> tuple[str, int, int]:
    """-> (mode, block_rows, block_cols); see the module docstring's
    regime table. Explicit block sizes are validated (a non-dividing size
    would silently evolve a truncated board) and pin their axis."""
    if block_rows is not None:
        _validate_block("block_rows", block_rows, rows, _SUBLANE)
    if block_cols is not None:
        _validate_block("block_cols", block_cols, width, _LANE)
    if block_cols is not None and block_cols < width:
        pb = block_rows if block_rows is not None else _pick_blocks(rows, width)[0]
        return "grid2d", pb, block_cols
    if block_rows is not None:
        # explicit rows, unpinned cols: full width if its ext fits the
        # budget, otherwise fill the column split from the picker (a
        # forced full-width ext on e.g. a 65536^2 board would be 6+ MiB —
        # past the measured Mosaic allocation failure point)
        er, ec = _ext_shape(block_rows, width, width)
        if block_cols is not None or er * ec * 4 <= _EXT_BYTES_TARGET:
            return "rows", block_rows, width
        # size the column split FOR the pinned rows (reusing the picker's
        # wb — chosen for a different pb — can exceed the ext budget)
        fitting = [
            wb
            for wb in _aligned_divisors(width, _LANE)
            if wb < width
            and (block_rows + 2 * _SUBLANE) * (wb + 2 * _LANE) * 4
            <= _EXT_BYTES_TARGET
        ]
        if not fitting:
            raise ValueError(
                f"block_rows={block_rows} leaves no block_cols fitting the "
                f"VMEM ext budget for packed shape {(rows, width)}"
            )
        return "grid2d", block_rows, max(fitting)
    if block_cols is not None:  # block_cols == width: pinned full width
        return "rows", _pick_blocks(rows, width)[0], width
    pb, wb = _pick_blocks(rows, width)
    return ("rows" if wb == width else "grid2d"), pb, wb


def _tiled_kernel_rows(
    top_ref,
    body_ref,
    bot_ref,
    out_ref,
    *,
    word_axis,
    birth_mask,
    survive_mask,
    interpret,
    turns=1,
):
    # 8-row edge strips only: (1 + 16/pb)x read instead of 3x, and the
    # ext stays sublane-aligned. bit_step's (i+-1, j+-1) word dependency
    # holds for EITHER packing (ops/bitpack.py module docstring), so the
    # same halo geometry serves word_axis=1 — the layout that keeps
    # packed rows narrow (hence contiguous, fast DMA) on very wide boards.
    #
    # ``turns`` > 1 is the fused-K form (ops/fused.py): each extra step
    # contaminates one more word-row inward from the ext's edges (the
    # shrinking dependency cone), so up to _SUBLANE turns can run on the
    # SAME 8-row halos before the garbage reaches the interior the write
    # below keeps — K turns per launch from one halo read.
    ext = jnp.concatenate([top_ref[:], body_ref[:], bot_ref[:]], axis=0)
    from .pallas_stencil import pick_rot1

    rot1 = pick_rot1(interpret)
    for _ in range(turns):
        ext = bit_step(
            ext,
            word_axis,
            rot1,
            birth_mask=birth_mask,
            survive_mask=survive_mask,
        )
    out_ref[:] = ext[_SUBLANE:-_SUBLANE, :]


def _tiled_kernel_2d(
    tl_ref,
    top_ref,
    tr_ref,
    left_ref,
    body_ref,
    right_ref,
    bl_ref,
    bot_ref,
    br_ref,
    out_ref,
    *,
    word_axis,
    birth_mask,
    survive_mask,
    interpret,
    turns=1,
):
    # nine views of the same array: body + the eight neighbours' edge
    # tiles, concatenated into a fully tile-aligned torus window.
    # ``turns`` > 1 (fused-K, ops/fused.py): the contamination cone grows
    # one word-row AND one lane element per step from every ext edge —
    # the 8-row strips bound K at _SUBLANE, the 128-lane tiles are never
    # the binding side for K <= 8.
    top = jnp.concatenate([tl_ref[:], top_ref[:], tr_ref[:]], axis=1)
    mid = jnp.concatenate([left_ref[:], body_ref[:], right_ref[:]], axis=1)
    bot = jnp.concatenate([bl_ref[:], bot_ref[:], br_ref[:]], axis=1)
    ext = jnp.concatenate([top, mid, bot], axis=0)
    from .pallas_stencil import pick_rot1

    rot1 = pick_rot1(interpret)
    for _ in range(turns):
        ext = bit_step(
            ext,
            word_axis,
            rot1,
            birth_mask=birth_mask,
            survive_mask=survive_mask,
        )
    out_ref[:] = ext[_SUBLANE:-_SUBLANE, _LANE:-_LANE]


def tiled_pallas_call(
    turns: int,
    shape: tuple[int, int],
    interpret: bool,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
    block_rows: int | None = None,
    block_cols: int | None = None,
    word_axis: int = 0,
):
    """The RAW grid-tiled launch advancing ``turns`` turns per grid
    program (1 = the classic per-turn launch; up to ``_SUBLANE`` = the
    fused-K form, ops/fused.py — the shrinking dependency cone inside the
    8-row halo strips bounds K). Returns a traceable callable
    ``int32[rows, width] -> int32[rows, width]``; callers compose it
    under their own jit + instrumentation."""
    from jax.experimental import pallas as pl

    if not 1 <= turns <= _SUBLANE:
        raise ValueError(
            f"tiled launches support 1..{_SUBLANE} fused turns (the 8-row "
            f"halo strips are the dependency-cone budget), got {turns}"
        )
    rows, width = shape
    mode, pb, wb = _plan(rows, width, block_rows, block_cols)
    gr, gc = rows // pb, width // wb
    rsub, csub = pb // _SUBLANE, wb // _LANE  # sublane/lane tiles per block

    # Index maps are in BLOCK units of each spec's own block shape. Halo
    # blocks address the neighbour's boundary tile; modulo wraps the torus
    # (including the degenerate single-block-per-axis grids, where the
    # neighbour is the block itself).
    def up(i):  # bottommost 8-row tile of the row-block above
        return ((i - 1) % gr) * rsub + rsub - 1

    def down(i):
        return ((i + 1) % gr) * rsub

    def lft(j):
        return ((j - 1) % gc) * csub + csub - 1

    def rgt(j):
        return ((j + 1) % gc) * csub

    masks = dict(
        word_axis=word_axis,
        birth_mask=birth_mask,
        survive_mask=survive_mask,
        interpret=interpret,
        turns=turns,
    )
    if mode == "rows":
        one_turn = pl.pallas_call(
            functools.partial(_tiled_kernel_rows, **masks),
            grid=(gr,),
            in_specs=[
                pl.BlockSpec((_SUBLANE, wb), lambda i: (up(i), 0)),
                pl.BlockSpec((pb, wb), lambda i: (i, 0)),
                pl.BlockSpec((_SUBLANE, wb), lambda i: (down(i), 0)),
            ],
            out_specs=pl.BlockSpec((pb, wb), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct(shape, jnp.int32),
            interpret=interpret,
        )
        n_in = 3
    else:
        one_turn = pl.pallas_call(
            functools.partial(_tiled_kernel_2d, **masks),
            grid=(gr, gc),
            in_specs=[
                pl.BlockSpec((_SUBLANE, _LANE), lambda i, j: (up(i), lft(j))),
                pl.BlockSpec((_SUBLANE, wb), lambda i, j: (up(i), j)),
                pl.BlockSpec((_SUBLANE, _LANE), lambda i, j: (up(i), rgt(j))),
                pl.BlockSpec((pb, _LANE), lambda i, j: (i, lft(j))),
                pl.BlockSpec((pb, wb), lambda i, j: (i, j)),
                pl.BlockSpec((pb, _LANE), lambda i, j: (i, rgt(j))),
                pl.BlockSpec((_SUBLANE, _LANE), lambda i, j: (down(i), lft(j))),
                pl.BlockSpec((_SUBLANE, wb), lambda i, j: (down(i), j)),
                pl.BlockSpec((_SUBLANE, _LANE), lambda i, j: (down(i), rgt(j))),
            ],
            out_specs=pl.BlockSpec((pb, wb), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct(shape, jnp.int32),
            interpret=interpret,
        )
        n_in = 9

    return lambda packed: one_turn(*([packed] * n_in))


@functools.lru_cache(maxsize=None)
def _tiled_compiled(
    n: int,
    shape: tuple[int, int],
    interpret: bool,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
    block_rows: int | None = None,
    block_cols: int | None = None,
    word_axis: int = 0,
):
    one_turn = tiled_pallas_call(
        1, shape, interpret, birth_mask, survive_mask,
        block_rows, block_cols, word_axis,
    )

    @jax.jit
    def run(packed):
        return lax.fori_loop(0, n, lambda _, p: one_turn(p), packed)

    # compile wall + cost analysis attributed to this kernel site (obs/)
    return _device.instrument_jit("pallas.tiled", run)


def tiled_bit_step_n_fn(
    *,
    interpret: bool | None = None,
    rule=None,
    block_rows: int | None = None,
    block_cols: int | None = None,
    word_axis: int = 0,
):
    """A ``(packed_int32, n) -> packed`` bitboard evolution for any size:
    n turns in one dispatch, one grid-tiled kernel launch per turn,
    regime-picked blocks (see module docstring). Either packing: the
    array is [H/32, W] for ``word_axis=0`` (lanes stay W wide — the
    default) or [H, W/32] for ``word_axis=1`` (the layout that keeps
    packed rows narrow and DMA contiguous on very wide boards)."""
    birth = rule.birth_mask if rule else CONWAY_BIRTH_MASK
    survive = rule.survive_mask if rule else CONWAY_SURVIVE_MASK
    if interpret is None:
        from .pallas_stencil import default_interpret

        interpret = default_interpret()

    def step_n(packed, n):
        return _tiled_compiled(
            int(n), packed.shape, interpret, birth, survive,
            block_rows, block_cols, word_axis,
        )(packed)

    return step_n
