"""Grid-tiled pallas bitboard kernel — the fast path for boards whose
packed form exceeds VMEM.

The whole-board VMEM kernel (ops/pallas_stencil.py) tops out at packed
<= ~1.5 MiB (measured; fits_vmem). Beyond that, round 2's fallback was the
XLA bitboard step, which at 16384^2 runs ~8x above the HBM-bandwidth floor:
XLA materialises the ~10 bit-plane intermediates of ``bit_step`` in HBM
once the working set stops fitting on-chip (measured 617 us/turn vs the
~80 us floor of read+write 2x32 MiB at ~800 GB/s).

This kernel runs at ~1x read + 1x write of the packed board per turn. The
array is processed on a 2-D grid of (block_rows x block_cols) blocks; each
grid step sees NINE views of the SAME array — its own block plus the
EDGES of the eight neighbours: 8-sublane word-row strips above/below,
128-lane word-column strips left/right, and (8, 128) corners (Mosaic
block shapes must be sublane(8)/lane(128)-aligned, which is why the halos
cannot be single word-rows). The kernel concatenates the tiles into a
fully tile-aligned (pb+16, wb+256) extended window of the torus — only
the innermost word-row/-column of each halo tile actually feeds the
``bit_step`` dependency (output word (i, j) reads words (i+-1, j+-1));
the rest buys alignment — steps it, and writes back the interior.
Neighbour indices wrap modulo the grid, so torus wrap falls out of the
index arithmetic. Per turn, HBM traffic is

    (1 + 16/pb + 256/wb + corners) x read + 1x write

~1.25x read at the default (128, 2048) block vs the previous full-block
scheme's 3x — and, unlike the round-2 kernel whose blocks spanned the full
board width, the lane axis splits too, so a 65536^2 board (packed row =
256 KiB) tiles with the same bounded VMEM working set as any other size.

The bit-plane temporaries of ``bit_step`` (the XLA path's downfall) live
in VMEM over one (pb+16, wb+256) ext: ~12x block bytes of working set,
double-buffered pipeline included, against the ~16 MiB budget.

All ``n`` turns run in ONE jitted dispatch (lax.fori_loop around the
pallas_call), one kernel launch per turn.

Measured at 16384^2 on v5e: 126-130 us/turn (round 2's full-block scheme:
138). The limit is NOT HBM (~75 us of traffic at these blocks) but the
VPU compute roofline: ~39 bitwise ops/word x 1.27 halo-overhead x 8.4M
words at ~4e12 int32 ops/s is ~115 us — the kernel runs within ~10% of
that. Multi-turn-per-launch variants (amortising halo DMA over up to 127
turns of in-VMEM evolution) measured SLOWER (~165 us/turn): the in-kernel
fori_loop defeats Mosaic's pipelining, so the single-turn form stands.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .bitpack import bit_step
from .stencil import CONWAY_BIRTH_MASK, CONWAY_SURVIVE_MASK

# Body-block byte budget. Working set per grid step is ~12x block bytes
# (ext + ~10 bit-plane temporaries + double-buffered in/out). Measured on
# v5e: 1 MiB blocks compile and run, 2 MiB blocks fail Mosaic allocation —
# and larger blocks shrink the halo-overhead fraction, so target the
# largest size that fits.
_BLOCK_BYTES_TARGET = 1024 * 1024

_SUBLANE = 8  # int32 sublane tile: min rows of any block
_LANE = 128  # lane tile: min cols of any block


def can_tile(shape: tuple[int, int]) -> bool:
    """Mosaic block shapes must be sublane(8)/lane(128)-aligned: the packed
    row count must factor into 8-row blocks with more than one block, and
    the width into 128-lane blocks."""
    return shape[0] % _SUBLANE == 0 and shape[0] // _SUBLANE >= 2 and shape[1] % _LANE == 0


def _aligned_divisors(n: int, align: int):
    return [d for d in range(align, n + 1, align) if n % d == 0]


def _pick_blocks(rows: int, width: int) -> tuple[int, int]:
    """Choose (block_rows, block_cols) minimising halo read overhead
    (8/pb + 128/wb) subject to the block byte budget.

    An (8, 128) block always qualifies (4 KiB), so any `can_tile` shape
    gets a valid choice — the round-2 scheme's failure mode (full-width
    blocks exceeding VMEM on very wide boards) cannot occur."""
    best = None
    for pb in _aligned_divisors(rows, _SUBLANE):
        for wb in _aligned_divisors(width, _LANE):
            if pb * wb * 4 > _BLOCK_BYTES_TARGET:
                break  # wb ascending: larger ones only get bigger
            overhead = _SUBLANE / pb + _LANE / wb
            key = (overhead, -pb * wb)
            if best is None or key < best[0]:
                best = (key, (pb, wb))
    assert best is not None, (rows, width)
    return best[1]


def _validate_block(name: str, val: int, total: int, align: int) -> None:
    if val % align or total % val:
        raise ValueError(
            f"{name}={val} must be a multiple of {align} dividing {total}"
        )


def _tiled_kernel(
    tl_ref,
    top_ref,
    tr_ref,
    left_ref,
    body_ref,
    right_ref,
    bl_ref,
    bot_ref,
    br_ref,
    out_ref,
    *,
    birth_mask,
    survive_mask,
    interpret,
):
    # The halo blocks are full (8, .) / (., 128) tiles — genuine board
    # windows, not just the single adjacent word-row/-column — so the
    # extended block stays sublane/lane ALIGNED: every rotate inside
    # bit_step is a native tile-aligned op (a (pb+2, wb+2) ext measured
    # ~2.5x slower from Mosaic's unaligned-lane handling). Temporaries
    # scale with (pb+16)(wb+256), ~1.4x the body, not 3x.
    top = jnp.concatenate([tl_ref[:], top_ref[:], tr_ref[:]], axis=1)
    mid = jnp.concatenate([left_ref[:], body_ref[:], right_ref[:]], axis=1)
    bot = jnp.concatenate([bl_ref[:], bot_ref[:], br_ref[:]], axis=1)
    ext = jnp.concatenate([top, mid, bot], axis=0)
    from .pallas_stencil import pick_rot1

    rot1 = pick_rot1(interpret)
    # cyclic rotates only contaminate ext's outer ring, well clear of the
    # interior slice
    out = bit_step(
        ext, 0, rot1, birth_mask=birth_mask, survive_mask=survive_mask
    )
    out_ref[:] = out[_SUBLANE:-_SUBLANE, _LANE:-_LANE]


@functools.lru_cache(maxsize=None)
def _tiled_compiled(
    n: int,
    shape: tuple[int, int],
    interpret: bool,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
    block_rows: int | None = None,
    block_cols: int | None = None,
):
    from jax.experimental import pallas as pl

    rows, width = shape
    auto = (
        _pick_blocks(rows, width) if not (block_rows and block_cols) else None
    )
    pb = block_rows or auto[0]
    wb = block_cols or auto[1]
    _validate_block("block_rows", pb, rows, _SUBLANE)
    _validate_block("block_cols", wb, width, _LANE)
    gr, gc = rows // pb, width // wb
    rsub, csub = pb // _SUBLANE, wb // _LANE  # sublane/lane tiles per block

    # Index maps are in BLOCK units of each spec's own block shape. Edge
    # blocks address the neighbour's boundary tile; modulo wraps the torus
    # (including the degenerate single-block-per-axis grids, where the
    # neighbour is the block itself).
    def up(i):  # topmost 8-row tile of the row-block above
        return ((i - 1) % gr) * rsub + rsub - 1

    def down(i):
        return ((i + 1) % gr) * rsub

    def lft(j):
        return ((j - 1) % gc) * csub + csub - 1

    def rgt(j):
        return ((j + 1) % gc) * csub

    kernel = functools.partial(
        _tiled_kernel,
        birth_mask=birth_mask,
        survive_mask=survive_mask,
        interpret=interpret,
    )
    one_turn = pl.pallas_call(
        kernel,
        grid=(gr, gc),
        in_specs=[
            pl.BlockSpec((_SUBLANE, _LANE), lambda i, j: (up(i), lft(j))),
            pl.BlockSpec((_SUBLANE, wb), lambda i, j: (up(i), j)),
            pl.BlockSpec((_SUBLANE, _LANE), lambda i, j: (up(i), rgt(j))),
            pl.BlockSpec((pb, _LANE), lambda i, j: (i, lft(j))),
            pl.BlockSpec((pb, wb), lambda i, j: (i, j)),
            pl.BlockSpec((pb, _LANE), lambda i, j: (i, rgt(j))),
            pl.BlockSpec((_SUBLANE, _LANE), lambda i, j: (down(i), lft(j))),
            pl.BlockSpec((_SUBLANE, wb), lambda i, j: (down(i), j)),
            pl.BlockSpec((_SUBLANE, _LANE), lambda i, j: (down(i), rgt(j))),
        ],
        out_specs=pl.BlockSpec((pb, wb), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.int32),
        interpret=interpret,
    )

    @jax.jit
    def run(packed):
        return lax.fori_loop(
            0, n, lambda _, p: one_turn(p, p, p, p, p, p, p, p, p), packed
        )

    return run


def tiled_bit_step_n_fn(
    *,
    interpret: bool | None = None,
    rule=None,
    block_rows: int | None = None,
    block_cols: int | None = None,
):
    """A ``(packed_int32 [P, W], n) -> packed`` for word_axis=0 bitboards of
    any size: n turns in one dispatch, one grid-tiled kernel launch per
    turn, ~BW-floor HBM traffic (edge-only halo reads). Row-packed layout
    only (the layout every large-board path uses — lanes stay W wide)."""
    birth = rule.birth_mask if rule else CONWAY_BIRTH_MASK
    survive = rule.survive_mask if rule else CONWAY_SURVIVE_MASK
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    def step_n(packed, n):
        return _tiled_compiled(
            int(n), packed.shape, interpret, birth, survive, block_rows, block_cols
        )(packed)

    return step_n
