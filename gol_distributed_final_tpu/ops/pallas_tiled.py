"""Grid-tiled pallas bitboard kernel — the fast path for boards whose
packed form exceeds VMEM.

The whole-board VMEM kernel (ops/pallas_stencil.py) tops out at packed
<= ~1.5 MiB (measured; fits_vmem). Beyond that, round 2's fallback was the
XLA bitboard step, which at 16384^2 runs ~8x above the HBM-bandwidth floor:
XLA materialises the ~10 bit-plane intermediates of ``bit_step`` in HBM
once the working set stops fitting on-chip (measured 617 us/turn vs the
~80 us floor of read+write 2x32 MiB at ~800 GB/s).

This kernel restores most of it: the packed array is processed in row
blocks; each grid step sees three views of the SAME array — the previous,
own, and next block (index maps offset by +-1 modulo the grid, so torus
wrap falls out of the index arithmetic; Mosaic requires sublane-aligned
block shapes, which rules out 1-row halo blocks) — and extends its body
with just the neighbours' edge word-rows (the full bit_step dependency:
output word (i, j) depends only on words (i+-1, j+-1); column wrap is a
lane rotate inside the block, which spans the full width). Per turn, HBM
traffic is ~3x read + 1x write of the packed board, pipelined against
compute — the bit-plane temporaries (the XLA path's downfall) stay in
VMEM.

All ``n`` turns run in ONE jitted dispatch (lax.fori_loop around the
pallas_call), one kernel launch per turn.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .bitpack import bit_step
from .stencil import CONWAY_BIRTH_MASK, CONWAY_SURVIVE_MASK

# per-block VMEM footprint target: body + 2 halo rows + out + temporaries,
# double-buffered by the pipeline. 512 KiB blocks keep the working set
# comfortably inside ~16 MiB VMEM.
_BLOCK_BYTES_TARGET = 512 * 1024


def can_tile(shape: tuple[int, int]) -> bool:
    """Mosaic block shapes must be sublane(8)-aligned: the packed row count
    must factor into 8-row blocks with more than one block."""
    return shape[0] % 8 == 0 and shape[0] // 8 >= 2


def _pick_block_rows(packed_rows: int, width: int) -> int:
    """Largest multiple-of-8 divisor of ``packed_rows`` with block bytes
    <= target (minimum 8 — the int32 sublane tile)."""
    limit = max(8, _BLOCK_BYTES_TARGET // (width * 4))
    divisors = [
        d
        for d in range(8, packed_rows, 8)
        if packed_rows % d == 0 and d <= limit
    ]
    return max(divisors) if divisors else 8


def _tiled_kernel(
    top_ref, body_ref, bot_ref, out_ref, *, birth_mask, survive_mask, interpret
):
    # only the neighbours' edge word-rows extend the body: temporaries
    # scale with (pb + 2) rows, not 3*pb
    ext = jnp.concatenate(
        [top_ref[-1:, :], body_ref[:], bot_ref[:1, :]], axis=0
    )
    from .pallas_stencil import pick_rot1

    rot1 = pick_rot1(interpret)
    # cyclic rotates only contaminate ext's outer rows, which are sliced
    out = bit_step(
        ext, 0, rot1, birth_mask=birth_mask, survive_mask=survive_mask
    )
    out_ref[:] = out[1:-1]


@functools.lru_cache(maxsize=None)
def _tiled_compiled(
    n: int,
    shape: tuple[int, int],
    interpret: bool,
    birth_mask: int = CONWAY_BIRTH_MASK,
    survive_mask: int = CONWAY_SURVIVE_MASK,
    block_rows: int | None = None,
):
    from jax.experimental import pallas as pl

    rows, width = shape
    pb = block_rows or _pick_block_rows(rows, width)
    grid = rows // pb
    kernel = functools.partial(
        _tiled_kernel,
        birth_mask=birth_mask,
        survive_mask=survive_mask,
        interpret=interpret,
    )
    one_turn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            # previous, own, next block of the same array; modulo wraps
            pl.BlockSpec((pb, width), lambda i: ((i - 1) % grid, 0)),
            pl.BlockSpec((pb, width), lambda i: (i, 0)),
            pl.BlockSpec((pb, width), lambda i: ((i + 1) % grid, 0)),
        ],
        out_specs=pl.BlockSpec((pb, width), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.int32),
        interpret=interpret,
    )

    @jax.jit
    def run(packed):
        return lax.fori_loop(0, n, lambda _, p: one_turn(p, p, p), packed)

    return run


def tiled_bit_step_n_fn(
    *,
    interpret: bool | None = None,
    rule=None,
    block_rows: int | None = None,
):
    """A ``(packed_int32 [P, W], n) -> packed`` for word_axis=0 bitboards of
    any size: n turns in one dispatch, one grid-tiled kernel launch per
    turn, ~BW-floor HBM traffic. Row-packed layout only (the layout every
    large-board path uses — lanes stay W wide)."""
    birth = rule.birth_mask if rule else CONWAY_BIRTH_MASK
    survive = rule.survive_mask if rule else CONWAY_SURVIVE_MASK
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    def step_n(packed, n):
        return _tiled_compiled(
            int(n), packed.shape, interpret, birth, survive, block_rows
        )(packed)

    return step_n
