from .stencil import ALIVE, DEAD, neighbour_counts, step, step_n
from .reduce import alive_count, alive_cells

__all__ = [
    "ALIVE",
    "DEAD",
    "neighbour_counts",
    "step",
    "step_n",
    "alive_count",
    "alive_cells",
]
