from .stencil import ALIVE, DEAD, neighbour_counts, step, step_n, step_n_batch
from .reduce import alive_count, alive_count_batch, alive_cells

__all__ = [
    "ALIVE",
    "DEAD",
    "neighbour_counts",
    "step",
    "step_n",
    "step_n_batch",
    "alive_count",
    "alive_count_batch",
    "alive_cells",
]
