"""Data planes — the engine's pluggable on-device board representation.

The reference's compute state is one concrete thing: a ``[][]byte`` world
re-shipped to workers every turn (broker/broker.go:135-224). Here the engine
holds an opaque device-resident *state* and talks to it through a small
interface, so the fast representations (the int32 bitboard, a mesh-sharded
bitboard) stay packed ACROSS chunk dispatches — encode once at Run start,
decode only for Retrieve/final. Round 1 repacked from host numpy on every
chunk (a 16 MiB+ D2H/H2D per dispatch at 4096^2, VERDICT.md); with a plane
the hot loop is pure device work.

Interface (duck-typed):
    encode(board_uint8) -> state      host/device uint8 [H, W] -> device state
    step_n(state, n) -> state         n turns, one or few dispatches
    decode(state) -> np.uint8 [H, W]  full host board (Retrieve/final only)
    alive_count(state) -> int         device-side reduction, tiny transfer

Optional fused step+count protocol (ops/fused.FusedBitPlane implements
it; the engine's chunk driver consumes it — ops/batched planes carry the
batch twin ``step_n_counts``):
    step_n_counted(state, n) -> (state, counts)
                                      n turns AND the alive reduction in
                                      ONE dispatch; ``counts`` is a
                                      device vector whose int64 host sum
                                      (ops/fused.fold_counts) is the
                                      alive count of the returned state —
                                      the count-only Retrieve ticker is
                                      served from it with no dispatch

Optional early-exit protocol (ops/sparse.SparseBitPlane implements it;
the engine consumes it through :func:`plane_steady_kind`):
    steady_kind(state) -> None | "still" | "period2"
                                      the plane's own verdict that the
                                      board has gone quiescent (set by a
                                      previous step_n, never computed on
                                      demand)
    fast_forward(state, k) -> state   k turns of a steady state in O(1)
                                      (a still life is itself; a
                                      period-2 cycle lands on phase k%2)
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..models import CONWAY, LifeRule
from ..obs import device as _device

# shape -> whether the whole-board VMEM kernel actually compiled+ran for it.
# fits_vmem's working-set factor is a single-point measurement
# (ops/pallas_stencil.py:_WORKING_SET_FACTOR); shapes near the boundary are
# one compiler version away from a Mosaic OOM at compile time, so the first
# failure for a shape routes it to the tiled/XLA path instead of crashing,
# and the decision is cached so the compile is never re-attempted.
_VMEM_KERNEL_OK: dict = {}


def plane_steady_kind(plane, state):
    """The early-exit protocol's read side, shared by every consumer
    (engine/engine.py's chunk loop): ``None`` unless the plane both
    implements ``steady_kind`` and has marked this state steady — so a
    caller can always gate a fast_forward jump on one call without
    caring which plane it holds."""
    probe = getattr(plane, "steady_kind", None)
    if probe is None or state is None:
        return None
    return probe(state)


def run_vmem_gated(cache: dict, key, kernel_call, fallback_call):
    """The VMEM-gate execution posture, shared by the single-board
    (``BitPlane``) and batched (``ops/batched.BatchBitPlane``) bitboard
    planes so the policy cannot diverge: try the pallas VMEM kernel while
    the cached gate admits ``key``; the FIRST failure for a key routes it
    to ``fallback_call`` and is cached so the compile is never
    re-attempted; a key that compiled before re-raises (a real runtime
    error, not a mis-calibrated gate)."""
    if cache.get(key, True):
        try:
            out = kernel_call()
            cache[key] = True
            return out
        except Exception:
            if cache.get(key):
                raise
            cache[key] = False
    return fallback_call()


class BytePlane:
    """The identity representation: a device uint8 {0,255} board.

    Wraps any ``(board, n) -> board`` step (the roll stencil, a shard_map
    halo step) into the plane interface."""

    def __init__(
        self,
        rule: LifeRule = CONWAY,
        step_n_fn: Optional[Callable] = None,
    ):
        self.rule = rule
        self._step_n = step_n_fn or rule.step_n

    def encode(self, board):
        import jax.numpy as jnp

        return jnp.asarray(board)

    def step_n(self, state, n: int):
        return self._step_n(state, n)

    def decode(self, state) -> np.ndarray:
        return np.asarray(state)

    def alive_count(self, state) -> int:
        from .reduce import alive_count

        return int(alive_count(state))

    def alive_cells(self, state):
        from .reduce import alive_cells

        return alive_cells(state)


class BitPlane:
    """The int32 bitboard representation: 32 cells/word, state stays packed
    across chunks. ``step_n`` routes by size: the whole-board pallas VMEM
    kernel under the measured VMEM working-set gate, the grid-tiled pallas
    kernel for larger boards on real TPU (ops/pallas_tiled.py — the XLA
    fallback spills the bit-plane temporaries to HBM, ~4.5x slower at
    16384^2), else the XLA bitboard step; ``alive_count`` is a popcount —
    no unpack."""

    def __init__(
        self,
        rule: LifeRule = CONWAY,
        word_axis: int = 0,
        interpret: Optional[bool] = None,
    ):
        from .pallas_stencil import default_interpret

        self.rule = rule
        self.word_axis = word_axis
        self.interpret = default_interpret() if interpret is None else interpret

    def encode(self, board):
        import jax.numpy as jnp

        from .bitpack import pack_device

        return pack_device(jnp.asarray(board), self.word_axis)

    def step_n(self, state, n: int):
        from . import pallas_stencil
        from .bitpack import bit_step_n
        from .pallas_tiled import can_tile, tiled_bit_step_n_fn

        n = int(n)
        birth, survive = self.rule.birth_mask, self.rule.survive_mask
        shape = tuple(state.shape)

        def fallback():
            if not self.interpret and self.word_axis == 0 and can_tile(shape):
                return tiled_bit_step_n_fn(rule=self.rule, interpret=False)(
                    state, n
                )
            # compile wall + cost analysis attributed to the XLA bitboard
            # fallback (obs/device.py); semantics identical to a direct call
            return _device.compile_and_call(
                "bitpack.xla_step", bit_step_n,
                state, n, self.word_axis, birth, survive,
                static_argnums=(1, 2, 3, 4),
            )

        if pallas_stencil.fits_vmem(shape, itemsize=4):
            return run_vmem_gated(
                _VMEM_KERNEL_OK,
                shape,
                lambda: pallas_stencil._bit_compiled(
                    n, self.word_axis, self.interpret, birth, survive
                )(state),
                fallback,
            )
        return fallback()

    def decode(self, state) -> np.ndarray:
        from .bitpack import unpack_device

        return np.asarray(unpack_device(state, self.word_axis))

    def alive_count(self, state) -> int:
        from .bitpack import alive_count_packed

        return alive_count_packed(state)

    def alive_cells(self, state):
        # sparse O(populated rows) extraction — no full unpack
        from .bitpack import alive_cells_packed

        return alive_cells_packed(state, self.word_axis)
