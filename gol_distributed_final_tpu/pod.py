"""Config 5 at its real topology: the packed big board on a MULTI-HOST mesh.

The reference's whole scaling story is "add machines to the list"
(broker/broker.go:288-300) — every machine then holds the full board.
Here the opposite: a ``jax.distributed`` job shards the packed bitboard
over a global ('rows', 'cols') mesh (parallel/bit_halo.ShardedBitPlane —
halo ppermutes ride ICI/DCN), and every host-side surface touches only the
rows its devices own:

* ``stream_packed_to_pgm_sharded`` / ``load_packed_from_pgm_sharded`` —
  each rank packs/unpacks ONLY its word rows, pwriting/reading disjoint
  ranges of one on-disk PGM (io/sharded.py). The byte raster never exists
  anywhere; peak host memory is one row block per rank.
* periodic crash-recovery checkpoints — per-rank shards
  (engine/checkpoint.save_packed_checkpoint_sharded), written between
  chunk dispatches by every rank at the same deterministic turn.
* ``pod_session`` — the reference session surface (2-second
  ``AliveCellsCount``, the s/q/k/p keyboard semantics, the closing
  ``FinalTurnComplete`` -> PGM -> ``ImageOutputComplete`` ->
  ``StateChange{Quitting}`` -> CLOSED sequence; gol/distributor.go:25-129)
  on the pod. Control is rank-0-driven: keypresses and the tick timer live
  on rank 0 only, and every decision is fanned out to all ranks through a
  small broadcast at the engine's chunk gate (EngineConfig.chunk_hook), so
  every collective — counts, snapshot streams, the pause barrier — runs in
  the same order on every rank. A blocked gate IS the pause: the dispatch
  loop cannot advance past it.

Single-host states pass through unchanged: the module's IO entry points
fall back to bigboard.py's local streaming when the state is fully
addressable, so the same program text serves one chip and a pod.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from .models import CONWAY, LifeRule
from .ops.bitpack import WORD, alive_count_packed, packed_shape

# control word bits broadcast from rank 0 at each chunk gate
_CTL_TICK = 1  # all ranks join the count collective; rank 0 emits the event
_CTL_SNAPSHOT = 2  # all ranks stream their rows to the session PGM
_CTL_PAUSE = 4  # enter/stay in the pause barrier
_CTL_QUIT = 8  # 'k': engine.quit() on every rank — coordinated shutdown
_CTL_DETACH = 16  # 'q': rank 0's controller surface closes; run continues


def _packed_dims(shape, word_axis: int) -> tuple[int, int]:
    rows, cols = shape
    return (rows * WORD, cols) if word_axis == 0 else (rows, cols * WORD)


def _broadcast_word(word: int) -> int:
    from jax.experimental import multihost_utils

    return int(multihost_utils.broadcast_one_to_all(np.int32(word)))


def _barrier(name: str) -> None:
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def stream_packed_to_pgm_sharded(
    path, state, word_axis: int = 0, row_block: int = 1024
) -> None:
    """Write a mesh-sharded packed board to ONE on-disk P5 PGM, each rank
    pwriting only the rows it owns (io/sharded.py disjoint ranges). Falls
    back to the local streamer for fully-addressable states. Collective:
    every rank must call it (two barriers inside).

    Matches the reference's output contract (gol/io.go:42-87) at a scale
    the reference cannot reach: no process ever holds more than
    ``row_block`` unpacked rows."""
    from .bigboard import stream_packed_to_pgm

    if getattr(state, "is_fully_addressable", True):
        stream_packed_to_pgm(path, state, word_axis, row_block)
        return

    import jax

    from .engine.checkpoint import local_packed_rows
    from .io.sharded import create_pgm, pgm_raster_offset, write_rows_at
    from .ops.bitpack import unpack_device

    height, width = _packed_dims(state.shape, word_axis)
    row_block = max(WORD, row_block - row_block % WORD)
    if jax.process_index() == 0:
        offset = create_pgm(path, width, height)
    else:
        offset = pgm_raster_offset(width, height)
    # rank != 0 must not pwrite before the file exists at full size
    _barrier("pod_pgm_created")

    word_row0, local = local_packed_rows(state)
    board_row0 = word_row0 * WORD if word_axis == 0 else word_row0
    step = row_block // WORD if word_axis == 0 else row_block
    for start in range(0, local.shape[0], step):
        block = local[start : start + step]
        rows = np.asarray(unpack_device(block, word_axis))
        write_rows_at(
            path,
            offset,
            width,
            board_row0 + (start * WORD if word_axis == 0 else start),
            rows,
        )
    _barrier("pod_pgm_written")


def load_packed_from_pgm_sharded(
    path, mesh, word_axis: int = 0, row_block: int = 1024, rule=None
):
    """Stream a P5 PGM into a mesh-sharded packed board: each rank reads
    ONLY its own board rows from disk (io/sharded.read_shard), packs them
    locally, and places the block onto the global mesh. Collective."""
    import jax
    import jax.numpy as jnp

    from .io.pgm import PgmReader
    from .io.sharded import read_shard
    from .ops.bitpack import pack_device
    from .parallel.bit_halo import packed_sharding
    from .parallel.multihost import host_row_range

    with PgmReader(path) as r:
        width, height = r.width, r.height
    if height % WORD or width % WORD:
        raise ValueError(f"{width}x{height} not divisible by {WORD}")
    lo, hi = host_row_range(mesh, height)
    row_block = max(WORD, row_block - row_block % WORD)
    blocks = []
    for start in range(lo, hi, row_block):
        stop = min(start + row_block, hi)
        rows = read_shard(path, start, stop)
        blocks.append(np.asarray(pack_device(jnp.asarray(rows), word_axis)))
    local = np.concatenate(blocks, axis=0)
    return jax.make_array_from_process_local_data(
        packed_sharding(mesh), local, packed_shape(height, width, word_axis)
    )


def decode_window_sharded(
    state, y0: int, x0: int, h: int, w: int, word_axis: int = 0
) -> np.ndarray:
    """The uint8 window ``[y0:y0+h, x0:x0+w]`` of a MESH-SHARDED packed
    board, decoded collectively: the word rows covering the window are
    gathered replicated (a window is KiB — the 4 GiB raster never forms),
    unpacked, and sliced, so EVERY rank returns the same array.

    Collective — all ranks must call with the same arguments (e.g. from
    the pod chunk gate, like the count). The single-host sibling is
    ``bigboard.decode_window``; this is its pod-topology form, serving
    the same role the reference's SDL window serves one-host
    (sdl/window.go:22-104)."""
    from jax.experimental import multihost_utils

    from .bigboard import check_window, decode_window, window_word_bounds

    if getattr(state, "is_fully_addressable", True):
        return decode_window(state, y0, x0, h, w, word_axis)

    check_window(state.shape, y0, x0, h, w, word_axis)
    # slice BOTH axes down to the window's covering word block before the
    # gather, so only KiB cross the hosts (decode_window does the same
    # locally); process_allgather is the repo's cached replication helper
    a0, a1, off = window_word_bounds(y0, x0, h, w, word_axis)
    if word_axis == 0:
        block = state[a0:a1, x0 : x0 + w]
    else:
        block = state[y0 : y0 + h, a0:a1]
    gathered = np.asarray(multihost_utils.process_allgather(block, tiled=True))
    from .ops.bitpack import unpack

    if word_axis == 0:
        return unpack(gathered, 0)[off : off + h]
    return unpack(gathered, 1)[:, off : off + w]


class _PodControl:
    """The rank-0-driven control gate installed as EngineConfig.chunk_hook.

    Rank 0 turns its local state (tick timer, drained keypresses) into a
    control word; ``multihost_utils.broadcast_one_to_all`` fans it to all
    ranks, which act identically. The pause barrier is a loop of further
    broadcasts — rank 0 re-polling its keyboard between them — so parked
    ranks stay rendezvoused with rank 0 until resume or quit.

    Key semantics match the reference's controller/broker split: ``q``
    detaches the controller (rank 0's event/key surface closes with
    Quitting + CLOSED; the run continues headless — the pod analogue of
    the broker surviving a controller quit, gol/distributor.go:64-77),
    ``k`` is the coordinated full shutdown (broker/broker.go:241-249)."""

    def __init__(
        self,
        params,
        events,
        keypresses,
        out_path,
        word_axis: int,
        row_block: int,
        tick_seconds: float,
        is_root: bool,
    ):
        self.params = params
        self.events = events
        self.keypresses = keypresses
        self.out_path = out_path
        self.word_axis = word_axis
        self.row_block = row_block
        self.tick_seconds = tick_seconds
        self.is_root = is_root
        self.paused = False
        self.detached = False  # 'q' pressed: the controller surface closed
        self._pause_pairs = 0  # toggle-pairs cancelled within one drain
        self._next_tick = time.monotonic() + tick_seconds

    # -- rank-0 side -------------------------------------------------------

    def _drain_key_word(self) -> int:
        import queue as queue_mod

        word = 0
        if self.keypresses is None or self.detached:
            return word
        while True:
            try:
                key = self.keypresses.get_nowait()
            except queue_mod.Empty:
                return word
            if key == "s":
                word |= _CTL_SNAPSHOT
            elif key == "p":
                # XOR, not OR: two presses drained at one gate cancel out
                # (pause + immediate resume), as two toggles should — but
                # the EVENT stream still shows the Paused/Executing pair,
                # like the reference handling each press as it arrives
                # (gol/distributor.go:108-121; ADVICE r4)
                if word & _CTL_PAUSE:
                    self._pause_pairs += 1
                word ^= _CTL_PAUSE
            elif key == "q":
                # controller quit (gol/distributor.go:64-77): the event/key
                # surface closes — keys queued BEHIND the 'q' belong to a
                # closed surface and are never consulted, so draining stops
                # here (keys before it were legitimately pressed first and
                # ride this word)
                word |= _CTL_DETACH
                return word
            elif key == "k":
                word |= _CTL_QUIT

    def _root_word(self) -> int:
        if self.detached:
            return 0  # controller gone: no keys, no ticker
        word = self._drain_key_word()
        if time.monotonic() >= self._next_tick:
            self._next_tick = time.monotonic() + self.tick_seconds
            word |= _CTL_TICK
        return word

    # -- every rank --------------------------------------------------------

    def __call__(self, engine, state, turn: int) -> None:
        word = _broadcast_word(self._root_word() if self.is_root else 0)
        self._apply(engine, state, turn, word)
        while self.paused and not (word & _CTL_QUIT):
            # the pause barrier: the gate does not return, so no rank can
            # dispatch another chunk (broker/broker.go:83-86's blocked
            # loop, pod-wide). Rank 0 paces the rendezvous.
            if self.is_root:
                time.sleep(0.05)
                word = _broadcast_word(self._drain_key_word())
            else:
                word = _broadcast_word(0)
            self._apply(engine, state, turn, word)

    def _apply(self, engine, state, turn: int, word: int) -> None:
        from .events import AliveCellsCount, Quitting, State, StateChange

        if word & _CTL_TICK:
            # EVERY rank joins the count collective (allgathered row
            # popcounts); only rank 0 emits — and, like the reference's
            # ticker, not while paused (gol/distributor.go:47)
            count = alive_count_packed(state)
            if self.is_root and not self.paused:
                self.events.put(AliveCellsCount(turn, count))
        if word & (_CTL_SNAPSHOT | _CTL_DETACH):
            # 's' streams on demand; 'q' streams the CURRENT state before
            # the controller surface closes — the reference's q handler
            # writes the PGM first (gol/distributor.go:63-77), and for a
            # detached run this snapshot is the only on-disk copy until
            # the completed final board overwrites it. 'k' needs no gate
            # write: the closing sequence's unconditional stream IS the
            # killed-at state (a second identical 4 GiB collective write
            # at 65536^2 would be pure waste). One stream even when both
            # bits land in the same word.
            stream_packed_to_pgm_sharded(
                self.out_path, state, self.word_axis, self.row_block
            )
            if self.is_root and word & _CTL_SNAPSHOT:
                print(self.params.output_filename)
        if self.is_root and self._pause_pairs:
            # toggle-pairs cancelled at this gate: the state never changed,
            # but each press still gets its event, in the order the
            # reference's press-at-a-time handling would have emitted —
            # pause/resume from a running board, resume/re-pause from a
            # paused one. Pairs are rank-0 cosmetics, so no bit rides the
            # broadcast word for them.
            for _ in range(self._pause_pairs):
                if self.paused:
                    self.events.put(StateChange(turn - 1, State.EXECUTING))
                    self.events.put(StateChange(turn, State.PAUSED))
                else:
                    self.events.put(StateChange(turn, State.PAUSED))
                    self.events.put(StateChange(turn - 1, State.EXECUTING))
            self._pause_pairs = 0
        if word & _CTL_PAUSE:
            self.paused = not self.paused
            if self.is_root:
                self.events.put(
                    StateChange(
                        turn if self.paused else turn - 1,
                        State.PAUSED if self.paused else State.EXECUTING,
                    )
                )
                print("State paused" if self.paused else "State unpaused")
        if word & _CTL_DETACH:
            # 'q' (gol/distributor.go:64-77 + README.md:187): the
            # controller detaches — StateChange{Quitting} then CLOSED end
            # rank 0's event stream, keys stop being consulted, and the
            # run continues headless to completion (a paused board is
            # resumed first: nobody is left to unpause it)
            from .engine.controller import CLOSED

            self.paused = False
            if self.is_root and not self.detached:
                self.events.put(StateChange(turn, Quitting))
                self.events.put(CLOSED)
            self.detached = True
        if word & _CTL_QUIT:
            # 'k' (broker/broker.go:241-249): coordinated full shutdown
            if self.is_root and not self.detached:
                self.events.put(StateChange(turn, Quitting))
            engine.quit()


class _CountOnlyAlive:
    """``FinalTurnComplete.alive`` for a pod run: the global count without
    any rank materialising cells it does not own. Iteration is refused —
    a pod-scale cell list is exactly what this surface promises never to
    build (the count was computed collectively before emission)."""

    def __init__(self, count: int):
        self._count = count

    def __len__(self) -> int:
        return self._count

    def __iter__(self):
        raise NotImplementedError(
            "a multi-host FinalTurnComplete carries only the count; decode "
            "windows via bigboard.decode_window or stream the PGM instead"
        )


def pod_session(
    size: int,
    turns: int,
    mesh,
    *,
    in_path=None,
    cells=None,
    rule: LifeRule = CONWAY,
    row_block: int = 1024,
    events=None,
    keypresses=None,
    tick_seconds: float = 2.0,
    out_dir="out",
    checkpoint_every: int = 0,
    checkpoint_path=None,
    resume_from=None,
    min_chunk: int = 16,
    max_chunk: int = 256,
    halo_depth: int = 1,
):
    """The full reference session surface over a multi-host packed board.

    Collective: every rank of the ``jax.distributed`` job calls this with
    the same arguments; ``events``/``keypresses`` are only consulted on
    rank 0 (the controller host). Returns the engine's RunResult (world is
    None; ``alive`` is count-only on every rank).

    ``resume_from`` continues from a per-rank sharded checkpoint
    (engine/checkpoint.load_packed_checkpoint_sharded) — combined with
    ``checkpoint_every`` this is the pod crash-recovery loop.

    Reference anchors: the session event contract gol/distributor.go:25-129
    + the scale-by-adding-machines story broker/broker.go:288-300."""
    import pathlib
    import queue as queue_mod

    import jax

    from .engine.controller import CLOSED
    from .engine.engine import Engine, EngineConfig
    from .events import (
        FinalTurnComplete,
        ImageOutputComplete,
        Quitting,
        StateChange,
    )
    from .params import Params
    from .parallel.bit_halo import make_bit_plane, packed_sharding
    from .parallel.mesh import COLS, ROWS
    from .parallel.multihost import host_row_range

    is_root = jax.process_index() == 0
    if events is None:
        events = queue_mod.Queue()
    control = None
    try:
        mesh_shape = (mesh.shape[ROWS], mesh.shape[COLS])
        plane = make_bit_plane(mesh, (size, size), rule, halo_depth=halo_depth)
        if plane is None:
            raise ValueError(
                f"no packed layout of {size}x{size} divides over mesh "
                f"{mesh_shape} with halo_depth={halo_depth} (the depth is "
                "bounded by the local word blocks)"
            )
        word_axis = plane.word_axis
        params = Params(turns=turns, image_width=size, image_height=size)
        out_file = pathlib.Path(out_dir) / f"{params.output_filename}.pgm"

        initial_turn = 0
        if resume_from is not None:
            from .engine.checkpoint import load_packed_checkpoint_sharded

            state, initial_turn, ck_rule, ck_axis = load_packed_checkpoint_sharded(
                resume_from, packed_sharding(mesh)
            )
            if ck_axis != word_axis:
                raise ValueError(
                    f"checkpoint word_axis {ck_axis} != layout {word_axis}"
                )
            if ck_rule.rulestring != rule.rulestring:
                raise ValueError(
                    f"checkpoint rule {ck_rule.rulestring} != {rule.rulestring}"
                )
            if turns <= initial_turn:
                raise ValueError(
                    f"turns={turns} not beyond checkpoint turn {initial_turn}"
                )
        elif in_path is not None:
            state = load_packed_from_pgm_sharded(
                in_path, mesh, word_axis, row_block
            )
        elif cells is not None:
            from .bigboard import seed_packed

            # each rank seeds ONLY its addressable row range — no
            # transient full-board host allocation (ADVICE r4; at
            # 65536^2 the full packed board is ~512 MiB per rank)
            lo, hi = host_row_range(mesh, size)
            host_local = np.asarray(
                seed_packed(size, cells, word_axis, row_range=(lo, hi))
            )
            state = jax.make_array_from_process_local_data(
                packed_sharding(mesh), host_local,
                packed_shape(size, size, word_axis),
            )
        else:
            raise ValueError("one of resume_from / in_path / cells is required")

        control = _PodControl(
            params,
            events,
            keypresses,
            out_file,
            word_axis,
            row_block,
            tick_seconds,
            is_root,
        )
        engine = Engine(
            EngineConfig(
                rule=rule,
                final_world=False,
                min_chunk=min_chunk,
                max_chunk=max_chunk,
                chunk_hook=control,
                checkpoint_every=checkpoint_every,
                checkpoint_path=str(checkpoint_path) if checkpoint_path else None,
            )
        )
        result = engine.run(
            params,
            None,
            plane=plane,
            initial_state=state,
            initial_turn=initial_turn,
        )
        final = engine.final_state()
        # the closing sequence (gol/distributor.go:161-184), pod-shaped:
        # count collectively, stream the PGM per rank, emit on rank 0
        count = alive_count_packed(final)
        # pre-fill the result's alive payload with the collectively-agreed
        # count on EVERY rank: a later rank-local result.alive_count must
        # not fire a collective outside the gate protocol
        result._alive = _CountOnlyAlive(count)
        # after a 'q' detach the controller surface already closed (the
        # Quitting + CLOSED pair went out at the gate): the run still
        # streams its output PGM, but emits no further events
        emit = is_root and not control.detached
        if emit:
            events.put(
                FinalTurnComplete(result.turns_completed, _CountOnlyAlive(count))
            )
        stream_packed_to_pgm_sharded(out_file, final, word_axis, row_block)
        if emit:
            events.put(
                ImageOutputComplete(
                    result.turns_completed, params.output_filename
                )
            )
            events.put(StateChange(result.turns_completed, Quitting))
        return result
    finally:
        if control is None or not control.detached:
            events.put(CLOSED)


def main(argv=None) -> int:
    """Pod entry point: one invocation per host of the ``jax.distributed``
    job (the reference's 'go run ./worker on every machine',
    broker/broker.go:288-300 — except the board is sharded, not copied).

    Rank 0 is the controller host: it owns the tty keys (s/q/k/p) and
    prints the event stream; other ranks run headless."""
    import argparse
    import queue as queue_mod
    import threading

    import jax

    from .__main__ import drain_events, start_tty_keys
    from .bigboard import r_pentomino
    from .parallel import make_mesh, multihost

    parser = argparse.ArgumentParser(
        description="multi-host packed big-board session (config 5 topology)"
    )
    parser.add_argument("-size", type=int, default=16384)
    parser.add_argument("-turns", type=int, default=1000)
    parser.add_argument("-in", dest="in_path", default=None,
                        help="seed PGM (default: the R-pentomino)")
    parser.add_argument("-out", default="out", help="output directory")
    parser.add_argument("-row-block", type=int, default=1024)
    parser.add_argument("-coordinator", default=None,
                        help="jax.distributed coordinator address host:port")
    parser.add_argument("-num-processes", type=int, default=1)
    parser.add_argument("-process-id", type=int, default=0)
    parser.add_argument("-ck", default=None, metavar="PATH",
                        help="periodic checkpoint base path (per-rank shards)")
    parser.add_argument("-ck-every", type=int, default=0)
    parser.add_argument("-resume", action="store_true", default=False,
                        help="resume from -ck's per-rank shards")
    parser.add_argument("-rule", default=None, metavar="B.../S...",
                        help="life-like rulestring (default Conway B3/S23)")
    parser.add_argument(
        "-halo-depth", dest="halo_depth", type=int, default=1,
        help="turns per halo exchange (wide halos: k-fold fewer collective "
             "latencies per turn — raise on DCN-crossed meshes; depth 8 "
             "also amortises the aligned-ext build 8-fold and measured "
             "~2x per-device at small blocks, so it is a good default "
             "whenever the local blocks are >= 8 words each way)",
    )
    args = parser.parse_args(argv)
    # fail on argument mistakes BEFORE every host pays jax.distributed
    # initialisation, with messages that name the flags involved
    if args.resume and not args.ck:
        parser.error("-resume needs -ck (the checkpoint base path)")
    if args.resume and args.in_path:
        parser.error("-resume restores the board from -ck; drop -in")
    rule = CONWAY
    if args.rule:
        try:
            rule = LifeRule.from_rulestring(args.rule)
        except ValueError as e:
            parser.error(str(e))
    if args.halo_depth < 1:
        parser.error(f"-halo-depth must be >= 1, got {args.halo_depth}")

    multihost.initialize(
        args.coordinator, args.num_processes, args.process_id
    )
    local = len(jax.local_devices())
    mesh = make_mesh((jax.process_count(), local))
    is_root = jax.process_index() == 0

    events: "queue_mod.Queue" = queue_mod.Queue()
    keypresses: "queue_mod.Queue | None" = None
    restore_tty = lambda: None
    consumer = None
    if is_root:
        keypresses = queue_mod.Queue()
        restore_tty = start_tty_keys(keypresses)
        consumer = threading.Thread(target=drain_events, args=(events,))
        consumer.start()
    try:
        result = pod_session(
            args.size,
            args.turns,
            mesh,
            in_path=args.in_path,
            cells=None if (args.in_path or args.resume) else r_pentomino(args.size),
            rule=rule,
            row_block=args.row_block,
            events=events,
            keypresses=keypresses,
            out_dir=args.out,
            checkpoint_every=args.ck_every,
            checkpoint_path=args.ck,
            resume_from=args.ck if args.resume else None,
            halo_depth=args.halo_depth,
        )
    finally:
        if consumer is not None:
            consumer.join()
        restore_tty()
    if is_root:
        print(f"alive {result.alive_count}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
