"""Shard-streamed PGM IO for boards larger than any single host's memory.

The reference materialises the full board in the controller, the broker,
AND every worker (SURVEY.md §5 long-context note) — board size is capped
by one machine's RAM. Here each host reads and writes only its own row
range of the on-disk PGM (the BASELINE.json 65536^2 config: a ~4 GiB
raster that never exists in one piece in memory):

* ``create_pgm`` writes the header and pre-sizes the file;
* ``write_rows_at`` lets each host pwrite its rows at the right offset
  (safe concurrently — ranges are disjoint);
* reading a shard is ``PgmReader.read_rows`` (io/pgm.py), which seeks
  straight to the range (native-codec-accelerated beyond 1 MiB).
"""

from __future__ import annotations

import os
import pathlib

import numpy as np

from .pgm import PgmError, PgmReader


def pgm_raster_offset(width: int, height: int) -> int:
    """Byte offset of the raster in a PGM created by ``create_pgm`` — what
    a rank that did NOT create the file passes to ``write_rows_at``."""
    return len(_pgm_header(width, height))


def _pgm_header(width: int, height: int) -> bytes:
    return b"P5\n%d %d\n255\n" % (width, height)


def create_pgm(path, width: int, height: int) -> int:
    """Write the P5 header and pre-size the raster; returns raster offset."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    header = _pgm_header(width, height)
    with open(path, "wb") as f:
        f.write(header)
        f.truncate(len(header) + width * height)
    return len(header)


def write_rows_at(path, raster_offset: int, width: int, start_row: int, rows) -> None:
    """pwrite ``rows`` (uint8 [n, width]) at their offset in the raster."""
    rows = np.ascontiguousarray(rows, np.uint8)
    if rows.ndim != 2 or rows.shape[1] != width:
        raise PgmError(f"row block shape {rows.shape} does not match width {width}")
    fd = os.open(str(path), os.O_WRONLY)
    try:
        os.pwrite(fd, rows.tobytes(), raster_offset + start_row * width)
        os.fsync(fd)
    finally:
        os.close(fd)


def read_shard(path, start_row: int, stop_row: int) -> np.ndarray:
    """This host's row range of an on-disk board."""
    with PgmReader(path) as r:
        return r.read_rows(start_row, stop_row)


def write_board_sharded(path, width: int, height: int, shards) -> None:
    """Convenience single-process form: ``shards`` is an iterable of
    (start_row, rows) pairs; creates the file, then streams each shard."""
    offset = create_pgm(path, width, height)
    for start_row, rows in shards:
        write_rows_at(path, offset, width, start_row, rows)
