"""ctypes binding to the native PGM codec (native/pgm_codec.cc).

Auto-builds ``libgolio.so`` with g++ on first use (cached); every entry
point degrades to None so io/pgm.py can fall back to the pure-Python codec
when no compiler or build fails. pybind11 is not in the image, hence the
plain C ABI + ctypes (environment constraint).
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import threading

import numpy as np

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libgolio.so"

_lock = threading.Lock()
_lib = None
_lib_tried = False


def _load():
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        if os.environ.get("GOL_TPU_NO_NATIVE"):
            return None
        if not _LIB_PATH.exists():
            try:
                subprocess.run(
                    ["make", "-s", "libgolio.so"],
                    cwd=_NATIVE_DIR,
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except (OSError, subprocess.SubprocessError):
                return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
        except OSError:
            return None
        lib.golio_read_header.argtypes = [ctypes.c_char_p] + [
            ctypes.POINTER(ctypes.c_long)
        ] * 4
        lib.golio_read_header.restype = ctypes.c_int
        lib.golio_read_rows.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_ubyte),
        ]
        lib.golio_read_rows.restype = ctypes.c_int
        lib.golio_write.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_ubyte),
        ]
        lib.golio_write.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def read_header(path) -> tuple[int, int, int, int] | None:
    """(width, height, maxval, raster_offset) or None if unavailable/invalid."""
    lib = _load()
    if lib is None:
        return None
    w, h, m, off = (ctypes.c_long() for _ in range(4))
    rc = lib.golio_read_header(str(path).encode(), w, h, m, off)
    if rc != 0:
        return None
    return w.value, h.value, m.value, off.value


def read_rows(path, offset: int, width: int, start: int, stop: int):
    """uint8[stop-start, width] or None."""
    lib = _load()
    if lib is None:
        return None
    out = np.empty(((stop - start), width), np.uint8)
    rc = lib.golio_read_rows(
        str(path).encode(),
        offset,
        width,
        start,
        stop,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
    )
    return out if rc == 0 else None


def write_board(path, board: np.ndarray) -> bool:
    """Write + fsync a full P5 board; False if unavailable/failed."""
    lib = _load()
    if lib is None:
        return False
    board = np.ascontiguousarray(board, np.uint8)
    rc = lib.golio_write(
        str(path).encode(),
        board.shape[1],
        board.shape[0],
        board.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
    )
    return rc == 0
