"""PGM (P5) board IO — the reference's image subsystem re-founded on arrays.

The reference runs a dedicated IO goroutine that streams the board one byte at
a time over channels (gol/io.go:12-149). That CSP plumbing is a Go idiom, not
a capability; here the same contract — ``images/<W>x<H>.pgm`` in,
``out/<W>x<H>x<Turns>.pgm`` out, P5 with maxval 255, strict validation —
is exposed as direct array IO plus a streamed row interface
(``PgmReader.read_rows`` / ``PgmWriter``) so a multi-host run can read and
write only its own shard of a board too large for any single host
(SURVEY.md §7 step 6).

Validation mirrors gol/io.go:103-120, including the messages:
"Not a pgm file", "Incorrect width", "Incorrect height",
"Incorrect maxval/bit depth".
"""

from __future__ import annotations

import os
import pathlib

import numpy as np


class PgmError(Exception):
    """Raised on malformed or mismatching PGM input (gol/io.go panics)."""


# boards at least this large route through the native C++ codec when it is
# available (io/native.py auto-builds it; small boards aren't worth the hop)
_NATIVE_THRESHOLD_BYTES = 1 << 20


_WHITESPACE = b" \t\n\r\x0b\x0c"


def _parse_header(f) -> tuple[str, int, int, int, int]:
    """Parse a PNM header, returning (magic, width, height, maxval, data_offset).

    Handles '#' comments and arbitrary whitespace, per the PGM spec — a
    superset of what the reference accepts (it splits the whole file on
    whitespace, gol/io.go:101).
    """
    tokens: list[bytes] = []
    pos = 0
    f.seek(0)
    data = f.read(4096)  # headers are tiny; 4 KiB is generous
    while len(tokens) < 4:
        if pos >= len(data):
            raise PgmError("Not a pgm file")
        c = data[pos : pos + 1]
        if c in _WHITESPACE:
            pos += 1
        elif c == b"#":
            nl = data.find(b"\n", pos)
            if nl == -1:
                raise PgmError("Not a pgm file")
            pos = nl + 1
        else:
            end = pos
            while end < len(data) and data[end : end + 1] not in _WHITESPACE:
                end += 1
            tokens.append(data[pos:end])
            pos = end
    # exactly one whitespace byte separates the header from the raster
    if pos >= len(data) or data[pos : pos + 1] not in _WHITESPACE:
        raise PgmError("Not a pgm file")
    pos += 1
    magic = tokens[0].decode("ascii", "replace")
    try:
        width, height, maxval = (int(t) for t in tokens[1:4])
    except ValueError as e:
        raise PgmError("Not a pgm file") from e
    return magic, width, height, maxval, pos


class PgmReader:
    """Random-access P5 reader: header up front, rows on demand.

    ``read_rows(start, stop)`` seeks directly to the row range, so a host in a
    multi-host mesh materialises only its shard.
    """

    def __init__(self, path, *, expect_width=None, expect_height=None):
        self.path = pathlib.Path(path)
        self._f = open(self.path, "rb")
        try:
            magic, w, h, maxval, offset = _parse_header(self._f)
            if magic != "P5":
                raise PgmError("Not a pgm file")
            if expect_width is not None and w != expect_width:
                raise PgmError("Incorrect width")
            if expect_height is not None and h != expect_height:
                raise PgmError("Incorrect height")
            if maxval != 255:
                raise PgmError("Incorrect maxval/bit depth")
        except BaseException:
            self._f.close()
            raise
        self.width, self.height, self._offset = w, h, offset

    def read_rows(self, start: int, stop: int) -> np.ndarray:
        if not 0 <= start <= stop <= self.height:
            raise PgmError(f"row range [{start}, {stop}) outside board height {self.height}")
        n = stop - start
        if n * self.width >= _NATIVE_THRESHOLD_BYTES:
            from . import native

            rows = native.read_rows(self.path, self._offset, self.width, start, stop)
            if rows is not None:
                return rows
        self._f.seek(self._offset + start * self.width)
        buf = self._f.read(n * self.width)
        if len(buf) != n * self.width:
            raise PgmError("Not a pgm file")
        return np.frombuffer(buf, np.uint8).reshape(n, self.width)

    def read_all(self) -> np.ndarray:
        return self.read_rows(0, self.height)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class PgmWriter:
    """Streaming P5 writer: header first, then rows appended top to bottom.

    ``close`` fsyncs, matching the reference's durability behavior
    (gol/io.go:84-85).
    """

    def __init__(self, path, width: int, height: int):
        self.path = pathlib.Path(path)
        self.width, self.height = width, height
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "wb")
        self._f.write(b"P5\n%d %d\n255\n" % (width, height))
        self._rows_written = 0

    def write_rows(self, rows: np.ndarray):
        rows = np.ascontiguousarray(rows, np.uint8)
        if rows.ndim != 2 or rows.shape[1] != self.width:
            raise PgmError(f"row block shape {rows.shape} does not match width {self.width}")
        self._rows_written += rows.shape[0]
        if self._rows_written > self.height:
            raise PgmError("more rows written than the declared height")
        self._f.write(rows.tobytes())

    def close(self):
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        if self._rows_written != self.height:
            raise PgmError(
                f"wrote {self._rows_written} rows, declared {self.height}"
            )

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
        else:
            self._f.close()


def read_pgm(path, *, expect_width=None, expect_height=None) -> np.ndarray:
    """Read a whole P5 board as ``uint8[H, W]`` with reference validation."""
    with PgmReader(path, expect_width=expect_width, expect_height=expect_height) as r:
        return r.read_all()


def write_pgm(path, board: np.ndarray) -> None:
    """Write a whole ``uint8[H, W]`` board as P5 (fsynced)."""
    board = np.asarray(board, np.uint8)
    if board.ndim != 2:
        raise PgmError(f"board must be 2-D, got shape {board.shape}")
    if board.nbytes >= _NATIVE_THRESHOLD_BYTES:
        from . import native

        pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
        if native.write_board(path, board):
            return
    with PgmWriter(path, board.shape[1], board.shape[0]) as w:
        w.write_rows(board)


def read_board(params, images_dir="images") -> np.ndarray:
    """Load ``images/<W>x<H>.pgm`` per the filename convention
    (gol/distributor.go:144, gol/io.go:95)."""
    path = pathlib.Path(images_dir) / f"{params.input_filename}.pgm"
    return read_pgm(
        path,
        expect_width=params.image_width,
        expect_height=params.image_height,
    )


def write_board(board, filename: str, out_dir="out") -> pathlib.Path:
    """Write the board to ``out/<filename>.pgm`` (gol/io.go:42-48)."""
    path = pathlib.Path(out_dir) / f"{filename}.pgm"
    write_pgm(path, board)
    print(f"File {filename} output done!")
    return path
