from .pgm import (
    PgmError,
    PgmReader,
    PgmWriter,
    read_board,
    read_pgm,
    write_board,
    write_pgm,
)

__all__ = [
    "PgmError",
    "PgmReader",
    "PgmWriter",
    "read_pgm",
    "write_pgm",
    "read_board",
    "write_board",
]
