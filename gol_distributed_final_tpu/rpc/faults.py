"""Deterministic fault injection — the harness the recovery paths are proven by.

Two complementary mechanisms, both dependency-free and off unless a test
(or an operator running a game-day) opts in:

* ``ChaosProxy`` — a frame-aware TCP proxy that sits between an RpcClient
  and an RpcServer and injects transport faults on the length-prefixed
  frame stream (rpc/protocol.py framing): per-frame ``delay``,
  ``wedge_after=N`` (stop forwarding after the N-th frame but hold the
  sockets open — the stalled-but-alive worker the per-scatter deadline
  exists for), ``drop_after=N`` (hard connection close — the SIGKILLed
  peer), ``corrupt_frame=N`` (flip payload bytes of exactly frame N —
  the poisoned wire, landing INSIDE the pickle so it is always loud; byte
  positions come from the constructor ``seed``, so a failing run
  replays), and ``corrupt_sidecar=N`` (flip ONE BIT inside a raw ndarray
  sidecar of the first flagged frame >= N — the SILENT corruption class,
  injectable since the checked-frame layer in rpc/integrity.py exists to
  catch it). The global frame counter spans all connections and both
  directions, so a wedge also starves NEW connections — the broker's
  readmission probe cannot readmit a worker through a wedged path. Frame
  ordering is deterministic for a single proxied connection; across
  concurrent connections only the per-connection order is.

* ``fault_point(name)`` — in-process fault sites compiled into the worker
  dispatch, the RPC server, and the broker turn loop, triggered by the
  ``GOL_FAULT_POINTS`` env var (parsed once per process) or
  ``configure()`` in tests. Spec: comma-separated ``name:action:k[:arg]``
  entries — ``raise`` (FaultInjected on exactly the k-th hit), ``exit``
  (``os._exit(70)`` on the k-th hit: the crash that runs no finallys,
  kill -9 with a deterministic trigger point), ``sleep`` (sleep ``arg``
  seconds on every hit >= k), ``wedge`` (block forever from hit k on),
  ``corrupt`` (flip one byte of the site's exposed ndarray in place on
  the k-th hit — ``worker.strip_corrupt`` exposes the resident strip).
  With the env var unset a fault point costs one global read and a dict
  check — cheap enough to keep compiled into the hot paths.

The chaos test suite (tests/test_chaos.py, ``scripts/check --chaos``)
drives both against live broker/worker subprocesses.
"""

from __future__ import annotations

import os
import random
import socket
import threading
import time
from typing import Optional

# the proxy frames with the REAL wire header: a private-but-shared import
# beats re-declaring the struct (a protocol framing change must re-frame
# the chaos proxy too, not silently desync it)
from .integrity import CK_WORD_SIZE
from .protocol import (
    _FLAG_CK,
    _FLAG_OOB,
    _HEADER,
    _LEN_MASK,
    _OOB_LEN,
    _OOB_SUB,
    _recv_exact,
)


class FaultInjected(RuntimeError):
    """Raised by a ``raise``-action fault point — distinguishable from any
    organic failure, so a chaos test knows its fault (and nothing else)
    fired."""


# -- in-process fault points -------------------------------------------------

_ENV = "GOL_FAULT_POINTS"
_lock = threading.Lock()
_spec: Optional[dict] = None
_loaded = False
_hits: dict = {}


def _parse(text: str) -> dict:
    """``name:action:k[:arg]`` entries, comma-separated. Malformed entries
    raise ValueError loudly: a chaos run with a typoed spec must not
    silently run fault-free and "pass"."""
    spec: dict = {}
    for entry in filter(None, (e.strip() for e in text.split(","))):
        parts = entry.split(":")
        if len(parts) not in (2, 3, 4):
            raise ValueError(f"bad fault spec entry {entry!r}")
        name, action = parts[0], parts[1]
        if action not in ("raise", "exit", "sleep", "wedge", "corrupt"):
            raise ValueError(f"unknown fault action {action!r} in {entry!r}")
        k = int(parts[2]) if len(parts) > 2 else 1
        arg = float(parts[3]) if len(parts) > 3 else 0.0
        if action == "sleep" and len(parts) < 4:
            raise ValueError(f"sleep needs seconds: {entry!r} wants :k:secs")
        spec[name] = (action, k, arg)
    return spec


def configure(text: Optional[str]) -> None:
    """Test hook: install a spec string directly (None: forget it and
    re-read the env var on the next hit). Resets the hit counters."""
    global _spec, _loaded
    with _lock:
        _spec = _parse(text) if text else None
        _loaded = text is not None
        _hits.clear()


def fault_point(name: str, target=None) -> None:
    """A named site a fault can be injected at. No-op (one global read)
    unless ``GOL_FAULT_POINTS`` / ``configure`` named this site.

    ``target`` is an optional mutable ndarray the site exposes to the
    ``corrupt`` action (``name:corrupt:k[:flat_index]``): on exactly the
    k-th hit one byte of it is flipped IN PLACE — the silent-state
    corruption the integrity digest chain (rpc/integrity.py) exists to
    catch. Sites that pass no target make ``corrupt`` a no-op there."""
    global _spec, _loaded
    if not _loaded:
        with _lock:
            if not _loaded:
                env = os.environ.get(_ENV, "")
                _spec = _parse(env) if env else None
                _loaded = True
    spec = _spec
    if not spec:
        return
    entry = spec.get(name)
    if entry is None:
        return
    with _lock:
        _hits[name] = n = _hits.get(name, 0) + 1
    action, k, arg = entry
    if action == "sleep":
        if n >= k:
            time.sleep(arg)
    elif action == "wedge":
        if n >= k:
            threading.Event().wait()  # forever: the alive-but-silent hang
    elif n == k:
        if action == "raise":
            raise FaultInjected(f"fault point {name!r} fired on hit {n}")
        if action == "exit":
            # no finallys, no flushes — the deterministic kill -9
            os._exit(70)
        if action == "corrupt" and target is not None and target.size:
            # deterministic single-byte flip at flat index ``arg`` (mod
            # size). XOR 0xFF maps a 0/255 cell to its VALID opposite —
            # exactly the plausible-looking wrong bit nothing downstream
            # would notice without a digest
            flat = target.reshape(-1)
            flat[int(arg) % flat.size] ^= 0xFF


# -- TCP chaos proxy ---------------------------------------------------------


class ChaosProxy:
    """Frame-aware TCP proxy injecting deterministic transport faults.

    ``target`` is the real server's ``host:port``; clients dial
    ``proxy.address`` instead. Faults can be set at construction or
    swapped live with ``set_fault`` (a game-day lever). ``close()`` tears
    down the listener and every proxied connection, releasing wedged
    pump threads."""

    def __init__(
        self,
        target: str,
        *,
        seed: int = 0,
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        delay: float = 0.0,
        wedge_after: Optional[int] = None,
        drop_after: Optional[int] = None,
        corrupt_frame: Optional[int] = None,
        corrupt_sidecar: Optional[int] = None,
    ):
        host, port = target.rsplit(":", 1)
        self._target = (host, int(port))
        self._seed = seed
        self._lock = threading.Lock()
        self._frames = 0
        self._sidecar_corrupted = False
        self._faults = {
            "delay": delay,
            "wedge_after": wedge_after,
            "drop_after": drop_after,
            "corrupt_frame": corrupt_frame,
            "corrupt_sidecar": corrupt_sidecar,
        }
        self._closed = threading.Event()
        self._conns: list = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, listen_port))
        self._listener.listen(16)
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    @property
    def frames_forwarded(self) -> int:
        with self._lock:
            return self._frames

    def set_fault(self, **kw) -> None:
        """Update fault knobs live (``delay`` / ``wedge_after`` /
        ``drop_after`` / ``corrupt_frame``)."""
        bad = set(kw) - set(self._faults)
        if bad:
            raise ValueError(f"unknown fault knob(s): {sorted(bad)}")
        with self._lock:
            self._faults.update(kw)

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                break
            if self._closed.is_set():
                # a thread parked in accept() holds the closed listener
                # alive in the kernel: a dial racing close() can still be
                # accepted here and must be refused, not proxied
                conn.close()
                break
            try:
                upstream = socket.create_connection(self._target, timeout=5)
            except OSError:
                conn.close()
                continue
            upstream.settimeout(None)  # connect timeout must not bound reads
            for s in (conn, upstream):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns += [conn, upstream]
            threading.Thread(
                target=self._pump, args=(conn, upstream), daemon=True
            ).start()
            threading.Thread(
                target=self._pump, args=(upstream, conn), daemon=True
            ).start()

    def _pump(self, src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                head = _recv_exact(src, _HEADER.size)
                (word,) = _HEADER.unpack(head)
                # mask the protocol-5 out-of-band flag bit: a flagged
                # header's length field is the body length either way, and
                # the body (subheader + pickle + sidecar buffers) forwards
                # as one opaque blob. A CHECKED frame carries a crc32
                # word behind the length word (rpc/protocol.py) — part of
                # the header, forwarded untouched: corruption lands in
                # the BODY, and the stale crc is exactly what convicts it
                oob = bool(word & _FLAG_OOB)
                if word & _FLAG_CK:
                    head += _recv_exact(src, CK_WORD_SIZE)
                length = word & _LEN_MASK
                payload = _recv_exact(src, length)
                with self._lock:
                    idx = self._frames
                    self._frames += 1
                    faults = dict(self._faults)
                if faults["delay"]:
                    time.sleep(faults["delay"])
                wedge = faults["wedge_after"]
                if wedge is not None and idx >= wedge:
                    # hold both sockets open, forward nothing: the peer
                    # sees a connection that is up but silent
                    self._closed.wait()
                    return
                drop = faults["drop_after"]
                if drop is not None and idx >= drop:
                    return  # finally closes both: the hard kill
                sidecar = faults["corrupt_sidecar"]
                if (
                    sidecar is not None
                    and idx >= sidecar
                    and oob
                    and not self._sidecar_corrupted
                ):
                    # flip ONE BIT inside a raw ndarray sidecar buffer —
                    # the silent-board-corruption fault corrupt_frame
                    # deliberately never lands (its flips stay inside the
                    # pickle so they surface as unpickling errors). This
                    # knob exists to prove the checked-frame layer
                    # (rpc/integrity.py): against a checksum-negotiated
                    # peer the flip is a loud IntegrityError; against an
                    # -integrity off peer it IS a silently-wrong board —
                    # by design, that run is undefended. Fires once, on
                    # the first flagged frame >= N that carries sidecar
                    # bytes.
                    body = bytearray(payload)
                    if length > _OOB_SUB.size:
                        nbufs, pickle_len = _OOB_SUB.unpack_from(body, 0)
                        s0 = _OOB_SUB.size + _OOB_LEN.size * nbufs + pickle_len
                        s_end = length
                        if s_end > s0:
                            rng = random.Random(self._seed ^ idx)
                            pos = rng.randrange(s0, s_end)
                            body[pos] ^= 1 << rng.randrange(8)
                            payload = bytes(body)
                            self._sidecar_corrupted = True
                corrupt = faults["corrupt_frame"]
                if corrupt is not None and idx == corrupt and length:
                    body = bytearray(payload)
                    # corrupt_frame's corruption must land INSIDE the
                    # pickle bytes so it surfaces loudly even against an
                    # un-negotiated peer (UnpicklingError on a plain
                    # frame; IntegrityError first on a checked one): for
                    # a plain frame the pickle IS the body (byte 0 = the
                    # PROTO opcode); for an out-of-band frame the pickle
                    # sits after the subheader. Flipping a sidecar BUFFER
                    # byte is the SILENT corruption class — that is the
                    # separate, deliberate corrupt_sidecar knob above
                    if oob and length > _OOB_SUB.size:
                        nbufs, pickle_len = _OOB_SUB.unpack_from(body, 0)
                        p0 = _OOB_SUB.size + _OOB_LEN.size * nbufs
                        p_end = min(p0 + pickle_len, length)
                    else:
                        p0, p_end = 0, length
                    if p_end > p0:
                        body[p0] ^= 0xFF  # the PROTO opcode
                        if p_end - p0 > 1:
                            rng = random.Random(self._seed ^ idx)
                            for _ in range(3):
                                body[rng.randrange(p0 + 1, p_end)] ^= 0xFF
                    payload = bytes(body)
                dst.sendall(head + payload)
        except (OSError, ConnectionError):
            pass
        finally:
            for s in (src, dst):
                try:
                    s.close()
                except OSError:
                    pass

    def close(self) -> None:
        self._closed.set()
        try:
            # wake a blocked accept() (close alone leaves it holding the
            # kernel socket alive — it would accept one more connection)
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            try:
                s.close()
            except OSError:
                pass
