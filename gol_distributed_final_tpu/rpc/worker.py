"""The worker process — GameOfLifeOperations service (worker/worker.go:72-112).

Serves ``Update`` (compute one row strip of the next board state) and
``WorkerQuit``. The strip kernel is a vectorized numpy stencil (see
``_strip_step`` — this is the reference-shaped CPU plane; the TPU plane
lives in the engine): the broker sends the strip plus its two wrap-around
halo rows, and the worker returns the evolved strip — unlike the
reference, which ships the ENTIRE board to every worker and lets each one
index its strip (worker/worker.go:78, broker/broker.go:144). The wire cost
drops from O(H x W) to O(strip + 2 rows) per call while preserving the
verbs.

For reference-exact wire behavior the worker also accepts full-board
requests (halo rows derived locally) — the broker chooses per its
``wire`` mode.
"""

from __future__ import annotations

import argparse
import os
import threading
import time

import numpy as np

from ..obs import instruments as _ins
from ..obs import journal as _journal
from ..utils import locksan as _locksan
from . import faults as _faults
from . import integrity as _integrity
from .protocol import Methods, Request, Response
from .server import RpcServer

#: dead-band skip engages when the live window (the frontier's K-deep
#: dependency cone) covers at most this fraction of the padded block's
#: rows — below it the saved rows dominate the extent scan's cost
_SKIP_MAX_WINDOW_FRAC = 0.75

#: the fused jax strip path (ops/fused.fused_strip_steps — one dispatch
#: for the whole K-turn batch) engages for strips at least this many
#: cells under GOL_WORKER_FUSED=auto: below it, per-dispatch overhead
#: beats the K numpy passes it replaces
FUSED_STRIP_MIN_CELLS = 1 << 20


def _worker_fused_mode() -> str:
    """``GOL_WORKER_FUSED``: ``auto`` (default — fused for strips past
    FUSED_STRIP_MIN_CELLS, dead-band skip preferred when it applies),
    ``on`` (EVERY batch through the fused path whenever jax imports —
    overrides the skip), ``off`` (never the fused path)."""
    return os.environ.get("GOL_WORKER_FUSED", "auto").lower()


def _strip_step(padded: np.ndarray) -> np.ndarray:
    """(h+2, w) padded strip -> (h, w) next strip, columns wrapping locally.

    Deliberately a vectorized NUMPY kernel, not jax: this is the
    reference-shaped CPU worker (its kernel is a plain Go loop,
    worker/worker.go:15-70, Conway hard-coded :41-46), called once per
    strip per turn — per-call jax dispatch overhead would dominate a
    sub-millisecond stencil. The TPU data plane lives in the engine
    (ops/, parallel/), not here."""
    ext = np.concatenate([padded[:, -1:], padded, padded[:, :1]], axis=1)
    b = (ext != 0).astype(np.uint8)
    counts = (
        b[:-2, :-2].astype(np.int32) + b[:-2, 1:-1] + b[:-2, 2:]
        + b[1:-1, :-2] + b[1:-1, 2:]
        + b[2:, :-2] + b[2:, 1:-1] + b[2:, 2:]
    )
    alive = b[1:-1, 1:-1] == 1
    next_alive = np.where(alive, (counts == 2) | (counts == 3), counts == 3)
    return np.where(next_alive, 255, 0).astype(np.uint8)


def compute_strip(world: np.ndarray, start_y: int, end_y: int) -> np.ndarray:
    """Next state of rows [start_y, end_y) given the full board —
    the calculateNextState contract (worker/worker.go:15)."""
    h = world.shape[0]
    rows = np.arange(start_y - 1, end_y + 1) % h
    padded = world[rows]
    return _strip_step(padded)


def compute_strip_haloed(padded: np.ndarray) -> np.ndarray:
    """Next state of a strip sent WITH its halo rows (rows 0 and -1)."""
    return _strip_step(padded)


def strip_step_batch(
    strip: np.ndarray,
    top: np.ndarray,
    bottom: np.ndarray,
    k: int,
    attest: bool = False,
    *,
    mode: str = "auto",
):
    """Advance a resident strip K turns from depth-K halo rows, in
    shrinking form: the (h + 2K)-row padded block loses one row per side
    per step, landing exactly on the K-turns-later strip — the same
    amortisation the mesh planes' wide halos use (parallel/halo.py), here
    in the reference-shaped numpy kernel. Returns ``(next_strip,
    per_step_alive_counts)``: the counts are of the STRIP's rows only, so
    summing them across workers gives the whole board's count per turn
    (the AliveCellsCount feed, no gather).

    ``attest=True`` additionally returns two band digests
    ``(..., attest_top, attest_bottom)`` — the halo cross-attestation
    feed (rpc/integrity.py). After step j (1-based) the padded block
    covers rows ``[s-(k-j), e+(k-j))`` of the board at turn ``t+j``; its
    FIRST ``2*(k-j)`` rows are exactly the rows the UPPER neighbour's
    block ends with at the same step (both strips compute the band
    ``[s-(k-j), s+(k-j))`` redundantly from the same turn-t inputs).
    Each side's per-step bands fold into ONE rolling state digest per
    batch (each fold binds the band's shape, so the step structure is
    pinned twice over: by the lockstep (k, width) contract and by the
    digest itself) — stream equality is band-wise equality, at one
    digest's cost, and no intermediate step's array outlives its fold.
    Worker i's ``attest_top`` must hash-equal worker i-1's
    ``attest_bottom``: the broker cross-checks every batch, and a worker
    computing wrong rows anywhere in a boundary's dependency cone is
    caught within the batch (≤K turns). The final step's band is empty
    (zero rows — folds only its shape header; k=1 attests the empty
    band, which still compares).

    Three bit-identical execution paths, routed per batch (``mode`` pins
    one for tests; every path yields the same strips, counts, AND band
    digests):

    * ``skip`` — the dead-band skip (the PR 14 named headroom): when the
      live rows' K-deep dependency cone covers a minority of the block,
      only that window is stepped — rows outside it are provably dead
      for all K turns (non-B0: a dead row with dead neighbours stays
      dead), so the window's zero padding is exact and the saved
      row-steps are metered on ``gol_strip_rows_skipped_total``.
    * ``fused`` — big strips route through ops/fused.fused_strip_steps:
      the whole K-turn shrinking batch as ONE jitted dispatch (the fused
      kernel under StripStep — PR 5's wire batching and launch fusion
      compound), bands materialised so the digest fold is byte-identical.
    * ``dense`` — the reference-shaped numpy loop."""
    h = strip.shape[0]
    if k < 1:
        raise ValueError(f"strip batch needs k >= 1, got {k}")
    if top.shape != (k, strip.shape[1]) or bottom.shape != (k, strip.shape[1]):
        raise ValueError(
            f"depth-{k} halos must each be ({k}, {strip.shape[1]}), got "
            f"{top.shape} and {bottom.shape}"
        )
    padded = np.concatenate([top, strip, bottom], axis=0)
    window = None
    if mode == "auto":
        fused = _worker_fused_mode()
        if fused == "on" and _jax_available():
            # an explicit operator override: EVERY batch takes the fused
            # one-dispatch path, the dead-band skip included — the knob
            # exists to pin the routing, not to advise it
            mode = "fused"
        else:
            window = _live_window(padded, k)
            if window[1] - window[0] <= _SKIP_MAX_WINDOW_FRAC * padded.shape[0]:
                mode = "skip"
            elif fused == "auto" and strip.size >= FUSED_STRIP_MIN_CELLS:
                mode = "fused" if _jax_available() else "dense"
            else:
                mode = "dense"
    if mode == "skip":
        if window is None:  # pinned mode: the routing scan never ran
            window = _live_window(padded, k)
        return _strip_batch_skip(padded, k, h, *window, attest)
    if mode == "fused":
        return _strip_batch_fused(padded, k, h, attest)
    if mode != "dense":
        raise ValueError(f"unknown strip batch mode {mode!r}")
    counts = []
    at = ab = _integrity.state_new()
    for i in range(k):
        padded = _strip_step(padded)  # 2 fewer rows per step
        off = k - (i + 1)
        counts.append(int(np.count_nonzero(padded[off : off + h])))
        if attest:
            # fold the bands NOW: keeping views of every step's padded
            # intermediate until batch end would hold ~K full strips live
            band = 2 * off
            at = _integrity.state_add(at, padded[:band])
            ab = _integrity.state_add(ab, padded[padded.shape[0] - band:])
    if attest:
        return (
            padded, counts,
            _integrity.state_hex(at), _integrity.state_hex(ab),
        )
    return padded, counts


def _jax_available() -> bool:
    try:
        import jax  # noqa: F401  (the fused path imports it for real)

        return True
    except Exception:
        return False


def _live_window(padded: np.ndarray, k: int) -> tuple[int, int]:
    """The frontier's K-deep dependency cone as a row window [lo, hi):
    every row outside it is dead at turn t AND at distance > K from any
    live row, so it stays dead through all K steps (Conway is non-B0 —
    a dead row with dead neighbours never births). (0, 0) when the whole
    block is dead."""
    live = np.flatnonzero(padded.any(axis=1))
    if live.size == 0:
        return 0, 0
    return (
        max(0, int(live[0]) - k),
        min(padded.shape[0], int(live[-1]) + 1 + k),
    )


def _strip_batch_skip(padded, k: int, h: int, a_lo: int, a_hi: int, attest):
    """The dead-band skip: step ONLY the live window, reconstruct every
    full-block artifact (strip, counts, attestation bands) from it.

    Rows outside [a_lo, a_hi) are dead for all K steps, so stepping the
    window between zero pads is exact there; where the window touches the
    block's EDGE, the zero pad stands in for halo data the dense path
    also discards — the resulting garbage cone reaches at most row j-1
    by step j, strictly outside both the strip rows [K, K+h) and that
    step's attestation bands (which start at row j), so every value any
    output reads is identical to the dense computation's."""
    H, w = padded.shape
    _ins.STRIP_ROWS_SKIPPED_TOTAL.inc((H - (a_hi - a_lo)) * k)
    zero = np.zeros((1, w), np.uint8)
    active = np.array(padded[a_lo:a_hi], np.uint8)

    def materialize(lo: int, hi: int) -> np.ndarray:
        out = np.zeros((max(0, hi - lo), w), np.uint8)
        o_lo, o_hi = max(lo, a_lo), min(hi, a_hi)
        if o_hi > o_lo:
            out[o_lo - lo : o_hi - lo] = active[o_lo - a_lo : o_hi - a_lo]
        return out

    counts = []
    at = ab = _integrity.state_new()
    for i in range(k):
        if active.shape[0]:
            # constant-size: the zero pads replace the rows the dense
            # shrinking form consumes (provably dead, or discarded cone)
            active = _strip_step(np.concatenate([zero, active, zero], axis=0))
        s_lo, s_hi = max(k, a_lo), min(k + h, a_hi)
        counts.append(
            int(np.count_nonzero(active[s_lo - a_lo : s_hi - a_lo]))
            if s_hi > s_lo
            else 0
        )
        if attest:
            band = 2 * (k - (i + 1))
            step = i + 1
            at = _integrity.state_add(at, materialize(step, step + band))
            ab = _integrity.state_add(
                ab, materialize(H - step - band, H - step)
            )
    final = materialize(k, k + h)
    if attest:
        return (
            final, counts,
            _integrity.state_hex(at), _integrity.state_hex(ab),
        )
    return final, counts


def _strip_batch_fused(padded, k: int, h: int, attest):
    """The fused jax path: ops/fused.fused_strip_steps runs the whole
    shrinking K-turn batch as one dispatch; the per-step bands come back
    materialised so the rolling digest fold is byte-identical to the
    dense path's (the broker's cross-attestation never sees a routing
    difference)."""
    from ..ops.fused import fused_strip_steps

    strip, counts, bands = fused_strip_steps(padded, k, h, attest=attest)
    if attest:
        at = ab = _integrity.state_new()
        for band_top, band_bot in bands:
            at = _integrity.state_add(at, band_top)
            ab = _integrity.state_add(ab, band_bot)
        return (
            strip, counts,
            _integrity.state_hex(at), _integrity.state_hex(ab),
        )
    return strip, counts


#: Conway's masks, duplicated from ops/stencil.py so the numpy worker
#: plane keeps its no-jax-at-import property (bit c set = the rule
#: births/survives on c live neighbours)
_CONWAY_BIRTH_MASK = 1 << 3
_CONWAY_SURVIVE_MASK = (1 << 2) | (1 << 3)

#: the 2-D attestation digest keys, fixed order — four edges plus the
#: four diagonal corner bands a K-step dependency cone shares with the
#: diagonal neighbours (see tile_step_batch)
_TILE_ATTEST_KEYS = (
    "attest_top", "attest_bottom", "attest_left", "attest_right",
    "attest_tl", "attest_tr", "attest_bl", "attest_br",
)


def _tile_step(
    padded: np.ndarray,
    birth_mask: int = _CONWAY_BIRTH_MASK,
    survive_mask: int = _CONWAY_SURVIVE_MASK,
) -> np.ndarray:
    """(h, w) padded block -> (h-2, w-2) next interior, NO wrap on either
    axis — the 2-D tile kernel (``_strip_step`` minus the local column
    wrap: a tile's column neighbours are OTHER workers' tiles, so its
    left/right context arrives as halo data exactly like its rows). Same
    deliberate numpy posture as ``_strip_step``. Masked rules ride for
    the oracle tests (HighLife parity); the resident wire itself stays
    Conway-only (the broker refuses other rules on it)."""
    b = (padded != 0).astype(np.uint8)
    counts = (
        b[:-2, :-2].astype(np.int32) + b[:-2, 1:-1] + b[:-2, 2:]
        + b[1:-1, :-2] + b[1:-1, 2:]
        + b[2:, :-2] + b[2:, 1:-1] + b[2:, 2:]
    )
    alive = b[1:-1, 1:-1] == 1
    if birth_mask == _CONWAY_BIRTH_MASK and survive_mask == _CONWAY_SURVIVE_MASK:
        next_alive = np.where(alive, (counts == 2) | (counts == 3), counts == 3)
    else:
        lut = np.array(
            [[(survive_mask if a else birth_mask) >> c & 1 for c in range(9)]
             for a in (0, 1)],
            bool,
        )
        next_alive = lut[alive.astype(np.intp), counts]
    return np.where(next_alive, 255, 0).astype(np.uint8)


def _packed_len(shape) -> int:
    """Bytes one bit-packed cell block of this shape occupies on the
    tile halo wire."""
    return (int(shape[0]) * int(shape[1]) + 7) // 8


def pack_tile_blocks(blocks) -> np.ndarray:
    """Bit-pack 0/255 cell blocks (1 bit per cell) into one flat uint8
    buffer — the tile halo wire format. Each block packs SEPARATELY
    (byte-aligned), so section offsets derive from shapes alone
    (``tile_halo_shapes``/``tile_edge_shapes``) and per-axis byte counts
    are exact for the ``gol_halo_bytes_total{axis}`` meter. The 8x
    reduction vs raw uint8 cells is what puts a 2-D grid's
    edge-plus-corner exchange strictly under the strip plane's row-only
    bytes even at the 2x2 break-even point of a square board. Lossless:
    halo cells only feed the nonzero-is-alive kernel, and every block a
    worker computes is already 0/255."""
    if not blocks:
        return np.zeros(0, np.uint8)
    return np.concatenate(
        [np.packbits((np.asarray(b, np.uint8) != 0).ravel()) for b in blocks]
    )


def unpack_tile_blocks(buf, shapes) -> list:
    """Inverse of ``pack_tile_blocks`` given the section shapes. Strict:
    a short buffer or trailing bytes is a protocol violation (raises),
    never a silent truncation."""
    buf = np.asarray(buf, np.uint8).ravel()
    out, off = [], 0
    for sh in shapes:
        n = int(sh[0]) * int(sh[1])
        ln = _packed_len(sh)
        seg = buf[off : off + ln]
        if seg.size != ln:
            raise ValueError(
                f"tile buffer truncated: section {sh} needs {ln} bytes, "
                f"{buf.size - off} left"
            )
        cells = np.unpackbits(seg, count=n).astype(np.uint8) * np.uint8(255)
        out.append(cells.reshape((int(sh[0]), int(sh[1]))))
        off += ln
    if off != buf.size:
        raise ValueError(f"tile buffer has {buf.size - off} trailing bytes")
    return out


def tile_halo_shapes(k: int, th: int, tw: int) -> list:
    """Downlink (StripStep ``world``) section shapes for a depth-K tile
    batch, fixed order: top, bottom (k x tile_w row bands), left, right
    (tile_h x k column bands), then the four K x K corner blocks
    (tl, tr, bl, br) — the full dependency cone of K steps."""
    return [
        (k, tw), (k, tw), (th, k), (th, k),
        (k, k), (k, k), (k, k), (k, k),
    ]


def tile_edge_shapes(k: int, th: int, tw: int) -> list:
    """Uplink (reply ``edges``) section shapes: the stepped tile's fresh
    top, bottom, left, right bands. No corners — the broker derives each
    diagonal corner block from the diagonal neighbour's row bands."""
    return [(k, tw), (k, tw), (th, k), (th, k)]


def tile_step_batch(
    tile: np.ndarray,
    halos,
    k: int,
    attest: bool = False,
    *,
    mode: str = "auto",
    rule=None,
):
    """Advance a resident 2-D TILE K turns from its four depth-K edge
    halos plus four K x K corner blocks — ``strip_step_batch``'s
    checkerboard generalisation, shrinking one cell per SIDE per step:
    the (th + 2K) x (tw + 2K) block lands exactly on the K-turns-later
    tile. ``halos`` is the 8-tuple ``(top, bottom, left, right, tl, tr,
    bl, br)`` in ``tile_halo_shapes`` order. Returns ``(next_tile,
    per_step_alive_counts)`` — counts cover the TILE's cells only, so
    the roster's sum is the whole board's count per turn, exactly like
    strips.

    ``attest=True`` additionally returns a dict of EIGHT rolling band
    digests (``_TILE_ATTEST_KEYS``): after step j (off = K - j) the
    top/bottom digests fold the block's first/last ``2*off`` rows over
    its full current width, left/right its first/last ``2*off`` columns
    over its full height, and the four corner digests the ``2*off x
    2*off`` corner sub-blocks. Two tiles sharing an edge compute that
    band redundantly from the same turn-t inputs, and diagonal
    neighbours likewise share a corner cone, so the broker cross-checks
    ``(r,c).attest_top == (r-1,c).attest_bottom``, ``.attest_left ==
    (r,c-1).attest_right``, ``.attest_tl == (r-1,c-1).attest_br`` and
    ``.attest_tr == (r-1,c+1).attest_bl`` (toroidal indices; a 1-band
    axis self-pairs, which still compares — the wrap makes both bands
    the same board cells). Disagreement quarantines BOTH parties, same
    contract as the strip plane's two-band attestation.

    Routing mirrors the strip batch minus the fused path: ``skip`` steps
    only the live frontier's K-deep 2-D bounding window between zero
    pads (exact for non-B0 rules by the same dead-stays-dead + discarded
    garbage-cone argument, now per axis), ``dense`` is the plain loop.
    There is deliberately NO fused tile path: ops/fused's strip kernel
    wraps columns locally, which a tile must not — GOL_WORKER_FUSED=on
    therefore pins big TILE batches to dense, not to a wrong kernel.
    ``rule`` is an optional LifeRule-shaped object (birth_mask/
    survive_mask) for oracle tests; the wire plane never sets it."""
    th, tw = tile.shape
    if k < 1:
        raise ValueError(f"tile batch needs k >= 1, got {k}")
    if k > min(th, tw):
        raise ValueError(
            f"batch depth {k} exceeds tile minimum dimension {min(th, tw)}"
        )
    top, bottom, left, right, tl, tr, bl, br = halos
    for name, arr, want in (
        ("top", top, (k, tw)), ("bottom", bottom, (k, tw)),
        ("left", left, (th, k)), ("right", right, (th, k)),
        ("tl", tl, (k, k)), ("tr", tr, (k, k)),
        ("bl", bl, (k, k)), ("br", br, (k, k)),
    ):
        if np.asarray(arr).shape != want:
            raise ValueError(
                f"depth-{k} tile halo {name} must be {want}, got "
                f"{np.asarray(arr).shape}"
            )
    birth = rule.birth_mask if rule is not None else _CONWAY_BIRTH_MASK
    survive = rule.survive_mask if rule is not None else _CONWAY_SURVIVE_MASK
    block = np.block([
        [np.asarray(tl, np.uint8), np.asarray(top, np.uint8), np.asarray(tr, np.uint8)],
        [np.asarray(left, np.uint8), np.asarray(tile, np.uint8), np.asarray(right, np.uint8)],
        [np.asarray(bl, np.uint8), np.asarray(bottom, np.uint8), np.asarray(br, np.uint8)],
    ])
    window = None
    if mode == "auto":
        if birth & 1:
            mode = "dense"  # B0: dead cells birth — no dead band exists
        else:
            window = _live_window_2d(block, k)
            area = (window[1] - window[0]) * (window[3] - window[2])
            if area <= _SKIP_MAX_WINDOW_FRAC * block.size:
                mode = "skip"
            else:
                mode = "dense"
    if mode == "fused":
        raise ValueError(
            "tile batches have no fused path: ops/fused's strip kernel "
            "wraps columns locally (a tile's column context is halo "
            "data); use auto/dense/skip"
        )
    if mode == "skip":
        if birth & 1:
            raise ValueError("the dead-band skip is unsound under a B0 rule")
        if window is None:  # pinned mode: the routing scan never ran
            window = _live_window_2d(block, k)
        return _tile_batch_skip(block, k, th, tw, window, attest, birth, survive)
    if mode != "dense":
        raise ValueError(f"unknown tile batch mode {mode!r}")
    counts = []
    states = {key: _integrity.state_new() for key in _TILE_ATTEST_KEYS}
    for i in range(k):
        block = _tile_step(block, birth, survive)  # 2 fewer rows AND cols
        off = k - (i + 1)
        counts.append(int(np.count_nonzero(block[off : off + th, off : off + tw])))
        if attest:
            _fold_tile_bands(states, block, 2 * off)
    if attest:
        return (
            block, counts,
            {key: _integrity.state_hex(st) for key, st in states.items()},
        )
    return block, counts


def _fold_tile_bands(states, block, band: int):
    """Fold one step's eight attestation bands into the rolling digests
    (band = 2*(K-j) cells per side; empty at the final step — the fold
    still binds the shape header, so the step structure is pinned)."""
    H, W = block.shape
    states["attest_top"] = _integrity.state_add(states["attest_top"], block[:band])
    states["attest_bottom"] = _integrity.state_add(
        states["attest_bottom"], block[H - band :]
    )
    states["attest_left"] = _integrity.state_add(
        states["attest_left"], block[:, :band]
    )
    states["attest_right"] = _integrity.state_add(
        states["attest_right"], block[:, W - band :]
    )
    states["attest_tl"] = _integrity.state_add(
        states["attest_tl"], block[:band, :band]
    )
    states["attest_tr"] = _integrity.state_add(
        states["attest_tr"], block[:band, W - band :]
    )
    states["attest_bl"] = _integrity.state_add(
        states["attest_bl"], block[H - band :, :band]
    )
    states["attest_br"] = _integrity.state_add(
        states["attest_br"], block[H - band :, W - band :]
    )


def _live_window_2d(block: np.ndarray, k: int):
    """The live frontier's K-deep dependency cone as a 2-D window
    (r0, r1, c0, c1) — ``_live_window`` per axis. Cells outside it are
    dead at turn t AND at distance > K from any live cell on BOTH axes,
    so they stay dead through all K steps under any non-B0 rule.
    All-zeros when the whole block is dead."""
    rows = np.flatnonzero(block.any(axis=1))
    if rows.size == 0:
        return 0, 0, 0, 0
    cols = np.flatnonzero(block.any(axis=0))
    return (
        max(0, int(rows[0]) - k),
        min(block.shape[0], int(rows[-1]) + 1 + k),
        max(0, int(cols[0]) - k),
        min(block.shape[1], int(cols[-1]) + 1 + k),
    )


def _tile_batch_skip(block, k, th, tw, window, attest, birth, survive):
    """The dead-band skip in 2-D: step ONLY the live window between zero
    pads, reconstruct every full-block artifact (tile, counts, all eight
    attestation bands) from it. Exactness is the strip argument per
    axis: outside the window is provably dead for all K steps, and where
    the window touches the BLOCK's edge the zero pad stands in for cone
    data the dense shrinking form also discards — the garbage reaches at
    most ``j-1`` cells in from that edge by step j, strictly outside the
    tile region and that step's bands (which start ``j`` cells in)."""
    H, W = block.shape
    r0, r1, c0, c1 = window
    active = np.array(block[r0:r1, c0:c1], np.uint8)

    def materialize(a: int, b: int, c: int, d: int) -> np.ndarray:
        out = np.zeros((max(0, b - a), max(0, d - c)), np.uint8)
        rlo, rhi = max(a, r0), min(b, r1)
        clo, chi = max(c, c0), min(d, c1)
        if rhi > rlo and chi > clo:
            out[rlo - a : rhi - a, clo - c : chi - c] = active[
                rlo - r0 : rhi - r0, clo - c0 : chi - c0
            ]
        return out

    counts = []
    states = {key: _integrity.state_new() for key in _TILE_ATTEST_KEYS}
    for i in range(k):
        if active.size:
            # constant-size: the zero ring replaces the cells the dense
            # shrinking form consumes (provably dead, or discarded cone)
            padded = np.zeros((active.shape[0] + 2, active.shape[1] + 2), np.uint8)
            padded[1:-1, 1:-1] = active
            active = _tile_step(padded, birth, survive)
        step = i + 1
        off = k - step
        rlo, rhi = max(k, r0), min(k + th, r1)
        clo, chi = max(k, c0), min(k + tw, c1)
        counts.append(
            int(np.count_nonzero(
                active[rlo - r0 : rhi - r0, clo - c0 : chi - c0]
            ))
            if rhi > rlo and chi > clo
            else 0
        )
        if attest:
            band = 2 * off
            # the shrunk block's bands in original-frame coordinates:
            # at step j the dense block occupies [j, H-j) x [j, W-j)
            shadow = {
                "attest_top": (step, step + band, step, W - step),
                "attest_bottom": (H - step - band, H - step, step, W - step),
                "attest_left": (step, H - step, step, step + band),
                "attest_right": (step, H - step, W - step - band, W - step),
                "attest_tl": (step, step + band, step, step + band),
                "attest_tr": (step, step + band, W - step - band, W - step),
                "attest_bl": (H - step - band, H - step, step, step + band),
                "attest_br": (
                    H - step - band, H - step, W - step - band, W - step,
                ),
            }
            for key, box in shadow.items():
                states[key] = _integrity.state_add(states[key], materialize(*box))
    final = materialize(k, k + th, k, k + tw)
    if attest:
        return (
            final, counts,
            {key: _integrity.state_hex(st) for key, st in states.items()},
        )
    return final, counts


class WorkerService:
    # the resident-strip session state moves as one unit under its lock
    # (machine-enforced: analysis/locks.py flags any access outside
    # 'with self._strip_lock')
    _GUARDED_BY = {
        "_strip": "_strip_lock",
        "_strip_turn": "_strip_lock",
        "_strip_index": "_strip_lock",
        "_strip_dirty": "_strip_lock",
        "_strip_clean_turn": "_strip_lock",
        "_strip_is_tile": "_strip_lock",
    }

    def __init__(self, server: RpcServer):
        self._server = server
        self.quit_event = threading.Event()
        # the resident-strip session (-wire resident): ONE strip per worker
        # process, held across turns. (strip, turn, index) under a lock —
        # StripStart replaces it wholesale, so a reseed after loss recovery
        # can never leave a stale session behind.
        self._strip_lock = _locksan.lock("WorkerService._strip_lock")
        self._strip: np.ndarray | None = None
        self._strip_turn = 0
        self._strip_index = 0
        # dirty-tile accumulator (ops/sparse.py wire tiles): which tiles
        # changed since the broker last held a full copy of this strip.
        # Anchored by _strip_clean_turn — the turn the accumulator was
        # last reset at (seed, or any StripFetch reply); a delta fetch is
        # only answered when the broker's base turn matches the anchor,
        # anything else degrades to a full frame.
        self._strip_dirty: np.ndarray | None = None
        self._strip_clean_turn = 0
        # True when the resident block is a 2-D TILE (-grid with >= 2
        # column bands): StripStep then ships bit-packed four-edge-plus-
        # corner halos instead of the strip plane's 2K raw rows. A
        # 1-column grid never sets it — the strip plane IS that case.
        self._strip_is_tile = False

    def update(self, req: Request) -> Response:
        # chaos hook (rpc/faults.py): GOL_FAULT_POINTS can wedge, crash, or
        # fail this worker's compute deterministically — one dict check when
        # unset. The broker's deadline/resplit/readmission paths are proven
        # against exactly this site (tests/test_chaos.py).
        t0 = time.monotonic()
        _faults.fault_point("worker.update")
        world = np.asarray(req.world, np.uint8)
        if req.start_y == -1:  # haloed-strip wire mode
            strip = compute_strip_haloed(world)
        else:
            strip = compute_strip(world, req.start_y, req.end_y)
        # service_seconds includes any injected fault stall on purpose: a
        # chaos-slowed worker must look slow to the broker's critical-path
        # attribution, exactly like an organically slow one
        return Response(
            work_slice=strip,
            worker=req.worker,
            service_seconds=time.monotonic() - t0,
        )

    # -- resident-strip verbs (-wire resident: the strip stays here) --------

    def strip_start(self, req: Request) -> Response:
        """Seed (or re-seed) this worker's resident strip at a turn. The
        broker calls it at run start, after loss recovery, and at every
        re-split — always with the full strip, so it REPLACES any previous
        session unconditionally."""
        strip = np.array(req.world, np.uint8, copy=True)  # own it: the
        # request array may be a view of the receive buffer (protocol-5
        # out-of-band), whose lifetime is the frame's, not the session's
        if strip.ndim != 2 or strip.shape[0] < 1:
            raise ValueError(f"strip must be a 2-D row block, got {strip.shape}")
        from ..ops.sparse import wire_tile_grid

        grid_cols = getattr(req, "grid_cols", 0)
        with self._strip_lock:
            self._strip = strip
            turn = self._strip_turn = getattr(req, "initial_turn", 0)
            self._strip_index = req.worker
            # a nonzero column-band count marks a 2-D tile session — the
            # legacy strip loop never sets the field, and a broker's tile
            # loop may degrade a shrunken roster to a one-column grid that
            # still speaks the tile wire (getattr-read: a version-skewed
            # broker's pickle lacks the field and this worker keeps
            # serving plain 1-D strips)
            self._strip_is_tile = isinstance(grid_cols, int) and grid_cols >= 1
            # the broker just sent this full strip, so its copy IS
            # current: a clean dirty accumulator anchored at the seed turn
            self._strip_dirty = np.zeros(wire_tile_grid(strip.shape), bool)
            self._strip_clean_turn = turn
        # reply with the turn captured UNDER the lock: a concurrent
        # StripStep landing between release and reply must not make this
        # seed acknowledgment claim the stepped turn (analysis/locks.py
        # caught the original unlocked read)
        _journal.record(
            "run.start", "worker", turn=turn, index=int(req.worker),
            rows=int(strip.shape[0]),
        )
        return Response(worker=req.worker, turns_completed=turn)

    def strip_step(self, req: Request) -> Response:
        """Advance the resident strip ``req.turns`` turns given depth-K halo
        rows (req.world = [top K; bottom K] stacked). Lockstep-guarded:
        ``req.initial_turn`` must equal the strip's turn — a mismatch means
        the broker and this worker disagree about history (a stale worker
        readmitted mid-recovery) and MUST be an error reply, never a
        silently-diverged strip."""
        t0 = time.monotonic()
        _faults.fault_point("worker.strip_step")
        k = req.turns
        with self._strip_lock:
            if self._strip is None:
                raise ValueError("no resident strip: StripStart must precede StripStep")
            if getattr(req, "initial_turn", 0) != self._strip_turn:
                raise ValueError(
                    f"lockstep violation: strip is at turn {self._strip_turn}, "
                    f"broker asked for turn {getattr(req, 'initial_turn', 0)}"
                )
            if req.worker != self._strip_index:
                raise ValueError(
                    f"strip index mismatch: seeded as {self._strip_index}, "
                    f"stepped as {req.worker}"
                )
            halo_blocks = None
            if self._strip_is_tile:
                if k < 1:
                    raise ValueError(f"tile batch needs k >= 1, got {k}")
                th, tw = self._strip.shape
                buf = np.asarray(req.world, np.uint8).ravel()
                shapes = tile_halo_shapes(k, th, tw)
                want = sum(_packed_len(s) for s in shapes)
                if buf.size != want:
                    raise ValueError(
                        f"depth-{k} tile halos for a {th}x{tw} tile must "
                        f"pack to {want} bytes, got {buf.size}"
                    )
                halo_blocks = unpack_tile_blocks(buf, shapes)
            else:
                halos = np.asarray(req.world, np.uint8)
                if halos.shape != (2 * k, self._strip.shape[1]):
                    raise ValueError(
                        f"depth-{k} halos must be ({2 * k}, "
                        f"{self._strip.shape[1]}), got {halos.shape}"
                    )
                if k > self._strip.shape[0]:
                    raise ValueError(
                        f"batch depth {k} exceeds strip height {self._strip.shape[0]}"
                    )
            # chaos site (rpc/faults.py "corrupt" action): flips a byte of
            # the RESIDENT strip in place — the silent-state-corruption
            # fault the digest chain below exists to catch. Placed before
            # the pre-digest so the corruption is visible to it: the
            # broker's chain comparison then refuses this reply.
            _faults.fault_point("worker.strip_corrupt", target=self._strip)
            check = _integrity.enabled()
            pre = _integrity.state_digest(self._strip) if check else None
            pre_strip = self._strip
            att = None
            if self._strip_is_tile:
                if check:
                    strip, counts, att = tile_step_batch(
                        self._strip, halo_blocks, k, attest=True
                    )
                else:
                    strip, counts = tile_step_batch(self._strip, halo_blocks, k)
            elif check:
                strip, counts, att_top, att_bottom = strip_step_batch(
                    self._strip, halos[:k], halos[k:], k, attest=True
                )
            else:
                strip, counts = strip_step_batch(
                    self._strip, halos[:k], halos[k:], k
                )
            # per-tile dirty bitmap over the batch (ops/sparse.py wire
            # tiles): rides the reply (the broker's frontier gauge +
            # delta-checkpoint feed) and accumulates locally so a later
            # StripFetch can ship only the tiles that changed since the
            # broker's last full copy
            from ..ops.sparse import dirty_tile_grid

            dirty = dirty_tile_grid(pre_strip, strip)
            if (
                self._strip_dirty is not None
                and self._strip_dirty.shape == dirty.shape
            ):
                self._strip_dirty |= dirty
            else:
                self._strip_dirty = dirty.copy()
            self._strip = strip
            self._strip_turn += k
            # the fresh boundary bands: the broker relays them to this
            # block's neighbours as their next batch's halos — the only
            # state that leaves this process per batch. A tile ships all
            # four edges bit-packed (the broker derives corner blocks
            # from the diagonal neighbours' row bands, so corners never
            # ride the uplink).
            if self._strip_is_tile:
                edges = pack_tile_blocks(
                    (strip[:k], strip[-k:], strip[:, :k], strip[:, -k:])
                )
            else:
                edges = np.concatenate([strip[:k], strip[-k:]], axis=0)
            digests = None
            if check:
                # the attestation payload (rpc/integrity.py): "pre"/"strip"
                # anchor the broker's per-strip digest chain (in-place
                # corruption between batches is caught on the NEXT step),
                # "edges" covers worker-side serialisation of the reply
                # bands (for a tile, the PACKED buffer — what actually
                # crosses), and the attest digests feed the neighbour
                # cross-check (two for a strip, eight for a tile)
                digests = {
                    "pre": pre,
                    "strip": _integrity.state_digest(strip),
                    "edges": _integrity.state_digest(edges),
                }
                if self._strip_is_tile:
                    digests.update(att)
                else:
                    digests["attest_top"] = att_top
                    digests["attest_bottom"] = att_bottom
            turn_done = self._strip_turn
        # journal outside the strip lock (one record per K-turn batch):
        # this worker's half of the chunk the broker is about to commit
        _journal.record(
            "chunk.commit", "worker", k=k, turn=turn_done,
            alive=int(counts[-1]) if counts else 0,
            route="attested" if check else "plain",
        )
        return Response(
            worker=req.worker,
            turns_completed=turn_done,
            edges=edges,
            counts=counts,
            digests=digests,
            dirty=dirty,
            service_seconds=time.monotonic() - t0,
        )

    def strip_fetch(self, req: Request) -> Response:
        """Read the resident strip + its turn back out (full re-syncs,
        snapshots, loss recovery).

        When the broker passes ``delta_base_turn`` matching this strip's
        dirty-accumulator anchor, the reply is a DELTA frame: the dirty
        bitmap plus only the changed tiles as one flat sidecar buffer
        (``ops/sparse.extract_dirty_tiles`` layout) — a <1%-active board
        syncs in a fraction of the full-strip bytes. Any mismatch (a
        version-skewed broker, a sync the broker failed to apply, a
        reseed) degrades to the full frame; either way the accumulator
        re-anchors at the current turn, and a broker that DIDN'T apply
        the reply simply fails the anchor match next time — delta state
        is self-healing, never trusted."""
        base_turn = getattr(req, "delta_base_turn", -1)
        with self._strip_lock:
            if self._strip is None:
                raise ValueError("no resident strip to fetch")
            delta_ok = (
                isinstance(base_turn, int)
                and base_turn >= 0
                and self._strip_dirty is not None
                and base_turn == self._strip_clean_turn
            )
            if delta_ok:
                from ..ops.sparse import extract_dirty_tiles

                dirty = self._strip_dirty
                flat = extract_dirty_tiles(self._strip, dirty)
                self._strip_dirty = np.zeros_like(dirty)
                self._strip_clean_turn = self._strip_turn
                return Response(
                    worker=self._strip_index,
                    turns_completed=self._strip_turn,
                    work_slice=flat,
                    dirty=dirty,
                )
            # the reference itself is safe to ship: StripStep REPLACES the
            # array (never mutates in place), so a concurrent step cannot
            # change these bytes under the serialiser
            if self._strip_dirty is not None:
                self._strip_dirty = np.zeros_like(self._strip_dirty)
            self._strip_clean_turn = self._strip_turn
            return Response(
                worker=self._strip_index,
                turns_completed=self._strip_turn,
                work_slice=self._strip,
            )

    def worker_quit(self, req: Request) -> Response:
        # reply first, then shut the listener (worker/worker.go:82-86).
        # gol: allow(hygiene): deliberately NON-daemon — the timer must
        # outlive this handler so the quit reply flushes before the
        # process exits; it fires once, 50 ms later, then the thread ends
        threading.Timer(0.05, self._shutdown).start()
        return Response()

    def status(self, req: Request) -> Response:
        """Read-only registry snapshot (obs/) — the broker verb's worker
        twin. The only request field read is the optional
        ``timeline_since`` seq (getattr + isinstance: version-skew-safe;
        absent means the full timeline ring)."""
        from ..obs.report import status_payload

        since = getattr(req, "timeline_since", 0)
        jsince = getattr(req, "journal_since", 0)
        psince = getattr(req, "profile_since", 0)
        return Response(status=status_payload(
            role="worker",
            timeline_since=since if isinstance(since, int) else 0,
            journal_since=jsince if isinstance(jsince, int) else 0,
            profile_since=psince if isinstance(psince, int) else 0,
        ))

    def _shutdown(self):
        self._server.stop()
        self.quit_event.set()


def serve(port: int = 8030, host: str = "127.0.0.1") -> tuple[RpcServer, WorkerService]:
    server = RpcServer(host=host, port=port)
    service = WorkerService(server)
    server.register(Methods.WORKER_UPDATE, service.update)
    server.register(Methods.WORKER_QUIT, service.worker_quit)
    server.register(Methods.WORKER_STATUS, service.status)
    server.register(Methods.STRIP_START, service.strip_start)
    server.register(Methods.STRIP_STEP, service.strip_step)
    server.register(Methods.STRIP_FETCH, service.strip_fetch)
    server.serve_background()
    return server, service


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="GoL worker node")
    parser.add_argument("-port", type=int, default=8030)
    parser.add_argument(
        "-host", default="127.0.0.1",
        help="bind address; 0.0.0.0 opts into external exposure",
    )
    parser.add_argument(
        "-metrics", action="store_true", default=False,
        help="enable the metrics registry (obs/), served live by the "
             "read-only GameOfLifeOperations.Status verb",
    )
    parser.add_argument(
        "-timeline", nargs="?", const=1.0, default=None, type=float,
        metavar="SECS",
        help="enable the server-side metric timeline + SLO rulebook "
             "(obs/timeline.py, obs/slo.py) at this sampling cadence "
             "(default 1 s); incremental windows + alert states ship in "
             "Status replies; implies -metrics",
    )
    parser.add_argument(
        "-trace", action="store_true", default=False,
        help="enable the span tracer + flight recorder (obs/): Update "
             "dispatch spans join the broker's trace via Request.trace_ctx "
             "and ship back in Status replies",
    )
    parser.add_argument(
        "-integrity", choices=("on", "off"), default="on",
        help="frame checksums + resident-strip attestation digests "
             "(rpc/integrity.py). Default on; off disables both "
             "advertising and computing — an off worker is undefended "
             "against silent corruption",
    )
    parser.add_argument(
        "-journal", nargs="?", const="out", default=None, metavar="DIR",
        help="enable the durable lifecycle journal (obs/journal.py): "
             "HLC-stamped lifecycle events append to "
             "DIR/journal_worker_<pid>.jsonl (default out/), crc-framed "
             "and size-rotated; merged cross-process by "
             "python -m ...obs.history",
    )
    parser.add_argument(
        "-profile", nargs="?", const=10.0, default=None, type=float,
        metavar="MS",
        help="enable the continuous sampling profiler (obs/profiler.py) "
             "at this cadence (default 10 ms, adaptive backoff): "
             "incremental windows in Status replies, collapsed-stack + "
             "speedscope artifacts at run end and on crash; implies "
             "-metrics",
    )
    args = parser.parse_args(argv)
    _integrity.set_enabled(args.integrity == "on")
    if args.journal is not None:
        _journal.enable(out_dir=args.journal, role="worker")
    if args.profile is not None:
        if args.profile <= 0:
            parser.error(f"-profile MS must be > 0, got {args.profile}")
        from ..obs import profiler as _profiler

        _profiler.enable(
            period_ms=args.profile, tag=f"worker_{os.getpid()}"
        )  # implies metrics.enable()
    if args.metrics:
        from ..obs import metrics

        metrics.enable()
    if args.timeline is not None:
        if args.timeline <= 0:
            parser.error(f"-timeline SECS must be > 0, got {args.timeline}")
        from ..obs import timeline

        timeline.enable(period=args.timeline)  # implies metrics.enable()
    server, service = serve(args.port, args.host)
    if args.trace:
        # after serve(): the BOUND port (not a requested 0) distinguishes
        # multiple workers' Chrome tracks; serve only binds the socket, so
        # no span can be recorded before the name is set
        from ..obs import flight, tracing

        tracing.enable()
        tracing.set_process_name(f"worker:{server.port}")
        flight.enable()
    print(f"worker listening on :{server.port}", flush=True)
    try:
        service.quit_event.wait()
    except BaseException as exc:
        # crash hook (the engine-path posture, engine/engine.py): leave
        # the flight ring + journal tail on disk before propagating —
        # the postmortem evidence for a dead worker (satellite of the
        # broker __main__ hook; both were engine-only before)
        from ..obs import flight as _flight
        from ..obs import profiler as _profiler

        _flight.dump_on_crash(exc)
        _journal.flush_on_crash(exc)
        _profiler.flush_on_crash(exc)
        raise
    finally:
        from ..obs import profiler as _profiler

        _journal.disable()  # flush + close the segment cleanly
        _profiler.shutdown()  # run-end collapsed/speedscope artifacts


if __name__ == "__main__":
    main()
