"""The worker process — GameOfLifeOperations service (worker/worker.go:72-112).

Serves ``Update`` (compute one row strip of the next board state) and
``WorkerQuit``. The strip kernel is the jitted XLA stencil: the broker sends
the strip plus its two wrap-around halo rows, and the worker returns the
evolved strip — unlike the reference, which ships the ENTIRE board to every
worker and lets each one index its strip (worker/worker.go:78,
broker/broker.go:144). The wire cost drops from O(H x W) to
O(strip + 2 rows) per call while preserving the verbs.

For reference-exact wire behavior the worker also accepts full-board
requests (halo rows derived locally) — the broker chooses per its
``wire`` mode.
"""

from __future__ import annotations

import argparse
import functools
import threading

import numpy as np

from .protocol import Methods, Request, Response
from .server import RpcServer


@functools.lru_cache(maxsize=None)
def _strip_step():
    """(h+2, w) padded strip -> (h, w) next strip, columns wrapping locally."""
    import jax
    import jax.numpy as jnp

    from ..models import CONWAY
    from ..ops.stencil import apply_rule, counts_from_extended

    @jax.jit
    def step(padded):
        ext = jnp.concatenate([padded[:, -1:], padded, padded[:, :1]], axis=1)
        h = padded.shape[0] - 2
        w = padded.shape[1]
        counts = counts_from_extended(ext, h, w)
        return apply_rule(
            padded[1:-1],
            counts,
            birth_mask=CONWAY.birth_mask,
            survive_mask=CONWAY.survive_mask,
        )

    return step


def compute_strip(world: np.ndarray, start_y: int, end_y: int) -> np.ndarray:
    """Next state of rows [start_y, end_y) given the full board —
    the calculateNextState contract (worker/worker.go:15)."""
    h = world.shape[0]
    rows = np.arange(start_y - 1, end_y + 1) % h
    padded = world[rows]
    return np.asarray(_strip_step()(padded))


def compute_strip_haloed(padded: np.ndarray) -> np.ndarray:
    """Next state of a strip sent WITH its halo rows (rows 0 and -1)."""
    return np.asarray(_strip_step()(padded))


class WorkerService:
    def __init__(self, server: RpcServer):
        self._server = server
        self.quit_event = threading.Event()

    def update(self, req: Request) -> Response:
        world = np.asarray(req.world, np.uint8)
        if req.start_y == -1:  # haloed-strip wire mode
            return Response(work_slice=compute_strip_haloed(world), worker=req.worker)
        return Response(
            work_slice=compute_strip(world, req.start_y, req.end_y),
            worker=req.worker,
        )

    def worker_quit(self, req: Request) -> Response:
        # reply first, then shut the listener (worker/worker.go:82-86)
        threading.Timer(0.05, self._shutdown).start()
        return Response()

    def _shutdown(self):
        self._server.stop()
        self.quit_event.set()


def serve(port: int = 8030) -> tuple[RpcServer, WorkerService]:
    server = RpcServer(port=port)
    service = WorkerService(server)
    server.register(Methods.WORKER_UPDATE, service.update)
    server.register(Methods.WORKER_QUIT, service.worker_quit)
    server.serve_background()
    return server, service


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="GoL worker node")
    parser.add_argument("-port", type=int, default=8030)
    args = parser.parse_args(argv)
    server, service = serve(args.port)
    print(f"worker listening on :{server.port}", flush=True)
    service.quit_event.wait()


if __name__ == "__main__":
    main()
