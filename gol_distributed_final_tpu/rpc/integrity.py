"""End-to-end data integrity — digests, the checked-frame contract, the knob.

PR 4/5 left exactly one failure class unhandled: SILENT corruption. A bit
flip inside a zero-copy ndarray sidecar, a worker computing wrong rows,
or a truncated checkpoint all used to produce a wrong board with no
detection anywhere in the stack (rpc/faults.py deliberately refused to
even inject the sidecar flip). This module is the shared vocabulary the
three integrity planes stand on:

* **Checked frames** (rpc/protocol.py): negotiated connections carry an
  in-header crc32 word covering the whole frame body — pickle bytes AND
  every out-of-band sidecar. A mismatch raises :class:`IntegrityError`
  before anything is parsed; the connection is dropped like any
  malformed frame.
* **Halo cross-attestation** (rpc/worker.py + rpc/broker.py): resident
  strips carry state digests — a pre/post digest chain per strip per
  batch (an in-place corruption is caught on the very next ``StripStep``)
  and a rolling digest per side of the overlap band neighbouring workers
  compute REDUNDANTLY in the shrinking batch form (a worker computing
  wrong rows near a boundary is caught the same batch, ≤K turns).
* **Verified checkpoints** (engine/checkpoint.py): npz files embed a
  digest over (geometry, turn, rule, board bytes); ``-resume`` refuses
  to reattach anything it cannot verify.

Three checksums, chosen by budget: crc32 guards the wire, where the
threat is random flips and its burst-detection guarantee matters;
adler32 (the ``state_*`` chain) guards the resident-strip plane, which
hashes every strip byte TWICE per batch — measured on hosts without
hardware CRC, zlib's crc32 and blake2b both crawl at ~0.4 GB/s while
adler32 sustains >2 GB/s, and within blocks under 64 KiB adler32 still
detects every 1- and 2-byte corruption (its weak spot is multi-MiB
inputs, which this plane never hashes — strips sync through the CHECKED
frame layer); blake2b-128 guards checkpoints, where the cost is
per-checkpoint and collision resistance is worth it.

``enabled()`` is the ``-integrity on|off`` knob (default ON): an off
process neither advertises checked frames nor computes attestations —
and is, by design, undefended. Skew-safe either way: integrity checks
only ever apply between peers that both advertised them.
"""

from __future__ import annotations

import hashlib
import struct
import zlib

import numpy as np

_CK = struct.Struct(">I")  # the in-header crc32 frame word
CK_WORD_SIZE = _CK.size

_enabled = True


def enabled() -> bool:
    """Whether this process participates in integrity checking (the
    ``-integrity on|off`` flag). ON by default: silent corruption is the
    failure mode you cannot opt into detecting after the fact."""
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


class IntegrityError(ConnectionError):
    """A checksum or digest mismatch — data that must NOT be parsed or
    committed. Subclasses ConnectionError deliberately: at the frame
    layer nothing later on the stream can be trusted either, and every
    transport-failure path (client read loop, server conn loop, broker
    loss recovery) already treats ConnectionError as fatal-for-the-peer."""


# zlib's crc32/adler32 release the GIL for buffers above ~5 KiB. A
# release is a handoff: under thread contention (an in-process worker
# cluster — tests, bench, small deployments) REACQUIRING can cost a
# scheduler quantum, milliseconds against the hash's microseconds —
# measured as the dominant integrity cost by an order of magnitude. So
# every fold feeds the checksum in chunks BELOW the threshold: the hash
# runs GIL-held (~2 us per chunk, far under the 5 ms switch interval, so
# other threads are never meaningfully blocked) and the handoff never
# happens. Chunked folding is exact: both checksums are streaming.
_GIL_CHUNK = 4096


def _fold_chunked(fn, val: int, data) -> int:
    mv = memoryview(data)
    if mv.nbytes == 0:
        return val  # a 0-d/empty view cannot cast; folds to a no-op
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    if len(mv) <= _GIL_CHUNK:
        return fn(mv, val)
    for off in range(0, len(mv), _GIL_CHUNK):
        val = fn(mv[off:off + _GIL_CHUNK], val)
    return val


# -- frame checksums (the wire plane) ----------------------------------------


def crc_new() -> int:
    return 0


def crc_add(crc: int, data) -> int:
    """Fold one body piece (bytes/memoryview) into a running crc32."""
    return _fold_chunked(zlib.crc32, crc, data)


def crc_pack(crc: int) -> bytes:
    return _CK.pack(crc & 0xFFFFFFFF)


def crc_check(crc: int, word: bytes, what: str) -> None:
    """Verify a received crc word against the computed crc, loudly."""
    (want,) = _CK.unpack(word)
    if (crc & 0xFFFFFFFF) != want:
        raise IntegrityError(
            f"frame checksum mismatch on {what}: computed "
            f"{crc & 0xFFFFFFFF:#010x}, frame claims {want:#010x} — "
            "refusing to parse a corrupted frame"
        )


# -- state digests (the resident-strip attestation plane) --------------------
#
# adler32, rolled: the hot plane digests every strip byte twice per batch
# (pre + post) plus the shrinking boundary bands, so the checksum has to
# run at memory-bandwidth-class speed to hold the <3% resident-wire
# overhead budget (bench.py's gate). Each fold binds shape and dtype
# before the bytes so a reshaped or recast buffer with the same bytes
# cannot impersonate the original, and a zero-row band (the final
# shrinking step) still folds its header — defined and comparable.


def state_new() -> int:
    return zlib.adler32(b"")


def state_add(val: int, arr) -> int:
    """Fold one ndarray — shape, dtype, bytes — into a rolling state
    digest."""
    arr = np.ascontiguousarray(arr)
    val = zlib.adler32(f"{arr.shape}:{arr.dtype.str}:".encode(), val)
    # zero-copy: the array is contiguous by now
    return _fold_chunked(zlib.adler32, val, arr.data)


def state_hex(val: int) -> str:
    return f"{val & 0xFFFFFFFF:08x}"


def state_digest(arr) -> str:
    """One-shot state digest of a single ndarray — the pre/post strip
    chain links, the reply-edge digest, and the broker-side anchors the
    chain is seeded from and fetches are verified against."""
    return state_hex(state_add(state_new(), arr))


def array_digest(arr) -> str:
    """blake2b-128 hex digest of an ndarray's shape, dtype and bytes —
    the collision-resistant tier (the construction
    engine/checkpoint.py's ``checkpoint_digest`` binds with turn/rule
    metadata; the per-batch strip plane uses the adler32 ``state_*``
    chain instead, priced above).

    Shape and dtype are folded in so a reshaped or recast buffer with the
    same bytes cannot impersonate the original; the empty array digests
    to a well-defined constant."""
    arr = np.ascontiguousarray(arr)
    h = hashlib.blake2b(digest_size=16)
    h.update(f"{arr.shape}:{arr.dtype.str}:".encode())
    h.update(arr.data)  # zero-copy: the array is contiguous by now
    return h.hexdigest()
