"""The RPC contract — shared vocabulary between controller, broker, workers.

Method names and wire-struct fields mirror the reference's stubs package
(stubs/stubs.go:5-38) so the control-plane semantics — Run blocks for the
whole game, Retrieve snapshots, Pause toggles, Quit detaches, SuperQuit
shuts the system down, Update computes one strip — carry over verbatim.

Transport is length-prefixed pickle frames over TCP. Unlike Go's gob, raw
pickle is a code-execution primitive, so the trust posture is hardened past
the reference's: servers bind loopback by default (rpc/server.py) and
deserialisation goes through a restricted Unpickler that only resolves the
wire vocabulary — Request/Response, Cell, and numpy array reconstruction —
rejecting every other global (ADVICE.md round 1).
"""

from __future__ import annotations

import dataclasses
import io
import pickle
import struct
from typing import List, Optional

import numpy as np

from ..obs import instruments as _ins
from ..obs import metrics as _metrics
from . import integrity as _integrity
from .integrity import CK_WORD_SIZE, IntegrityError


class Methods:
    """Method-name constants (stubs/stubs.go:5-11)."""

    BROKER_RUN = "Operations.Run"
    RETRIEVE = "Operations.RetrieveCurrentData"
    PAUSE = "Operations.Pause"
    QUIT = "Operations.Quit"
    SUPER_QUIT = "Operations.SuperQuit"
    # extension: read-only metrics snapshot (obs/) — interrogate a running
    # server without touching the engine or the board. Three roles answer
    # this verb: a broker (role="broker"), a worker (via WORKER_STATUS,
    # role="worker"), and the fleet collector (obs/fleet.py,
    # role="fleet"), whose payload carries the exactly-merged cluster
    # registry plus a "fleet" section of per-target scrape health — the
    # same verb, so every Status consumer reaches all three unchanged
    STATUS = "Operations.Status"
    WORKER_UPDATE = "GameOfLifeOperations.Update"
    WORKER_QUIT = "GameOfLifeOperations.WorkerQuit"
    WORKER_STATUS = "GameOfLifeOperations.Status"
    # extension: the resident-strip data plane (-wire resident). The strip
    # STAYS on the worker across turns; only O(W)-sized halo rows move:
    # StripStart seeds a strip at a turn, StripStep advances K turns given
    # depth-K halo rows (per-step alive counts + fresh edge rows ride the
    # reply), StripFetch reads the strip + its turn back out (full
    # re-syncs, snapshots, loss recovery).
    STRIP_START = "GameOfLifeOperations.StripStart"
    STRIP_STEP = "GameOfLifeOperations.StripStep"
    STRIP_FETCH = "GameOfLifeOperations.StripFetch"
    # extension: multi-universe serving (rpc/broker.SessionScheduler).
    # SessionRun has Run's blocking contract — evolve this world for
    # req.turns and reply with the final board — but MANY may be in
    # flight at once: concurrent sessions of one geometry/rule pack into
    # a device-resident batch tensor, advanced together (one dispatch per
    # k-turn batch amortises the per-launch dispatch-latency floor over
    # every universe). Admission control (capacity / geometry / rule)
    # refuses with an error reply instead of queueing unboundedly. A
    # nonzero req.session_id tags the session so RetrieveCurrentData with
    # the same tag serves THAT universe's (turn, alive count, board).
    SESSION_RUN = "Operations.SessionRun"


#: verbs whose handler BLOCKS for the whole game by contract (Run parks
#: until the run completes, SessionRun until its universe drains): their
#: handler wall is the run length, not a serving latency, so the
#: ``gol_rpc_dispatch_seconds`` SLO histogram skips them — the
#: 'rpc-dispatch-latency' rule must never page on a healthy long run.
#: (They stay covered by ``gol_rpc_server_request_seconds`` and, for
#: sessions, ``gol_session_turn_seconds``/``_admit_wait_seconds``.)
BLOCKING_METHODS = frozenset({Methods.BROKER_RUN, Methods.SESSION_RUN})


@dataclasses.dataclass
class Request:
    """Mirror of stubs.Request (stubs/stubs.go:20-29)."""

    world: Optional[np.ndarray] = None
    turns: int = 0
    image_height: int = 0
    image_width: int = 0
    threads: int = 0
    start_y: int = 0
    end_y: int = 0
    worker: int = 0
    include_world: bool = True  # extension: count-only Retrieve
    initial_turn: int = 0  # extension: resume-from-checkpoint support
    # extension: the checkpoint's rule on a resumed Run ("" = the server's
    # default). Without it a remote resume of e.g. a HIGHLIFE checkpoint
    # would silently continue under Conway.
    rulestring: str = ""
    # extension: wide-halo depth for the tpu backend's mesh planes (0 =
    # the server's -halo-depth default) — the DCN-latency lever must be
    # reachable from the deployment surface, not only the library
    # (VERDICT r4 item 5)
    halo_depth: int = 0
    # extension: the caller's span context (obs/tracing.py — plain dict of
    # {trace_id, span_id, sampled}, so it crosses the restricted
    # unpickler). Servers read it via getattr: a version-skewed peer's
    # pickle simply lacks it and skew degrades to "no trace", never an
    # AttributeError. None = the caller isn't tracing.
    trace_ctx: Optional[dict] = None
    # extension: the multi-universe serving tag (Methods.SESSION_RUN).
    # A CLIENT-CHOSEN nonzero id on SessionRun registers the session so a
    # concurrent RetrieveCurrentData carrying the same id serves that
    # universe's per-session snapshot (demuxed from the batched
    # reduction) instead of the broker-global board. 0 (and a
    # version-skewed pickle without the field, via getattr) = untagged /
    # the classic broker-global Retrieve.
    session_id: int = 0
    # extension: incremental metric-timeline windows (obs/timeline.py).
    # A Status caller echoes the last timeline ``seq`` it received and
    # the server ships only newer samples — history without re-shipping
    # the whole ring each poll. Servers read it via getattr: a
    # version-skewed older client's pickle lacks it and 0 means "the
    # full ring", exactly like the other extension defaults.
    timeline_since: int = 0
    # extension: incremental tenant-accounting windows (obs/accounting.py)
    # — timeline_since's twin for the per-tenant usage ledger: a Status
    # caller echoes the last ledger ``seq`` it received and the server
    # ships only tenants that changed since (totals always ride). Same
    # skew posture: getattr, absent/0 = the full (bounded) ledger.
    accounting_since: int = 0
    # extension: dirty-tile delta StripFetch (ops/sparse.py wire tiles).
    # The broker asks for a DELTA against the full strip copy it holds at
    # this turn; a worker whose dirty accumulator is anchored at exactly
    # that turn replies with only the tiles that changed since
    # (Response.dirty + the flat tile buffer), anything else replies with
    # the full strip. -1 (and a version-skewed older broker's pickle,
    # via getattr) = full fetch, the pre-delta wire behavior.
    delta_base_turn: int = -1
    # extension: the caller's hybrid-logical-clock stamp (obs/journal.py
    # — a plain [physical_ms, logical, node] list, so it crosses the
    # restricted unpickler). The server merges it into its process clock
    # before dispatching, so every journal event the handler records is
    # causally ordered after the client-side events that caused the
    # call. getattr-read: a skewed peer's pickle means "no causality
    # hint", never an error.
    hlc: Optional[list] = None
    # extension: incremental journal-tail windows (obs/journal.py) —
    # timeline_since's twin for the lifecycle journal: a Status caller
    # echoes the last journal ``seq`` it received and the server ships
    # only newer tail events (obs/history.py rides it).
    journal_since: int = 0
    # extension: incremental profile windows (obs/profiler.py) —
    # timeline_since's twin for the continuous sampling profiler: a
    # Status caller echoes the last profile ``seq`` it received and the
    # server ships only frames whose hit counts moved since (the window
    # head — cadence, stacks, gc pauses — always rides). Same skew
    # posture: getattr, absent/0 = the full frame table.
    profile_since: int = 0
    # extensions: the 2-D tile-resident data plane (-grid). On StripStart
    # a nonzero ``grid_cols`` marks the seeded block as a TILE of an
    # R x C checkerboard (grid_rows x grid_cols tile bands) spanning rows
    # [start_y, end_y) x cols [start_x, end_x) of the board; StripStep
    # then ships bit-packed four-edge-plus-corner halos in ``world``
    # instead of the strip plane's 2K raw rows. getattr-read everywhere:
    # a version-skewed older broker's pickle lacks the fields and every
    # worker keeps serving plain 1-D row strips — and an EXPLICIT
    # one-column grid never sets them at all (the broker routes it
    # through the strip loop: the strip plane IS the C == 1 special
    # case, byte-identical on the wire).
    grid_rows: int = 0
    grid_cols: int = 0
    # the tile's column band [start_x, end_x) — start_y/end_y's column
    # twins (those row fields are frozen Go-mirror base fields)
    start_x: int = 0
    end_x: int = 0


@dataclasses.dataclass
class Response:
    """Mirror of stubs.Response (stubs/stubs.go:31-38)."""

    alive: Optional[List] = None
    alive_count: int = 0
    turns_completed: int = 0
    world: Optional[np.ndarray] = None
    work_slice: Optional[np.ndarray] = None
    worker: int = 0
    # extension: the Status verb's payload (obs/report.status_payload) —
    # plain JSON-able dict so it crosses the restricted unpickler. Readers
    # use getattr(res, "status", None): an older peer's pickle lacks it.
    status: Optional[dict] = None
    # extension: the server dispatch span's context (obs/tracing.py), so
    # the client can link its round-trip span to the handler-side span.
    # Same skew posture as Request.trace_ctx: getattr, absent = no trace.
    trace_ctx: Optional[dict] = None
    # extensions for the resident-strip verbs (read via getattr — absent on
    # a version-skewed peer's pickle): ``edges`` is the strip's boundary
    # rows at its new turn, stacked [top K; bottom K] as one (2K, W) array
    # (the broker relays them as the neighbours' next-batch halos, so only
    # O(W·K) bytes move per batch); ``counts`` is the strip's per-step
    # alive counts across the batch (the AliveCellsCount ticker's feed —
    # no gather needed).
    edges: Optional[np.ndarray] = None
    counts: Optional[List] = None
    # extension: the integrity attestation payload (rpc/integrity.py) — a
    # plain dict of digest strings/lists, so it crosses the restricted
    # unpickler. StripStep replies carry {"pre", "strip", "edges",
    # "attest_top", "attest_bottom"}; readers use getattr + isinstance
    # (absent on a version-skewed or -integrity off peer's pickle — skew
    # degrades to "no attestation", never an AttributeError).
    digests: Optional[dict] = None
    # extension: the worker-side handler wall of this reply's compute
    # (Update / StripStep), in seconds — the broker's dispatch-wall
    # decomposition subtracts it from the measured round trip to split
    # wire time from worker compute (obs/perf.py, obs/critical.py).
    # Readers use getattr: an older worker's pickle lacks it and 0.0
    # degrades the split to "whole round trip counted as wire+compute".
    service_seconds: float = 0.0
    # extension: the per-tile dirty bitmap of the resident strip
    # (ops/sparse.py wire tiles — bool [grid_rows, grid_cols]). On a
    # StripStep reply it covers THIS batch's changes (the broker's
    # frontier/checkpoint-delta feed); on a StripFetch reply its
    # presence marks a DELTA frame whose dirty tiles ride in
    # ``work_slice`` as one flat uint8 sidecar buffer instead of the
    # full strip. Readers use getattr + isinstance: absent on a
    # version-skewed or pre-delta peer's pickle — skew degrades to
    # "full frames", never an AttributeError.
    dirty: Optional[np.ndarray] = None
    # extension: the server's hybrid-logical-clock stamp (obs/journal.py)
    # — Request.hlc's reply-side twin: the client merges it into its
    # process clock, so client-side events after the reply are causally
    # ordered after everything the handler journalled. Same skew posture.
    hlc: Optional[list] = None


# -- deserialisation allowlist ----------------------------------------------

# every global a legitimate frame can reference: the wire dataclasses, the
# Cell payload type, and numpy's array/scalar reconstruction machinery
# (module path differs across numpy 1.x/2.x)
_ALLOWED_GLOBALS = {
    (__name__, "Request"),
    (__name__, "Response"),
    ("gol_distributed_final_tpu.utils.cell", "Cell"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
    # protocol-5 contiguous-array path (what the wire actually uses)
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("_codecs", "encode"),  # numpy string-dtype reconstruction (proto <= 2)
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _ALLOWED_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"frame references forbidden global {module}.{name}"
        )


def loads_restricted(payload: bytes, buffers=None):
    """``buffers`` is the protocol-5 out-of-band sidecar list (in frame
    order): each ndarray pickled out-of-band reconstructs as a VIEW of its
    sidecar buffer (numpy's ``_frombuffer``), so the receive path pays no
    parse-time copy. Same allowlist either way."""
    return _RestrictedUnpickler(io.BytesIO(payload), buffers=buffers).load()


# -- framing ----------------------------------------------------------------
#
# Two frame shapes share one 8-byte big-endian header word:
#
# * plain (the original wire, and the only shape an un-negotiated peer ever
#   receives): header = payload length, payload = one pickle
#   (protocol HIGHEST, ndarrays in-band).
# * out-of-band (protocol 5): header = _FLAG_OOB | body length, body =
#   [>IQ nbufs,pickle_len][>Q buf_len × nbufs][pickle][raw buffers...].
#   Every ndarray ≥ _OOB_THRESHOLD travels as a raw sidecar buffer after
#   the pickle: the sender hands the array's own memory to sendall (no
#   serialize-time copy), the receiver reads each sidecar with recv_into
#   into a preallocated buffer the unpickled array then WRAPS (no
#   parse-time copy).
#
# A third header bit (62) flags a CHECKED frame (rpc/integrity.py): a
# 4-byte crc32 word covering every body byte — the subheader, the
# pickle, and every raw sidecar buffer — rides immediately after the
# length word, IN the same sendall as the header. In-header rather than
# trailing deliberately: a trailer would land in its own late TCP
# segment, and a receiver that has already drained the body then blocks
# on 4 bytes whose delivery waits on the sender thread being scheduled
# again — a per-frame scheduling stall that measured as double-digit
# percent on a loopback cluster, vs. the crc compute's microseconds. The
# sender has every body piece in memory before the first sendall anyway,
# so hashing first costs nothing. The receiver folds each piece into the
# crc as it arrives and verifies BEFORE anything is unpickled: a
# mismatch is a loud IntegrityError, never a parse. Like the out-of-band
# flag, the checksum is negotiated per transport ("ck": 1 in the
# envelopes), so an un-advertising old peer only ever receives unflagged
# frames.
#
# Skew safety: MAX_FRAME < 2^34 keeps bits 62-63 free, so an OLD receiver
# that is sent a flagged frame fails its length check loudly (connection
# drop, never a mis-parse) — and the RPC layer only ever sends flagged
# frames to peers that advertised support in their envelopes
# (rpc/client.py, rpc/server.py), so old peers keep getting plain
# protocol-HIGHEST frames.

_HEADER = struct.Struct(">Q")
MAX_FRAME = 1 << 34  # 16 GiB: a 65536^2 board is ~4 GiB
_FLAG_OOB = 1 << 63
_FLAG_CK = 1 << 62
_LEN_MASK = _FLAG_CK - 1
_OOB_SUB = struct.Struct(">IQ")  # (nbufs, pickle_len)
_OOB_LEN = struct.Struct(">Q")  # one sidecar buffer's length
# below this, a buffer stays in-band: two syscalls + a subheader entry cost
# more than memcpy'ing a few hundred bytes into the pickle
_OOB_THRESHOLD = 1024
# a frame may reference at most this many sidecars — a hostile subheader
# must not make the receiver allocate an unbounded list
_MAX_OOB_BUFFERS = 4096


def send_frame(sock, obj, oob: bool = False, checksum: bool = False) -> int:
    """Callers must serialise sends per-socket (both RpcClient and RpcServer
    hold a write lock). Separate sendalls avoid concatenating header+payload,
    which would double peak memory on multi-GiB board frames. Returns the
    frame size in bytes (header + payload) — the senders' byte meters.

    ``oob=True`` selects the protocol-5 out-of-band shape; ``checksum=True``
    sends the in-header crc32 word over the whole body (pickle AND
    sidecars — rpc/integrity.py). Either flag asserts the peer can parse
    the shape (the envelope negotiation in rpc/client.py /
    rpc/server.py)."""
    if not oob:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        if checksum:
            head = _HEADER.pack(_FLAG_CK | len(payload)) + _integrity.crc_pack(
                _integrity.crc_add(0, payload)
            )
        else:
            head = _HEADER.pack(len(payload))
        sock.sendall(head)
        sock.sendall(payload)
        return len(head) + len(payload)
    raws = []

    def _sidecar(pb: pickle.PickleBuffer):
        raw = pb.raw()
        if raw.nbytes < _OOB_THRESHOLD:
            return True  # truthy: pickle keeps it in-band
        raws.append(raw)
        return False  # falsy: out-of-band, we transport it below

    payload = pickle.dumps(obj, protocol=5, buffer_callback=_sidecar)
    sub = _OOB_SUB.pack(len(raws), len(payload)) + b"".join(
        _OOB_LEN.pack(r.nbytes) for r in raws
    )
    total = len(sub) + len(payload) + sum(r.nbytes for r in raws)
    if checksum:
        crc = _integrity.crc_add(_integrity.crc_add(0, sub), payload)
        for raw in raws:
            crc = _integrity.crc_add(crc, raw)
        head = _HEADER.pack(
            _FLAG_OOB | _FLAG_CK | total
        ) + _integrity.crc_pack(crc)
    else:
        head = _HEADER.pack(_FLAG_OOB | total)
    sock.sendall(head)
    sock.sendall(sub)
    sock.sendall(payload)
    for raw in raws:
        sock.sendall(raw)  # the array's own memory: zero-copy send
    return len(head) + total


def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed the connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_into_exact(sock, buf) -> None:
    """Fill ``buf`` completely, straight off the socket — the sidecar
    receive path: no intermediate bytes objects, no join, no copy."""
    view = memoryview(buf)
    got = 0
    while got < len(view):
        n = sock.recv_into(view[got:])
        if not n:
            raise ConnectionError("peer closed the connection")
        got += n


def _verify_crc(want: bytes, crc: int, what: str) -> None:
    """Verify the computed body crc against the frame's in-header crc
    word — the checked-frame gate: on mismatch the frame is never parsed,
    the stream never trusted again (IntegrityError is a ConnectionError).
    Counted either way."""
    if _metrics.enabled():
        _ins.INTEGRITY_CHECKS_TOTAL.inc()
    try:
        _integrity.crc_check(crc, want, what)
    except IntegrityError:
        _ins.INTEGRITY_FAILURES_TOTAL.labels("frame").inc()
        raise


def recv_frame_sized(sock):
    """``(obj, frame_bytes)`` — the receivers' byte meters ride along."""
    # opportunistic 12-byte first read: a checked frame's crc word rides
    # right behind the length word in the sender's single header sendall,
    # so asking for both up front costs no extra syscall — and for an
    # unchecked frame the surplus (≤ 4 bytes) is simply the body's first
    # bytes, consumed below. One recv per frame header either way; an
    # extra per-frame syscall measures whole percents on hosts with slow
    # syscall paths (gVisor-class sandboxes, the loopback bench)
    head = b""
    while len(head) < _HEADER.size:
        chunk = sock.recv(_HEADER.size + CK_WORD_SIZE - len(head))
        if not chunk:
            raise ConnectionError("peer closed the connection")
        head += chunk
    (word,) = _HEADER.unpack(head[:_HEADER.size])
    extra = head[_HEADER.size:]  # 0..4 bytes already past the length word
    length = word & _LEN_MASK
    checked = bool(word & _FLAG_CK)
    if length > MAX_FRAME:
        raise ConnectionError(f"frame of {length} bytes exceeds limit")
    if checked:
        if len(extra) < CK_WORD_SIZE:
            extra += _recv_exact(sock, CK_WORD_SIZE - len(extra))
        want, pre = extra, b""
    else:
        want, pre = b"", extra
        if len(pre) > length:
            # no real pickle is under 4 bytes (PROTO + opcode + STOP),
            # so a shorter claimed length means a mis-framed stream —
            # refuse rather than bleed the surplus into the next frame
            raise ConnectionError(f"implausibly short frame ({length} B)")
    head_len = _HEADER.size + (CK_WORD_SIZE if checked else 0)
    if not word & _FLAG_OOB:
        payload = pre + _recv_exact(sock, length - len(pre))
        if checked:
            _verify_crc(want, _integrity.crc_add(0, payload), "pickle body")
        return loads_restricted(payload), head_len + length
    # out-of-band shape: every subheader quantity is validated against the
    # framed length BEFORE any allocation happens on its say-so
    if length < _OOB_SUB.size:
        raise ConnectionError("out-of-band frame shorter than its subheader")
    sub = pre + _recv_exact(sock, _OOB_SUB.size - len(pre))
    nbufs, pickle_len = _OOB_SUB.unpack(sub)
    if nbufs > _MAX_OOB_BUFFERS:
        raise ConnectionError(f"frame claims {nbufs} sidecar buffers")
    lens_blob = _recv_exact(sock, _OOB_LEN.size * nbufs)
    buf_lens = [
        _OOB_LEN.unpack_from(lens_blob, i * _OOB_LEN.size)[0]
        for i in range(nbufs)
    ]
    if _OOB_SUB.size + _OOB_LEN.size * nbufs + pickle_len + sum(buf_lens) != length:
        raise ConnectionError("out-of-band frame length mismatch")
    payload = _recv_exact(sock, pickle_len)
    crc = 0
    if checked:
        crc = _integrity.crc_add(
            _integrity.crc_add(_integrity.crc_add(0, sub), lens_blob), payload
        )
    buffers = []
    for n in buf_lens:
        buf = bytearray(n)
        _recv_into_exact(sock, buf)
        if checked:
            crc = _integrity.crc_add(crc, buf)
        buffers.append(buf)
    if checked:
        # verified BEFORE the unpickle wraps any sidecar: a flipped bit in
        # a raw ndarray buffer — the silent-board-corruption class — is a
        # loud refusal here, never a wrong cell downstream
        _verify_crc(want, crc, f"body + {nbufs} sidecar(s)")
    return loads_restricted(payload, buffers), head_len + length


def recv_frame(sock):
    return recv_frame_sized(sock)[0]
