"""The RPC contract — shared vocabulary between controller, broker, workers.

Method names and wire-struct fields mirror the reference's stubs package
(stubs/stubs.go:5-38) so the control-plane semantics — Run blocks for the
whole game, Retrieve snapshots, Pause toggles, Quit detaches, SuperQuit
shuts the system down, Update computes one strip — carry over verbatim.

Transport is length-prefixed pickle frames over TCP. Unlike Go's gob, raw
pickle is a code-execution primitive, so the trust posture is hardened past
the reference's: servers bind loopback by default (rpc/server.py) and
deserialisation goes through a restricted Unpickler that only resolves the
wire vocabulary — Request/Response, Cell, and numpy array reconstruction —
rejecting every other global (ADVICE.md round 1).
"""

from __future__ import annotations

import dataclasses
import io
import pickle
import struct
from typing import List, Optional

import numpy as np


class Methods:
    """Method-name constants (stubs/stubs.go:5-11)."""

    BROKER_RUN = "Operations.Run"
    RETRIEVE = "Operations.RetrieveCurrentData"
    PAUSE = "Operations.Pause"
    QUIT = "Operations.Quit"
    SUPER_QUIT = "Operations.SuperQuit"
    # extension: read-only metrics snapshot (obs/) — interrogate a running
    # server without touching the engine or the board
    STATUS = "Operations.Status"
    WORKER_UPDATE = "GameOfLifeOperations.Update"
    WORKER_QUIT = "GameOfLifeOperations.WorkerQuit"
    WORKER_STATUS = "GameOfLifeOperations.Status"


@dataclasses.dataclass
class Request:
    """Mirror of stubs.Request (stubs/stubs.go:20-29)."""

    world: Optional[np.ndarray] = None
    turns: int = 0
    image_height: int = 0
    image_width: int = 0
    threads: int = 0
    start_y: int = 0
    end_y: int = 0
    worker: int = 0
    include_world: bool = True  # extension: count-only Retrieve
    initial_turn: int = 0  # extension: resume-from-checkpoint support
    # extension: the checkpoint's rule on a resumed Run ("" = the server's
    # default). Without it a remote resume of e.g. a HIGHLIFE checkpoint
    # would silently continue under Conway.
    rulestring: str = ""
    # extension: wide-halo depth for the tpu backend's mesh planes (0 =
    # the server's -halo-depth default) — the DCN-latency lever must be
    # reachable from the deployment surface, not only the library
    # (VERDICT r4 item 5)
    halo_depth: int = 0
    # extension: the caller's span context (obs/tracing.py — plain dict of
    # {trace_id, span_id, sampled}, so it crosses the restricted
    # unpickler). Servers read it via getattr: a version-skewed peer's
    # pickle simply lacks it and skew degrades to "no trace", never an
    # AttributeError. None = the caller isn't tracing.
    trace_ctx: Optional[dict] = None


@dataclasses.dataclass
class Response:
    """Mirror of stubs.Response (stubs/stubs.go:31-38)."""

    alive: Optional[List] = None
    alive_count: int = 0
    turns_completed: int = 0
    world: Optional[np.ndarray] = None
    work_slice: Optional[np.ndarray] = None
    worker: int = 0
    # extension: the Status verb's payload (obs/report.status_payload) —
    # plain JSON-able dict so it crosses the restricted unpickler. Readers
    # use getattr(res, "status", None): an older peer's pickle lacks it.
    status: Optional[dict] = None
    # extension: the server dispatch span's context (obs/tracing.py), so
    # the client can link its round-trip span to the handler-side span.
    # Same skew posture as Request.trace_ctx: getattr, absent = no trace.
    trace_ctx: Optional[dict] = None


# -- deserialisation allowlist ----------------------------------------------

# every global a legitimate frame can reference: the wire dataclasses, the
# Cell payload type, and numpy's array/scalar reconstruction machinery
# (module path differs across numpy 1.x/2.x)
_ALLOWED_GLOBALS = {
    (__name__, "Request"),
    (__name__, "Response"),
    ("gol_distributed_final_tpu.utils.cell", "Cell"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
    # protocol-5 contiguous-array path (what the wire actually uses)
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("_codecs", "encode"),  # numpy string-dtype reconstruction (proto <= 2)
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _ALLOWED_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"frame references forbidden global {module}.{name}"
        )


def loads_restricted(payload: bytes):
    return _RestrictedUnpickler(io.BytesIO(payload)).load()


# -- framing ----------------------------------------------------------------

_HEADER = struct.Struct(">Q")
MAX_FRAME = 1 << 34  # 16 GiB: a 65536^2 board is ~4 GiB


def send_frame(sock, obj) -> int:
    """Callers must serialise sends per-socket (both RpcClient and RpcServer
    hold a write lock). Two sendalls avoid concatenating header+payload,
    which would double peak memory on multi-GiB board frames. Returns the
    frame size in bytes (header + payload) — the senders' byte meters."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HEADER.pack(len(payload)))
    sock.sendall(payload)
    return _HEADER.size + len(payload)


def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed the connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame_sized(sock):
    """``(obj, frame_bytes)`` — the receivers' byte meters ride along."""
    (length,) = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if length > MAX_FRAME:
        raise ConnectionError(f"frame of {length} bytes exceeds limit")
    return loads_restricted(_recv_exact(sock, length)), _HEADER.size + length


def recv_frame(sock):
    return recv_frame_sized(sock)[0]
