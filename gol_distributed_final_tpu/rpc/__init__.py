from .protocol import Methods, Request, Response
from .client import RemoteBroker, RpcClient
from .server import RpcServer

__all__ = ["Methods", "Request", "Response", "RpcClient", "RpcServer", "RemoteBroker"]
