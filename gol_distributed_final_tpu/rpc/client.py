"""RPC client with concurrent in-flight calls (the rpc.Client role).

The controller needs this concurrency: its main thread blocks in
``Operations.Run`` for the entire game while the ticker thread issues
``RetrieveCurrentData``/``Pause`` on the same connection
(gol/distributor.go:159 + :45). Calls are multiplexed by id; a reader
thread routes replies to per-call events.
"""

from __future__ import annotations

import itertools
import random
import socket
import threading
import time

from ..obs import flight as _flight
from ..obs import instruments as _ins
from ..obs import journal as _journal
from ..obs import metrics as _metrics
from ..obs import tracing as _tracing
from ..utils import locksan as _locksan
from . import integrity as _integrity
from .protocol import Methods, Request, recv_frame_sized, send_frame


class RpcError(Exception):
    """A server-side error surfaced to the caller (net/rpc's error return).

    ``kind`` is the remote exception CLASS name and ``remote_traceback``
    a truncated remote traceback — populated from the structured error
    reply of a current server (both None against an older peer), so a
    worker-side failure reaching the controller names the exception class
    and site instead of an opaque string.

    ``is_reply`` distinguishes an error the SERVER sent (a completed
    round-trip — the peer is alive) from a transport-level failure raised
    client-side (timeout, closed connection, failed send/reconnect): the
    broker's readmission probe treats the former as proof of life.

    ``reason`` is the machine-readable refusal reason when the remote
    exception carried one (``SessionRejected.reason`` — the
    ``gol_sessions_rejected_total`` label): callers classify an
    admission refusal structurally (obs/loadgen.py does) instead of
    string-matching the message. None against an older server."""

    is_reply = False

    def __init__(self, message, kind=None, remote_traceback=None, reason=None):
        super().__init__(message)
        self.kind = kind
        self.remote_traceback = remote_traceback
        self.reason = reason


_RECONNECT_BACKOFF0 = 0.2  # first retry delay; doubles per failure


class RpcClient:
    """``reconnect=True`` makes the transport self-healing: when the
    connection dies, the NEXT call dials again under capped jittered
    exponential backoff (one attempt per call, gated by the backoff
    window). Calls that were in flight when the connection died always
    FAIL — no verb is ever silently re-sent (Run/Pause/Quit are not
    idempotent); only the transport is retried, and the caller decides
    what is safe to re-issue."""

    def __init__(
        self,
        address: str,
        timeout: float | None = None,
        reconnect: bool = False,
        max_backoff: float = 15.0,
    ):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._connect_timeout = timeout
        self._reconnect = reconnect
        self._max_backoff = max_backoff
        self._backoff = 0.0
        self._retry_at = 0.0  # monotonic gate for the next dial attempt
        # guards transport swaps and the backoff state; NEVER held across
        # a dial, so close() and other threads' calls stay prompt while a
        # reconnect attempt waits out an unreachable peer's connect timeout
        self._conn_lock = _locksan.lock("RpcClient._conn_lock")
        self._dialing = False
        self._user_closed = False
        self._ids = itertools.count()
        self._pending: dict[int, dict] = {}
        self._pending_lock = _locksan.lock("RpcClient._pending_lock")
        # ONE write lock for the client's lifetime, not per-connection: a
        # sender that acquired it just before a reconnect swapped the
        # socket must still exclude senders on the new socket — two locks
        # would let their header+payload writes interleave on one stream
        self._write_lock = _locksan.lock("RpcClient._write_lock")
        self._install(self._dial())

    def _dial(self) -> socket.socket:
        sock = socket.create_connection(self._addr, timeout=self._connect_timeout)
        sock.settimeout(None)
        # send_frame writes header and payload separately; without NODELAY
        # Nagle holds the second small write for the peer's delayed ACK
        # (~40-200 ms per call — fatal for a per-turn scatter/gather)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _install(self, sock: socket.socket) -> None:
        """Publish a fresh transport and start its reader. The transport
        is ONE tuple attribute — (socket, closed Event) — so a concurrent
        call captures both atomically: a send failure can then only ever
        tear down the connection the call actually used, never mark a
        fresh socket dead through a torn sock/closed pair."""
        # protocol-5 + checksum negotiation state resets per transport: a
        # reconnect may land on an older peer (rolling restart), which
        # must re-prove support before any flagged frame is sent to it
        self._peer_oob = False
        self._peer_ck = False
        closed = threading.Event()
        self._transport = (sock, closed)
        threading.Thread(
            target=self._read_loop, args=(sock, closed), daemon=True
        ).start()

    def _read_loop(self, sock: socket.socket, closed: threading.Event) -> None:
        # broad catch: an allowlist-rejected or corrupt reply frame
        # (pickle.UnpicklingError, EOFError, ...) must fail every pending
        # call, not silently kill this thread and hang them forever
        try:
            while True:
                msg, nbytes = recv_frame_sized(sock)
                with self._pending_lock:
                    slot = self._pending.pop(msg["id"], None)
                if slot is not None:
                    slot["reply"] = msg
                    slot["reply_bytes"] = nbytes
                    slot["event"].set()
        except Exception:
            closed.set()
            with self._pending_lock:
                # only the CURRENT connection's reader may drain: after a
                # reconnect swapped in a fresh transport (draining first),
                # a stale reader racing here must not fail new calls
                if closed is self._transport[1]:
                    for slot in self._pending.values():
                        slot["event"].set()
                    self._pending.clear()

    def _maybe_reconnect(self) -> None:
        """Called when a call finds the transport dead. Either installs a
        fresh connection or raises RpcError; backoff between ATTEMPTS is
        capped jittered exponential, so a dead peer is probed, not
        hammered, and the first call after it returns gets through. The
        dial itself runs OUTSIDE the lock (one attempt at a time via
        ``_dialing``): an unreachable peer stalls only this caller for
        the connect timeout, never close() or other threads' calls."""
        if not self._reconnect or self._user_closed:
            raise RpcError("connection closed")
        with self._conn_lock:
            if self._user_closed:
                # re-check under the lock: a close() racing this attempt
                # must win — it must never be resurrected by a reconnect
                # that passed the unlocked check first
                raise RpcError("connection closed")
            old_sock, old_closed = self._transport
            if not old_closed.is_set():
                return  # another thread already reconnected
            if self._dialing:
                raise RpcError(
                    f"connection to {self._addr[0]}:{self._addr[1]} is "
                    "down; a reconnect attempt is already in progress"
                )
            now = time.monotonic()
            if now < self._retry_at:
                raise RpcError(
                    f"connection to {self._addr[0]}:{self._addr[1]} is down; "
                    f"reconnect backing off {self._retry_at - now:.1f}s"
                )
            self._dialing = True
            _ins.RPC_RETRIES_TOTAL.inc()
            _flight.record(
                "rpc.reconnect", f"{self._addr[0]}:{self._addr[1]}"
            )
            try:
                # shutdown, like close(): a sender still stuck in sendall
                # on this dead socket holds the lifetime write lock — it
                # must be WOKEN, or every call on the fresh transport
                # would block on that lock forever
                old_sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                old_sock.close()
            except OSError:
                pass
            with self._pending_lock:
                # anything still pending rode the dead connection: fail it
                # now (it is never re-sent — the non-idempotency contract)
                for slot in self._pending.values():
                    slot["event"].set()
                self._pending.clear()
        try:
            sock = self._dial()
        except OSError as e:
            with self._conn_lock:
                self._dialing = False
                self._backoff = min(
                    self._max_backoff, (self._backoff * 2) or _RECONNECT_BACKOFF0
                )
                self._retry_at = (
                    time.monotonic() + self._backoff * random.uniform(0.5, 1.5)
                )
            raise RpcError(
                f"reconnect to {self._addr[0]}:{self._addr[1]} "
                f"failed: {e}"
            ) from e
        with self._conn_lock:
            self._dialing = False
            if self._user_closed:
                # close() won while we dialed: discard, never resurrect
                try:
                    sock.close()
                except OSError:
                    pass
                raise RpcError("connection closed")
            self._install(sock)
            self._backoff = 0.0
            self._retry_at = 0.0

    def call(
        self,
        method: str,
        request: Request,
        timeout: float | None = None,
        trace_parent: dict | None = None,
    ):
        """Blocking call, safe from any thread. ``timeout`` bounds the wait
        for the REPLY (None: forever — Run legitimately blocks for the
        whole game); on expiry the pending slot is dropped and RpcError
        raised, so a wedged server can't hang a poller (obs/status.py).

        ``trace_parent`` explicitly parents this call's span for work
        handed to pool threads (where the caller's thread-local span stack
        is invisible — the workers-backend scatter); by default the span
        parents on the calling thread's current span."""
        if not _metrics.enabled() and not _tracing.enabled():
            return self._call(method, request, timeout)
        # per-verb observability (obs/instruments.py): count + round-trip
        # latency on every outcome, errors separately; plus a client span
        # (obs/tracing.py) whose context rides Request.trace_ctx so the
        # server's dispatch span joins the same trace
        span = _tracing.start_span(
            _tracing.SPAN_RPC_CLIENT, parent_ctx=trace_parent, method=method
        )
        if span is not None and isinstance(request, Request):
            request.trace_ctx = span.ctx()
        _flight.record("rpc.send", method)
        if _metrics.enabled():
            _ins.RPC_CLIENT_REQUESTS_TOTAL.labels(method).inc()
        t0 = time.monotonic()
        err_kind = None
        try:
            result = self._call(method, request, timeout)
            _flight.record("rpc.recv", method, ok=True)
            if span is not None:
                # link to the server-side span when a current server
                # replied with one (older peers: no field, no link)
                peer = getattr(result, "trace_ctx", None)
                if isinstance(peer, dict):
                    span.args["server_span_id"] = peer.get("span_id")
            return result
        except RpcError as e:
            err_kind = e.kind or type(e).__name__
            _flight.record("rpc.recv", method, ok=False, error_kind=err_kind)
            if _metrics.enabled():
                _ins.RPC_CLIENT_ERRORS_TOTAL.labels(method).inc()
            raise
        finally:
            if _metrics.enabled():
                _ins.RPC_CLIENT_REQUEST_SECONDS.labels(method).observe(
                    time.monotonic() - t0
                )
            if err_kind is None:
                _tracing.end_span(span)
            else:
                _tracing.end_span(span, error_kind=err_kind)

    def _call(self, method: str, request: Request, timeout: float | None = None):
        # capture THIS call's transport atomically (one tuple read, like
        # _read_loop's args): a failure below must tear down the connection
        # the call actually used, never a fresh one a concurrent reconnect
        # swapped in meanwhile
        sock, closed = self._transport
        if closed.is_set():
            self._maybe_reconnect()  # raises unless a fresh transport is up
            sock, closed = self._transport
        # hybrid-logical-clock stamp (obs/journal.py): every outbound
        # request carries this process's causal position, so the server's
        # journal events order after ours. Unconditional — the clock is a
        # few integer compares, and causality must not depend on which
        # side happened to enable its journal.
        if isinstance(request, Request):
            request.hlc = _journal.stamp()
        call_id = next(self._ids)
        slot = {"event": threading.Event(), "reply": None}
        with self._pending_lock:
            self._pending[call_id] = slot
        # re-check after registering: if the reader died in between, it has
        # already drained _pending and our slot's event would never be set
        if closed.is_set():
            with self._pending_lock:
                self._pending.pop(call_id, None)
            raise RpcError("connection closed")
        try:
            with self._write_lock:
                # "oob": 1 advertises this side parses protocol-5 sidecar
                # frames, "ck": 1 that it verifies checked frames
                # (rpc/integrity.py — only advertised with -integrity on;
                # old servers ignore unknown envelope keys); the frame
                # itself only upgrades once the PEER advertised in a
                # reply — so an old server keeps receiving plain frames
                envelope = {"id": call_id, "method": method,
                            "request": request, "oob": 1}
                if _integrity.enabled():
                    envelope["ck"] = 1
                # gol: allow(blocking-under-lock): deliberate — ONE
                # writer at a time per stream is the framing contract
                # (header+payload must not interleave), so the send
                # happens under the lifetime write lock by design; a
                # sender stuck in sendall is woken by close()/reconnect
                # via socket.shutdown (see _maybe_reconnect and close)
                sent = send_frame(
                    sock,
                    envelope,
                    oob=self._peer_oob,
                    checksum=self._peer_ck and _integrity.enabled(),
                )
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(call_id, None)
            # a write-side failure means this transport is gone: mark it so
            # the next call takes the reconnect path instead of re-failing.
            # shutdown, like close(): it wakes the reader blocked in recv
            # (a silently-vanished peer sends no FIN/RST), whose death
            # drains _pending so concurrent timeout=None callers unblock
            closed.set()
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
            raise RpcError(f"send failed: {e}") from e
        if _metrics.enabled():
            _ins.RPC_CLIENT_SENT_BYTES_TOTAL.labels(method).inc(sent)
            _ins.WIRE_BYTES_TOTAL.labels(method, "sent").inc(sent)
        if not slot["event"].wait(timeout):
            with self._pending_lock:
                self._pending.pop(call_id, None)
            raise RpcError(f"no reply to {method} within {timeout}s")
        reply = slot["reply"]
        if reply is None:
            raise RpcError("connection closed before reply")
        if reply.get("oob"):
            # the peer is new enough to both SEND the key and (being a
            # current server) parse flagged frames: upgrade this transport
            self._peer_oob = True
        if reply.get("ck"):
            # the peer verifies checked frames: checksum everything we
            # send it from now on (it only advertises with -integrity on)
            self._peer_ck = True
        if _metrics.enabled():
            _ins.RPC_CLIENT_RECEIVED_BYTES_TOTAL.labels(method).inc(
                slot.get("reply_bytes", 0)
            )
            _ins.WIRE_BYTES_TOTAL.labels(method, "received").inc(
                slot.get("reply_bytes", 0)
            )
        if "error" in reply:
            # structured error extension: a current server names the remote
            # exception class + truncated traceback beside the message; an
            # older server's reply simply lacks the keys (dict.get — the
            # envelope-level twin of the getattr field posture)
            err = RpcError(
                reply["error"],
                kind=reply.get("error_kind"),
                remote_traceback=reply.get("error_traceback"),
                reason=reply.get("error_reason"),
            )
            err.is_reply = True  # a reply arrived: the peer is alive
            raise err
        # gol: allow(skew-safety): 'result' is a REQUIRED key of every
        # non-error reply in every protocol version — a missing key is a
        # malformed envelope that must fail loudly, not default to None
        # (None is a legitimate result value)
        result = reply["result"]
        # fold the server's reply stamp into our clock: events we record
        # after this call are causally after everything it journalled
        _journal.observe(getattr(result, "hlc", None))
        return result

    def close(self) -> None:
        # _user_closed first, then the lock: a reconnect attempt mid-dial
        # re-checks it under the lock before installing, so either it
        # discards its fresh socket, or it installed first and the
        # transport read below sees exactly that socket — nothing leaks
        self._user_closed = True
        with self._conn_lock:
            sock, closed = self._transport
        closed.set()
        try:
            # shutdown first: close() alone does not wake a thread blocked
            # in sendall (a peer that stopped draining its receive buffer
            # mid-frame) — the broker frees its stuck scatter thread by
            # closing the lost worker's client, so the wake must be real
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass


class RemoteBroker:
    """The controller-side broker handle: same surface as InProcessBroker,
    served over RPC (the rpc.Dial("tcp", *server) role, gol/distributor.go:136)."""

    def __init__(
        self,
        address: str = "127.0.0.1:8040",
        timeout: float | None = 10.0,
        reconnect: bool = True,
    ):
        # reconnect by default: the controller's ticker keeps polling
        # Retrieve across a broker restart (crash + -resume) instead of
        # dying with the first dropped connection; the blocking Run that
        # was in flight still FAILS — it is never silently re-issued
        self.client = RpcClient(address, timeout=timeout, reconnect=reconnect)

    def run(
        self,
        params,
        world,
        *,
        emit=None,
        emit_flips=False,
        initial_turn=0,
        rule=None,
        halo_depth=0,
    ):
        # emit/emit_flips are single-host features; the distributed reference
        # never emits CellFlipped/TurnComplete either (SURVEY.md §4 TestSdl note)
        req = Request(
            world=world,
            turns=params.turns,
            image_height=params.image_height,
            image_width=params.image_width,
            threads=params.threads,
            initial_turn=initial_turn,
            rulestring=rule.rulestring if rule is not None else "",
            halo_depth=halo_depth,  # 0 = the server's -halo-depth default
        )
        res = self.client.call(Methods.BROKER_RUN, req)
        from ..engine.engine import RunResult

        # the broker ships alive=[] (cells are derivable from the world, and
        # pickling O(alive) Cell objects onto the wire is pure waste) — an
        # empty list means "derive locally"; a non-empty one is honoured for
        # compatibility with servers that do ship cells
        return RunResult(res.turns_completed, res.world, res.alive or None)

    def session_run(
        self,
        params,
        world,
        *,
        session_id: int = 0,
        rule=None,
        timeout: float | None = None,
    ):
        """Blocking multi-universe Run (Operations.SessionRun): this
        universe joins the broker's device-resident session batch and the
        call returns ITS final board. Many may be issued concurrently
        (each on its own connection/thread); a nonzero ``session_id``
        tags the session so ``retrieve(session_id=...)`` serves its
        per-universe ticker snapshot mid-flight. Admission refusals
        (capacity / geometry / rule / tag) surface as RpcError replies
        with ``kind == "SessionRejected"`` and the STRUCTURED refusal
        reason on ``RpcError.reason`` (skew-safe: None from an older
        server) — classify on that, never on the message text. A
        tenant-packed tag (obs/accounting.make_tag: tenant id in the
        high 32 bits) attributes this session's usage in the broker's
        accounting ledger."""
        req = Request(
            world=world,
            turns=params.turns,
            image_height=params.image_height,
            image_width=params.image_width,
            threads=params.threads,
            rulestring=rule.rulestring if rule is not None else "",
            session_id=session_id,
        )
        kw = {"timeout": timeout} if timeout is not None else {}
        res = self.client.call(Methods.SESSION_RUN, req, **kw)
        from ..engine.engine import RunResult

        return RunResult(res.turns_completed, res.world, res.alive or None)

    def pause(self):
        self.client.call(Methods.PAUSE, Request())

    def quit(self):
        self.client.call(Methods.QUIT, Request())

    def super_quit(self):
        self.client.call(Methods.SUPER_QUIT, Request())

    def retrieve(self, include_world: bool = True, session_id: int = 0):
        # a nonzero session_id demuxes ONE universe's snapshot from the
        # broker's session batch (the tag a session_run registered);
        # 0 keeps the classic broker-global Retrieve
        res = self.client.call(
            Methods.RETRIEVE,
            Request(include_world=include_world, session_id=session_id),
        )
        from ..engine.engine import Snapshot

        return Snapshot(res.world, res.turns_completed, res.alive_count)

    def status(self, timeout: float = 10.0) -> dict:
        """Read-only metrics snapshot of the remote broker (the Status
        verb, obs/). Empty dict from a pre-Status server's Response.
        ``timeout`` bounds the reply wait: the controller's end-of-session
        trace export calls this, and a broker wedged after the run — the
        very failure mode tracing exists to debug — must cost seconds,
        not hang the session exit."""
        res = self.client.call(Methods.STATUS, Request(), timeout=timeout)
        return getattr(res, "status", None) or {}

    def close(self):
        self.client.close()
