"""RPC client with concurrent in-flight calls (the rpc.Client role).

The controller needs this concurrency: its main thread blocks in
``Operations.Run`` for the entire game while the ticker thread issues
``RetrieveCurrentData``/``Pause`` on the same connection
(gol/distributor.go:159 + :45). Calls are multiplexed by id; a reader
thread routes replies to per-call events.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time

from ..obs import flight as _flight
from ..obs import instruments as _ins
from ..obs import metrics as _metrics
from ..obs import tracing as _tracing
from .protocol import Methods, Request, recv_frame_sized, send_frame


class RpcError(Exception):
    """A server-side error surfaced to the caller (net/rpc's error return).

    ``kind`` is the remote exception CLASS name and ``remote_traceback``
    a truncated remote traceback — populated from the structured error
    reply of a current server (both None against an older peer), so a
    worker-side failure reaching the controller names the exception class
    and site instead of an opaque string."""

    def __init__(self, message, kind=None, remote_traceback=None):
        super().__init__(message)
        self.kind = kind
        self.remote_traceback = remote_traceback


class RpcClient:
    def __init__(self, address: str, timeout: float | None = None):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout)
        self._sock.settimeout(None)
        # send_frame writes header and payload separately; without NODELAY
        # Nagle holds the second small write for the peer's delayed ACK
        # (~40-200 ms per call — fatal for a per-turn scatter/gather)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._write_lock = threading.Lock()
        self._ids = itertools.count()
        self._pending: dict[int, dict] = {}
        self._pending_lock = threading.Lock()
        self._closed = threading.Event()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        # broad catch: an allowlist-rejected or corrupt reply frame
        # (pickle.UnpicklingError, EOFError, ...) must fail every pending
        # call, not silently kill this thread and hang them forever
        try:
            while True:
                msg, nbytes = recv_frame_sized(self._sock)
                with self._pending_lock:
                    slot = self._pending.pop(msg["id"], None)
                if slot is not None:
                    slot["reply"] = msg
                    slot["reply_bytes"] = nbytes
                    slot["event"].set()
        except Exception:
            self._closed.set()
            with self._pending_lock:
                for slot in self._pending.values():
                    slot["event"].set()
                self._pending.clear()

    def call(
        self,
        method: str,
        request: Request,
        timeout: float | None = None,
        trace_parent: dict | None = None,
    ):
        """Blocking call, safe from any thread. ``timeout`` bounds the wait
        for the REPLY (None: forever — Run legitimately blocks for the
        whole game); on expiry the pending slot is dropped and RpcError
        raised, so a wedged server can't hang a poller (obs/status.py).

        ``trace_parent`` explicitly parents this call's span for work
        handed to pool threads (where the caller's thread-local span stack
        is invisible — the workers-backend scatter); by default the span
        parents on the calling thread's current span."""
        if not _metrics.enabled() and not _tracing.enabled():
            return self._call(method, request, timeout)
        # per-verb observability (obs/instruments.py): count + round-trip
        # latency on every outcome, errors separately; plus a client span
        # (obs/tracing.py) whose context rides Request.trace_ctx so the
        # server's dispatch span joins the same trace
        span = _tracing.start_span(
            _tracing.SPAN_RPC_CLIENT, parent_ctx=trace_parent, method=method
        )
        if span is not None and isinstance(request, Request):
            request.trace_ctx = span.ctx()
        _flight.record("rpc.send", method)
        if _metrics.enabled():
            _ins.RPC_CLIENT_REQUESTS_TOTAL.labels(method).inc()
        t0 = time.monotonic()
        err_kind = None
        try:
            result = self._call(method, request, timeout)
            _flight.record("rpc.recv", method, ok=True)
            if span is not None:
                # link to the server-side span when a current server
                # replied with one (older peers: no field, no link)
                peer = getattr(result, "trace_ctx", None)
                if isinstance(peer, dict):
                    span.args["server_span_id"] = peer.get("span_id")
            return result
        except RpcError as e:
            err_kind = e.kind or type(e).__name__
            _flight.record("rpc.recv", method, ok=False, error_kind=err_kind)
            if _metrics.enabled():
                _ins.RPC_CLIENT_ERRORS_TOTAL.labels(method).inc()
            raise
        finally:
            if _metrics.enabled():
                _ins.RPC_CLIENT_REQUEST_SECONDS.labels(method).observe(
                    time.monotonic() - t0
                )
            if err_kind is None:
                _tracing.end_span(span)
            else:
                _tracing.end_span(span, error_kind=err_kind)

    def _call(self, method: str, request: Request, timeout: float | None = None):
        if self._closed.is_set():
            raise RpcError("connection closed")
        call_id = next(self._ids)
        slot = {"event": threading.Event(), "reply": None}
        with self._pending_lock:
            self._pending[call_id] = slot
        # re-check after registering: if the reader died in between, it has
        # already drained _pending and our slot's event would never be set
        if self._closed.is_set():
            with self._pending_lock:
                self._pending.pop(call_id, None)
            raise RpcError("connection closed")
        try:
            with self._write_lock:
                sent = send_frame(
                    self._sock,
                    {"id": call_id, "method": method, "request": request},
                )
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(call_id, None)
            raise RpcError(f"send failed: {e}") from e
        if _metrics.enabled():
            _ins.RPC_CLIENT_SENT_BYTES_TOTAL.labels(method).inc(sent)
        if not slot["event"].wait(timeout):
            with self._pending_lock:
                self._pending.pop(call_id, None)
            raise RpcError(f"no reply to {method} within {timeout}s")
        reply = slot["reply"]
        if reply is None:
            raise RpcError("connection closed before reply")
        if _metrics.enabled():
            _ins.RPC_CLIENT_RECEIVED_BYTES_TOTAL.labels(method).inc(
                slot.get("reply_bytes", 0)
            )
        if "error" in reply:
            # structured error extension: a current server names the remote
            # exception class + truncated traceback beside the message; an
            # older server's reply simply lacks the keys (dict.get — the
            # envelope-level twin of the getattr field posture)
            raise RpcError(
                reply["error"],
                kind=reply.get("error_kind"),
                remote_traceback=reply.get("error_traceback"),
            )
        return reply["result"]

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteBroker:
    """The controller-side broker handle: same surface as InProcessBroker,
    served over RPC (the rpc.Dial("tcp", *server) role, gol/distributor.go:136)."""

    def __init__(self, address: str = "127.0.0.1:8040", timeout: float | None = 10.0):
        self.client = RpcClient(address, timeout=timeout)

    def run(
        self,
        params,
        world,
        *,
        emit=None,
        emit_flips=False,
        initial_turn=0,
        rule=None,
        halo_depth=0,
    ):
        # emit/emit_flips are single-host features; the distributed reference
        # never emits CellFlipped/TurnComplete either (SURVEY.md §4 TestSdl note)
        req = Request(
            world=world,
            turns=params.turns,
            image_height=params.image_height,
            image_width=params.image_width,
            threads=params.threads,
            initial_turn=initial_turn,
            rulestring=rule.rulestring if rule is not None else "",
            halo_depth=halo_depth,  # 0 = the server's -halo-depth default
        )
        res = self.client.call(Methods.BROKER_RUN, req)
        from ..engine.engine import RunResult

        # the broker ships alive=[] (cells are derivable from the world, and
        # pickling O(alive) Cell objects onto the wire is pure waste) — an
        # empty list means "derive locally"; a non-empty one is honoured for
        # compatibility with servers that do ship cells
        return RunResult(res.turns_completed, res.world, res.alive or None)

    def pause(self):
        self.client.call(Methods.PAUSE, Request())

    def quit(self):
        self.client.call(Methods.QUIT, Request())

    def super_quit(self):
        self.client.call(Methods.SUPER_QUIT, Request())

    def retrieve(self, include_world: bool = True):
        res = self.client.call(Methods.RETRIEVE, Request(include_world=include_world))
        from ..engine.engine import Snapshot

        return Snapshot(res.world, res.turns_completed, res.alive_count)

    def status(self, timeout: float = 10.0) -> dict:
        """Read-only metrics snapshot of the remote broker (the Status
        verb, obs/). Empty dict from a pre-Status server's Response.
        ``timeout`` bounds the reply wait: the controller's end-of-session
        trace export calls this, and a broker wedged after the run — the
        very failure mode tracing exists to debug — must cost seconds,
        not hang the session exit."""
        res = self.client.call(Methods.STATUS, Request(), timeout=timeout)
        return getattr(res, "status", None) or {}

    def close(self):
        self.client.close()
